"""Gateway Prometheus metrics — same families as the reference
(/root/reference/pkg/gateway/metrics/metrics.go:24-132)."""

from __future__ import annotations

from arks_tpu.utils import metrics as prom


class GatewayMetrics:
    def __init__(self, registry: prom.Registry | None = None):
        self.registry = registry or prom.Registry()
        r = self.registry
        self.requests_total = r.counter(
            "gateway_requests_total", "Requests by namespace/user/model/status")
        self.request_duration = r.histogram(
            "gateway_request_duration_seconds", "End-to-end request duration",
            buckets=[0.1, 0.25, 0.5, 1, 2.5, 5, 10, 20, 30, 60])
        self.response_process_duration = r.histogram(
            "gateway_response_process_duration_milliseconds",
            "Gateway-side processing time",
            buckets=[1, 5, 10, 25, 50, 100, 250, 500, 1000, 5000])
        self.token_usage = r.counter(
            "gateway_token_usage_total", "Token usage by type")
        self.token_distribution = r.histogram(
            "gateway_token_distribution", "Per-request total tokens",
            buckets=[2 ** i for i in range(0, 17)])
        self.rate_limit_hits_total = r.counter(
            "gateway_rate_limit_hits_total", "Rate-limit rejections by rule")
        self.rate_limit_tokens = r.counter(
            "gateway_rate_limit_tokens_total",
            "Tokens counted toward rate limits")
        self.quota_usage = r.gauge("gateway_quota_usage", "Quota used")
        self.quota_limit = r.gauge("gateway_quota_limit", "Quota limit")
        self.errors_total = r.counter(
            "gateway_errors_total", "Gateway errors by stage")
        self.shed_total = r.counter(
            "gateway_shed_total",
            "Requests shed at the edge by bounded tenant label and reason "
            "(inflight_overshare = gateway at ARKS_GW_SHED_INFLIGHT and the "
            "tenant at/over its weighted fair share)")
        self.client_disconnects_total = r.counter(
            "gateway_client_disconnects_total",
            "Streaming responses whose client hung up before the stream "
            "finished (the gateway drains the backend to meter usage)")
        self.usage_unmetered_total = r.counter(
            "gateway_usage_unmetered_total",
            "Disconnected streams abandoned before the usage frame arrived "
            "(ARKS_GW_DISCONNECT_DRAIN_S exceeded) — billed-but-unmetered "
            "tokens; should be ~0")


class RouterMetrics:
    """Routing-layer families (the router is part of the same data-plane
    metrics surface as the gateway).  Besides the request/backend basics,
    this carries the sketch-routing observability set: per-backend sketch
    age, route decisions by reason, and the expected-vs-actual hit-depth
    pair that makes a mis-scoring sketch visible in monitoring."""

    def __init__(self, registry: prom.Registry | None = None):
        self.registry = registry or prom.Registry()
        r = self.registry
        self.requests_total = r.counter(
            "router_requests_total", "Routed requests")
        self.backends = r.gauge("router_backends", "Known backends")
        self.retries_total = r.counter(
            "router_retries_total",
            "Requests retried on another backend (by reason)")
        self.sketch_age = r.gauge(
            "router_sketch_age_seconds",
            "Seconds since each backend's sketch was last accepted")
        self.route_decisions_total = r.counter(
            "router_route_decisions_total",
            "Routing decisions by reason "
            '(sketch_hit|tie_fallback|stale_sketch|no_key)')
        self.expected_hit_blocks_total = r.counter(
            "router_expected_hit_blocks_total",
            "Sketch-predicted prefix hit depth in blocks, by backend/tier "
            "(compare against the actual router_backend_hit_tokens)")
        self.backend_hit_tokens = r.gauge(
            "router_backend_hit_tokens",
            "Actual cumulative per-tier prefix hit tokens each backend "
            "reports in its sketch")
        self.sketch_epoch_drops_total = r.counter(
            "router_sketch_epoch_drops_total",
            "Sketches dropped because the backend's epoch changed "
            "(restart/reset)")
        self.planned_membership_total = r.counter(
            "router_planned_membership_total",
            "Planned membership changes by op (join|leave) and outcome "
            "(ok|timeout) — the elastic scale-up/down handoff path")
        self.join_seconds = r.gauge(
            "router_join_seconds",
            "Duration of the last planned join per backend: readiness "
            "polling + sketch prime, before first traffic was routed")
