"""Gateway data plane: auth, QoS, rate limiting, quota, weighted routing.

The reference implements this as an Envoy ext_proc plugin
(/root/reference/pkg/gateway/); here the gateway IS the proxy (one less
moving part, same wire behaviors):

request path (handle_request.go:33-249):
  Bearer token -> 401 if absent; parse {model, stream,
  stream_options.include_usage}; resolve QoS by (token, model); validate the
  model against the namespace's endpoints; streaming REQUIRES
  include_usage=true (or usage can't be metered); pre-check rate limits and
  quota (429); count the request (rpm/rpd); forward with injected
  {model, namespace, username} headers.

response path (handle_response.go:80-268):
  non-streaming -> parse {usage} from the JSON body; streaming -> relay SSE
  frames while scanning for the final usage frame; then TPM/TPD DoLimit +
  quota IncrUsage({prompt,response,total}) + metrics.

routing (arksendpoint_controller.go:283-369 + dist/gateway.yaml:230-248):
  weighted choice over Endpoint.status.routes; passive ejection of backends
  after 3 consecutive 5xx/connect errors for 30s.

defaults (types.go:24-64): rpm=100 when unset; tpm=rpm*1000 when unset.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import random
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from arks_tpu import slo as slo_mod
from arks_tpu import tenancy
from arks_tpu.control.store import Store
from arks_tpu.gateway.metrics import GatewayMetrics
from arks_tpu.gateway.qos import QosProvider, TokenQos
from arks_tpu.gateway.quota import QuotaService, QuotaStatusSyncer
from arks_tpu.gateway.ratelimiter import (
    RateLimiter, REQUEST_RULES, RULES, TOKEN_RULES,
)
from arks_tpu.control.resources import (
    QUOTA_PROMPT, QUOTA_RESPONSE, QUOTA_TOTAL, RL_RPM, RL_TPM,
)
from arks_tpu.obs import logctx
from arks_tpu.obs import trace as trace_mod
from arks_tpu.utils import knobs
from arks_tpu.utils.swallow import swallowed

log = logging.getLogger("arks_tpu.gateway")
logctx.install(log)

# End-to-end tracing: the gateway is the trace ROOT — it mints the W3C
# trace id, completes its admit span, and forwards both downstream
# (traceparent + x-arks-trace-spans); the engine's store assembles them.
_TRACE_ON = knobs.get_bool("ARKS_TRACE")

DEFAULT_RPM = 100            # types.go:24-64
DEFAULT_TPM_MULTIPLIER = 1000

EJECT_AFTER_CONSECUTIVE_5XX = 3   # dist/gateway.yaml:230-248
EJECT_SECONDS = 30.0

# Edge policies (dist/gateway.yaml:250-282): the reference fronts the plugin
# with Envoy's ClientTrafficPolicy 4MiB client buffer and a 5s ext_proc
# messageTimeout per processing stage.  Here the gateway IS the proxy, so it
# enforces both itself: oversized bodies are rejected with 413 before
# buffering, and the admission stage (body read + parse + QoS + limit
# checks) runs under a deadline that turns a slow stage into a clean 504
# instead of an unbounded latency hit (wedged counter backends are bounded
# by their own socket timeouts).
MAX_BODY_BYTES = 4 * 1024 * 1024
PROCESS_TIMEOUT_S = 5.0

# Memory bounds for per-key state that grows with CLIENT-chosen inputs
# (namespace/endpoint pairs, backend addresses).  Both trackers are
# LRU-evicted at these caps: hostile key/address churn costs the oldest
# entry its history (a fresh window / fresh failure count — benign),
# never unbounded gateway memory.
RATE_TRACKER_MAX_KEYS = 4096
EJECTOR_MAX_ADDRS = 1024

HDR_MODEL = "x-arks-model"
HDR_NAMESPACE = "x-arks-namespace"
HDR_USER = "x-arks-username"
# SLO tier (arks_tpu.slo): validated against ARKS_SLO_TIERS at admission
# (unknown tier -> 400), forwarded to the backend, where the OpenAI server
# maps it onto the engine priority scale.  Echoed back on tier-capacity
# 503s so clients know WHICH tier to back off.
HDR_TIER = "x-arks-tier"


class _ApiError(Exception):
    def __init__(self, code: int, message: str, stage: str = "",
                 retry_after: int | None = None,
                 tenant: str | None = None):
        super().__init__(message)
        self.code, self.message, self.stage = code, message, stage
        # Emitted as a Retry-After header on the error response (cold-start
        # backpressure: retry, don't fail the request class).
        self.retry_after = retry_after
        # Backpressure errors raised while the token is already resolved
        # carry the tenant so the 429/503 can say WHO should slow down
        # even when the handler never got past admission.
        self.tenant = tenant


class PyUsageScanner:
    """Pure-Python SSE usage scan — the fallback for (and the test oracle
    of) arks_tpu.gateway.native.SseUsageScanner."""

    def __init__(self) -> None:
        self._buf = b""
        self._usage: dict | None = None

    def feed(self, chunk: bytes) -> None:
        self._buf += chunk
        while b"\n\n" in self._buf or b"\r\n\r\n" in self._buf:
            a = self._buf.find(b"\n\n")
            b = self._buf.find(b"\r\n\r\n")
            if b != -1 and (a == -1 or b < a):
                frame, self._buf = self._buf[:b], self._buf[b + 4:]
            else:
                frame, self._buf = self._buf[:a], self._buf[a + 2:]
            for line in frame.splitlines():
                if not line.startswith(b"data:"):
                    continue
                data = line[5:].strip()
                if data == b"[DONE]":
                    continue
                try:
                    obj = json.loads(data)
                except (ValueError, json.JSONDecodeError):
                    continue
                u = obj.get("usage") if isinstance(obj, dict) else None
                # Replace only when the frame carries a countable usage
                # object: a later empty/non-numeric usage frame must not
                # clear previously captured counters (same rule as the
                # native scanner, keeping metering backend-independent).
                if isinstance(u, dict) and any(
                        isinstance(u.get(k), (int, float))
                        and not isinstance(u.get(k), bool)
                        for k in ("prompt_tokens", "completion_tokens",
                                  "total_tokens")):
                    self._usage = u

    def usage(self) -> dict | None:
        return self._usage


def make_usage_scanner():
    from arks_tpu.gateway import native
    if native.available():
        return native.SseUsageScanner()
    return PyUsageScanner()


class RequestRateTracker:
    """Per-endpoint admitted-request rate (rpm), two-minute-window sliding
    estimate: prev-window count weighted by the un-elapsed fraction + the
    current window — cheap, lock-bounded, and smooth enough for the
    autoscaler (arks_tpu.control.autoscaler) to damp on."""

    def __init__(self, max_keys: int = RATE_TRACKER_MAX_KEYS) -> None:
        self._lock = threading.Lock()
        self._max_keys = max_keys
        # Insertion order doubles as LRU order (record() moves its key to
        # the end): dict ordering makes next(iter(...)) the LRU victim.
        self._counts: dict[tuple[str, str], dict[int, int]] = {}

    def record(self, namespace: str, endpoint: str) -> None:
        m = int(time.time() // 60)
        key = (namespace, endpoint)
        with self._lock:
            w = self._counts.pop(key, None)
            if w is None:
                w = {}
                while len(self._counts) >= self._max_keys:
                    del self._counts[next(iter(self._counts))]
            self._counts[key] = w
            w[m] = w.get(m, 0) + 1
            for k in [k for k in w if k < m - 1]:
                del w[k]

    def rpm(self, namespace: str, endpoint: str) -> float:
        now = time.time()
        m = int(now // 60)
        frac = (now % 60) / 60
        with self._lock:
            w = self._counts.get((namespace, endpoint), {})
            return w.get(m - 1, 0) * (1 - frac) + w.get(m, 0)


class _Ejector:
    """Passive outlier detection per backend address.  State is bounded
    (EJECTOR_MAX_ADDRS, LRU): addresses come from the control store's
    routes, which endpoint churn can grow without limit."""

    def __init__(self, max_addrs: int = EJECTOR_MAX_ADDRS) -> None:
        self._lock = threading.Lock()
        self._max_addrs = max_addrs
        self._bad: dict[str, int] = {}
        self._ejected_until: dict[str, float] = {}

    def ok(self, addr: str) -> None:
        with self._lock:
            self._bad.pop(addr, None)

    def fail(self, addr: str) -> None:
        now = time.monotonic()
        with self._lock:
            # Expired ejections are dead weight — reap them before the
            # LRU bound so eviction only ever hits live state.
            for a in [a for a, t in self._ejected_until.items() if t <= now]:
                del self._ejected_until[a]
            n = self._bad.pop(addr, 0) + 1
            while len(self._bad) >= self._max_addrs:
                del self._bad[next(iter(self._bad))]
            self._bad[addr] = n
            if n >= EJECT_AFTER_CONSECUTIVE_5XX:
                while len(self._ejected_until) >= self._max_addrs:
                    del self._ejected_until[next(iter(self._ejected_until))]
                self._ejected_until[addr] = now + EJECT_SECONDS
                self._bad[addr] = 0

    def available(self, addrs: list[str]) -> list[str]:
        now = time.monotonic()
        with self._lock:
            live = [a for a in addrs if self._ejected_until.get(a, 0) <= now]
        # Max 100% ejection protection: if everything is ejected, try all.
        return live or addrs


class Gateway:
    def __init__(self, store: Store, host: str = "0.0.0.0", port: int = 8081,
                 rate_limiter: RateLimiter | None = None,
                 quota: QuotaService | None = None,
                 quota_sync_s: float = 2.0,
                 max_body_bytes: int = MAX_BODY_BYTES,
                 process_timeout_s: float = PROCESS_TIMEOUT_S):
        self.store = store
        self.host, self.port = host, port
        self.qos = QosProvider(store)
        self.limiter = rate_limiter or RateLimiter()
        self.quota = quota or QuotaService()
        self.syncer = QuotaStatusSyncer(store, self.quota, sync_s=quota_sync_s)
        self.metrics = GatewayMetrics()
        self.ejector = _Ejector()
        self.rate = RequestRateTracker()
        self.max_body_bytes = max_body_bytes
        self.process_timeout_s = process_timeout_s
        # Cold-start-aware admission: while a model has no ready backend
        # (scale-from-zero, weights still loading into a pool), QUEUE the
        # request — poll routing for up to this many seconds — instead of
        # an instant 503.  Past the window, 503 + Retry-After.
        self.cold_start_wait_s = knobs.get_float("ARKS_GW_COLD_START_WAIT_S")
        # SLO-tier ladder (ARKS_SLO_TIERS).  Empty = tier headers rejected.
        self.slo = slo_mod.from_env()
        # Edge shedding (ARKS_GW_SHED_INFLIGHT, 0 = off): once gateway
        # in-flight requests reach the cap, the tenant MOST over its
        # weighted fair share is rejected 429 here — before its flood
        # even reaches the engine queue.  Weights match the engine's
        # WDRR (ARKS_FAIR_WEIGHTS), so edge and engine agree on "share".
        self.shed_inflight_max = knobs.get_int("ARKS_GW_SHED_INFLIGHT")
        self.fair_weights = tenancy.weights_from_env()
        self.tenant_labels = tenancy.TenantLabels()
        self._inflight: dict[str, int] = {}
        self._inflight_lock = threading.Lock()
        # How long to keep draining (and metering) a backend stream after
        # the CLIENT hung up — usage must still be billed exactly once.
        self.disconnect_drain_s = knobs.get_float("ARKS_GW_DISCONNECT_DRAIN_S")
        self._httpd: ThreadingHTTPServer | None = None

    # ------------------------------------------------------------------

    def start(self, background: bool = True) -> None:
        gw = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _json(self, code: int, payload: dict,
                      headers: dict | None = None) -> None:
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(data)

            def _error(self, code: int, message: str,
                       retry_after: int | None = None,
                       headers: dict | None = None) -> None:
                # error body parity (util.go:40-77)
                hdrs = dict(headers or {})
                if retry_after:
                    hdrs["Retry-After"] = retry_after
                self._json(code, {"error": {"message": message, "code": code}},
                           headers=hdrs or None)

            def do_GET(self):
                if self.path == "/v1/models":
                    try:
                        secret = gw._bearer(self.headers)
                        models = gw.qos.get_models_by_token(secret)
                        self._json(200, {"object": "list", "data": [
                            {"id": m, "object": "model", "owned_by": "arks-tpu"}
                            for m in models]})
                    except _ApiError as e:
                        self._error(e.code, e.message)
                elif self.path == "/metrics":
                    text = gw.metrics.registry.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(text)))
                    self.end_headers()
                    self.wfile.write(text)
                elif self.path in ("/healthz", "/readiness"):
                    self._json(200, {"status": "ok"})
                else:
                    self._error(404, f"no route {self.path}")

            def do_POST(self):
                if self.path not in ("/v1/chat/completions", "/v1/completions"):
                    return self._error(404, f"no route {self.path}")
                gw._handle_inference(self)

        class Server(ThreadingHTTPServer):
            # Absorb connection bursts (hundreds of concurrent clients
            # reconnecting at once): the default backlog of 5 makes the
            # kernel RST the overflow (measured in tools/bench_gateway.py).
            request_queue_size = 512
            daemon_threads = True

        self._httpd = Server((self.host, self.port), Handler)
        self.port = self._httpd.server_port
        self.syncer.start()
        if background:
            threading.Thread(target=self._httpd.serve_forever, name="gateway",
                             daemon=True).start()
        else:
            self._httpd.serve_forever()

    def stop(self) -> None:
        self.syncer.stop()
        self.qos.stop()
        if self._httpd:
            self._httpd.shutdown()

    # ------------------------------------------------------------------
    # Admission
    # ------------------------------------------------------------------

    @staticmethod
    def _bearer(headers) -> str:
        auth = headers.get("Authorization", "")
        if not auth.startswith("Bearer ") or not auth[7:].strip():
            raise _ApiError(401, "missing or malformed Authorization header",
                            "auth")
        return auth[7:].strip()

    def _effective_limits(self, qos: TokenQos) -> dict[str, int]:
        limits = dict(qos.rate_limits)
        if RL_RPM not in limits:
            limits[RL_RPM] = DEFAULT_RPM
        if RL_TPM not in limits:
            limits[RL_TPM] = limits[RL_RPM] * DEFAULT_TPM_MULTIPLIER
        return limits

    def _admit(self, handler) -> tuple[TokenQos, dict, dict[str, int]]:
        deadline = time.monotonic() + self.process_timeout_s
        secret = self._bearer(handler.headers)
        try:
            length = int(handler.headers.get("Content-Length", 0))
        except ValueError:
            handler.close_connection = True  # body never drained
            raise _ApiError(400, "invalid Content-Length", "parse")
        if length > self.max_body_bytes:
            # Client-buffer parity (dist/gateway.yaml:250-261): reject before
            # reading — buffering an unbounded body is the DoS vector.  The
            # unread body would desync this keep-alive connection, so drop it.
            handler.close_connection = True
            raise _ApiError(413, f"request body {length} bytes exceeds the "
                            f"{self.max_body_bytes}-byte limit", "parse")
        try:
            # Slow-loris protection: read incrementally (read1 returns what
            # has arrived, not a full block) and check the TOTAL deadline
            # between reads — a per-recv socket timeout alone would let a
            # client trickling one byte per few seconds pin this thread for
            # hours while every individual recv stays "fast".
            handler.connection.settimeout(self.process_timeout_s)
            chunks: list[bytes] = []
            got = 0
            while got < length:
                if time.monotonic() > deadline:
                    raise TimeoutError
                chunk = handler.rfile.read1(min(65536, length - got))
                if not chunk:
                    break
                chunks.append(chunk)
                got += len(chunk)
            body = json.loads(b"".join(chunks) or b"{}")
        except TimeoutError:
            handler.close_connection = True  # partial body left on the wire
            raise _ApiError(408, "timed out reading request body", "parse")
        except (ValueError, json.JSONDecodeError):
            raise _ApiError(400, "invalid JSON body", "parse")
        finally:
            handler.connection.settimeout(None)
        model = body.get("model", "")
        if not model:
            raise _ApiError(400, "missing model field", "parse")

        # SLO tier (after the body is drained so a 400 here keeps the
        # keep-alive connection in sync).  Typos must not silently demote
        # a latency-class request to the default tier — reject them.
        tier = (handler.headers.get(HDR_TIER) or "").strip() or None
        if tier is not None:
            if not self.slo:
                raise _ApiError(
                    400, f"{HDR_TIER} header sent but no SLO tiers are "
                    "configured (ARKS_SLO_TIERS)", "parse")
            if self.slo.get(tier) is None:
                raise _ApiError(
                    400, f"unknown SLO tier {tier!r} (configured: "
                    f"{', '.join(self.slo.names)})", "parse")

        qos = self.qos.get_qos_by_token(secret, model)
        if qos is None:
            if not self.qos.token_known(secret):
                raise _ApiError(401, "invalid token", "auth")
            raise _ApiError(403, f"token has no access to model {model!r}", "auth")
        if model not in self.qos.get_model_list(qos.namespace):
            raise _ApiError(404, f"model {model!r} not found", "route")

        # Streaming requires include_usage so usage can be metered
        # (handle_request.go:160-171).
        if body.get("stream", False):
            if not (body.get("stream_options") or {}).get("include_usage"):
                raise _ApiError(
                    400, "streaming requests require "
                    "stream_options.include_usage=true", "parse")

        limits = self._effective_limits(qos)
        for res in self.limiter.check_limit(
                qos.namespace, qos.username, model, limits,
                requested={r: 1 for r in REQUEST_RULES}):
            if res.over:
                self.metrics.rate_limit_hits_total.inc(
                    rule=res.rule, namespace=qos.namespace, user=qos.username)
                # Windows are wall-clock-aligned, so the exact moment this
                # rule resets is known: Retry-After = time to window edge.
                # Every 429 carries the header — clients and the router
                # back off with precision instead of guess-retrying.
                period = RULES[res.rule][0]
                raise _ApiError(429, f"rate limit exceeded: {res.rule} "
                                f"({res.current}/{res.limit})", "ratelimit",
                                retry_after=max(
                                    1, int(period - (time.time() % period))),
                                tenant=tenancy.tenant_id(
                                    qos.namespace, qos.username))
        if qos.quota_name:
            q_limits = self.qos.get_quota_limits(qos.namespace, qos.quota_name)
            over, typ = self.quota.check(qos.namespace, qos.quota_name, q_limits)
            if over:
                # Quota recovers on the syncer's status cadence, not a
                # rate window — a minute is the honest retry horizon.
                raise _ApiError(429, f"quota exceeded: {typ}", "quota",
                                retry_after=60,
                                tenant=tenancy.tenant_id(
                                    qos.namespace, qos.username))
            for typ, limit in q_limits.items():
                self.metrics.quota_limit.set(
                    limit, namespace=qos.namespace, quota=qos.quota_name, type=typ)

        # Processing-stage deadline (EnvoyExtensionPolicy 5s messageTimeout,
        # dist/gateway.yaml:263-282): a SLOW counter backend fails the
        # request with 504 instead of silently eating the latency budget.
        # (A fully wedged backend is bounded separately by its own socket
        # timeout — RespClient — since a blocked call can't observe this
        # deadline until it returns.)
        if time.monotonic() > deadline:
            raise _ApiError(504, "request processing exceeded "
                            f"{self.process_timeout_s}s", "timeout")

        # Count the admitted request (rpm/rpd).
        self.limiter.do_limit(qos.namespace, qos.username, model,
                              {r: 1 for r in REQUEST_RULES})
        return qos, body, limits, tier

    # ------------------------------------------------------------------
    # Routing + proxy
    # ------------------------------------------------------------------

    def _pick_backends(self, namespace: str, model: str) -> list[str]:
        """Weighted-ordered backend candidates; cold-start-aware: a model
        with routes but no ready backend yet (scale-from-zero, weight pool
        still streaming) is POLLED for up to cold_start_wait_s before the
        503 — the request queues on the gateway instead of bouncing.
        Unknown models (404) fail fast."""
        deadline = time.monotonic() + self.cold_start_wait_s
        while True:
            try:
                return self._pick_backends_once(namespace, model)
            except _ApiError as e:
                if e.code != 503 or time.monotonic() >= deadline:
                    if e.code == 503 and e.retry_after is None:
                        e.retry_after = max(int(self.cold_start_wait_s), 1)
                    raise
            time.sleep(0.25)

    def _pick_backends_once(self, namespace: str, model: str) -> list[str]:
        ep = self.qos.get_endpoint(namespace, model)
        if ep is None:
            raise _ApiError(404, f"model {model!r} not found", "route")
        routes = ep.status.get("routes", [])
        weighted: list[tuple[str, int]] = []
        for r in routes:
            for addr in r.get("backend", {}).get("addresses", []):
                weighted.append((addr, max(r.get("weight", 1), 1)))
        if not weighted:
            raise _ApiError(503, f"no ready backends for model {model!r}", "route")
        addrs = self.ejector.available([a for a, _ in weighted])
        pool = [(a, w) for a, w in weighted if a in addrs]
        ordered: list[str] = []
        while pool:
            total = sum(w for _, w in pool)
            x = random.uniform(0, total)
            acc = 0.0
            for i, (a, w) in enumerate(pool):
                acc += w
                if x <= acc:
                    ordered.append(a)
                    pool.pop(i)
                    break
        return ordered

    def _handle_inference(self, handler) -> None:
        t0 = time.monotonic()
        qos = None
        status = 500
        tier = None
        ctx = (trace_mod.TraceCtx.from_headers(handler.headers)
               if _TRACE_ON else None)
        tenant = None
        try:
            with logctx.bound(trace_id=ctx.trace_id if ctx else None):
                qos, body, limits, tier = self._admit(handler)
                if ctx is not None:
                    ctx.upstream.append({
                        "component": "gateway", "name": "gateway.admit",
                        "start": t0, "end": time.monotonic(),
                        "arg": qos.username})
                # Admitted demand feeds the autoscaler's per-endpoint rate.
                self.rate.record(qos.namespace, qos.endpoint)
                tenant = tenancy.tenant_id(qos.namespace, qos.username)
                self._edge_admit(tenant)
                try:
                    status = self._proxy(handler, qos, body, limits, tier,
                                         tenant=tenant, ctx=ctx)
                finally:
                    self._edge_done(tenant)
        except _ApiError as e:
            status = e.code
            self.metrics.errors_total.inc(stage=e.stage or "other")
            ra = getattr(e, "retry_after", None)
            hdrs = {}
            if e.code in (429, 503):
                # Backpressure responses carry the full picture: WHO to
                # slow down (tenant), WHICH tier is saturated, and WHEN to
                # come back (Retry-After — every 429/503 has one).
                if tier is not None:
                    hdrs[HDR_TIER] = tier
                tnt = tenant or getattr(e, "tenant", None)
                if tnt is not None:
                    hdrs[tenancy.HDR_TENANT] = tnt
                if ra is None:
                    ra = 1
            try:
                handler._error(e.code, e.message, retry_after=ra,
                               headers=hdrs or None)
            except Exception as e2:
                # Client hung up before the error response went out.
                swallowed("gateway.error-response", e2)
        except Exception as e:
            log.exception("gateway failure")
            self.metrics.errors_total.inc(stage="internal")
            try:
                handler._error(500, f"gateway error: {e}")
            except Exception as e2:
                swallowed("gateway.error-response", e2)
        finally:
            labels = dict(status=str(status))
            if qos is not None:
                labels.update(namespace=qos.namespace, user=qos.username,
                              model=qos.endpoint)
            self.metrics.requests_total.inc(**labels)
            self.metrics.request_duration.observe(time.monotonic() - t0)

    def _edge_admit(self, tenant: str) -> None:
        """Pre-emptive edge shed: with the gateway at its in-flight cap
        (ARKS_GW_SHED_INFLIGHT), reject the tenant MOST over its weighted
        fair share — the flood pays, steady tenants keep flowing.  429 +
        Retry-After 1: this clears as soon as any in-flight completes."""
        if self.shed_inflight_max <= 0:
            with self._inflight_lock:
                self._inflight[tenant] = self._inflight.get(tenant, 0) + 1
            return
        w = tenancy.weight_of(self.fair_weights, tenant)
        with self._inflight_lock:
            total = sum(self._inflight.values())
            if total >= self.shed_inflight_max:
                mine = (self._inflight.get(tenant, 0) + 1) / w
                worst = max(
                    (n / tenancy.weight_of(self.fair_weights, t)
                     for t, n in self._inflight.items()), default=0.0)
                if mine >= worst:
                    self.metrics.shed_total.inc(
                        tenant=self.tenant_labels.label(tenant),
                        reason="inflight_overshare")
                    raise _ApiError(
                        429, f"gateway saturated ({total} in-flight >= "
                        f"{self.shed_inflight_max}) and tenant {tenant!r} "
                        "is at or above its fair share", "shed",
                        retry_after=1)
            self._inflight[tenant] = self._inflight.get(tenant, 0) + 1

    def _edge_done(self, tenant: str) -> None:
        with self._inflight_lock:
            n = self._inflight.get(tenant, 0) - 1
            if n <= 0:
                self._inflight.pop(tenant, None)
            else:
                self._inflight[tenant] = n

    def _proxy(self, handler, qos: TokenQos, body: dict,
               limits: dict[str, int], tier: str | None = None,
               tenant: str | None = None, ctx=None) -> int:
        payload = json.dumps(body).encode()
        stream = bool(body.get("stream", False))
        last_err: Exception | None = None
        trace_headers = {}
        if ctx is not None:
            fwd = ctx.child()
            trace_headers[trace_mod.TRACEPARENT_HEADER] = fwd.traceparent()
            if fwd.upstream:
                trace_headers[trace_mod.SPANS_HEADER] = \
                    trace_mod.spans_header(fwd.upstream)
        for addr in self._pick_backends(qos.namespace, qos.endpoint):
            host, _, port = addr.partition(":")
            conn = http.client.HTTPConnection(host, int(port or 80), timeout=300)
            try:
                conn.request("POST", handler.path, body=payload, headers={
                    "Content-Type": "application/json",
                    # Routing headers parity (handle_request.go:208-231).
                    HDR_MODEL: qos.endpoint,
                    HDR_NAMESPACE: qos.namespace,
                    HDR_USER: qos.username,
                    # Tenant identity: minted HERE (namespace/username is
                    # what the token resolved to — clients cannot spoof
                    # it), consumed by the engine's weighted-fair queue.
                    **({tenancy.HDR_TENANT: tenant}
                       if tenant is not None else {}),
                    **({HDR_TIER: tier} if tier is not None else {}),
                    **trace_headers,
                })
                resp = conn.getresponse()
            except OSError as e:
                self.ejector.fail(addr)
                last_err = e
                conn.close()
                continue
            try:
                if resp.status >= 500:
                    self.ejector.fail(addr)
                else:
                    self.ejector.ok(addr)
                def account(usage, _resp=resp):
                    # Billing must never corrupt an in-flight response:
                    # accounting failures are recorded, not raised.
                    if _resp.status >= 500 or not usage:
                        return
                    try:
                        self._account_usage(qos, usage, limits)
                    except Exception:
                        log.exception("usage accounting failed")
                        self.metrics.errors_total.inc(stage="accounting")
                if stream and resp.status == 200:
                    self._relay_stream(handler, resp, account)
                else:
                    self._relay_full(handler, resp, account)
                return resp.status
            finally:
                conn.close()
        raise _ApiError(503, f"all backends unreachable: {last_err}", "route",
                        retry_after=5)

    def _relay_full(self, handler, resp, account) -> None:
        data = resp.read()
        # Account before the body reaches the client so usage is visible the
        # moment the response is (billing ordering).
        if resp.status == 200:
            try:
                obj = json.loads(data)
            except (ValueError, json.JSONDecodeError):
                obj = None
            account(obj.get("usage") if isinstance(obj, dict) else None)
        handler.send_response(resp.status)
        handler.send_header("Content-Type",
                            resp.headers.get("Content-Type", "application/json"))
        handler.send_header("Content-Length", str(len(data)))
        # Cold-start backpressure travels end-to-end: the serving pod's
        # Retry-After (model_pool_exhausted) reaches the client.
        ra = resp.headers.get("Retry-After")
        if ra:
            handler.send_header("Retry-After", ra)
        # Tier-capacity 503s echo the tier so per-tier clients back off
        # independently; tenant-fair sheds echo the tenant and the
        # backend's queue-saturation signal the same way.
        for h in (HDR_TIER, tenancy.HDR_TENANT, tenancy.HDR_SATURATION):
            v = resp.headers.get(h)
            if v:
                handler.send_header(h, v)
        handler.end_headers()
        handler.wfile.write(data)

    def _relay_stream(self, handler, resp, account) -> None:
        """Relay SSE to the client, scanning frames for the usage object
        (handle_response.go:113-133). Robust to chunk fragmentation: frames
        are reassembled on blank-line boundaries.  The scan runs in the
        native library when available (arks_tpu.gateway.native)."""
        handler.send_response(resp.status)
        handler.send_header("Content-Type",
                            resp.headers.get("Content-Type", "text/event-stream"))
        handler.send_header("Transfer-Encoding", "chunked")
        handler.end_headers()

        scanner = make_usage_scanner()
        t_proc = 0.0
        client_dead = False
        drain_deadline = None
        drained = True
        while True:
            chunk = resp.read1(65536)
            if not chunk:
                break
            if not client_dead:
                try:
                    handler.wfile.write(
                        f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
                    handler.wfile.flush()
                except OSError:
                    # Client hung up mid-stream.  The backend has already
                    # generated (and will bill) these tokens, so KEEP
                    # READING — the usage frame at the end of the stream
                    # is the only exact record.  Bounded: past the drain
                    # window we give up rather than babysit a slow
                    # backend for a client that's gone.
                    client_dead = True
                    drain_deadline = (time.monotonic()
                                      + self.disconnect_drain_s)
            tp = time.monotonic()
            scanner.feed(chunk)
            t_proc += time.monotonic() - tp
            if drain_deadline is not None and time.monotonic() > drain_deadline:
                drained = False
                break
        # Exactly-once metering: account() runs once per stream, with
        # whatever the scanner captured — a disconnect neither
        # double-counts (no retry path re-accounts) nor leaks tokens
        # (the drain usually reaches the usage frame).
        account(scanner.usage())
        if client_dead:
            self.metrics.client_disconnects_total.inc()
            if not drained or scanner.usage() is None:
                # Gave up before the usage frame: tokens the backend
                # billed that the gateway could not meter.  Alert on this.
                self.metrics.usage_unmetered_total.inc()
            handler.close_connection = True
        else:
            handler.wfile.write(b"0\r\n\r\n")
            handler.wfile.flush()
        self.metrics.response_process_duration.observe(t_proc * 1000)

    # ------------------------------------------------------------------
    # Usage accounting (handle_response.go:184-223)
    # ------------------------------------------------------------------

    def _account_usage(self, qos: TokenQos, usage: dict,
                       limits: dict[str, int]) -> None:
        prompt = int(usage.get("prompt_tokens", 0))
        completion = int(usage.get("completion_tokens", 0))
        total = int(usage.get("total_tokens", prompt + completion))
        self.limiter.do_limit(qos.namespace, qos.username, qos.endpoint,
                              {r: total for r in TOKEN_RULES})
        self.metrics.rate_limit_tokens.inc(
            total, namespace=qos.namespace, user=qos.username)
        if qos.quota_name:
            self.quota.incr_usage(qos.namespace, qos.quota_name, {
                QUOTA_PROMPT: prompt, QUOTA_RESPONSE: completion,
                QUOTA_TOTAL: total})
            for typ, used in self.quota.get_usage(
                    qos.namespace, qos.quota_name).items():
                self.metrics.quota_usage.set(
                    used, namespace=qos.namespace, quota=qos.quota_name, type=typ)
        for typ, amount in (("prompt", prompt), ("response", completion),
                            ("total", total)):
            self.metrics.token_usage.inc(
                amount, type=typ, namespace=qos.namespace, user=qos.username)
        self.metrics.token_distribution.observe(total)
