from arks_tpu.gateway.server import Gateway

__all__ = ["Gateway"]
