"""Shared counter store over the Redis protocol (RESP2).

Why: the reference gateway keeps rate-limit windows and quota usage in
Redis (/root/reference/pkg/gateway/ratelimiter/redis_impl.go:47-168,
quota/redis_impl.go:38-107, dist/gateway.yaml:199-228) precisely so that a
SECOND gateway replica shares the same counters — in-process stores would
let N replicas each grant the full limit.  This module gives the TPU-native
gateway the same HA story:

- ``RespClient`` — a minimal, dependency-free RESP2 client (the image has
  no redis-py).  Pipelining + the handful of commands the gateway needs.
- ``RedisCounterBackend`` — ratelimiter.CounterBackend over any
  RESP-speaking server (real Redis in production).  Same key layout and
  fixed-window semantics as the in-memory/native backends.
- ``RedisQuotaService`` — gateway.quota.QuotaService over the same server
  (plain non-expiring counters keyed namespace/quotaname/type, reference
  quota/redis_impl.go).
- ``RespServer`` — a tiny in-process RESP server (GET/SET/INCRBY/EXPIRE/
  TTL/DEL/PING/FLUSHALL with expiry).  The test double for the above, and
  a single-binary alternative for small deployments:
  ``python -m arks_tpu.gateway.rediskv --port 6380``.
"""

from __future__ import annotations

import logging
import socket
import socketserver
import threading
import time

from arks_tpu.gateway.quota import QuotaService

log = logging.getLogger("arks_tpu.gateway.rediskv")


# ---------------------------------------------------------------------------
# RESP2 client
# ---------------------------------------------------------------------------


class RespError(RuntimeError):
    pass


def _encode_command(args: tuple) -> bytes:
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        if isinstance(a, bytes):
            b = a
        else:
            b = str(a).encode()
        out.append(b"$%d\r\n%s\r\n" % (len(b), b))
    return b"".join(out)


class _Conn:
    """One RESP connection with its read buffer."""

    def __init__(self, host: str, port: int, timeout_s: float):
        self.sock = socket.create_connection((host, port), timeout=timeout_s)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.buf = b""

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def read_line(self) -> bytes:
        while b"\r\n" not in self.buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self.buf += chunk
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    def read_exact(self, n: int) -> bytes:
        while len(self.buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self.buf += chunk
        data, self.buf = self.buf[:n], self.buf[n:]
        return data

    def read_reply(self):
        """One reply; error replies come back as RespError VALUES so the
        caller always consumes every reply of a pipelined batch — raising
        mid-batch would leave replies buffered and desynchronize the
        stream for every later command."""
        line = self.read_line()
        t, rest = line[:1], line[1:]
        if t == b"+":
            return rest.decode()
        if t == b"-":
            return RespError(rest.decode())
        if t == b":":
            return int(rest)
        if t == b"$":
            n = int(rest)
            if n == -1:
                return None
            data = self.read_exact(n)
            self.read_exact(2)  # trailing \r\n
            return data
        if t == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [self.read_reply() for _ in range(n)]
        raise ConnectionError(f"unexpected reply type {line!r}")


class RespClient:
    """Minimal RESP2 client with one connection PER THREAD — the gateway
    calls from concurrent request-handler threads, and a single locked
    connection would serialize every admission's round-trips head-of-line
    behind the slowest one."""

    def __init__(self, host: str, port: int, timeout_s: float = 5.0):
        self.host, self.port, self.timeout_s = host, port, timeout_s
        self._tls = threading.local()
        self._all: list[_Conn] = []
        self._all_lock = threading.Lock()
        self._conn()  # fail fast on a bad address

    def _conn(self) -> _Conn:
        conn = getattr(self._tls, "conn", None)
        if conn is None:
            conn = _Conn(self.host, self.port, self.timeout_s)
            self._tls.conn = conn
            with self._all_lock:
                self._all.append(conn)
        return conn

    def _drop_conn(self) -> None:
        conn = getattr(self._tls, "conn", None)
        if conn is not None:
            conn.close()
            with self._all_lock:
                if conn in self._all:
                    self._all.remove(conn)
            self._tls.conn = None

    def close(self) -> None:
        with self._all_lock:
            for c in self._all:
                c.close()
            self._all.clear()

    def pipeline(self, *commands: tuple) -> list:
        """Send several commands in one write, read all replies (the
        reference pipelines GET+TTL and INCRBY+EXPIRE the same way).

        Retry policy: a failure during SEND reconnects and resends once
        (the server cannot have executed a partially-delivered batch, and
        pipelined writes are small enough to fit the send buffer whole); a
        failure while READING replies does NOT resend — the server may
        have executed the commands, and re-applying INCRBYs would double-
        count rate windows and permanently inflate quota ledgers.
        """
        payload = b"".join(_encode_command(c) for c in commands)
        try:
            conn = self._conn()
            conn.sock.sendall(payload)
        except (OSError, ConnectionError):
            # Reconnect once (gateway pods outlive store restarts).
            self._drop_conn()
            conn = self._conn()
            conn.sock.sendall(payload)
        try:
            replies = [conn.read_reply() for _ in commands]
        except (OSError, ConnectionError):
            self._drop_conn()
            raise
        for r in replies:
            if isinstance(r, RespError):
                raise r
        return replies

    def command(self, *args):
        return self.pipeline(tuple(args))[0]


# ---------------------------------------------------------------------------
# Gateway backends over RESP
# ---------------------------------------------------------------------------


class RedisCounterBackend:
    """ratelimiter.CounterBackend over a RESP server — the HA replacement
    for the in-process stores (two gateway replicas share one window)."""

    def __init__(self, client: RespClient):
        self.client = client

    def get(self, key: str) -> int:
        val = self.client.command("GET", key)
        return int(val) if val is not None else 0

    def incr(self, key: str, amount: int, ttl_s: int) -> int:
        # Pipelined INCRBY + TTL, then EXPIRE only when the key has no
        # expiry yet (reference redis_impl.go:116-168).
        val, ttl = self.client.pipeline(("INCRBY", key, amount), ("TTL", key))
        if ttl is not None and int(ttl) < 0:
            self.client.command("EXPIRE", key, ttl_s)
        return int(val)


def quota_key(namespace: str, quota_name: str, typ: str) -> str:
    # key layout parity: prefix:namespace=..quotaname=..type=..
    # (reference quota/redis_impl.go)
    return f"arks:quota:namespace={namespace}:quotaname={quota_name}:type={typ}"


class RedisQuotaService(QuotaService):
    """gateway.quota.QuotaService over a RESP server (plain non-expiring
    counters; reference quota/redis_impl.go:38-107).  Only the storage
    methods are overridden — ``check`` is inherited so over-limit
    semantics can never diverge from the single-replica path."""

    def __init__(self, client: RespClient):
        self.client = client

    def incr_usage(self, namespace: str, quota_name: str,
                   amounts: dict[str, int]) -> None:
        from arks_tpu.control.resources import VALID_QUOTAS
        cmds = [("INCRBY", quota_key(namespace, quota_name, t), a)
                for t, a in amounts.items() if t in VALID_QUOTAS and a > 0]
        if cmds:
            self.client.pipeline(*cmds)

    def get_usage(self, namespace: str, quota_name: str) -> dict[str, int]:
        from arks_tpu.control.resources import VALID_QUOTAS
        types = list(VALID_QUOTAS)
        vals = self.client.pipeline(
            *(("GET", quota_key(namespace, quota_name, t)) for t in types))
        return {t: int(v) if v is not None else 0
                for t, v in zip(types, vals)}

    def set_usage(self, namespace: str, quota_name: str, typ: str,
                  value: int) -> None:
        self.client.command("SET", quota_key(namespace, quota_name, typ), value)


# ---------------------------------------------------------------------------
# Tiny RESP server (test double + single-binary deployments)
# ---------------------------------------------------------------------------


class _KV:
    _GC_THRESHOLD = 65536

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.data: dict[bytes, bytes] = {}
        self.expiry: dict[bytes, float] = {}
        self._gc_at = self._GC_THRESHOLD

    def _alive(self, key: bytes, now: float) -> bool:
        exp = self.expiry.get(key)
        if exp is not None and exp <= now:
            self.data.pop(key, None)
            self.expiry.pop(key, None)
            return False
        return key in self.data

    def gc(self, now: float) -> None:
        """Amortized sweep of expired keys.  Rate-limit window keys embed
        their window start and are never read again after the window rolls,
        so lazy-on-access expiry alone would grow the store without bound
        (one key per user/model/rule/window, forever)."""
        if len(self.data) <= self._gc_at:
            return
        dead = [k for k, exp in self.expiry.items() if exp <= now]
        for k in dead:
            self.data.pop(k, None)
            self.expiry.pop(k, None)
        # If most keys are live (long windows), wait for the map to double
        # before re-scanning rather than sweeping every write.
        self._gc_at = max(self._GC_THRESHOLD, len(self.data) * 2)


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        kv: _KV = self.server.kv  # type: ignore[attr-defined]
        while True:
            try:
                line = self.rfile.readline()
            except OSError:
                return
            if not line:
                return
            if not line.startswith(b"*"):
                self.wfile.write(b"-ERR protocol error\r\n")
                return
            try:
                nargs = int(line[1:].strip())
                args = []
                for _ in range(nargs):
                    hdr = self.rfile.readline()
                    n = int(hdr[1:].strip())
                    args.append(self.rfile.read(n))
                    self.rfile.read(2)
            except (ValueError, OSError):
                return
            try:
                self.wfile.write(self._dispatch(kv, args))
                self.wfile.flush()
            except OSError:
                return

    def _dispatch(self, kv: _KV, args: list[bytes]) -> bytes:
        cmd = args[0].upper()
        now = time.time()
        with kv.lock:
            if cmd in (b"SET", b"INCRBY"):
                kv.gc(now)
            if cmd == b"PING":
                return b"+PONG\r\n"
            if cmd == b"GET":
                if not kv._alive(args[1], now):
                    return b"$-1\r\n"
                v = kv.data[args[1]]
                return b"$%d\r\n%s\r\n" % (len(v), v)
            if cmd == b"SET":
                kv.data[args[1]] = args[2]
                kv.expiry.pop(args[1], None)
                return b"+OK\r\n"
            if cmd == b"INCRBY":
                cur = int(kv.data[args[1]]) if kv._alive(args[1], now) else 0
                cur += int(args[2])
                kv.data[args[1]] = str(cur).encode()
                return b":%d\r\n" % cur
            if cmd == b"EXPIRE":
                if not kv._alive(args[1], now):
                    return b":0\r\n"
                kv.expiry[args[1]] = now + int(args[2])
                return b":1\r\n"
            if cmd == b"TTL":
                if not kv._alive(args[1], now):
                    return b":-2\r\n"
                exp = kv.expiry.get(args[1])
                return b":-1\r\n" if exp is None else b":%d\r\n" % int(exp - now)
            if cmd == b"DEL":
                n = 0
                for key in args[1:]:
                    if kv._alive(key, now):
                        kv.data.pop(key, None)
                        kv.expiry.pop(key, None)
                        n += 1
                return b":%d\r\n" % n
            if cmd == b"FLUSHALL":
                kv.data.clear()
                kv.expiry.clear()
                return b"+OK\r\n"
        return b"-ERR unknown command '%s'\r\n" % cmd


class RespServer:
    """Threaded RESP server over an in-memory KV with expiry."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socketserver.ThreadingTCPServer((host, port), _Handler,
                                                    bind_and_activate=True)
        self._srv.daemon_threads = True
        self._srv.kv = _KV()  # type: ignore[attr-defined]
        self.host, self.port = self._srv.server_address

    def start(self, background: bool = True) -> None:
        if background:
            threading.Thread(target=self._srv.serve_forever,
                             name="rediskv", daemon=True).start()
        else:
            self._srv.serve_forever()

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


def main() -> None:
    import argparse

    p = argparse.ArgumentParser("arks_tpu.gateway.rediskv")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=6380)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    srv = RespServer(args.host, args.port)
    log.info("rediskv serving on %s:%d", srv.host, srv.port)
    srv.start(background=False)


if __name__ == "__main__":
    main()
