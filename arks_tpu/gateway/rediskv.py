"""Shared counter store over the Redis protocol (RESP2).

Why: the reference gateway keeps rate-limit windows and quota usage in
Redis (/root/reference/pkg/gateway/ratelimiter/redis_impl.go:47-168,
quota/redis_impl.go:38-107, dist/gateway.yaml:199-228) precisely so that a
SECOND gateway replica shares the same counters — in-process stores would
let N replicas each grant the full limit.  This module gives the TPU-native
gateway the same HA story:

- ``RespClient`` — a minimal, dependency-free RESP2 client (the image has
  no redis-py).  Pipelining + the handful of commands the gateway needs.
- ``RedisCounterBackend`` — ratelimiter.CounterBackend over any
  RESP-speaking server (real Redis in production).  Same key layout and
  fixed-window semantics as the in-memory/native backends.
- ``RedisQuotaService`` — gateway.quota.QuotaService over the same server
  (plain non-expiring counters keyed namespace/quotaname/type, reference
  quota/redis_impl.go).
- ``RespServer`` — a tiny in-process RESP server (GET/SET/INCRBY/EXPIRE/
  TTL/DEL/PING/FLUSHALL with expiry).  The test double for the above, and
  a single-binary alternative for small deployments:
  ``python -m arks_tpu.gateway.rediskv --port 6380``.
"""

from __future__ import annotations

import logging
import socket
import socketserver
import threading
import time

from arks_tpu.gateway.quota import QuotaService

log = logging.getLogger("arks_tpu.gateway.rediskv")


# ---------------------------------------------------------------------------
# RESP2 client
# ---------------------------------------------------------------------------


class RespError(RuntimeError):
    pass


def _encode_command(args: tuple) -> bytes:
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        if isinstance(a, bytes):
            b = a
        else:
            b = str(a).encode()
        out.append(b"$%d\r\n%s\r\n" % (len(b), b))
    return b"".join(out)


class _Conn:
    """One RESP connection with its read buffer."""

    def __init__(self, host: str, port: int, timeout_s: float):
        self.sock = socket.create_connection((host, port), timeout=timeout_s)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.buf = b""

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def read_line(self) -> bytes:
        while b"\r\n" not in self.buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self.buf += chunk
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    def read_exact(self, n: int) -> bytes:
        while len(self.buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self.buf += chunk
        data, self.buf = self.buf[:n], self.buf[n:]
        return data

    def read_reply(self):
        """One reply; error replies come back as RespError VALUES so the
        caller always consumes every reply of a pipelined batch — raising
        mid-batch would leave replies buffered and desynchronize the
        stream for every later command."""
        line = self.read_line()
        t, rest = line[:1], line[1:]
        if t == b"+":
            return rest.decode()
        if t == b"-":
            return RespError(rest.decode())
        if t == b":":
            return int(rest)
        if t == b"$":
            n = int(rest)
            if n == -1:
                return None
            data = self.read_exact(n)
            self.read_exact(2)  # trailing \r\n
            return data
        if t == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [self.read_reply() for _ in range(n)]
        raise ConnectionError(f"unexpected reply type {line!r}")


class RespClient:
    """Minimal RESP2 client with one connection PER THREAD — the gateway
    calls from concurrent request-handler threads, and a single locked
    connection would serialize every admission's round-trips head-of-line
    behind the slowest one."""

    def __init__(self, host: str, port: int, timeout_s: float = 5.0):
        self.host, self.port, self.timeout_s = host, port, timeout_s
        self._tls = threading.local()
        self._all: list[_Conn] = []
        self._all_lock = threading.Lock()
        self._conn()  # fail fast on a bad address

    def _conn(self) -> _Conn:
        conn = getattr(self._tls, "conn", None)
        if conn is None:
            conn = _Conn(self.host, self.port, self.timeout_s)
            self._tls.conn = conn
            with self._all_lock:
                self._all.append(conn)
        return conn

    def _drop_conn(self) -> None:
        conn = getattr(self._tls, "conn", None)
        if conn is not None:
            conn.close()
            with self._all_lock:
                if conn in self._all:
                    self._all.remove(conn)
            self._tls.conn = None

    def close(self) -> None:
        with self._all_lock:
            for c in self._all:
                c.close()
            self._all.clear()

    def pipeline(self, *commands: tuple) -> list:
        """Send several commands in one write, read all replies (the
        reference pipelines GET+TTL and INCRBY+EXPIRE the same way).

        Retry policy: a failure during SEND reconnects and resends once
        (the server cannot have executed a partially-delivered batch, and
        pipelined writes are small enough to fit the send buffer whole); a
        failure while READING replies does NOT resend — the server may
        have executed the commands, and re-applying INCRBYs would double-
        count rate windows and permanently inflate quota ledgers.
        """
        replies = self.pipeline_raw(*commands)
        for r in replies:
            if isinstance(r, RespError):
                raise r
        return replies

    def pipeline_raw(self, *commands: tuple) -> list:
        """Like ``pipeline`` but error replies come back as RespError
        VALUES — the cluster client inspects them for redirects."""
        payload = b"".join(_encode_command(c) for c in commands)
        try:
            conn = self._conn()
            conn.sock.sendall(payload)
        except (OSError, ConnectionError):
            # Reconnect once (gateway pods outlive store restarts).
            self._drop_conn()
            conn = self._conn()
            conn.sock.sendall(payload)
        try:
            return [conn.read_reply() for _ in commands]
        except (OSError, ConnectionError) as e:
            self._drop_conn()
            # Mark for the cluster client: the batch MAY have executed
            # (failure while reading replies) — re-executing it on another
            # node could double-count INCRBYs.
            e._resp_read_phase = True  # type: ignore[attr-defined]
            raise

    def command(self, *args):
        return self.pipeline(tuple(args))[0]


# ---------------------------------------------------------------------------
# Cluster + sentinel topologies (reference cmd/gateway/main.go:137-170:
# redis.NewUniversalClient — sentinel when a master name is set, cluster
# when several addresses are given, else single).
# ---------------------------------------------------------------------------


def _crc16(data: bytes) -> int:
    """CRC16-CCITT (XMODEM) — the Redis Cluster key-slot hash."""
    crc = 0
    for byte in data:
        crc ^= byte << 8
        for _ in range(8):
            crc = ((crc << 1) ^ 0x1021) if crc & 0x8000 else (crc << 1)
            crc &= 0xFFFF
    return crc


def key_slot(key) -> int:
    """Cluster slot for a key, honoring {hash tags}."""
    k = key if isinstance(key, bytes) else str(key).encode()
    start = k.find(b"{")
    if start >= 0:
        end = k.find(b"}", start + 1)
        if end > start + 1:
            k = k[start + 1: end]
    return _crc16(k) % 16384


class RespClusterClient:
    """Slot-routing client over several cluster nodes — the counter/quota
    backends' commands are all single-key, so routing is: hash the key,
    send to the slot's node, follow ``-MOVED``/``-ASK`` redirects (and
    remember MOVED re-mappings).  Pipelines are regrouped per node and the
    replies re-assembled in request order.  Same public surface as
    ``RespClient``."""

    def __init__(self, addrs: list[tuple[str, int]], timeout_s: float = 5.0):
        if not addrs:
            raise ValueError("cluster mode needs at least one address")
        self.timeout_s = timeout_s
        self._clients: dict[tuple[str, int], RespClient] = {}
        self._default = tuple(addrs[0])
        self._slots: dict[int, tuple[str, int]] = {}
        # Known nodes = seeds + masters learned from CLUSTER SLOTS + MOVED
        # targets: the candidate set for failover when a node dies.
        self._nodes: set[tuple[str, int]] = {tuple(a) for a in addrs}
        self._lock = threading.Lock()
        # Fail fast needs ONE reachable seed, not all of them — a seed down
        # for maintenance must not block gateway startup when the rest of
        # the cluster can serve every slot.
        last: Exception | None = None
        for a in addrs:
            try:
                self._default = tuple(a)
                self._client(self._default)
                self._bootstrap_slots()
                return
            except (OSError, ConnectionError) as e:
                last = e
        raise ConnectionError(f"no cluster seed reachable: {last}")

    def _bootstrap_slots(self) -> None:
        """Populate the slot map up front via ``CLUSTER SLOTS`` so commands
        go to the right node on the FIRST try, and record every master as a
        failover candidate.  Best-effort: a standalone Redis answers -ERR
        (cluster support disabled) and the client falls back to learning
        mappings from MOVED redirects."""
        with self._lock:
            candidates = [self._default] + sorted(self._nodes
                                                  - {self._default})
        for addr in candidates:
            try:
                reply = self._client(addr).pipeline_raw(
                    ("CLUSTER", "SLOTS"))[0]
            except (OSError, ConnectionError):
                continue
            if isinstance(reply, RespError) or not isinstance(reply, list):
                return  # not a cluster — MOVED-learning mode
            mapping: dict[int, tuple[str, int]] = {}
            nodes: set[tuple[str, int]] = set()
            for entry in reply:
                try:
                    start, end, master = int(entry[0]), int(entry[1]), entry[2]
                    host = master[0].decode() \
                        if isinstance(master[0], (bytes, bytearray)) \
                        else str(master[0])
                    node = (host, int(master[1]))
                except (TypeError, ValueError, IndexError):
                    continue
                nodes.add(node)
                for s in range(start, end + 1):
                    mapping[s] = node
            with self._lock:
                self._slots.update(mapping)
                self._nodes |= nodes
            return

    def _failover(self, dead: tuple[str, int]) -> bool:
        """``dead`` stopped answering: drop its client, purge its slot
        entries, re-point the default at a reachable survivor, and re-learn
        the topology (the cluster may have promoted a replica).  Returns
        True if another node is available to retry against."""
        with self._lock:
            c = self._clients.pop(dead, None)
            self._nodes.discard(dead)
            self._slots = {s: a for s, a in self._slots.items() if a != dead}
            survivors = sorted(self._nodes)
            was_default = self._default == dead
        if c is not None:
            c.close()
        if not survivors:
            return False
        if was_default:
            for cand in survivors:
                try:
                    self._client(cand)
                except (OSError, ConnectionError):
                    continue
                self._default = cand
                break
            else:
                return False
        self._bootstrap_slots()
        return True

    def _client(self, addr: tuple[str, int]) -> RespClient:
        with self._lock:
            c = self._clients.get(addr)
        if c is not None:
            return c
        # Connect OUTSIDE the lock: a slow node's connect timeout must not
        # freeze every other thread's slot lookups.  Double-checked insert
        # tolerates a racing duplicate (the loser is closed).
        c = RespClient(addr[0], addr[1], self.timeout_s)
        with self._lock:
            cur = self._clients.get(addr)
            if cur is not None:
                close_me, c = c, cur
            else:
                self._clients[addr] = c
                close_me = None
        if close_me is not None:
            close_me.close()
        return c

    def close(self) -> None:
        with self._lock:
            for c in self._clients.values():
                c.close()
            self._clients.clear()

    @staticmethod
    def _cmd_key(cmd: tuple):
        # Every command the gateway issues is single-key with the key at
        # position 1 (GET/SET/INCRBY/EXPIRE/TTL/DEL); keyless commands
        # (PING/FLUSHALL) route to the default node.
        return cmd[1] if len(cmd) > 1 else None

    def _addr_for(self, cmd: tuple) -> tuple[str, int]:
        key = self._cmd_key(cmd)
        if key is None:
            return self._default
        with self._lock:
            return self._slots.get(key_slot(key), self._default)

    @staticmethod
    def _parse_redirect(err: RespError) -> tuple[str, int, int] | None:
        parts = str(err).split()
        if len(parts) == 3 and parts[0] in ("MOVED", "ASK"):
            host, _, port = parts[2].rpartition(":")
            return int(parts[1]), (host, int(port)), parts[0]
        return None

    def _follow_redirect(self, cmd: tuple, err: RespError):
        red = self._parse_redirect(err)
        if red is None:
            raise err
        slot, new_addr, kind = red
        if kind == "MOVED":
            with self._lock:
                self._slots[int(slot)] = new_addr
                self._nodes.add(new_addr)  # redirect target = live master
        try:
            target = self._client(new_addr)
            if kind == "ASK":
                reply = target.pipeline_raw(("ASKING",), cmd)[1]
            else:
                reply = target.pipeline_raw(cmd)[0]
        except (OSError, ConnectionError) as e:
            # Tag the failing hop so pipeline() can run its failover path
            # (the redirect pointed at a node that just died).
            e._arks_addr = new_addr  # type: ignore[attr-defined]
            raise
        if isinstance(reply, RespError):
            raise reply
        return reply

    def pipeline(self, *commands: tuple) -> list:
        # Group commands by their slot's node so same-node batches (the
        # hot-path INCRBY+TTL pair) stay ONE round trip; redirected replies
        # are retried individually and the results restored to input order.
        #
        # Node failure: connect/send failures CANNOT have executed, so the
        # affected commands are re-routed through the relearned topology
        # (bounded retries).  A failure while READING replies may have
        # executed — the topology is still relearned, but the error
        # propagates (re-running INCRBYs would double-count rate windows;
        # same policy as RespClient.pipeline).
        out: list = [None] * len(commands)
        todo = list(range(len(commands)))
        last: Exception | None = None
        for _ in range(3):
            by_addr: dict[tuple[str, int], list[int]] = {}
            for i in todo:
                by_addr.setdefault(self._addr_for(commands[i]), []).append(i)
            failed: list[int] = []
            for addr, idxs in by_addr.items():
                try:
                    replies = self._client(addr).pipeline_raw(
                        *(commands[i] for i in idxs))
                except (OSError, ConnectionError) as e:
                    alive = self._failover(addr)
                    if getattr(e, "_resp_read_phase", False) or not alive:
                        raise
                    last = e
                    failed.extend(idxs)
                    continue
                for i, reply in zip(idxs, replies):
                    if isinstance(reply, RespError):
                        try:
                            reply = self._follow_redirect(commands[i], reply)
                        except (OSError, ConnectionError) as e:
                            # The REDIRECT TARGET died mid-hop: same
                            # failover rules as a direct node failure (a
                            # connect/send failure never executed, so the
                            # command is safe to re-route).
                            hop = getattr(e, "_arks_addr", None)
                            alive = hop is not None and self._failover(hop)
                            if getattr(e, "_resp_read_phase", False) \
                                    or not alive:
                                raise
                            last = e
                            failed.append(i)
                            continue
                    out[i] = reply
            if not failed:
                return out
            todo = failed
        raise last if last is not None else ConnectionError(
            "cluster pipeline retries exhausted")

    def command(self, *args):
        return self.pipeline(tuple(args))[0]


class SentinelRespClient(RespClient):
    """RESP client that resolves its master through Redis Sentinel
    (``SENTINEL GET-MASTER-ADDR-BY-NAME``) and RE-resolves on connection
    loss or on a ``-READONLY`` reply (failover promoted a replica)."""

    def __init__(self, sentinel_addrs: list[tuple[str, int]],
                 master_name: str, timeout_s: float = 5.0):
        self.sentinels = [tuple(a) for a in sentinel_addrs]
        self.master_name = master_name
        self._resolve()
        super().__init__(self.host, self.port, timeout_s)

    def _resolve(self) -> None:
        last: Exception | None = None
        for host, port in self.sentinels:
            try:
                c = _Conn(host, port, 5.0)
                try:
                    c.sock.sendall(_encode_command(
                        ("SENTINEL", "GET-MASTER-ADDR-BY-NAME",
                         self.master_name)))
                    reply = c.read_reply()
                finally:
                    c.close()
                if isinstance(reply, list) and len(reply) == 2:
                    self.host = reply[0].decode()
                    self.port = int(reply[1])
                    return
                last = RespError(f"sentinel {host}:{port} returned {reply!r}")
            except (OSError, ConnectionError) as e:
                last = e
        raise ConnectionError(
            f"no sentinel could resolve master {self.master_name!r}: {last}")

    def _drop_conn(self) -> None:
        super()._drop_conn()
        # The master may have moved: ask the sentinels again before the
        # next connection attempt.
        try:
            self._resolve()
        except ConnectionError:
            log.warning("sentinel re-resolution failed; keeping %s:%s",
                        self.host, self.port, exc_info=True)

    def pipeline(self, *commands: tuple) -> list:
        try:
            return super().pipeline(*commands)
        except RespError as e:
            if not str(e).startswith("READONLY"):
                raise
            # Failover flipped this node to replica: re-resolve and retry
            # once.  (READONLY on a read-modify batch means the batch did
            # not execute — safe to resend.)
            self._drop_conn()
            return super().pipeline(*commands)


def make_resp_client(addrs: str, sentinel_master: str | None = None,
                     timeout_s: float = 5.0):
    """Factory matching the reference's UniversalClient selection
    (cmd/gateway/main.go:137-170): comma-separated ``addrs`` + a sentinel
    master name -> sentinel; several addrs -> cluster; one -> single."""
    parsed = []
    for a in addrs.split(","):
        a = a.strip()
        if not a:
            continue
        host, sep, port = a.rpartition(":")
        if sep and port.isdigit():
            parsed.append((host, int(port)))
        else:
            parsed.append((a, 6379))  # bare hostname defaults like redis-cli
    if sentinel_master:
        return SentinelRespClient(parsed, sentinel_master, timeout_s)
    if len(parsed) > 1:
        return RespClusterClient(parsed, timeout_s)
    return RespClient(parsed[0][0], parsed[0][1], timeout_s)


# ---------------------------------------------------------------------------
# Gateway backends over RESP
# ---------------------------------------------------------------------------


class RedisCounterBackend:
    """ratelimiter.CounterBackend over a RESP server — the HA replacement
    for the in-process stores (two gateway replicas share one window)."""

    def __init__(self, client: RespClient):
        self.client = client

    def get(self, key: str) -> int:
        val = self.client.command("GET", key)
        return int(val) if val is not None else 0

    def incr(self, key: str, amount: int, ttl_s: int) -> int:
        # Pipelined INCRBY + TTL, then EXPIRE only when the key has no
        # expiry yet (reference redis_impl.go:116-168).
        val, ttl = self.client.pipeline(("INCRBY", key, amount), ("TTL", key))
        if ttl is not None and int(ttl) < 0:
            self.client.command("EXPIRE", key, ttl_s)
        return int(val)


def quota_key(namespace: str, quota_name: str, typ: str) -> str:
    # key layout parity: prefix:namespace=..quotaname=..type=..
    # (reference quota/redis_impl.go)
    return f"arks:quota:namespace={namespace}:quotaname={quota_name}:type={typ}"


class RedisQuotaService(QuotaService):
    """gateway.quota.QuotaService over a RESP server (plain non-expiring
    counters; reference quota/redis_impl.go:38-107).  Only the storage
    methods are overridden — ``check`` is inherited so over-limit
    semantics can never diverge from the single-replica path."""

    def __init__(self, client: RespClient):
        self.client = client

    def incr_usage(self, namespace: str, quota_name: str,
                   amounts: dict[str, int]) -> None:
        from arks_tpu.control.resources import VALID_QUOTAS
        cmds = [("INCRBY", quota_key(namespace, quota_name, t), a)
                for t, a in amounts.items() if t in VALID_QUOTAS and a > 0]
        if cmds:
            self.client.pipeline(*cmds)

    def get_usage(self, namespace: str, quota_name: str) -> dict[str, int]:
        from arks_tpu.control.resources import VALID_QUOTAS
        types = list(VALID_QUOTAS)
        vals = self.client.pipeline(
            *(("GET", quota_key(namespace, quota_name, t)) for t in types))
        return {t: int(v) if v is not None else 0
                for t, v in zip(types, vals)}

    def set_usage(self, namespace: str, quota_name: str, typ: str,
                  value: int) -> None:
        self.client.command("SET", quota_key(namespace, quota_name, typ), value)


# ---------------------------------------------------------------------------
# Tiny RESP server (test double + single-binary deployments)
# ---------------------------------------------------------------------------


class _KV:
    _GC_THRESHOLD = 65536

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.data: dict[bytes, bytes] = {}
        self.expiry: dict[bytes, float] = {}
        self._gc_at = self._GC_THRESHOLD

    def _alive(self, key: bytes, now: float) -> bool:
        exp = self.expiry.get(key)
        if exp is not None and exp <= now:
            self.data.pop(key, None)
            self.expiry.pop(key, None)
            return False
        return key in self.data

    def gc(self, now: float) -> None:
        """Amortized sweep of expired keys.  Rate-limit window keys embed
        their window start and are never read again after the window rolls,
        so lazy-on-access expiry alone would grow the store without bound
        (one key per user/model/rule/window, forever)."""
        if len(self.data) <= self._gc_at:
            return
        dead = [k for k, exp in self.expiry.items() if exp <= now]
        for k in dead:
            self.data.pop(k, None)
            self.expiry.pop(k, None)
        # If most keys are live (long windows), wait for the map to double
        # before re-scanning rather than sweeping every write.
        self._gc_at = max(self._GC_THRESHOLD, len(self.data) * 2)


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        kv: _KV = self.server.kv  # type: ignore[attr-defined]
        while True:
            try:
                line = self.rfile.readline()
            except OSError:
                return
            if not line:
                return
            if not line.startswith(b"*"):
                self.wfile.write(b"-ERR protocol error\r\n")
                return
            try:
                nargs = int(line[1:].strip())
                args = []
                for _ in range(nargs):
                    hdr = self.rfile.readline()
                    n = int(hdr[1:].strip())
                    args.append(self.rfile.read(n))
                    self.rfile.read(2)
            except (ValueError, OSError):
                return
            try:
                self.wfile.write(self._dispatch(kv, args))
                self.wfile.flush()
            except OSError:
                return

    def _dispatch(self, kv: _KV, args: list[bytes]) -> bytes:
        cmd = args[0].upper()
        now = time.time()
        srv = self.server
        # Topology test doubles: sentinel resolution + cluster redirects.
        if cmd == b"SENTINEL" and len(args) >= 3 \
                and args[1].upper() == b"GET-MASTER-ADDR-BY-NAME":
            master = getattr(srv, "sentinel_masters", {}).get(
                args[2].decode())
            if master is None:
                return b"*-1\r\n"
            h, p = str(master[0]).encode(), str(master[1]).encode()
            return (b"*2\r\n$%d\r\n%s\r\n$%d\r\n%s\r\n"
                    % (len(h), h, len(p), p))
        if cmd == b"ASKING":
            return b"+OK\r\n"
        if cmd == b"CLUSTER" and len(args) >= 2 \
                and args[1].upper() == b"SLOTS":
            ranges = getattr(srv, "cluster_slots", None)
            if not ranges:
                return b"-ERR This instance has cluster support disabled\r\n"
            out = b"*%d\r\n" % len(ranges)
            for start, end, host, port in ranges:
                h = str(host).encode()
                out += (b"*3\r\n:%d\r\n:%d\r\n*2\r\n$%d\r\n%s\r\n:%d\r\n"
                        % (int(start), int(end), len(h), h, int(port)))
            return out
        moved = getattr(srv, "moved_slots", None)
        if moved and len(args) > 1:
            slot = key_slot(args[1])
            target = moved.get(slot)
            if target is not None:
                return (b"-MOVED %d %s\r\n"
                        % (slot, str(target).encode()))
        with kv.lock:
            if cmd in (b"SET", b"INCRBY"):
                kv.gc(now)
            if cmd == b"PING":
                return b"+PONG\r\n"
            if cmd == b"GET":
                if not kv._alive(args[1], now):
                    return b"$-1\r\n"
                v = kv.data[args[1]]
                return b"$%d\r\n%s\r\n" % (len(v), v)
            if cmd == b"SET":
                kv.data[args[1]] = args[2]
                kv.expiry.pop(args[1], None)
                return b"+OK\r\n"
            if cmd == b"INCRBY":
                cur = int(kv.data[args[1]]) if kv._alive(args[1], now) else 0
                cur += int(args[2])
                kv.data[args[1]] = str(cur).encode()
                return b":%d\r\n" % cur
            if cmd == b"EXPIRE":
                if not kv._alive(args[1], now):
                    return b":0\r\n"
                kv.expiry[args[1]] = now + int(args[2])
                return b":1\r\n"
            if cmd == b"TTL":
                if not kv._alive(args[1], now):
                    return b":-2\r\n"
                exp = kv.expiry.get(args[1])
                return b":-1\r\n" if exp is None else b":%d\r\n" % int(exp - now)
            if cmd == b"DEL":
                n = 0
                for key in args[1:]:
                    if kv._alive(key, now):
                        kv.data.pop(key, None)
                        kv.expiry.pop(key, None)
                        n += 1
                return b":%d\r\n" % n
            if cmd == b"FLUSHALL":
                kv.data.clear()
                kv.expiry.clear()
                return b"+OK\r\n"
        return b"-ERR unknown command '%s'\r\n" % cmd


class RespServer:
    """Threaded RESP server over an in-memory KV with expiry."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socketserver.ThreadingTCPServer((host, port), _Handler,
                                                    bind_and_activate=True)
        self._srv.daemon_threads = True
        self._srv.kv = _KV()  # type: ignore[attr-defined]
        # Topology test doubles (see _Handler._dispatch):
        # sentinel_masters: {master_name: (host, port)};
        # moved_slots: {slot: "host:port"} -> -MOVED redirects;
        # cluster_slots: [(start, end, host, port)] -> CLUSTER SLOTS reply.
        self._srv.sentinel_masters = {}  # type: ignore[attr-defined]
        self._srv.moved_slots = {}  # type: ignore[attr-defined]
        self._srv.cluster_slots = []  # type: ignore[attr-defined]
        self.host, self.port = self._srv.server_address

    @property
    def sentinel_masters(self) -> dict:
        return self._srv.sentinel_masters  # type: ignore[attr-defined]

    @property
    def moved_slots(self) -> dict:
        return self._srv.moved_slots  # type: ignore[attr-defined]

    @property
    def cluster_slots(self) -> list:
        return self._srv.cluster_slots  # type: ignore[attr-defined]

    def start(self, background: bool = True) -> None:
        if background:
            threading.Thread(target=self._srv.serve_forever,
                             name="rediskv", daemon=True).start()
        else:
            self._srv.serve_forever()

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


def main() -> None:
    import argparse

    p = argparse.ArgumentParser("arks_tpu.gateway.rediskv")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=6380)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    srv = RespServer(args.host, args.port)
    log.info("rediskv serving on %s:%d", srv.host, srv.port)
    srv.start(background=False)


if __name__ == "__main__":
    main()
