"""ctypes bindings for the native gateway data-plane library (native/arksgw.cpp).

The reference's gateway hot loops run in compiled Go; ours run here when the
shared library is present (built on demand with g++ — baked into the image)
and fall back to pure Python otherwise.  ``ARKS_NATIVE=0`` forces the
fallback; ``ARKS_NATIVE_LIB`` points at a prebuilt .so.

Two surfaces, mirroring pkg/gateway's hot paths:
- ``NativeCounterBackend`` — fixed-window rate-limit counters
  (ratelimiter/redis_impl.go semantics, in-process).  Drop-in for
  arks_tpu.gateway.ratelimiter.CounterBackend.
- ``SseUsageScanner`` — incremental SSE frame scanner extracting the final
  usage object (handle_response.go:113-133), robust to arbitrary chunk
  fragmentation including frames and keys split across feeds.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

from arks_tpu.utils import knobs

log = logging.getLogger("arks_tpu.gateway.native")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _source_dir() -> str:
    # repo layout: <root>/native/arksgw.cpp with this file at
    # <root>/arks_tpu/gateway/native.py
    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "native")


def _build() -> str | None:
    src_dir = _source_dir()
    src = os.path.join(src_dir, "arksgw.cpp")
    if not os.path.isfile(src):
        return None
    out = os.path.join(src_dir, "build", "libarksgw.so")
    if os.path.isfile(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    try:
        os.makedirs(os.path.dirname(out), exist_ok=True)
        subprocess.run(
            ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-o", out, src],
            check=True, capture_output=True, timeout=120)
        return out
    except (OSError, subprocess.SubprocessError) as e:
        log.warning("native gateway lib build failed (%s); using Python paths", e)
        return None


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not knobs.get_bool("ARKS_NATIVE"):
            return None
        path = knobs.get_str("ARKS_NATIVE_LIB") or _build()
        if not path:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError as e:
            log.warning("failed to load %s: %s", path, e)
            return None
        lib.arks_store_new.restype = ctypes.c_void_p
        lib.arks_store_free.argtypes = [ctypes.c_void_p]
        lib.arks_store_get.restype = ctypes.c_longlong
        lib.arks_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_double]
        lib.arks_store_incr.restype = ctypes.c_longlong
        lib.arks_store_incr.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_longlong, ctypes.c_double,
                                        ctypes.c_double]
        lib.arks_store_size.restype = ctypes.c_longlong
        lib.arks_store_size.argtypes = [ctypes.c_void_p]
        lib.arks_sse_new.restype = ctypes.c_void_p
        lib.arks_sse_free.argtypes = [ctypes.c_void_p]
        lib.arks_sse_feed.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_size_t]
        lib.arks_sse_result.restype = ctypes.c_int
        lib.arks_sse_result.argtypes = [ctypes.c_void_p] + \
            [ctypes.POINTER(ctypes.c_longlong)] * 3
        lib.arks_sse_done.restype = ctypes.c_int
        lib.arks_sse_done.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


class NativeCounterBackend:
    """CounterBackend over the C++ store (see ratelimiter.CounterBackend)."""

    def __init__(self) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError("native gateway library unavailable")
        self._lib = lib
        self._h = lib.arks_store_new()

    def get(self, key: str) -> int:
        import time
        return self._lib.arks_store_get(self._h, key.encode(), time.time())

    def incr(self, key: str, amount: int, ttl_s: int) -> int:
        import time
        return self._lib.arks_store_incr(self._h, key.encode(), amount,
                                         float(ttl_s), time.time())

    def __len__(self) -> int:
        return self._lib.arks_store_size(self._h)

    def __del__(self) -> None:
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.arks_store_free(h)


class SseUsageScanner:
    """Incremental usage extraction from an SSE byte stream."""

    def __init__(self) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError("native gateway library unavailable")
        self._lib = lib
        self._h = lib.arks_sse_new()

    def feed(self, chunk: bytes) -> None:
        self._lib.arks_sse_feed(self._h, chunk, len(chunk))

    def usage(self) -> dict | None:
        p = ctypes.c_longlong()
        c = ctypes.c_longlong()
        t = ctypes.c_longlong()
        if not self._lib.arks_sse_result(self._h, ctypes.byref(p),
                                         ctypes.byref(c), ctypes.byref(t)):
            return None
        out = {}
        if p.value >= 0:
            out["prompt_tokens"] = p.value
        if c.value >= 0:
            out["completion_tokens"] = c.value
        if t.value >= 0:
            out["total_tokens"] = t.value
        return out or None

    @property
    def done(self) -> bool:
        return bool(self._lib.arks_sse_done(self._h))

    def __del__(self) -> None:
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.arks_sse_free(h)
