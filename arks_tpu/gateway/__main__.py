"""Standalone gateway: ``python -m arks_tpu.gateway [flags]``.

The analogue of the reference's gateway binary (cmd/gateway/main.go) in its
``file`` config-provider mode: QoS resources (Token/Quota/Endpoint) come
from YAML manifests instead of a live operator store.  When embedded next
to the operator (python -m arks_tpu.control), the gateway shares the
operator's store instead and this entrypoint is not used.
"""

from __future__ import annotations

import argparse
import logging
import signal
import time

log = logging.getLogger("arks_tpu.gateway.main")


def main() -> None:
    p = argparse.ArgumentParser("arks_tpu.gateway")
    p.add_argument("--manifests", action="append", default=[],
                   help="YAML files with Token/Quota/Endpoint resources "
                        "(the reference's file provider)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8081)
    p.add_argument("--backend", choices=("auto", "memory", "native", "redis"),
                   default="auto",
                   help="counter/quota store: auto = native C++ if built "
                        "else memory (single replica); redis = shared store "
                        "for HA gateways (reference redis_impl.go parity)")
    p.add_argument("--redis-addr", default="127.0.0.1:6379",
                   help="RESP server address(es) for --backend redis — "
                        "comma-separated list selects cluster mode; with "
                        "--redis-sentinel-master the list is sentinel "
                        "addresses (reference cmd/gateway/main.go:137-170)")
    p.add_argument("--redis-sentinel-master", default=None,
                   help="Redis Sentinel master name (enables sentinel mode)")
    p.add_argument("--max-body-bytes", type=int, default=4 * 1024 * 1024,
                   help="request-body cap -> 413 (reference "
                        "ClientTrafficPolicy 4MiB client buffer)")
    p.add_argument("--process-timeout", type=float, default=5.0,
                   help="per-stage processing deadline in seconds "
                        "(reference ext_proc messageTimeout)")
    args = p.parse_args()

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    from arks_tpu.control.__main__ import apply_manifests
    from arks_tpu.control.store import Store
    from arks_tpu.gateway.server import Gateway

    store = Store()
    for path in args.manifests:
        apply_manifests(store, path)

    rate_limiter = quota = None
    if args.backend == "redis":
        from arks_tpu.gateway.ratelimiter import RateLimiter
        from arks_tpu.gateway.rediskv import (
            RedisCounterBackend, RedisQuotaService, make_resp_client)
        client = make_resp_client(args.redis_addr,
                                  args.redis_sentinel_master)
        rate_limiter = RateLimiter(RedisCounterBackend(client))
        quota = RedisQuotaService(client)
    elif args.backend == "memory":
        from arks_tpu.gateway.ratelimiter import (
            MemoryCounterBackend, RateLimiter)
        rate_limiter = RateLimiter(MemoryCounterBackend())
    elif args.backend == "native":
        from arks_tpu.gateway import native
        from arks_tpu.gateway.ratelimiter import RateLimiter
        rate_limiter = RateLimiter(native.NativeCounterBackend())

    gw = Gateway(store, host=args.host, port=args.port,
                 rate_limiter=rate_limiter, quota=quota,
                 max_body_bytes=args.max_body_bytes,
                 process_timeout_s=args.process_timeout)
    gw.start(background=True)
    log.info("gateway on %s:%d (/v1/* + /metrics, backend=%s)",
             args.host, gw.port, args.backend)

    stop: list[int] = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.5)
    finally:
        gw.stop()


if __name__ == "__main__":
    main()
