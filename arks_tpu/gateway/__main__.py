"""Standalone gateway: ``python -m arks_tpu.gateway [flags]``.

The analogue of the reference's gateway binary (cmd/gateway/main.go) in its
``file`` config-provider mode: QoS resources (Token/Quota/Endpoint) come
from YAML manifests instead of a live operator store.  When embedded next
to the operator (python -m arks_tpu.control), the gateway shares the
operator's store instead and this entrypoint is not used.
"""

from __future__ import annotations

import argparse
import logging
import signal
import time

log = logging.getLogger("arks_tpu.gateway.main")


def main() -> None:
    p = argparse.ArgumentParser("arks_tpu.gateway")
    p.add_argument("--manifests", action="append", default=[],
                   help="YAML files with Token/Quota/Endpoint resources "
                        "(the reference's file provider)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8081)
    args = p.parse_args()

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    from arks_tpu.control.__main__ import apply_manifests
    from arks_tpu.control.store import Store
    from arks_tpu.gateway.server import Gateway

    store = Store()
    for path in args.manifests:
        apply_manifests(store, path)
    gw = Gateway(store, host=args.host, port=args.port)
    gw.start(background=True)
    log.info("gateway on %s:%d (/v1/* + /metrics)", args.host, gw.port)

    stop: list[int] = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.5)
    finally:
        gw.stop()


if __name__ == "__main__":
    main()
