"""Cumulative token-usage quota service with CR-status persistence.

Mirrors the reference quota service (/root/reference/pkg/gateway/quota/ —
plain non-expiring counters keyed namespace/quotaname/type) plus the
qosconfig sync loop (qosconfig/arks_impl.go:217-300): every ``sync_s`` the
gateway writes live usage into Quota.status.quotaStatus, and re-seeds its
counters from the CR when its own store is behind (restart recovery).
"""

from __future__ import annotations

import logging
import threading
import time

from arks_tpu.control.resources import Quota, VALID_QUOTAS, now_iso
from arks_tpu.control.store import NotFound, Store

log = logging.getLogger("arks_tpu.gateway.quota")


class QuotaService:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._usage: dict[tuple[str, str, str], int] = {}  # (ns, quota, type)

    def incr_usage(self, namespace: str, quota_name: str,
                   amounts: dict[str, int]) -> None:
        with self._lock:
            for typ, amount in amounts.items():
                if typ in VALID_QUOTAS and amount > 0:
                    key = (namespace, quota_name, typ)
                    self._usage[key] = self._usage.get(key, 0) + amount

    def get_usage(self, namespace: str, quota_name: str) -> dict[str, int]:
        with self._lock:
            return {typ: self._usage.get((namespace, quota_name, typ), 0)
                    for typ in VALID_QUOTAS}

    def set_usage(self, namespace: str, quota_name: str, typ: str, value: int) -> None:
        with self._lock:
            self._usage[(namespace, quota_name, typ)] = value

    def check(self, namespace: str, quota_name: str,
              limits: dict[str, int]) -> tuple[bool, str]:
        """True = over limit; returns (over, which_type)."""
        usage = self.get_usage(namespace, quota_name)
        for typ, limit in limits.items():
            if limit > 0 and usage.get(typ, 0) >= limit:
                return True, typ
        return False, ""


class QuotaStatusSyncer:
    """The 10s Redis<->CR reconciliation loop (arks_impl.go:217-300)."""

    def __init__(self, store: Store, service: QuotaService, sync_s: float = 2.0):
        self.store = store
        self.service = service
        self.sync_s = sync_s
        self._running = False
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._loop, name="quota-sync",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread:
            self._thread.join(timeout=5)

    def sync_once(self) -> None:
        for q in self.store.list(Quota):
            live = self.service.get_usage(q.namespace, q.name)
            persisted = {s["type"]: s.get("used", 0)
                         for s in q.status.get("quotaStatus", [])}
            changed = False
            for typ in VALID_QUOTAS:
                if live[typ] < persisted.get(typ, 0):
                    # Gateway restarted: re-seed from the CR (the durable copy).
                    self.service.set_usage(q.namespace, q.name, typ,
                                           persisted[typ])
                    live[typ] = persisted[typ]
                if live[typ] != persisted.get(typ, 0):
                    changed = True
            if changed:
                q.status["quotaStatus"] = [
                    {"type": t, "used": live[t], "lastUpdateTime": now_iso()}
                    for t in VALID_QUOTAS]
                try:
                    self.store.update_status(q)
                except NotFound:
                    pass

    def _loop(self) -> None:
        while self._running:
            try:
                self.sync_once()
            except Exception:
                log.exception("quota status sync failed")
            time.sleep(self.sync_s)
