"""QoS config provider: token -> per-endpoint limits, from the store.

The reference's ConfigProvider (/root/reference/pkg/gateway/qosconfig/) runs
its own controller-runtime cache over ArksToken/ArksQuota/ArksEndpoint with a
``spec.token`` index (arks_impl.go:59-73).  Here the store IS the cache; the
token index is maintained from a Token watch.
"""

from __future__ import annotations

import dataclasses
import queue
import threading

from arks_tpu.control.resources import Endpoint, Quota, Token
from arks_tpu.control.store import Store


@dataclasses.dataclass
class TokenQos:
    namespace: str
    username: str          # token resource name (identifier labels parity)
    endpoint: str
    rate_limits: dict[str, int]
    quota_name: str | None


class QosProvider:
    def __init__(self, store: Store):
        self.store = store
        self._lock = threading.Lock()
        self._by_token: dict[str, Token] = {}
        self._watch_thread = threading.Thread(target=self._pump, daemon=True,
                                              name="qos-token-index")
        self._running = True
        self._queue = store.watch(Token)
        self._watch_thread.start()

    def stop(self) -> None:
        self._running = False

    def _pump(self) -> None:
        while self._running:
            try:
                event, tok = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            with self._lock:
                secret = tok.spec.get("token", "")
                if event == "DELETED":
                    self._by_token.pop(secret, None)
                else:
                    # Re-index: drop stale secrets pointing at this resource.
                    for k, v in list(self._by_token.items()):
                        if v.key == tok.key and k != secret:
                            del self._by_token[k]
                    if secret:
                        self._by_token[secret] = tok

    # ------------------------------------------------------------------

    def get_qos_by_token(self, secret: str, model: str) -> TokenQos | None:
        """Resolve (token, model) -> QoS (arks_impl.go:303-338)."""
        with self._lock:
            tok = self._by_token.get(secret)
        if tok is None:
            return None
        for qos in tok.spec.get("qos", []):
            ep_ref = qos.get("endpoint", {})
            if ep_ref.get("name") == model:
                return TokenQos(
                    namespace=tok.namespace,
                    username=tok.name,
                    endpoint=model,
                    rate_limits={rl["type"]: rl["value"]
                                 for rl in qos.get("rateLimits", [])},
                    quota_name=(qos.get("quota") or {}).get("name"),
                )
        return None

    def token_known(self, secret: str) -> bool:
        with self._lock:
            return secret in self._by_token

    def get_model_list(self, namespace: str) -> list[str]:
        """All endpoints in a namespace (arks_impl.go:364-376)."""
        return [e.name for e in self.store.list(Endpoint, namespace=namespace)]

    def get_models_by_token(self, secret: str) -> list[str]:
        """Token-visible endpoint names for /v1/models (arks_impl.go:378-397)."""
        with self._lock:
            tok = self._by_token.get(secret)
        if tok is None:
            return []
        eps = set(self.get_model_list(tok.namespace))
        return [q["endpoint"]["name"] for q in tok.spec.get("qos", [])
                if q.get("endpoint", {}).get("name") in eps]

    def get_quota_limits(self, namespace: str, quota_name: str) -> dict[str, int]:
        q = self.store.try_get(Quota, quota_name, namespace)
        if q is None:
            return {}
        return {item["type"]: item["value"] for item in q.spec.get("quotas", [])}

    def get_endpoint(self, namespace: str, name: str) -> Endpoint | None:
        return self.store.try_get(Endpoint, name, namespace)
