"""Fixed-window rate limiting (rpm/rpd/tpm/tpd).

Semantics mirror the reference's Redis limiter (/root/reference/pkg/gateway/
ratelimiter): windows are wall-clock-aligned (``now.Truncate(period)``,
cache_key.go:42-80), admission pre-checks without incrementing
(``CheckLimit`` = over iff current + requested > limit, redis_impl.go:47-114),
and usage lands post-hoc (``DoLimit`` = INCRBY, :116-168).  Request-type
rules (rpm/rpd) increment by 1 at admission; token-type rules (tpm/tpd)
increment by actual usage at completion.

Backends are pluggable: the native C++ counter store (native/arksgw.cpp via
arks_tpu.gateway.native — the compiled-data-plane counterpart of the
reference's Go gateway) when buildable, a pure-Python in-memory store
otherwise; a Redis backend can implement the same surface for HA gateways.
"""

from __future__ import annotations

import threading
import time
from typing import Protocol

from arks_tpu.control.resources import RL_RPD, RL_RPM, RL_TPD, RL_TPM

MINUTE = 60
DAY = 24 * 3600

# rule -> (window seconds, is_token_rule)  (reference rate_limiter.go:31-68)
RULES: dict[str, tuple[int, bool]] = {
    RL_RPM: (MINUTE, False),
    RL_RPD: (DAY, False),
    RL_TPM: (MINUTE, True),
    RL_TPD: (DAY, True),
}

REQUEST_RULES = [r for r, (_, tok) in RULES.items() if not tok]
TOKEN_RULES = [r for r, (_, tok) in RULES.items() if tok]


class LimitResult:
    def __init__(self, rule: str, limit: int, current: int, over: bool):
        self.rule, self.limit, self.current, self.over = rule, limit, current, over


class CounterBackend(Protocol):
    def get(self, key: str) -> int: ...
    def incr(self, key: str, amount: int, ttl_s: int) -> int: ...


class MemoryCounterBackend:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._data: dict[str, tuple[int, float]] = {}  # key -> (value, expiry)

    def _gc(self, now: float) -> None:
        if len(self._data) > 4096:
            self._data = {k: v for k, v in self._data.items() if v[1] > now}

    def get(self, key: str) -> int:
        now = time.time()
        with self._lock:
            val = self._data.get(key)
            return val[0] if val and val[1] > now else 0

    def incr(self, key: str, amount: int, ttl_s: int) -> int:
        now = time.time()
        with self._lock:
            self._gc(now)
            val = self._data.get(key)
            cur = val[0] if val and val[1] > now else 0
            expiry = val[1] if val and val[1] > now else now + ttl_s
            cur += amount
            self._data[key] = (cur, expiry)
            return cur


def window_key(namespace: str, user: str, model: str, rule: str,
               now: float | None = None) -> str:
    period = RULES[rule][0]
    start = int((now if now is not None else time.time()) // period) * period
    # key layout parity: prefix:ns=..user=..model=..<rule>:<windowStart>
    return f"arks:ns={namespace}:user={user}:model={model}:{rule}:{start}"


def default_backend() -> CounterBackend:
    from arks_tpu.gateway import native
    if native.available():
        return native.NativeCounterBackend()
    return MemoryCounterBackend()


class RateLimiter:
    """check_limit/do_limit over (namespace, user, model) identifiers."""

    def __init__(self, backend: CounterBackend | None = None):
        self.backend = backend or default_backend()

    def check_limit(self, namespace: str, user: str, model: str,
                    rules: dict[str, int], requested: dict[str, int]) -> list[LimitResult]:
        """Pre-admission check; increments nothing. over ⇔ current + req > limit."""
        out = []
        for rule, limit in rules.items():
            if rule not in RULES or limit <= 0:
                continue
            key = window_key(namespace, user, model, rule)
            cur = self.backend.get(key)
            req = requested.get(rule, 1 if rule in REQUEST_RULES else 0)
            out.append(LimitResult(rule, limit, cur, cur + req > limit))
        return out

    def do_limit(self, namespace: str, user: str, model: str,
                 amounts: dict[str, int]) -> None:
        """Record consumption (admission +1 for request rules; usage for
        token rules)."""
        for rule, amount in amounts.items():
            if rule not in RULES or amount <= 0:
                continue
            period = RULES[rule][0]
            key = window_key(namespace, user, model, rule)
            # TTL slightly beyond the window end (the reference adds jitter
            # to avoid synchronized expiry; same idea).
            self.backend.incr(key, amount, ttl_s=period + 5)
