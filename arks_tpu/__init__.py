"""arks-tpu: a TPU-native LLM inference orchestration framework.

A ground-up re-creation of the capabilities of scitix/arks (a Kubernetes
operator + gateway data plane for LLM inference, reference at
/root/reference) with TPU/JAX as the first-class runtime:

- ``arks_tpu.models`` / ``arks_tpu.ops`` — JAX transformer forward passes
  (Qwen2/Llama families), RoPE/RMSNorm/attention ops, Pallas kernels.
- ``arks_tpu.engine`` — continuous-batching serving engine (the part the
  reference delegates to vLLM/SGLang runtime containers).
- ``arks_tpu.parallel`` — device mesh, tensor-parallel sharding over ICI,
  multi-host distributed bootstrap (replaces Ray/NCCL rendezvous).
- ``arks_tpu.server`` — OpenAI-compatible HTTP serving surface on :8080.
- ``arks_tpu.control`` — resource schemas + reconcilers mirroring the
  reference's CRDs/controllers (api/v1, internal/controller).
- ``arks_tpu.gateway`` — auth / rate-limit / quota / metrics data plane
  mirroring the reference's Envoy ext_proc plugin (pkg/gateway).
"""

__version__ = "0.1.0"
