"""Request/response dataclasses for the serving engine."""

from __future__ import annotations

import dataclasses
import queue
import time


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    max_tokens: int = 256
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0          # 0 = disabled
    stop_token_ids: tuple[int, ...] = ()
    ignore_eos: bool = False
    seed: int | None = None
    # OpenAI presence/frequency penalties over OUTPUT tokens (vLLM
    # semantics): logits -= presence*1[seen] + frequency*count.
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    # None = no logprobs; 0 = chosen-token logprob only; N>0 = plus the
    # top-N alternatives (clamped to sampler.TOP_LOGPROBS_MAX).
    # Logprob-bearing slots ride the fused loop.
    logprobs: int | None = None
    # OpenAI logit_bias as (token_id, bias) pairs (bias in [-100, 100];
    # at most sampler.LOGIT_BIAS_MAX entries — the server rejects more).
    logit_bias: tuple[tuple[int, float], ...] = ()
    # vLLM-style min_tokens: eos/stop token ids are suppressed on device
    # until at least this many tokens have been generated.
    min_tokens: int = 0
    # Admission priority (vLLM semantics: LOWER value admits first; equal
    # priorities stay FIFO).  With SLO tiers configured (arks_tpu.slo)
    # this is the tier index, and under ARKS_PREEMPT a queued lower value
    # may seize a running higher-value slot via preemptive KV swap;
    # ARKS_QUEUE_AGING_S decays a queued request's effective priority so
    # the worst tier still admits under sustained load.
    priority: int = 0
    # Guided decoding: (kind, pattern) compiled by engine.guides —
    # ("json", "") for JSON mode, ("regex", pat) for a regex constraint.
    guide: tuple[str, str] | None = None


@dataclasses.dataclass
class PrefilledState:
    """Result of a detached prefill, transferable between engines.

    The KV tensors are [L, 1, T, Hkv, D] (T = prefill bucket length); the
    decode engine inserts them into its own slotted cache.  ``seed`` lets the
    decode engine reconstruct the sampling key stream exactly where the
    prefill engine left it (prefill consumed the base key; decode starts from
    fold_in(key, 1)).
    """

    first_token: int
    num_prompt: int
    seed: int
    k: object  # np.ndarray | jax.Array [L, 1, T, Hkv, D]
    v: object
    # First-token logprob data (chosen_logprob, [(token_id, logprob)...]),
    # present when the request asked for logprobs — the decode side serves
    # the logprob stream seamlessly from here (its own dispatches cover
    # every later token).
    first_lp: object | None = None
    # Guided decoding: the DFA state AFTER the first token, RELATIVE to
    # the guide's start row (the prefill engine sampled under the guide;
    # the decode engine rebases onto its own table — absolute rows would
    # break when the two engines compiled guides in different orders).
    guide_row: int = 0
    # Prompt token ids (rides the kv_transfer meta).  The decode side
    # needs them to key the transferred KV by chain digest: paged engines
    # register the inserted pages into the device prefix index and
    # publish them into the host spill tier, so a decode-side restart
    # keeps the prefill peer's warm prefixes.  None/[] from a pre-upgrade
    # prefill peer simply skips the publish.
    prompt_ids: list | None = None
    # Informational: the dtype the k/v tensors are stored in ("bf16" /
    # "float32" / ...).  Transferred KV is always full-width (the decode
    # engine re-quantizes on insert — int8 or int4-packed per its own
    # kv_cache_dtype); this marker lets a receiver sanity-check a peer
    # rather than change behavior.
    kv_dtype: str = "bf16"


@dataclasses.dataclass
class Request:
    request_id: str
    prompt_ids: list[int]
    params: SamplingParams
    arrival_time: float = dataclasses.field(default_factory=time.monotonic)
    # Per-request output stream: the engine puts RequestOutput items here;
    # the server consumes them (None-terminated via ``finished``).
    outputs: "queue.Queue[RequestOutput]" = dataclasses.field(default_factory=queue.Queue)
    # Disaggregated serving: KV produced by a prefill engine; when set, the
    # decode engine inserts it instead of running its own prefill.
    prefilled: PrefilledState | None = None
    # Engine-assigned sampling seed (set once at first admission when
    # params.seed is None).  Pinned on the REQUEST so fault recovery can
    # re-admit/replay it with the identical key stream — a fresh counter
    # draw on replay would silently change the resumed stream's tokens.
    assigned_seed: int | None = None
    # Multi-model serving: which pool model this request targets.  None =
    # the engine's primary model.  Requests for a non-active model park in
    # the ``awaiting_model`` state until the scheduler switches to it.
    model: str | None = None
    # Tenant identity (arks_tpu.tenancy): "namespace/username" minted by
    # the gateway (x-arks-tenant) and mapped here by the OpenAI server.
    # Drives the engine's weighted-fair admission and per-tenant queue
    # caps.  None = untenanted (direct-to-pod clients) — all such
    # requests share one fair-queue lane, the pre-tenancy behavior.
    tenant: str | None = None
    # End-to-end tracing: the W3C trace context for this request
    # (arks_tpu.obs.trace.TraceCtx), carrying the gateway-minted trace id
    # and any upstream (gateway/router) spans.  None = untraced or an
    # engine-local request; the engine mints a local trace id on demand.
    trace: object | None = None
    # Fleet prefix cache: peer base address ("host:port") the router
    # believes holds this prompt's warm prefix blocks (X-Arks-Peer-Hint).
    # On an admission miss with ARKS_PEER_FETCH, the engine fetches the
    # blocks from this peer over GET /v1/cache/blocks/{digest} instead
    # of re-prefilling.  None = no hint; ARKS_PEER_ADDRS is the static
    # fallback probe list.
    peer_hint: str | None = None


@dataclasses.dataclass
class RequestOutput:
    request_id: str
    token_ids: list[int]          # newly generated token ids in this chunk
    finished: bool = False
    finish_reason: str | None = None   # "stop" | "length" | "abort" | "error"
    num_prompt_tokens: int = 0
    num_generated_tokens: int = 0      # cumulative, set when finished
    ttft_s: float | None = None        # set on the first chunk
    # Machine-readable rejection code when finish_reason == "error"
    # (e.g. "context_length_exceeded" -> HTTP 400 at the server).
    error: str | None = None
    # Per-token logprob data aligned with token_ids (present only when the
    # request asked for logprobs): each entry is
    # (chosen_logprob, [(token_id, logprob), ...top-N...]).
    logprobs: list | None = None
