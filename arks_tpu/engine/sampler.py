"""On-device token sampling for the fused decode loop.

Sampling lives inside the jitted multi-step loop so only sampled ids ever
cross the host boundary (per-dispatch host traffic on a tunneled PJRT
platform is the latency budget — see bench.py).

Per-slot params come in as arrays so one compiled program serves any mix of
greedy/temperature/top-k/top-p requests.  Top-k/top-p work on a static
``top_k_max``-wide slice of the vocab (lax.top_k), the standard TPU trick to
avoid sorting the full vocab each step.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

TOP_K_MAX = 64


class SamplingState(NamedTuple):
    """Per-slot sampling params, stacked into arrays (all [B])."""

    temperature: jnp.ndarray  # f32; <=0 means greedy
    top_p: jnp.ndarray        # f32 in (0, 1]
    top_k: jnp.ndarray        # i32; 0 = disabled (use TOP_K_MAX window)
    key: jnp.ndarray          # uint32 [B, 2] per-slot PRNG keys


def init_sampling_state(batch: int, seed: int = 0) -> SamplingState:
    keys = jax.random.split(jax.random.PRNGKey(seed), batch)
    return SamplingState(
        temperature=jnp.zeros((batch,), jnp.float32),
        top_p=jnp.ones((batch,), jnp.float32),
        top_k=jnp.zeros((batch,), jnp.int32),
        key=jnp.asarray(keys),
    )


def set_slot(state: SamplingState, slot: int | jnp.ndarray, temperature: float,
             top_p: float, top_k: int, key: jnp.ndarray) -> SamplingState:
    return SamplingState(
        temperature=state.temperature.at[slot].set(temperature),
        top_p=state.top_p.at[slot].set(top_p),
        top_k=state.top_k.at[slot].set(top_k),
        key=state.key.at[slot].set(key),
    )


def sample(logits: jnp.ndarray, state: SamplingState) -> tuple[jnp.ndarray, SamplingState]:
    """Sample one token per slot. logits [B, V] float32 -> ids [B] int32.

    Greedy where temperature <= 0; otherwise temperature + top-k + top-p over
    the TOP_K_MAX highest-logit candidates.
    """
    b, v = logits.shape
    window = min(TOP_K_MAX, v)
    greedy_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    top_logits, top_idx = jax.lax.top_k(logits, window)  # [B, K], descending
    temp = jnp.maximum(state.temperature, 1e-6)[:, None]
    scaled = top_logits / temp

    # top-k mask within the window (0 = keep whole window).
    k = jnp.where(state.top_k <= 0, window, jnp.minimum(state.top_k, window))
    rank = jnp.arange(window)[None, :]
    scaled = jnp.where(rank < k[:, None], scaled, -jnp.inf)

    # top-p (nucleus) over the kept candidates: keep the smallest prefix with
    # cumulative prob >= top_p; candidates are already sorted descending.
    probs = jax.nn.softmax(scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < state.top_p[:, None]  # first candidate always kept
    scaled = jnp.where(keep, scaled, -jnp.inf)

    new_keys = jax.vmap(lambda k: jax.random.split(k, 2))(state.key)
    step_keys, carry_keys = new_keys[:, 0], new_keys[:, 1]
    choice = jax.vmap(lambda key, s: jax.random.categorical(key, s))(step_keys, scaled)
    sampled_ids = jnp.take_along_axis(top_idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)

    ids = jnp.where(state.temperature <= 0.0, greedy_ids, sampled_ids)
    return ids, state._replace(key=carry_keys)
