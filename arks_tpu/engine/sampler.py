"""On-device token sampling for the fused decode loop.

Sampling lives inside the jitted multi-step loop so only sampled ids ever
cross the host boundary (per-dispatch host traffic on a tunneled PJRT
platform is the latency budget — see bench.py).

Per-slot params come in as arrays so one compiled program serves any mix of
greedy/temperature/top-k/top-p requests.  Top-k/top-p work on a static
``top_k_max``-wide slice of the vocab (lax.top_k), the standard TPU trick to
avoid sorting the full vocab each step.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

TOP_K_MAX = 64
TOP_LOGPROBS_MAX = 8

_NP_KEY_OK: bool | None = None


def np_prng_key(seed: int) -> np.ndarray:
    """Host-side ``jax.random.PRNGKey`` for the default threefry impl —
    byte-identical key data with ZERO device dispatches.  PRNGKey costs a
    traced jit + device round-trip (~0.7ms); at tens of admissions per
    scheduler cycle that is real engine-thread time (profiled: ~5% of the
    host-side loop).  Self-checks against jax once (covering x32/x64 and
    impl differences) and falls back to the real thing on mismatch.

    Used by BOTH the leader's admission batching and the follower's
    dispatch replay — the two must produce identical keys or gang
    sampling diverges.  Unlike ``jax.random.PRNGKey``, seeds outside the
    int64 range are MASKED rather than rejected: every key site (leader
    and follower) goes through this helper, so an absurd client-supplied
    seed yields a consistent key everywhere instead of an OverflowError
    on one side of a gang collective."""
    global _NP_KEY_OK
    if _NP_KEY_OK is None:
        probe = (1 << 35) + 7  # high bits exercise the truncation rule
        _NP_KEY_OK = bool(
            np.array_equal(np.array([0, probe & 0xFFFFFFFF], np.uint32),
                           np.asarray(jax.random.PRNGKey(probe)))
            and np.array_equal(np.array([0, (-1) & 0xFFFFFFFF], np.uint32),
                               np.asarray(jax.random.PRNGKey(-1))))
    if not _NP_KEY_OK:
        return np.asarray(jax.random.PRNGKey(seed))
    return np.array([0, seed & 0xFFFFFFFF], np.uint32)


def top_logprobs(logits: jnp.ndarray, chosen: jnp.ndarray
                 ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Logprob data for OpenAI ``logprobs`` responses: (chosen token's
    logprob [B], top-``TOP_LOGPROBS_MAX`` logprobs [B, L], their vocab ids
    [B, L]).  Computed over the RAW model distribution (full-vocab
    log-softmax) — the conventional reading of the API field, independent
    of temperature/penalty shaping."""
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    vals, ids = jax.lax.top_k(lp, min(TOP_LOGPROBS_MAX, lp.shape[-1]))
    chosen_lp = jnp.take_along_axis(lp, chosen[:, None], -1)[:, 0]
    return chosen_lp, vals, ids.astype(jnp.int32)


LOGIT_BIAS_MAX = 300  # full OpenAI logit_bias key budget; the bias pass
                      # is lax.cond-gated so unbiased batches pay nothing.
SUPPRESS_MAX = 8      # eos + stop_token_ids suppressed under min_tokens.
STOP_IDS_MAX = 32     # per-slot stop set (eos + stop_token_ids) mirrored
                      # onto the device so the pipelined decode path can
                      # compute liveness without a host round-trip.  A
                      # request whose stop set exceeds this rides the
                      # sequential path instead (never truncated).


def np_stop_col(stop_ids) -> np.ndarray | None:
    """Host-side [STOP_IDS_MAX] stop column for device-side liveness
    (pipelined decoding); ids < 0 pad.  Returns None on overflow — the
    caller must then keep the slot on the host-resolved sequential path
    (silently dropping a stop id would let the device keep a slot alive
    past its stop token and emit overshoot the host never discards)."""
    ids = list(dict.fromkeys(int(t) for t in stop_ids))
    if len(ids) > STOP_IDS_MAX:
        return None
    col = np.full((STOP_IDS_MAX,), -1, np.int32)
    col[: len(ids)] = ids
    return col


def advance_liveness(toks: jnp.ndarray, alive: jnp.ndarray,
                     lengths: jnp.ndarray, stop_ids: jnp.ndarray,
                     dead_len: jnp.ndarray) -> jnp.ndarray:
    """End-of-dispatch device liveness for the pipelined decode path.

    ``toks`` [K, B] are the dispatch's sampled tokens, ``lengths`` [B] the
    POST-dispatch absolute lengths, ``stop_ids`` [B, S] the per-slot stop
    sets (< 0 pad), ``dead_len`` [B] the absolute length at which the host
    would retire the slot (min of the max_tokens cutoff and the cache-cap
    margin).  A slot stays alive iff none of its K tokens is a stop token
    AND its new length sits below dead_len — EXACTLY the host's retire
    condition in _resolve_decode, which is what lets in-flight dispatches
    self-mask dead slots before the host has seen the death."""
    valid = stop_ids >= 0                                   # [B, S]
    hit = jnp.any((toks[:, :, None] == stop_ids[None, :, :])
                  & valid[None, :, :], axis=(0, 2))         # [B]
    return alive & ~hit & (lengths < dead_len)


class SamplingState(NamedTuple):
    """Per-slot sampling params, stacked into arrays (all [B])."""

    temperature: jnp.ndarray  # f32; <=0 means greedy
    top_p: jnp.ndarray        # f32 in (0, 1]
    top_k: jnp.ndarray        # i32; 0 = disabled (use TOP_K_MAX window)
    key: jnp.ndarray          # uint32 [B, 2] per-slot PRNG keys
    # OpenAI presence/frequency penalties over OUTPUT tokens (vLLM
    # semantics): logits -= presence*1[count>0] + frequency*count.
    presence: jnp.ndarray     # f32 [B]
    frequency: jnp.ndarray    # f32 [B]
    counts: jnp.ndarray       # i32 [B, V] per-slot generated-token counts
    # OpenAI logit_bias: up to LOGIT_BIAS_MAX (id, bias) pairs per slot;
    # id < 0 = empty entry.  Applied before greedy/filtering, like the
    # penalties (lax.cond-gated so unbiased batches pay nothing).
    bias_ids: jnp.ndarray     # i32 [B, NB]
    bias_vals: jnp.ndarray    # f32 [B, NB]
    # min_tokens: ids in suppress_ids (< 0 = empty) are masked to -inf
    # while the slot's sequence length is below min_until (0 = off).
    suppress_ids: jnp.ndarray  # i32 [B, NS]
    min_until: jnp.ndarray     # i32 [B]
    # Guided decoding (guides.py): guide = packed guide id (-1 = none),
    # guide_row = ABSOLUTE row in the trans table (the slot's DFA state).
    # shaped() masks tokens whose transition is dead; sample() advances
    # the row.  Both need the (class_ids, trans) tables passed alongside —
    # they live on the ENGINE (fixed budget shapes), not in this state.
    guide: jnp.ndarray        # i32 [B]
    guide_row: jnp.ndarray    # i32 [B]


def init_sampling_state(batch: int, seed: int = 0,
                        vocab_size: int = 1) -> SamplingState:
    keys = jax.random.split(jax.random.PRNGKey(seed), batch)
    return SamplingState(
        temperature=jnp.zeros((batch,), jnp.float32),
        top_p=jnp.ones((batch,), jnp.float32),
        top_k=jnp.zeros((batch,), jnp.int32),
        key=jnp.asarray(keys),
        presence=jnp.zeros((batch,), jnp.float32),
        frequency=jnp.zeros((batch,), jnp.float32),
        counts=jnp.zeros((batch, vocab_size), jnp.int32),
        bias_ids=jnp.full((batch, LOGIT_BIAS_MAX), -1, jnp.int32),
        bias_vals=jnp.zeros((batch, LOGIT_BIAS_MAX), jnp.float32),
        suppress_ids=jnp.full((batch, SUPPRESS_MAX), -1, jnp.int32),
        min_until=jnp.zeros((batch,), jnp.int32),
        guide=jnp.full((batch,), -1, jnp.int32),
        guide_row=jnp.zeros((batch,), jnp.int32),
    )


def np_bias_cols(params, vocab_size: int):
    """Host-side [NB] bias columns (ids, vals) for one request's
    ``logit_bias``; ids < 0 pad empty entries."""
    ids = np.full((LOGIT_BIAS_MAX,), -1, np.int32)
    vals = np.zeros((LOGIT_BIAS_MAX,), np.float32)
    for i, (tid, b) in enumerate(params.logit_bias[:LOGIT_BIAS_MAX]):
        if 0 <= tid < vocab_size:
            ids[i] = tid
            vals[i] = b
    return ids, vals


def np_suppress_col(stop_ids) -> np.ndarray:
    """Host-side [NS] suppress column for min_tokens; ids < 0 pad.

    Overflow raises instead of truncating: a silently-dropped id would let
    that token end the stream before min_tokens (the HTTP layer 400s the
    same condition; direct engine callers must fail just as loudly)."""
    ids = list(dict.fromkeys(stop_ids))
    if len(ids) > SUPPRESS_MAX:
        raise ValueError(
            f"min_tokens suppress set has {len(ids)} ids; at most "
            f"{SUPPRESS_MAX} eos/stop token ids are supported")
    col = np.full((SUPPRESS_MAX,), -1, np.int32)
    for i, tid in enumerate(ids):
        col[i] = tid
    return col


def set_slot(state: SamplingState, slot: int | jnp.ndarray, temperature: float,
             top_p: float, top_k: int, key: jnp.ndarray,
             presence: float = 0.0, frequency: float = 0.0,
             bias_ids=None, bias_vals=None, suppress_ids=None,
             min_until: int = 0, guide: int = -1,
             guide_row: int = 0) -> SamplingState:
    nb = state.bias_ids.shape[1]
    ns = state.suppress_ids.shape[1]
    return SamplingState(
        temperature=state.temperature.at[slot].set(temperature),
        top_p=state.top_p.at[slot].set(top_p),
        top_k=state.top_k.at[slot].set(top_k),
        key=state.key.at[slot].set(key),
        presence=state.presence.at[slot].set(presence),
        frequency=state.frequency.at[slot].set(frequency),
        counts=state.counts.at[slot].set(0),
        bias_ids=state.bias_ids.at[slot].set(
            jnp.full((nb,), -1, jnp.int32) if bias_ids is None else bias_ids),
        bias_vals=state.bias_vals.at[slot].set(
            jnp.zeros((nb,), jnp.float32) if bias_vals is None else bias_vals),
        suppress_ids=state.suppress_ids.at[slot].set(
            jnp.full((ns,), -1, jnp.int32) if suppress_ids is None
            else suppress_ids),
        min_until=state.min_until.at[slot].set(min_until),
        guide=state.guide.at[slot].set(guide),
        guide_row=state.guide_row.at[slot].set(guide_row),
    )


def transient_state(temperature, top_p, top_k, key,
                    vocab_size: int, bias_ids=None, bias_vals=None,
                    suppress_ids=None, min_first=None, guide=None,
                    guide_row=None) -> SamplingState:
    """One-row state for first-token sampling (prefill paths): penalties
    are identity there — the output is empty, so counts are all zero.
    ``min_first`` (i32 scalar, 1 when min_tokens >= 1): the first token
    must already respect suppression (sample's lengths=None reading of
    min_until)."""
    return SamplingState(
        temperature=temperature[None], top_p=top_p[None], top_k=top_k[None],
        key=key[None],
        presence=jnp.zeros((1,), jnp.float32),
        frequency=jnp.zeros((1,), jnp.float32),
        counts=jnp.zeros((1, vocab_size), jnp.int32),
        bias_ids=(jnp.full((1, LOGIT_BIAS_MAX), -1, jnp.int32)
                  if bias_ids is None else bias_ids[None]),
        bias_vals=(jnp.zeros((1, LOGIT_BIAS_MAX), jnp.float32)
                   if bias_vals is None else bias_vals[None]),
        suppress_ids=(jnp.full((1, SUPPRESS_MAX), -1, jnp.int32)
                      if suppress_ids is None else suppress_ids[None]),
        min_until=(jnp.zeros((1,), jnp.int32)
                   if min_first is None else min_first[None]),
        guide=(jnp.full((1,), -1, jnp.int32)
               if guide is None else guide[None]),
        guide_row=(jnp.zeros((1,), jnp.int32)
                   if guide_row is None else guide_row[None]),
    )


def transient_state_batch(temperature, top_p, top_k, keys,
                          vocab_size: int, bias_ids=None, bias_vals=None,
                          suppress_ids=None, min_first=None, guide=None,
                          guide_row=None) -> SamplingState:
    """M-row transient state for BATCHED first-token sampling (fused
    multi-prompt admissions): all params already [M]-shaped."""
    m = temperature.shape[0]
    return SamplingState(
        temperature=temperature, top_p=top_p, top_k=top_k, key=keys,
        presence=jnp.zeros((m,), jnp.float32),
        frequency=jnp.zeros((m,), jnp.float32),
        counts=jnp.zeros((m, vocab_size), jnp.int32),
        bias_ids=(jnp.full((m, LOGIT_BIAS_MAX), -1, jnp.int32)
                  if bias_ids is None else bias_ids),
        bias_vals=(jnp.zeros((m, LOGIT_BIAS_MAX), jnp.float32)
                   if bias_vals is None else bias_vals),
        suppress_ids=(jnp.full((m, SUPPRESS_MAX), -1, jnp.int32)
                      if suppress_ids is None else suppress_ids),
        min_until=(jnp.zeros((m,), jnp.int32)
                   if min_first is None else min_first),
        guide=(jnp.full((m,), -1, jnp.int32) if guide is None else guide),
        guide_row=(jnp.zeros((m,), jnp.int32)
                   if guide_row is None else guide_row),
    )


def set_slots(state: SamplingState, slots: jnp.ndarray, temperature,
              top_p, top_k, keys, presence, frequency,
              bias_ids=None, bias_vals=None, suppress_ids=None,
              min_until=None, guide=None, guide_row=None) -> SamplingState:
    """Batched set_slot: write M slots' sampling params in one scatter
    (one compiled program per batch size M)."""
    m = temperature.shape[0]
    return SamplingState(
        temperature=state.temperature.at[slots].set(temperature),
        top_p=state.top_p.at[slots].set(top_p),
        top_k=state.top_k.at[slots].set(top_k),
        key=state.key.at[slots].set(keys),
        presence=state.presence.at[slots].set(presence),
        frequency=state.frequency.at[slots].set(frequency),
        counts=state.counts.at[slots].set(0),
        bias_ids=state.bias_ids.at[slots].set(
            jnp.full((m, state.bias_ids.shape[1]), -1, jnp.int32)
            if bias_ids is None else bias_ids),
        bias_vals=state.bias_vals.at[slots].set(
            jnp.zeros((m, state.bias_vals.shape[1]), jnp.float32)
            if bias_vals is None else bias_vals),
        suppress_ids=state.suppress_ids.at[slots].set(
            jnp.full((m, state.suppress_ids.shape[1]), -1, jnp.int32)
            if suppress_ids is None else suppress_ids),
        min_until=state.min_until.at[slots].set(
            jnp.zeros((m,), jnp.int32) if min_until is None else min_until),
        guide=state.guide.at[slots].set(
            jnp.full((m,), -1, jnp.int32) if guide is None else guide),
        guide_row=state.guide_row.at[slots].set(
            jnp.zeros((m,), jnp.int32) if guide_row is None else guide_row),
    )


def clear_slot_penalties(state: SamplingState,
                         slot: jnp.ndarray) -> SamplingState:
    """Zero a freed slot's penalties, bias, and suppression so the
    shaping fast-path gates (jnp.any over ALL rows) re-arm once no live
    slot needs them."""
    return state._replace(
        presence=state.presence.at[slot].set(0.0),
        frequency=state.frequency.at[slot].set(0.0),
        bias_ids=state.bias_ids.at[slot].set(-1),
        bias_vals=state.bias_vals.at[slot].set(0.0),
        suppress_ids=state.suppress_ids.at[slot].set(-1),
        min_until=state.min_until.at[slot].set(0),
        guide=state.guide.at[slot].set(-1),
        guide_row=state.guide_row.at[slot].set(0))


def count_tokens(state: SamplingState, tokens: jnp.ndarray,
                 active: jnp.ndarray | None = None) -> SamplingState:
    """Record one emitted token per slot (called on the tokens FED to a
    decode step — every generated token is fed exactly once, so feed-time
    counting covers the one-shot, chunked, and disagg admission paths
    uniformly; free slots' garbage rows are reset at set_slot).

    ``active`` (bool [B]) masks the update to live slots: with deferred
    admissions a slot's set_slots (in the admit program) may precede
    intervening decode dispatches, and counting its garbage feed rows
    there would poison the new request's penalties."""
    b = tokens.shape[0]
    inc = 1 if active is None else active.astype(jnp.int32)
    return state._replace(
        counts=state.counts.at[jnp.arange(b), tokens].add(inc))


def penalized(logits: jnp.ndarray, state: SamplingState) -> jnp.ndarray:
    """Apply presence/frequency penalties (identity when both are 0).

    Runtime-gated with ``lax.cond``: the un-penalized common case skips the
    two [B, V] reads entirely instead of multiplying by zero."""
    def apply(logits):
        cnt = state.counts.astype(jnp.float32)
        return (logits - state.presence[:, None] * (cnt > 0)
                - state.frequency[:, None] * cnt)

    active = jnp.any((state.presence != 0.0) | (state.frequency != 0.0))
    return jax.lax.cond(active, apply, lambda x: x, logits)


def guide_mask(logits: jnp.ndarray, state: SamplingState,
               guide_tables) -> jnp.ndarray:
    """Mask tokens with dead guide transitions to -inf.  guide_tables =
    (class_ids [G, V] i32, trans [R, C] i32).  lax.cond-gated: unguided
    batches skip the [B, V] class gather entirely."""
    class_ids, trans = guide_tables

    def apply(lg):
        b = lg.shape[0]
        cls = class_ids[jnp.maximum(state.guide, 0)]          # [B, V]
        row = trans[jnp.maximum(state.guide_row, 0)]          # [B, C]
        nxt = jnp.take_along_axis(row, cls, axis=1)           # [B, V]
        bad = (nxt < 0) & (state.guide >= 0)[:, None]
        return jnp.where(bad, jnp.float32(-1e30), lg)

    return jax.lax.cond(jnp.any(state.guide >= 0), apply,
                        lambda x: x, logits)


def guide_advance(state: SamplingState, ids: jnp.ndarray, guide_tables,
                  active: jnp.ndarray | None = None) -> SamplingState:
    """Advance each guided slot's DFA row by its sampled token.  A dead
    transition (only reachable when every token was masked — degenerate
    grammar) holds the row instead of corrupting it."""
    class_ids, trans = guide_tables
    b = ids.shape[0]
    cls = class_ids[jnp.maximum(state.guide, 0), ids]         # [B]
    nxt = trans[jnp.maximum(state.guide_row, 0), cls]         # [B]
    upd = state.guide >= 0
    if active is not None:
        upd = upd & active
    upd = upd & (nxt >= 0)
    return state._replace(
        guide_row=jnp.where(upd, nxt, state.guide_row))


def shaped(logits: jnp.ndarray, state: SamplingState,
           lengths: jnp.ndarray | None = None,
           guide_tables=None) -> jnp.ndarray:
    """Penalties + OpenAI logit_bias + min_tokens suppression + guided-
    decoding masks, each lax.cond-gated so the plain batch pays none of it.

    min_tokens: suppress_ids are masked to -inf while the slot's current
    sequence length sits below min_until.  Without ``lengths`` (first-token
    prefill paths), min_until > 0 itself means "still under the minimum"
    (the engine sets it to 1 only when min_tokens >= 1 there)."""
    logits = penalized(logits, state)
    b = logits.shape[0]

    def apply_bias(lg):
        valid = state.bias_ids >= 0
        ids = jnp.maximum(state.bias_ids, 0)
        return lg.at[jnp.arange(b)[:, None], ids].add(
            jnp.where(valid, state.bias_vals, 0.0))

    logits = jax.lax.cond(jnp.any(state.bias_ids >= 0), apply_bias,
                          lambda x: x, logits)

    def apply_min(lg):
        if lengths is None:
            hold = state.min_until > 0
        else:
            hold = lengths < state.min_until
        valid = (state.suppress_ids >= 0) & hold[:, None]
        ids = jnp.maximum(state.suppress_ids, 0)
        return lg.at[jnp.arange(b)[:, None], ids].add(
            jnp.where(valid, jnp.float32(-1e30), 0.0))

    logits = jax.lax.cond(jnp.any(state.min_until > 0), apply_min,
                          lambda x: x, logits)
    # Guide mask LAST: a +100 logit_bias must not resurrect a token the
    # grammar forbids.
    if guide_tables is not None:
        logits = guide_mask(logits, state, guide_tables)
    return logits


def _filtered_scaled(logits: jnp.ndarray, state: SamplingState
                     ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The effective per-slot sampling distribution in window form:
    (scaled logits [B, W] with filtered entries at -inf, vocab ids
    [B, W]) after temperature + top-k + top-p over the TOP_K_MAX window."""
    b, v = logits.shape
    window = min(TOP_K_MAX, v)
    top_logits, top_idx = jax.lax.top_k(logits, window)  # [B, W], descending
    temp = jnp.maximum(state.temperature, 1e-6)[:, None]
    scaled = top_logits / temp

    # top-k mask within the window (0 = keep whole window).
    k = jnp.where(state.top_k <= 0, window, jnp.minimum(state.top_k, window))
    rank = jnp.arange(window)[None, :]
    scaled = jnp.where(rank < k[:, None], scaled, -jnp.inf)

    # top-p (nucleus) over the kept candidates: keep the smallest prefix with
    # cumulative prob >= top_p; candidates are already sorted descending.
    probs = jax.nn.softmax(scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < state.top_p[:, None]  # first candidate always kept
    return jnp.where(keep, scaled, -jnp.inf), top_idx


def filtered_probs(logits: jnp.ndarray, state: SamplingState
                   ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(probs [B, W], vocab ids [B, W], scaled logits [B, W]) — the exact
    distribution ``sample`` draws from, exposed for speculative decoding's
    acceptance ratios and residual distributions."""
    scaled, idx = _filtered_scaled(logits, state)
    return jax.nn.softmax(scaled, axis=-1), idx, scaled


def sample(logits: jnp.ndarray, state: SamplingState,
           active: jnp.ndarray | None = None,
           lengths: jnp.ndarray | None = None,
           guide_tables=None,
           ) -> tuple[jnp.ndarray, SamplingState]:
    """Sample one token per slot. logits [B, V] float32 -> ids [B] int32.

    Greedy where temperature <= 0; otherwise temperature + top-k + top-p over
    the TOP_K_MAX highest-logit candidates.  Penalties, logit_bias, and
    min_tokens suppression apply BEFORE greedy/filtering (identity at the
    defaults — see ``shaped``).

    ``active`` (bool [B]) freezes INACTIVE slots' PRNG keys: with deferred
    admissions, decode dispatches can land between a slot's set_slots (in
    the admit program) and its registration — advancing its fresh key
    stream there would make seeded sampling depend on scheduler timing.
    """
    logits = shaped(logits, state, lengths, guide_tables)
    greedy_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled, top_idx = _filtered_scaled(logits, state)

    new_keys = jax.vmap(lambda k: jax.random.split(k, 2))(state.key)
    step_keys, carry_keys = new_keys[:, 0], new_keys[:, 1]
    choice = jax.vmap(lambda key, s: jax.random.categorical(key, s))(step_keys, scaled)
    sampled_ids = jnp.take_along_axis(top_idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)

    ids = jnp.where(state.temperature <= 0.0, greedy_ids, sampled_ids)
    if active is not None:
        carry_keys = jnp.where(active[:, None], carry_keys, state.key)
    state = state._replace(key=carry_keys)
    if guide_tables is not None:
        state = guide_advance(state, ids, guide_tables, active)
    return ids, state


def draft_sample(logits: jnp.ndarray, state: SamplingState, keys: jnp.ndarray
                 ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                            jnp.ndarray, jnp.ndarray]:
    """One draft proposal per slot for speculative decoding.

    Returns (token [B], q(token) [B], q probs [B, W], window ids [B, W],
    advanced keys [B, 2]).  Greedy slots propose argmax with q=1 (the
    temperature->0 limit of the acceptance rule reduces to exact-match)."""
    probs, idx, scaled = filtered_probs(logits, state)
    new_keys = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
    step_keys, carry_keys = new_keys[:, 0], new_keys[:, 1]
    choice = jax.vmap(lambda key, s: jax.random.categorical(key, s))(step_keys, scaled)
    samp_tok = jnp.take_along_axis(idx, choice[:, None], -1)[:, 0].astype(jnp.int32)
    samp_q = jnp.take_along_axis(probs, choice[:, None], -1)[:, 0]
    greedy = state.temperature <= 0.0
    tok = jnp.where(greedy, jnp.argmax(logits, -1).astype(jnp.int32), samp_tok)
    q = jnp.where(greedy, 1.0, samp_q)
    return tok, q, probs, idx, carry_keys


def speculative_accept(
    drafts: jnp.ndarray,        # [B, K-1] draft proposals
    q_sel: jnp.ndarray,         # [B, K-1] q(draft) under the draft dist
    q_probs: jnp.ndarray,       # [B, K-1, W] draft window probs
    q_idx: jnp.ndarray,         # [B, K-1, W] draft window vocab ids
    target_logits: jnp.ndarray,  # [B, K, V] verifier logits per position
    state: SamplingState,
    keys: jnp.ndarray,          # [B, 2]
    enable: jnp.ndarray | None = None,  # [B] bool; False = no speculation
    lengths: jnp.ndarray | None = None,  # [B] — min_tokens gating for the
                                         # disabled slots' plain sample
    guide_tables=None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Rejection-sampled acceptance (Leviathan et al.): accept draft i with
    prob min(1, p_i(d_i)/q_i(d_i)); at the first rejection sample from the
    residual norm(max(p - q, 0)); after a fully-accepted block sample the
    bonus token from p_{K-1}.  The emitted tokens are distributed EXACTLY
    as the engine's own effective sampling distribution (the windowed
    temperature/top-k/top-p dist ``sample`` uses) — the draft only changes
    how many land per dispatch.  Greedy slots reduce to exact argmax
    matching + the argmax bonus token.

    ``enable`` gates speculation PER SLOT: a disabled slot (penalized /
    logprob-bearing / stale draft mirror) advances exactly ONE token,
    sampled from the target's position-0 logits through the NORMAL path —
    penalties included — so one such request no longer drops the whole
    batch off the speculative path.

    Guided slots SPECULATE (``guide_tables``): the DFA is threaded through
    the draft prefix — position i's candidate row is the current row
    advanced by drafts[0..i-1] — and each position's TARGET logits are
    masked with that row's dead transitions before the acceptance
    distribution is formed.  A draft token the grammar forbids has p = 0
    at its own position, so it is always rejected and the residual (masked
    target) distribution resamples a legal one — exactness is untouched
    because only the target side defines the emitted distribution.  The
    returned rows are rolled back to the ACCEPTED prefix: row after the
    accepted drafts, advanced once more by the bonus/residual token.
    Draft proposals themselves stay unmasked (the draft model has no DFA),
    costing only acceptance rate, never correctness.

    Returns (tokens [B, K] — first counts[b] are valid, counts [B] in
    1..K, advanced keys, advanced guide rows [B])."""
    b, km1 = drafts.shape
    kk = km1 + 1
    greedy = state.temperature <= 0.0

    # Guided lanes: candidate DFA rows per position + per-position target
    # masks.  The [B, V] class gathers are cond-gated like guide_mask so
    # unguided batches skip them.
    rows_arr = None
    if guide_tables is not None:
        class_ids, trans = guide_tables
        guided = state.guide >= 0

        def _row_next(row, toks):
            cls = class_ids[jnp.maximum(state.guide, 0), toks]    # [B]
            nxt = trans[jnp.maximum(row, 0), cls]                 # [B]
            # Dead transition holds the row (degenerate grammar), exactly
            # like guide_advance.
            return jnp.where(guided & (nxt >= 0), nxt, row)

        rows = [state.guide_row]
        for i in range(km1):
            rows.append(_row_next(rows[-1], drafts[:, i]))
        rows_arr = jnp.stack(rows, axis=1)                        # [B, K]

        def _with_guides(tl):
            cls_all = class_ids[jnp.maximum(state.guide, 0)]      # [B, V]

            def mask_pos(lg, row):
                r = trans[jnp.maximum(row, 0)]                    # [B, C]
                nxt = jnp.take_along_axis(r, cls_all, axis=1)     # [B, V]
                bad = (nxt < 0) & guided[:, None]
                return jnp.where(bad, jnp.float32(-1e30), lg)

            return jnp.stack([mask_pos(tl[:, i], rows_arr[:, i])
                              for i in range(kk)], axis=1)

        target_eff = jax.lax.cond(jnp.any(guided), _with_guides,
                                  lambda tl: tl, target_logits)
    else:
        target_eff = target_logits

    # Target filtered dist per position: [B, K, W].
    def per_pos(logits_i):
        return filtered_probs(logits_i, state)

    p_probs, p_idx, _ = jax.vmap(per_pos, in_axes=1, out_axes=1)(target_eff)
    g_t = jnp.argmax(target_eff, axis=-1).astype(jnp.int32)  # [B, K]

    new_keys = jax.vmap(lambda k: jax.random.split(k, 3))(keys)
    u_keys, r_keys, carry_keys = new_keys[:, 0], new_keys[:, 1], new_keys[:, 2]
    u = jax.vmap(lambda key: jax.random.uniform(key, (km1,)))(u_keys)

    # p_i(d_i): the draft token's prob under the target window (0 when the
    # token fell outside the target's filtered support).
    p_at_d = jnp.sum(p_probs[:, :km1]
                     * (p_idx[:, :km1] == drafts[..., None]), axis=-1)
    accept_samp = u < p_at_d / jnp.maximum(q_sel, 1e-20)
    accept_greedy = g_t[:, :km1] == drafts
    accept = jnp.where(greedy[:, None], accept_greedy, accept_samp)
    j = jnp.cumprod(accept.astype(jnp.int32), axis=1).sum(axis=1)  # [B] 0..K-1
    counts = 1 + j

    # Residual/bonus token at position j.
    pj = jnp.take_along_axis(p_probs, j[:, None, None], axis=1)[:, 0]   # [B, W]
    pidxj = jnp.take_along_axis(p_idx, j[:, None, None], axis=1)[:, 0]
    jq = jnp.minimum(j, km1 - 1)
    qj = jnp.take_along_axis(q_probs, jq[:, None, None], axis=1)[:, 0]
    qidxj = jnp.take_along_axis(q_idx, jq[:, None, None], axis=1)[:, 0]
    # Map q onto the target window's index set.
    q_on_p = jnp.sum(qj[:, None, :] * (qidxj[:, None, :] == pidxj[:, :, None]),
                     axis=-1)                                           # [B, W]
    rejected = (j < km1)[:, None]
    res = jnp.maximum(pj - jnp.where(rejected, q_on_p, 0.0), 0.0)
    norm = res.sum(-1, keepdims=True)
    res = jnp.where(norm > 1e-20, res / jnp.maximum(norm, 1e-20), pj)
    rchoice = jax.vmap(lambda key, pr: jax.random.categorical(
        key, jnp.log(pr + 1e-30)))(r_keys, res)
    y_samp = jnp.take_along_axis(pidxj, rchoice[:, None], -1)[:, 0].astype(jnp.int32)
    y = jnp.where(greedy, jnp.take_along_axis(g_t, j[:, None], 1)[:, 0], y_samp)

    out = jnp.concatenate([drafts, jnp.zeros((b, 1), jnp.int32)], axis=1)
    out = out.at[jnp.arange(b), j].set(y)

    guide_row = state.guide_row
    if rows_arr is not None:
        # Roll back to the accepted prefix's row, then advance by the
        # bonus/residual token — the state the NEXT dispatch's position-0
        # mask (and the engine's persistent guide_row) must carry.
        row_j = jnp.take_along_axis(rows_arr, j[:, None], axis=1)[:, 0]
        guide_row = _row_next(row_j, y)
    if enable is not None:
        # Disabled slots: one token via the regular sampler (which applies
        # penalties / logit_bias / min_tokens / guide shaping) from the
        # position-0 target logits.
        plain, pstate = sample(target_logits[:, 0],
                               state._replace(key=r_keys),
                               lengths=lengths, guide_tables=guide_tables)
        out = jnp.where(enable[:, None], out, out.at[:, 0].set(plain))
        counts = jnp.where(enable, counts, 1)
        guide_row = jnp.where(enable, guide_row, pstate.guide_row)
    return out, counts, carry_keys, guide_row
