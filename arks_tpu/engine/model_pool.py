"""Device-HBM weight pool for multi-model serving.

One live engine process serves several models; the pool owns their weights
as a first-class budgeted resource (``ARKS_MODEL_POOL_HBM_MB``).  Each
registered model is resident / loading / evicted; residency is guarded by
refcounts against in-flight use (the engine holds a ref on the active
model) plus a ``pinned`` flag for the flagship and small co-resident
models (draft, guide models) that must never be evicted.  Eviction is LRU
over unpinned refcount-0 entries.

The pool deliberately mirrors the guide-compiler discipline
(``guides.GuideCompiler``): ``ensure()`` is a NON-BLOCKING claim — it
returns the resident entry or a ``LoadTicket`` whose ``event`` fires when
a background loader thread finishes.  The engine's scheduler polls the
ticket from its step loop (the ``awaiting_model`` parked state), so
pipelined decode of the current model keeps full depth while the next
model's weights stream host→device.

Budget accounting covers WEIGHTS only (logical bytes over the param tree
leaves).  KV caches and per-model scheduler state live with the engine's
model context and are not pool-budgeted; a model's first-ever load may
transiently overshoot the budget (its size is unknown until the leaves
exist) — the pool then evicts or fails the load immediately after.  Once
a model has been resident its size is remembered, and later reloads make
room BEFORE streaming.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time

from arks_tpu.utils import knobs

log = logging.getLogger("arks_tpu.model_pool")


class PoolFullError(RuntimeError):
    """The HBM budget cannot fit the model even after evicting every
    unpinned idle entry.  Surfaces to clients as ``model_pool_exhausted``
    (HTTP 503 + Retry-After)."""


@dataclasses.dataclass
class LoadTicket:
    """Returned by ``ensure()`` when the model is not resident: ``event``
    fires when the background load finishes; ``error`` is set on failure
    (``model_pool_exhausted: ...`` when the budget can't fit it)."""

    name: str
    event: threading.Event = dataclasses.field(default_factory=threading.Event)
    error: str | None = None
    t0: float = dataclasses.field(default_factory=time.monotonic)


@dataclasses.dataclass
class ModelEntry:
    name: str
    cfg: object                      # models.config.ModelConfig
    model_path: str | None = None
    loader: object | None = None     # zero-arg callable -> params
    params: object | None = None
    nbytes: int = 0                  # logical bytes; remembered across evictions
    pinned: bool = False
    refcount: int = 0
    state: str = "evicted"           # "resident" | "loading" | "evicted"
    last_used: float = 0.0
    cold_starts: int = 0


def tree_bytes(params) -> int:
    """Logical bytes over the param-tree leaves (sharded arrays count
    their GLOBAL size — the pool budgets the model, not one shard)."""
    import jax
    return sum(int(getattr(x, "nbytes", 0)) for x in jax.tree_util.tree_leaves(params))


class ModelPool:
    """Thread-safe registry of models sharing one device's weight HBM."""

    def __init__(self, hbm_budget_mb: int | None = None):
        if hbm_budget_mb is None:
            hbm_budget_mb = knobs.get_int("ARKS_MODEL_POOL_HBM_MB")
        if hbm_budget_mb < 0:
            raise ValueError(f"ARKS_MODEL_POOL_HBM_MB={hbm_budget_mb} (want >= 0)")
        self.budget_bytes = hbm_budget_mb * (1 << 20)  # 0 = unlimited
        self._lock = threading.Lock()
        self._entries: dict[str, ModelEntry] = {}
        self._tickets: dict[str, LoadTicket] = {}
        # Fired (outside the lock) with the evicted model's name; the
        # engine drops its saved per-model context so the HBM actually
        # frees (the context holds a params reference).
        self.on_evict = None
        # Optional namespace with .resident_bytes gauge / .cold_starts
        # counter (labelled by model); the engine wires this up.
        self.metrics = None

    # ---- registration ------------------------------------------------

    def register(self, name: str, cfg, *, model_path: str | None = None,
                 loader=None, pinned: bool = False) -> ModelEntry:
        """Declare a model the pool may serve.  ``loader`` is a zero-arg
        callable returning the (device-resident, sharded) params; when
        omitted the registrant must ``adopt()`` params later."""
        with self._lock:
            e = self._entries.get(name)
            if e is None:
                e = ModelEntry(name=name, cfg=cfg, model_path=model_path)
                self._entries[name] = e
            if loader is not None:
                e.loader = loader
            if model_path is not None:
                e.model_path = model_path
            e.pinned = e.pinned or pinned
            return e

    def adopt(self, name: str, cfg, params, *, pinned: bool = False) -> ModelEntry:
        """Attach already-loaded params (e.g. the flagship the process
        booted with, or a draft model loaded at startup) as resident."""
        e = self.register(name, cfg, pinned=pinned)
        with self._lock:
            e.params = params
            e.nbytes = tree_bytes(params)
            e.state = "resident"
            e.last_used = time.monotonic()
        self._publish_metrics()
        return e

    # ---- residency ---------------------------------------------------

    def ensure(self, name: str) -> ModelEntry | LoadTicket:
        """Non-blocking: resident entry, or a ticket for an in-flight /
        newly-kicked background load.  Raises KeyError on unknown models."""
        evicted = []
        try:
            with self._lock:
                e = self._entries[name]
                if e.state == "resident":
                    e.last_used = time.monotonic()
                    return e
                t = self._tickets.get(name)
                if t is not None:
                    return t
                if e.loader is None:
                    raise KeyError(f"model {name!r} has no loader and no params")
                # Known size from a previous residency: make room BEFORE
                # the load streams, so we never overshoot the budget.
                if e.nbytes:
                    evicted = self._make_room_locked(e.nbytes, exclude=name)
                e.state = "loading"
                t = self._tickets[name] = LoadTicket(name=name)
                threading.Thread(target=self._load, args=(e, t),
                                 name=f"model-load-{name}", daemon=True).start()
                return t
        finally:
            self._notify_evicted(evicted)

    def load(self, name: str, timeout: float | None = None):
        """Blocking convenience wrapper over ``ensure`` (startup, tests).
        Returns the params; raises on load failure/timeout."""
        got = self.ensure(name)
        if isinstance(got, LoadTicket):
            if not got.event.wait(timeout):
                raise TimeoutError(f"model {name!r} load timed out")
            if got.error:
                raise PoolFullError(got.error) if "model_pool_exhausted" in got.error \
                    else RuntimeError(got.error)
        with self._lock:
            e = self._entries[name]
            if e.state != "resident":
                raise RuntimeError(f"model {name!r} not resident after load")
            e.last_used = time.monotonic()
            return e.params

    def _load(self, e: ModelEntry, t: LoadTicket) -> None:
        evicted = []
        try:
            params = e.loader()
            nbytes = tree_bytes(params)
            with self._lock:
                try:
                    evicted = self._make_room_locked(nbytes, exclude=e.name)
                except PoolFullError as pf:
                    e.state = "evicted"
                    t.error = f"model_pool_exhausted: {pf}"
                    return
                e.params = params
                e.nbytes = nbytes
                e.state = "resident"
                e.last_used = time.monotonic()
                e.cold_starts += 1
            if self.metrics is not None:
                self.metrics.cold_starts.inc(1, model=e.name)
            log.info("model %s loaded (%.1f MiB) in %.2fs", e.name,
                     nbytes / (1 << 20), time.monotonic() - t.t0)
        except Exception as exc:  # noqa: BLE001 — surfaces via the ticket
            with self._lock:
                e.state = "evicted"
            t.error = f"{type(exc).__name__}: {exc}"
            log.error("model %s load failed: %s", e.name, t.error)
        finally:
            self._notify_evicted(evicted)
            self._publish_metrics()
            with self._lock:
                self._tickets.pop(e.name, None)
            t.event.set()

    def _make_room_locked(self, need: int, exclude: str) -> list[str]:
        """Evict LRU unpinned refcount-0 entries until ``need`` fits the
        budget.  Returns evicted names (caller notifies outside the lock);
        raises PoolFullError when eviction can't make room."""
        if not self.budget_bytes:
            return []
        evicted: list[str] = []

        def resident_bytes():
            return sum(x.nbytes for x in self._entries.values()
                       if x.state == "resident")

        victims = sorted((x for x in self._entries.values()
                          if x.state == "resident" and not x.pinned
                          and x.refcount == 0 and x.name != exclude),
                         key=lambda x: x.last_used)
        vi = iter(victims)
        while resident_bytes() + need > self.budget_bytes:
            v = next(vi, None)
            if v is None:
                raise PoolFullError(
                    f"need {need >> 20} MiB but only "
                    f"{(self.budget_bytes - resident_bytes()) >> 20} MiB free "
                    f"of {self.budget_bytes >> 20} MiB budget "
                    f"(pinned/in-use models cannot be evicted)")
            v.params = None
            v.state = "evicted"
            evicted.append(v.name)
        return evicted

    def _notify_evicted(self, names: list[str]) -> None:
        for n in names:
            log.info("model %s evicted (LRU)", n)
            if self.on_evict is not None:
                self.on_evict(n)
        if names:
            self._publish_metrics()

    def scale_to_zero(self, name: str) -> bool:
        """Drop a model's device weights even when PINNED — the engine's
        elastic scale-to-zero path.  LRU eviction never touches pinned
        entries, but an engine explicitly disarming itself may: the only
        live reference allowed is the caller's own active-model ref
        (refcount <= 1).  The remembered ``nbytes`` survives, so the
        re-arm load makes room before streaming, and a ``loader``/
        ``model_path`` registration keeps ``ensure()`` able to restream
        the weights on demand."""
        with self._lock:
            e = self._entries.get(name)
            if e is None or e.state != "resident":
                return False
            if e.refcount > 1:
                raise RuntimeError(
                    f"model {name!r} has refcount {e.refcount}; cannot "
                    "scale to zero while other holders are live")
            e.params = None
            e.state = "evicted"
            freed = e.nbytes
        self._publish_metrics()
        log.info("model %s scaled to zero (%.1f MiB of weights dropped)",
                 name, freed / (1 << 20))
        return True

    # ---- refcounts ---------------------------------------------------

    def acquire(self, name: str) -> ModelEntry:
        """Pin ``name`` against eviction while in use.  Raises if the
        model is not resident — callers go through ``ensure`` first."""
        with self._lock:
            e = self._entries[name]
            if e.state != "resident":
                raise RuntimeError(f"model {name!r} is {e.state}, not resident")
            e.refcount += 1
            e.last_used = time.monotonic()
            return e

    def release(self, name: str) -> None:
        with self._lock:
            e = self._entries.get(name)
            if e is not None and e.refcount > 0:
                e.refcount -= 1

    # ---- introspection -----------------------------------------------

    def has(self, name: str) -> bool:
        with self._lock:
            return name in self._entries

    def names(self) -> list[str]:
        with self._lock:
            return list(self._entries)

    def params_of(self, name: str):
        with self._lock:
            e = self._entries[name]
            if e.state != "resident":
                raise RuntimeError(f"model {name!r} is {e.state}, not resident")
            return e.params

    def entry(self, name: str) -> ModelEntry:
        with self._lock:
            return self._entries[name]

    def snapshot(self) -> list[dict]:
        """Residency listing for ``/v1/models``."""
        with self._lock:
            return [{
                "name": e.name,
                "state": e.state,
                "resident_bytes": e.nbytes if e.state == "resident" else 0,
                "pinned": e.pinned,
                "refcount": e.refcount,
                "cold_starts": e.cold_starts,
            } for e in self._entries.values()]

    def _publish_metrics(self) -> None:
        if self.metrics is None:
            return
        with self._lock:
            rows = [(e.name, e.nbytes if e.state == "resident" else 0)
                    for e in self._entries.values()]
        for name, nbytes in rows:
            self.metrics.resident_bytes.set(nbytes, model=name)
