"""Windowed-residency decode: contexts larger than the device page pool.

A slot whose logical context outgrows ``ARKS_RESIDENCY_WINDOW_PAGES``
*engages*: all but its two newest KV pages spill to a host-RAM store
(pool-native bytes, the same gather/scatter pair the prefix host tier
uses), and from then on the slot decodes span-by-span on a host loop —
the engine's resident budget per slot is the window, while the slot's
LOGICAL block table keeps its full ``max_cache_len`` width.

Per decode token, per layer:

- the new token's q/k/v come from the SAME ``_block_qkv`` the mixed
  program runs, and its KV row lands on the resident hot-tail page via
  the same ``paged_kv_update(_quant)`` kernel;
- attention walks the causal page prefix in SPANS: cold spans stream
  through a rotating two-half staging area (scatter the next span's
  host blocks H2D while the current span attends — the prefetch
  overlap), the final span reads the resident tail in place;
- the ragged mixed kernel chains its online-softmax (m, l, acc) state
  across spans (``carry_state``/``emit_state``), which reproduces the
  single-call result BITWISE — so an engaged slot's token stream is
  byte-identical to the same request on a pool big enough to never
  engage.

Residency requires the Pallas ragged path (``ARKS_ATTN_IMPL=pallas``):
the XLA oracle attend is a one-shot softmax and cannot carry state
across spans.  Layer sequencing is fundamental — layer l+1's q/k/v
need layer l's full attention output — so the span loop nests inside a
host layer loop; per-layer params come from ``jax.tree.map(x[l])``,
which slices the SAME stacked arrays ``lax.scan`` feeds the fused
program (bitwise-identical weights).

Engine-thread only, like the rest of the scheduler state.
"""

from __future__ import annotations

import functools
import logging

import numpy as np

import jax
import jax.numpy as jnp

from arks_tpu.engine import sampler as sampler_mod
from arks_tpu.models import transformer as tf

log = logging.getLogger("arks_tpu.residency")

__all__ = ["ResidencyManager"]


class _WindowedSlot:
    """Host bookkeeping for one engaged slot.

    ``store`` maps logical page index -> pool-native block tuple
    (k, v, k_scale, v_scale; scales None when unquantized), each array
    ``[L, 1, Hkv, P(, D)]`` — raw pool bytes, so a staging scatter
    reproduces the original device pages bit-exactly.  ``cold`` pages
    [0, cold) live ONLY in the store; ``tail`` holds the two resident
    hot pages (device ids, logical order) the decode writes into;
    ``staging`` holds the two half-buffers (chunk device pages each)
    cold spans rotate through, and ``staged`` remembers which span a
    half currently holds so unchanged spans skip the re-scatter."""

    __slots__ = ("cold", "tail", "staging", "staged", "store")

    def __init__(self) -> None:
        self.cold = 0
        self.tail: list[int] = []
        self.staging: list[list[int]] = [[], []]
        self.staged: list[tuple | None] = [None, None]
        self.store: dict[int, tuple] = {}


class ResidencyManager:
    """Span-by-span decode for slots whose context exceeds the window.

    Holds the per-slot windowed state plus the jitted per-layer helper
    programs.  Every helper replicates the corresponding piece of the
    engine's mixed program on the SAME batch shapes (flat token width
    ``num_slots + mixed_budget``, per-lane qmax ``mixed_budget + 1``),
    so an engaged slot's logits row is computed by the same ops on the
    same values as an un-windowed engine's — only the attention call is
    substituted, and the span chain is bitwise-equal to the single
    call."""

    def __init__(self, eng, window: int) -> None:
        if window < 4:
            raise ValueError(
                f"ARKS_RESIDENCY_WINDOW_PAGES={window}: the window must "
                "cover 2 hot-tail pages + 2 staging halves (>= 4)")
        self.eng = eng
        self.window = int(window)
        # Staging half width: two halves + two tail pages fit the window.
        self.chunk = max(1, (self.window - 2) // 2)
        self.slots: dict[int, _WindowedSlot] = {}
        self._interpret = jax.default_backend() != "tpu"

        cfg = eng.cfg
        mesh = eng.mesh
        num_slots = eng.ecfg.num_slots
        quantized = eng._cache.quantized
        t_flat = num_slots + eng._mixed_budget
        qmax = eng._mixed_budget + 1
        b_lanes = num_slots
        self._t_flat = t_flat
        self._qmax = qmax
        interpret = self._interpret

        def _embed(params, tokens):
            return tf.embed_lookup(params["embed"], tokens[None],
                                   params["layers"]["attn_norm"].dtype)

        def _head(lp, h, rope_pos, kc, vc, ksc, vsc, tables_tok, write_idx,
                  seq_q_start, layer):
            # Mirrors mixed_step's _block_qkv + the pallas branch of
            # paged_mixed_update_and_attend up to (but not including)
            # the attend: write the new KV rows through the table and
            # return the per-lane query blocks the span calls consume.
            from arks_tpu.ops.attention import _pad_last
            from arks_tpu.ops.paged_attention import (paged_kv_update,
                                                      paged_kv_update_quant)
            q, k, v = tf._block_qkv(h, lp, cfg, rope_pos)
            q, kn, vn = q[0], k[0], v[0]
            d = kc.shape[-1]
            d_model = q.shape[-1]
            if d != d_model:
                q = _pad_last(q, d) * ((d / d_model) ** 0.5)
                kn = _pad_last(kn, d)
                vn = _pad_last(vn, d)
            if quantized:
                kc, vc, ksc, vsc = paged_kv_update_quant(
                    kc, vc, ksc, vsc, kn, vn, write_idx, tables_tok, layer,
                    interpret=interpret)
            else:
                kc, vc = paged_kv_update(kc, vc, kn, vn, write_idx,
                                         tables_tok, layer,
                                         interpret=interpret)
            hkv = cfg.num_kv_heads
            g = cfg.num_heads // hkv
            qg = q.reshape(t_flat, hkv, g, d)
            span = seq_q_start[:, None] + jnp.arange(qmax, dtype=jnp.int32)
            gather_idx = jnp.minimum(span, t_flat - 1)
            qs = jnp.take(qg, gather_idx.reshape(-1), axis=0).reshape(
                b_lanes, qmax, hkv, g, d)
            qs = jnp.transpose(qs, (0, 2, 3, 1, 4))
            return qs, kc, vc, ksc, vsc

        def _tail(h, out_seq, lp, seq_q_start, seq_q_len):
            # The scatter-back + block tail of the mixed layer body.
            hkv = cfg.num_kv_heads
            g = cfg.num_heads // hkv
            d = out_seq.shape[-1]
            rows = jnp.transpose(out_seq, (0, 3, 1, 2, 4)).reshape(
                b_lanes * qmax, hkv, g, d)
            span = seq_q_start[:, None] + jnp.arange(qmax, dtype=jnp.int32)
            q_valid = (jnp.arange(qmax, dtype=jnp.int32)[None]
                       < seq_q_len[:, None])
            scatter_idx = jnp.where(q_valid, span, t_flat)
            out = jnp.zeros((t_flat, hkv, g, d), out_seq.dtype).at[
                scatter_idx.reshape(-1)].set(rows)
            attn = out.reshape(t_flat, cfg.num_heads, d)[..., :cfg.head_dim]
            attn = attn.reshape(1, t_flat, cfg.q_dim)
            attn = tf._constrain(attn, mesh, None, None, tf.AXIS_MODEL)
            return tf._block_tail(h, attn, lp, cfg, mesh, None)

        def _logits(params, h, sample_src):
            h_sel = jnp.take(h[0], sample_src.astype(jnp.int32), axis=0)
            return tf._unembed(h_sel, params, cfg, mesh, None)

        def _sample(sampling, logits, feed_tokens, feed_active, lengths,
                    gtables, want_lp: bool):
            # The mixed program's sampler tail for a plain decode lane
            # (no transient override columns — a jnp.where with an
            # all-False mask is the identity, so skipping the columns is
            # bitwise-equal to the fused program's path).
            sampling = sampler_mod.count_tokens(sampling, feed_tokens,
                                                feed_active)
            ids, eff2 = sampler_mod.sample(logits, sampling, feed_active,
                                           lengths, guide_tables=gtables)
            sampling = sampling._replace(
                key=jnp.where(feed_active[:, None], eff2.key, sampling.key),
                guide_row=jnp.where(feed_active, eff2.guide_row,
                                    sampling.guide_row))
            if want_lp:
                clp, vals, lids = sampler_mod.top_logprobs(logits, ids)
                return ids, clp, vals, lids, sampling
            return ids, sampling

        self._embed_fn = jax.jit(_embed)
        self._head_fn = jax.jit(_head)
        self._tail_fn = jax.jit(_tail)
        self._logits_fn = jax.jit(_logits)
        self.sample_fn = jax.jit(functools.partial(_sample, want_lp=False))
        self.sample_lp_fn = jax.jit(functools.partial(_sample, want_lp=True))

    # -- engagement ----------------------------------------------------

    def engage_pending(self) -> None:
        """Engage every decoding slot whose NEXT write would outgrow the
        window.  Deterministic — driven by the host length mirror, never
        by allocator pressure — so a given request engages at the same
        token on every run."""
        from arks_tpu.engine.paged import pages_needed
        eng = self.eng
        page = eng._page_size()
        for slot in list(eng._slots):
            if slot in self.slots:
                continue
            need = pages_needed(int(eng._lengths[slot]), 1, page,
                                eng._max_pages)
            if need > self.window:
                self.engage(slot)

    def engage(self, slot: int) -> None:
        """Spill the slot's cold page prefix to the host store, keep the
        two newest pages resident, and carve the staging halves out of
        the freed budget.  Shared prefix pages spill by COPY — the
        slot's reference drops but the allocator's index retains them
        for other slots' hits."""
        eng = self.eng
        ws = _WindowedSlot()
        row = list(eng._slot_pages[slot])
        cold = max(len(row) - 2, 0)
        for lo in range(0, cold, self.chunk):
            grp = row[lo: min(lo + self.chunk, cold)]
            kb, vb, ksb, vsb = eng._spill_gather_fn(
                eng._cache, jnp.asarray(grp, jnp.int32))
            kb, vb = np.asarray(kb), np.asarray(vb)
            ksb = None if ksb is None else np.asarray(ksb)
            vsb = None if vsb is None else np.asarray(vsb)
            for j in range(len(grp)):
                ws.store[lo + j] = (
                    kb[:, j: j + 1], vb[:, j: j + 1],
                    None if ksb is None else ksb[:, j: j + 1],
                    None if vsb is None else vsb[:, j: j + 1])
            eng._alloc.decref(grp)
        ws.cold = cold
        ws.tail = row[cold:]
        half_ids = eng._alloc.alloc(2 * self.chunk)
        eng._spill_flush()
        ws.staging = [half_ids[: self.chunk], half_ids[self.chunk:]]
        eng._slot_pages[slot] = list(half_ids) + list(ws.tail)
        self.slots[slot] = ws
        eng.trace.evt("", "residency.engage", "I", slot)
        log.info("residency: slot %d engaged (%d cold pages spilled, "
                 "window=%d, staging=2x%d)", slot, cold, self.window,
                 self.chunk)

    def release(self, slot: int) -> None:
        """Drop the windowed state (device pages are returned by the
        engine's normal _release_slot_pages — slot_pages already lists
        staging + tail)."""
        self.slots.pop(slot, None)

    # -- per-token forward ---------------------------------------------

    def _rotate_tail(self, slot: int, ws: _WindowedSlot,
                     p_total: int) -> None:
        """Grow the hot tail to cover logical page ``p_total - 1``:
        spill the oldest tail page (it is full — two newer pages exist)
        and allocate a fresh device page for the new logical tail."""
        eng = self.eng
        while ws.cold + len(ws.tail) < p_total:
            victim = ws.tail.pop(0)
            kb, vb, ksb, vsb = eng._spill_gather_fn(
                eng._cache, jnp.asarray([victim], jnp.int32))
            ws.store[ws.cold] = (
                np.asarray(kb), np.asarray(vb),
                None if ksb is None else np.asarray(ksb),
                None if vsb is None else np.asarray(vsb))
            eng._alloc.decref([victim])
            ws.cold += 1
            # Span boundaries shifted: every staged half is stale.
            ws.staged = [None, None]
            new = eng._alloc.alloc(1)[0]
            eng._spill_flush()
            ws.tail.append(new)
            eng._tables[slot, ws.cold + len(ws.tail) - 1] = new
            eng._slot_pages[slot] = (ws.staging[0] + ws.staging[1]
                                     + list(ws.tail))

    def _ensure_staged(self, ws: _WindowedSlot, i: int, lo: int, hi: int,
                       kc, vc, ksc, vsc):
        """Scatter cold span [lo, hi) into staging half ``i % 2`` unless
        the half already holds it.  Issued async (the device stream
        orders it before any attend issued after) — calling this for
        span i+1 right before attending span i is the prefetch
        overlap."""
        half = i % 2
        if ws.staged[half] == (lo, hi):
            return kc, vc, ksc, vsc
        eng = self.eng
        eng.trace.evt("", "residency.prefetch", "B", (lo, hi))
        n = hi - lo
        pad = self.chunk - n
        blocks = [ws.store[j] for j in range(lo, hi)]
        kb = np.concatenate([b[0] for b in blocks] + [blocks[-1][0]] * pad,
                            axis=1)
        vb = np.concatenate([b[1] for b in blocks] + [blocks[-1][1]] * pad,
                            axis=1)
        ksb = vsb = None
        if blocks[0][2] is not None:
            ksb = np.concatenate(
                [b[2] for b in blocks] + [blocks[-1][2]] * pad, axis=1)
            vsb = np.concatenate(
                [b[3] for b in blocks] + [blocks[-1][3]] * pad, axis=1)
        pages = np.array(ws.staging[half], np.int32)
        cache = tf.PagedKVCache(k=kc, v=vc, k_scale=ksc, v_scale=vsc)
        cache, _ = eng._restore_fn(cache, jax.device_put(kb),
                                   jax.device_put(vb),
                                   None if ksb is None
                                   else jax.device_put(ksb),
                                   None if vsb is None
                                   else jax.device_put(vsb),
                                   jnp.asarray(pages),
                                   jnp.asarray(n, jnp.int32))
        ws.staged[half] = (lo, hi)
        eng.metrics.residency_prefetch_pages_total.inc(n)
        eng.trace.evt("", "residency.prefetch", "E", (lo, hi))
        return cache.k, cache.v, cache.k_scale, cache.v_scale

    def _span_tables(self, slot: int, ws: _WindowedSlot, lo: int, hi: int,
                     half: int | None) -> jnp.ndarray:
        """Temp block tables for one span: the slot's row maps logical
        pages [lo, hi) to the staging half (cold spans) or the resident
        tail (half None).  Only [page_lo, page_hi) entries are ever
        read — the rest stay zero."""
        eng = self.eng
        tbl = np.zeros_like(eng._tables)
        if half is None:
            tbl[slot, lo:hi] = ws.tail[: hi - lo]
        else:
            tbl[slot, lo:hi] = ws.staging[half][: hi - lo]
        return jnp.asarray(tbl)

    def forward(self, slot: int) -> jnp.ndarray:
        """One decode token for an engaged slot: the mixed program's
        layer stack on the engine's standard flat batch shape, with the
        attend replaced by the span chain.  Returns the ``[B, V]``
        logits (only the slot's row is meaningful); the engine runs the
        sampler tail and fans the token out."""
        from arks_tpu.ops.paged_attention import paged_mixed_attention
        eng = self.eng
        ws = self.slots[slot]
        cfg = eng.cfg
        page = eng._page_size()
        num_slots = eng.ecfg.num_slots
        L = int(eng._lengths[slot])
        p_total = L // page + 1
        self._rotate_tail(slot, ws, p_total)

        t_flat = self._t_flat
        sentinel = eng._park_sentinel()
        tokens = np.zeros((t_flat,), np.int32)
        token_slot = np.full((t_flat,), -1, np.int32)
        token_pos = np.full((t_flat,), sentinel, np.int32)
        tokens[0] = eng._last_token[slot]
        token_slot[0] = slot
        token_pos[0] = L
        sample_src = np.zeros((num_slots,), np.int32)
        seq_q_start = np.zeros((num_slots,), np.int32)
        seq_q_len = np.zeros((num_slots,), np.int32)
        seq_pos_start = np.zeros((num_slots,), np.int32)
        seq_q_len[slot] = 1
        seq_pos_start[slot] = L

        cover = eng._max_pages * page
        token_slot_d = jnp.asarray(token_slot)
        tables_tok = jnp.take(jnp.asarray(eng._tables),
                              jnp.maximum(token_slot_d, 0), axis=0)
        write_idx = jnp.where(token_slot_d < 0, cover,
                              jnp.asarray(token_pos))
        rope_pos = jnp.minimum(jnp.asarray(token_pos), cover - 1)[None]
        pos0 = jnp.asarray(seq_pos_start)
        qlen = jnp.asarray(seq_q_len)
        qstart = jnp.asarray(seq_q_start)

        spans = [(lo, min(lo + self.chunk, ws.cold))
                 for lo in range(0, ws.cold, self.chunk)]
        h = self._embed_fn(eng.params, jnp.asarray(tokens))
        cache = eng._cache
        kc, vc, ksc, vsc = cache.k, cache.v, cache.k_scale, cache.v_scale
        layers = eng.params["layers"]
        for l in range(cfg.num_layers):
            lp = jax.tree.map(lambda x: x[l], layers)
            lyr = jnp.asarray(l, jnp.int32)
            qs, kc, vc, ksc, vsc = self._head_fn(
                lp, h, rope_pos, kc, vc, ksc, vsc, tables_tok, write_idx,
                qstart, lyr)
            carry = None
            for i, (lo, hi) in enumerate(spans):
                kc, vc, ksc, vsc = self._ensure_staged(ws, i, lo, hi,
                                                       kc, vc, ksc, vsc)
                if i + 1 < len(spans):
                    # Prefetch the NEXT cold span into the other half
                    # before this span's attend — the H2D scatter
                    # overlaps the attend on the device stream.
                    kc, vc, ksc, vsc = self._ensure_staged(
                        ws, i + 1, *spans[i + 1], kc, vc, ksc, vsc)
                plo = np.zeros((num_slots,), np.int32)
                phi = np.zeros((num_slots,), np.int32)
                plo[slot], phi[slot] = lo, hi
                eng.trace.evt("", "residency.attend", "B", (lo, hi))
                carry = paged_mixed_attention(
                    qs, kc, vc, self._span_tables(slot, ws, lo, hi, i % 2),
                    pos0, qlen, lyr, k_scale=ksc, v_scale=vsc,
                    interpret=self._interpret, page_lo=jnp.asarray(plo),
                    page_hi=jnp.asarray(phi), carry_state=carry,
                    emit_state=True)
                eng.trace.evt("", "residency.attend", "E")
                eng.metrics.residency_spans_total.inc(1)
            plo = np.zeros((num_slots,), np.int32)
            phi = np.zeros((num_slots,), np.int32)
            plo[slot], phi[slot] = ws.cold, p_total
            eng.trace.evt("", "residency.attend", "B",
                          (ws.cold, p_total))
            out = paged_mixed_attention(
                qs, kc, vc, self._span_tables(slot, ws, ws.cold, p_total,
                                              None),
                pos0, qlen, lyr, k_scale=ksc, v_scale=vsc,
                interpret=self._interpret, page_lo=jnp.asarray(plo),
                page_hi=jnp.asarray(phi), carry_state=carry,
                emit_state=False)
            eng.trace.evt("", "residency.attend", "E")
            eng.metrics.residency_spans_total.inc(1)
            h = self._tail_fn(h, out, lp, qstart, qlen)
        eng._cache = tf.PagedKVCache(k=kc, v=vc, k_scale=ksc, v_scale=vsc)
        return self._logits_fn(eng.params, h, jnp.asarray(sample_src))
