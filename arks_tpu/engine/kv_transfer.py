"""KV-cache wire format for prefill/decode disaggregation.

The reference gets cross-worker KV transfer for free from SGLang's
disaggregation backend (/root/reference/internal/controller/
arksdisaggregatedapplication_controller.go:1672-1724 only wires
``--disaggregation-mode`` flags).  The TPU-native build owns the transfer:

- On one host (and in tests) the KV rides this compact binary format over
  HTTP between the prefill and decode server processes.
- Across TPU slices the same PrefilledState can instead be moved with
  ``jax.device_put`` onto the decode slice's mesh (ICI/DCN does the actual
  transport); the wire format is the host-RAM fallback and the e2e-testable
  path.

Layout: ``AKV1 | u32 header_len | header JSON | tensor bytes...`` where the
header carries {meta, tensors: [{dtype, shape}]} and tensor bytes are
concatenated raw buffers in header order.  bfloat16 is first-class (ml_dtypes
backs the numpy dtype).
"""

from __future__ import annotations

import json
import struct
from typing import Any

import numpy as np

MAGIC = b"AKV1"


def pack(meta: dict[str, Any], tensors: list[np.ndarray]) -> bytes:
    header = {
        "meta": meta,
        "tensors": [{"dtype": str(t.dtype), "shape": list(t.shape)}
                    for t in tensors],
    }
    hbytes = json.dumps(header).encode()
    parts = [MAGIC, struct.pack("<I", len(hbytes)), hbytes]
    for t in tensors:
        parts.append(np.ascontiguousarray(t).tobytes())
    return b"".join(parts)


# Canonical field order for a pool-native prefix page block.  Disk files
# (DiskPrefixTier) and the peer-fetch wire (GET /v1/cache/blocks/{digest})
# both serialize blocks through pack_block/unpack_block so every tier and
# every replica agrees on one byte layout — which is what keeps a
# spill → disk → peer-fetch → restore round trip bit-exact by construction.
BLOCK_FIELDS = ("k", "v", "k_scale", "v_scale")


def pack_block(digest: bytes, epoch: str, block: dict[str, np.ndarray]) -> bytes:
    """One prefix page block as an AKV1 message.  ``epoch`` is the pool
    layout signature digest: a reader on a different layout (other model,
    page size, or kv dtype) must reject the block, not reinterpret it."""
    fields = [f for f in BLOCK_FIELDS if block.get(f) is not None]
    return pack({"digest": digest.hex(), "epoch": epoch, "fields": fields},
                [block[f] for f in fields])


def unpack_block(buf: bytes, digest: bytes,
                 epoch: str) -> dict[str, np.ndarray]:
    """Validate and decode one pack_block message.  Raises ValueError on
    any mismatch — digest (content), epoch (pool layout), or field set —
    so a stale or cross-layout block can never be served as a hit."""
    meta, tensors = unpack(buf)
    if meta.get("digest") != digest.hex():
        raise ValueError(f"block digest mismatch: {meta.get('digest')!r}")
    if meta.get("epoch") != epoch:
        raise ValueError(f"block epoch mismatch: {meta.get('epoch')!r} "
                         f"!= {epoch!r}")
    fields = meta.get("fields") or []
    if len(fields) != len(tensors) or any(f not in BLOCK_FIELDS
                                          for f in fields):
        raise ValueError(f"bad block fields: {fields!r}")
    return dict(zip(fields, tensors))


def unpack(buf: bytes) -> tuple[dict[str, Any], list[np.ndarray]]:
    if buf[:4] != MAGIC:
        raise ValueError("bad KV transfer magic")
    (hlen,) = struct.unpack_from("<I", buf, 4)
    header = json.loads(buf[8:8 + hlen].decode())
    tensors = []
    off = 8 + hlen
    for spec in header["tensors"]:
        dtype = np.dtype(spec["dtype"])
        shape = tuple(spec["shape"])
        n = int(np.prod(shape)) * dtype.itemsize
        tensors.append(np.frombuffer(buf, dtype=dtype, count=int(np.prod(shape)),
                                     offset=off).reshape(shape))
        off += n
    if off != len(buf):
        raise ValueError(f"KV transfer length mismatch: {off} != {len(buf)}")
    return header["meta"], tensors
