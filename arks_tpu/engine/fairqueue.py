"""Tenant-fair, bounded admission queue (weighted deficit round-robin).

Drop-in replacement for the scheduler's old ``queue.PriorityQueue`` of
``(priority, seq, Request)`` tuples, keeping the surface the engine uses
(``put`` / ``get`` / ``get_nowait`` / ``empty`` / ``qsize`` raising the
stdlib ``queue.Empty``) while fixing its two overload failures:

- **tenant blindness** — one key's burst used to starve every other key
  in the same SLO tier.  Now each (tier, tenant) pair holds its own FIFO
  and, within a tier, tenants are served by deficit round-robin: each
  visit credits ``weight x ARKS_FAIR_QUANTUM_TOKENS`` and a request is
  released only when the tenant's deficit covers its token cost
  (prompt + max_tokens) — so admission bandwidth, measured in TOKENS,
  converges to the configured weights no matter how requests are sized
  or how hard one tenant floods.  Strict tier ordering is preserved:
  tier N admits nothing while tier N-1 has entries, exactly as before.
- **unboundedness** — sustained overload used to grow the queue without
  limit.  ``ARKS_QUEUE_MAX`` / ``ARKS_QUEUE_TENANT_MAX`` cap the queue
  (whole and per tenant); a bounded ``put`` past a cap raises
  ``QueueFullError`` carrying a drain-rate-derived Retry-After, on the
  CALLER's (server) thread — the scheduler never sees the reject.

Invariance contracts (the hard gates for any scheduler change):

- with a single tenant, the pick order is byte-for-byte the old
  tier-then-FIFO order — untenanted deployments see NO schedule change;
- replay/swap-resume entries (priority < 0) ride a separate urgent heap
  served before everything, exempt from bounds, fairness, and aging —
  they were already decoding before their fault/preemption;
- ``ARKS_FAIR=0`` degrades to the old flat priority heap (the bench
  control arm), bounds still enforceable;
- engine-internal re-queues (fault survivors, preempt replay, guide /
  model unparks) use unbounded ``put`` — a request the engine already
  accepted is never shed by the ladder.

Aging (``ARKS_QUEUE_AGING_S``) generalizes the PR-10 machinery
per-tenant: an entry's effective tier is ``base - elapsed/aging_s``
(floored at 0); promotions move it to the better tier's (tenant) FIFO in
arrival order, so a starved batch request still climbs one rung per
window under sustained latency-tier load.

jax-free by design (the ``knobs``-and-stdlib diet of arks_tpu.slo): the
HTTP layers import the error type without dragging in the engine.
``arkslint`` covers ``put``/``get_nowait``/``head_prio``/``age_tick`` as
hot-path roots — the pick path holds only its own mutex, never blocks.
"""

from __future__ import annotations

import heapq
import queue as _stdq
import threading
import time
from collections import deque

from arks_tpu import tenancy
from arks_tpu.utils import knobs

# Retry-After bounds: never tell a client "0" (thundering re-herd) and
# never more than 2 minutes (past that, capacity — not backoff — is the
# problem and the operator alert rows in docs/monitoring.md own it).
RETRY_AFTER_MIN_S = 1
RETRY_AFTER_MAX_S = 120
RETRY_AFTER_DEFAULT_S = 5
# Drain-rate sample window: timestamps of the most recent pops.
_DRAIN_SAMPLES = 64


class QueueFullError(Exception):
    """A bounded put hit a cap.  ``scope`` is ``"queue"`` (total cap —
    the whole backend is saturated, HTTP 503) or ``"tenant"`` (one
    tenant's cap — the others are fine, HTTP 429)."""

    def __init__(self, scope: str, tenant: str, depth: int, limit: int,
                 retry_after: int) -> None:
        super().__init__(
            f"admission queue full ({scope}): depth {depth} >= {limit}")
        self.scope = scope
        self.tenant = tenant
        self.depth = depth
        self.limit = limit
        self.retry_after = retry_after


def request_cost(req) -> int:
    """Admission token cost: prefill (prompt) plus the decode budget the
    request ASKS for.  Charging max_tokens up front is deliberately
    pessimistic — a tenant cannot buy extra admission bandwidth by
    requesting huge decode budgets it never uses only at the price of
    its own future turns."""
    return max(1, len(req.prompt_ids) + int(req.params.max_tokens))


class FairQueue:
    """Per-(tier, tenant) WDRR admission queue; see the module doc.

    Thread model: server threads ``put``; the engine thread pops and
    ages; ``qsize``/``empty``/``saturation`` read cross-thread.  One
    mutex guards everything — every critical section is a few dict/deque
    operations, no blocking calls inside."""

    def __init__(self, fair: bool | None = None,
                 quantum: int | None = None,
                 weights: dict[str, float] | None = None,
                 max_total: int | None = None,
                 max_tenant: int | None = None) -> None:
        self.fair = knobs.get_bool("ARKS_FAIR") if fair is None else fair
        q = (knobs.get_int("ARKS_FAIR_QUANTUM_TOKENS") if quantum is None
             else quantum)
        if q < 1:
            raise ValueError(
                f"ARKS_FAIR_QUANTUM_TOKENS={q}: must be >= 1")
        self.quantum = q
        self.weights = (tenancy.weights_from_env() if weights is None
                        else dict(weights))
        mt = knobs.get_int("ARKS_QUEUE_MAX") if max_total is None \
            else max_total
        mp = knobs.get_int("ARKS_QUEUE_TENANT_MAX") if max_tenant is None \
            else max_tenant
        if mt < 0 or mp < 0:
            raise ValueError(
                f"ARKS_QUEUE_MAX={mt} / ARKS_QUEUE_TENANT_MAX={mp}: "
                "must be >= 0 (0 = unbounded)")
        self.max_total = mt
        self.max_tenant = mp
        self._mutex = threading.Lock()
        self._not_empty = threading.Condition(self._mutex)
        self._count = 0
        # Urgent lane: priority < 0 (fault replayers at prio - 2**20).
        self._urgent: list = []
        # Fair mode: tier -> tenant -> deque[(seq, req, cost, base_prio)],
        # plus the per-tier round-robin ring and per-(tier, tenant) token
        # deficit.  _fresh marks "the ring head has not yet received its
        # quantum this visit" (DRR serves a tenant until its deficit runs
        # dry, then rotates).
        self._buckets: dict[int, dict[str, deque]] = {}
        self._ring: dict[int, deque] = {}
        self._deficit: dict[tuple[int, str], float] = {}
        self._fresh: dict[int, bool] = {}
        # Plain mode (ARKS_FAIR=0): the old flat heap.
        self._heap: list = []
        # Per-tenant depth (both modes — the ARKS_QUEUE_TENANT_MAX
        # denominator and the saturation report).
        self._tenant_depth: dict[str, int] = {}
        # Drain-rate estimate: monotonic timestamps of recent pops.
        self._pops: deque = deque(maxlen=_DRAIN_SAMPLES)

    # ---------------------------------------------------------- helpers

    @staticmethod
    def _tenant(req) -> str:
        return getattr(req, "tenant", None) or tenancy.DEFAULT_TENANT

    def _weight(self, tenant: str) -> float:
        return tenancy.weight_of(self.weights, tenant)

    # -------------------------------------------------------------- put

    def put(self, item, bounded: bool = False) -> None:
        """Enqueue ``(priority, seq, request)``.  ``bounded=True`` (the
        external-admission path) enforces the caps and raises
        ``QueueFullError``; internal re-queues leave it False."""
        prio, seq, req = item
        tenant = self._tenant(req)
        with self._not_empty:
            if bounded and prio >= 0:
                if self.max_total and self._count >= self.max_total:
                    raise QueueFullError(
                        "queue", tenant, self._count, self.max_total,
                        self._retry_after_locked())
                td = self._tenant_depth.get(tenant, 0)
                if self.max_tenant and td >= self.max_tenant:
                    raise QueueFullError(
                        "tenant", tenant, td, self.max_tenant,
                        self._retry_after_locked())
            if prio < 0:
                heapq.heappush(self._urgent, (prio, seq, req))
            elif not self.fair:
                heapq.heappush(self._heap, (prio, seq, req))
                self._tenant_depth[tenant] = \
                    self._tenant_depth.get(tenant, 0) + 1
            else:
                tier = int(prio)
                bucket = self._buckets.setdefault(tier, {})
                if tenant not in bucket:
                    bucket[tenant] = deque()
                    self._ring.setdefault(tier, deque()).append(tenant)
                bucket[tenant].append((seq, req, request_cost(req), prio))
                self._tenant_depth[tenant] = \
                    self._tenant_depth.get(tenant, 0) + 1
            self._count += 1
            self._not_empty.notify()

    # -------------------------------------------------------------- get

    def get(self, timeout: float | None = None):
        """Blocking pop (the engine's idle path).  Raises queue.Empty on
        timeout, matching the stdlib contract the scheduler handles."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._not_empty:
            while self._count == 0:
                if deadline is None:
                    self._not_empty.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._not_empty.wait(remaining):
                        if self._count == 0:
                            raise _stdq.Empty
            return self._pop_locked()

    def get_nowait(self):
        with self._mutex:
            if self._count == 0:
                raise _stdq.Empty
            return self._pop_locked()

    def _pop_locked(self):
        if self._urgent:
            item = heapq.heappop(self._urgent)
        elif not self.fair:
            prio, seq, req = heapq.heappop(self._heap)
            self._note_served(self._tenant(req))
            item = (prio, seq, req)
        else:
            tier = min(t for t, b in self._buckets.items() if b)
            item = self._pop_tier(tier)
        self._count -= 1
        self._pops.append(time.monotonic())
        return item

    def _note_served(self, tenant: str) -> None:
        left = self._tenant_depth.get(tenant, 1) - 1
        if left > 0:
            self._tenant_depth[tenant] = left
        else:
            self._tenant_depth.pop(tenant, None)

    def _pop_tier(self, tier: int):
        """One WDRR pick from a non-empty tier.  Each ring visit credits
        one quantum x weight; when a full pass over the ring serves
        nothing (every head costs more than its tenant's deficit), the
        minimum number of whole rounds needed is credited to every
        tenant at once — same schedule as spinning the ring that many
        times, without the spinning."""
        ring = self._ring[tier]
        bucket = self._buckets[tier]
        scanned = 0
        while True:
            tenant = ring[0]
            dq = bucket.get(tenant)
            if not dq:
                ring.popleft()
                bucket.pop(tenant, None)
                self._deficit.pop((tier, tenant), None)
                self._fresh[tier] = True
                continue
            key = (tier, tenant)
            if self._fresh.get(tier, True):
                self._deficit[key] = (self._deficit.get(key, 0.0)
                                      + self.quantum * self._weight(tenant))
                self._fresh[tier] = False
            seq, req, cost, base = dq[0]
            if self._deficit[key] >= cost:
                dq.popleft()
                self._deficit[key] -= cost
                self._note_served(tenant)
                if not dq:
                    bucket.pop(tenant, None)
                    ring.popleft()
                    self._deficit.pop(key, None)
                    self._fresh[tier] = True
                    if not bucket:
                        self._buckets.pop(tier, None)
                        self._ring.pop(tier, None)
                        self._fresh.pop(tier, None)
                return (tier, seq, req)
            ring.rotate(-1)
            self._fresh[tier] = True
            scanned += 1
            if scanned >= len(ring):
                # Full fruitless pass: fast-forward the rounds.
                rounds = min(
                    -(-(bucket[t][0][2] - self._deficit.get((tier, t), 0.0))
                      // (self.quantum * self._weight(t)))
                    for t in ring if bucket.get(t))
                rounds = max(1.0, rounds)
                for t in ring:
                    if bucket.get(t):
                        k = (tier, t)
                        self._deficit[k] = (self._deficit.get(k, 0.0)
                                            + rounds * self.quantum
                                            * self._weight(t))
                self._fresh[tier] = False
                scanned = 0

    # ----------------------------------------------------- introspection

    def empty(self) -> bool:
        return self._count == 0

    def qsize(self) -> int:
        return self._count

    def head_prio(self):
        """Effective priority of the pick head (None when empty) — the
        preemption comparator (_preempt_victims)."""
        with self._mutex:
            if self._urgent:
                return self._urgent[0][0]
            if not self.fair:
                return self._heap[0][0] if self._heap else None
            tiers = [t for t, b in self._buckets.items() if b]
            return min(tiers) if tiers else None

    def tenant_depth(self, tenant: str) -> int:
        with self._mutex:
            return self._tenant_depth.get(tenant, 0)

    # ------------------------------------------------------------- aging

    def age_tick(self, now: float, aging_s: float) -> None:
        """Re-derive effective tiers (base - elapsed/aging_s, floored at
        0) and move promoted entries to the better tier's tenant FIFO in
        arrival (seq) order.  The caller throttles (engine._queue_age_tick
        keeps the old cadence); urgent entries never age."""
        if not aging_s:
            return
        with self._mutex:
            if not self.fair:
                changed = False
                for i, (prio, seq, req) in enumerate(self._heap):
                    if prio < 0:
                        continue
                    base = req.params.priority
                    eff = max(0, base - int((now - req.arrival_time)
                                            / aging_s))
                    if eff != prio:
                        self._heap[i] = (eff, seq, req)
                        changed = True
                if changed:
                    heapq.heapify(self._heap)
                return
            moves = []
            for tier, bucket in self._buckets.items():
                if tier <= 0:
                    continue
                for tenant, dq in bucket.items():
                    for entry in dq:
                        seq, req, cost, base = entry
                        eff = max(0, base - int((now - req.arrival_time)
                                                / aging_s))
                        if eff < tier:
                            moves.append((tier, tenant, entry, eff))
            for tier, tenant, entry, eff in moves:
                bucket = self._buckets.get(tier, {})
                dq = bucket.get(tenant)
                if dq is None:
                    continue
                try:
                    dq.remove(entry)
                except ValueError:
                    continue
                if not dq:
                    bucket.pop(tenant, None)
                    try:
                        self._ring[tier].remove(tenant)
                    except (KeyError, ValueError):
                        pass
                    self._deficit.pop((tier, tenant), None)
                    if not bucket:
                        self._buckets.pop(tier, None)
                        self._ring.pop(tier, None)
                        self._fresh.pop(tier, None)
                target = self._buckets.setdefault(eff, {})
                if tenant not in target:
                    target[tenant] = deque()
                    self._ring.setdefault(eff, deque()).append(tenant)
                tdq = target[tenant]
                seq = entry[0]
                idx = len(tdq)
                for i, e in enumerate(tdq):
                    if e[0] > seq:
                        idx = i
                        break
                tdq.insert(idx, entry)

    # -------------------------------------------------------- saturation

    def _drain_rate_locked(self) -> float:
        """Recent pops per second (0.0 = no evidence yet)."""
        if len(self._pops) < 2:
            return 0.0
        span = self._pops[-1] - self._pops[0]
        if span <= 0:
            return 0.0
        return (len(self._pops) - 1) / span

    def _retry_after_locked(self, depth: int | None = None) -> int:
        d = self._count if depth is None else depth
        rate = self._drain_rate_locked()
        if rate <= 0:
            return RETRY_AFTER_DEFAULT_S
        return int(min(RETRY_AFTER_MAX_S,
                       max(RETRY_AFTER_MIN_S, -(-d // rate))))

    def retry_after(self) -> int:
        """Seconds a rejected client should back off: current depth over
        the observed drain rate, clamped to [1, 120]."""
        with self._mutex:
            return self._retry_after_locked()

    def saturation(self) -> dict:
        """The overload signal /readiness and shed-response headers
        export: depth, caps, distinct waiting tenants, drain rate, and
        the 0-1 fraction of ARKS_QUEUE_MAX in use (0.0 unbounded)."""
        with self._mutex:
            frac = (self._count / self.max_total) if self.max_total else 0.0
            return {
                "queue_depth": self._count,
                "queue_max": self.max_total,
                "tenants_waiting": len(self._tenant_depth),
                "drain_per_s": round(self._drain_rate_locked(), 3),
                "saturation": round(min(1.0, frac), 4),
                "fair": bool(self.fair),
            }
