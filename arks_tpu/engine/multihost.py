"""Multi-host serving: leader dispatch replication.

Under ``jax.distributed`` every process must execute the SAME jitted
computations in the same order — collectives hang otherwise.  The engine's
scheduler runs only on the leader (process 0, the one that serves HTTP);
follower processes mirror its device dispatches.

Mechanism: before each device dispatch the leader broadcasts a tiny
(op, host-args) record over a TCP channel; followers execute the identical
jit call against their OWN device state (params/cache/sampling are
constructed identically on every process — same spec, same seed or same
checkpoint shards).  Device-side lockstep then comes for free: the leader's
host-sync on a dispatch result cannot complete until followers join the
collectives.

This replaces what the reference gets from Ray/NCCL inside vLLM containers
(/root/reference/internal/controller/arksapplication_controller.go:941-1014
only wires rendezvous env vars; the engine brings its own execution model —
SURVEY.md §2.4).  The channel is a trusted intra-gang link (same security
domain as the NCCL/gloo sockets themselves).

Wire format: 4-byte big-endian length + pickled (op, payload) tuple, after
a mutual shared-secret handshake (the secret comes from the gang's env —
ARKS_GANG_SECRET — injected by whoever launches the gang).  Followers prove
identity with the secret; the leader proves itself with a derived ack, so a
port-squatting process can neither take a follower slot nor feed a follower
pickles.  Beyond the handshake the link is trusted, like the gloo/NCCL
sockets beside it.
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import socket
import struct
import threading
import time

from arks_tpu.utils import knobs

log = logging.getLogger("arks_tpu.multihost")

DISPATCH_PORT_OFFSET = 1  # default dispatch port = coordinator port + 1


def dispatch_address(coordinator: str) -> tuple[str, int]:
    """Dispatch endpoint: explicit ARKS_DISPATCH_ADDRESS when the launcher
    reserved one (the local gang driver does — derived ports can collide on
    a shared host), else coordinator port + 1 (fine where each process has
    its own network namespace, e.g. one pod per host)."""
    explicit = knobs.get_str("ARKS_DISPATCH_ADDRESS")
    if explicit:
        host, _, port = explicit.partition(":")
        return host, int(port)
    host, _, port = coordinator.partition(":")
    return host, int(port) + DISPATCH_PORT_OFFSET


def _secret() -> bytes:
    return knobs.get_str("ARKS_GANG_SECRET").encode()


def _leader_ack(secret: bytes) -> bytes:
    return hashlib.sha256(secret + b"/leader-ack").digest()


def _send_msg(sock: socket.socket, obj) -> None:
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv_msg(sock: socket.socket):
    hdr = _recv_exact(sock, 4)
    (n,) = struct.unpack(">I", hdr)
    return pickle.loads(_recv_exact(sock, n))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("dispatch channel closed")
        buf += chunk
    return buf


class DispatchLeader:
    """Leader side: accepts follower connections, broadcasts dispatches.

    Worker-wedge detection: followers HEARTBEAT on the channel's return
    direction (it is otherwise leader→follower only), and a per-connection
    reader thread tracks the last-seen timestamp.  ``follower_health``
    surfaces staleness to the serving readiness gate (so a hung-but-
    connected worker drops the gang out of Service endpoints within a
    bounded window), and a monitor thread ESCALATES past
    ``ARKS_GANG_WEDGE_FATAL_S``: the leader exits so the gang driver
    restarts the whole group — the same shared-fate policy as a broken
    channel (engine._emit), and the behavior the reference buys from LWS
    RecreateGroupOnPodRestart (arksapplication_controller.go:581-584),
    which only reacts to pod DEATH; the heartbeat also catches hangs."""

    def __init__(self, bind_host: str, port: int, num_followers: int,
                 accept_timeout_s: float = 120.0):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((bind_host, port))
        self._srv.listen(num_followers)
        self._conns: list[socket.socket] = []
        self._lock = threading.Lock()
        self._hb_lock = threading.Lock()
        self._last_hb: list[float] = []
        self._wedge_fatal_s = knobs.get_float("ARKS_GANG_WEDGE_FATAL_S")
        secret = _secret()
        deadline = time.monotonic() + accept_timeout_s
        while len(self._conns) < num_followers:
            self._srv.settimeout(max(0.1, deadline - time.monotonic()))
            try:
                conn, addr = self._srv.accept()
            except socket.timeout:
                raise TimeoutError(
                    f"only {len(self._conns)}/{num_followers} followers "
                    "connected to the dispatch channel")
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Handshake: follower proves the gang secret; a stray connection
            # (port scanner) must not consume a follower slot.
            try:
                conn.settimeout(10)
                proof = _recv_exact(conn, 32)
                if proof != hashlib.sha256(secret).digest():
                    raise ConnectionError("bad gang secret")
                conn.sendall(_leader_ack(secret))
                conn.settimeout(None)
            except (OSError, ConnectionError) as e:
                log.warning("rejecting dispatch connection from %s: %s",
                            addr, e)
                conn.close()
                continue
            log.info("follower connected from %s", addr)
            self._conns.append(conn)
            self._last_hb.append(time.monotonic())
        for i, conn in enumerate(self._conns):
            threading.Thread(target=self._hb_reader, args=(i, conn),
                             name=f"dispatch-hb-{i}", daemon=True).start()
        if self._conns and self._wedge_fatal_s > 0:
            threading.Thread(target=self._wedge_monitor,
                             name="dispatch-wedge-monitor",
                             daemon=True).start()

    def _hb_reader(self, idx: int, conn: socket.socket) -> None:
        """Drain the follower's return direction (heartbeats only)."""
        while True:
            try:
                op, _ = _recv_msg(conn)
            except (OSError, ConnectionError):
                return  # channel death is handled by broadcast/sendall
            if op == "hb":
                with self._hb_lock:
                    self._last_hb[idx] = time.monotonic()

    def _wedge_monitor(self) -> None:
        while True:
            time.sleep(max(self._wedge_fatal_s / 8, 0.25))
            health = self.follower_health(self._wedge_fatal_s)
            if health["stale"]:
                log.critical(
                    "follower(s) %s heartbeat stale > %.0fs (hung, not "
                    "dead); exiting so the gang driver restarts the whole "
                    "group", health["stale"], self._wedge_fatal_s)
                os._exit(71)

    def follower_health(self, stale_after_s: float) -> dict:
        """Heartbeat ages per follower; ``stale`` lists followers not heard
        from within ``stale_after_s`` (the readiness gate's input)."""
        now = time.monotonic()
        with self._hb_lock:
            ages = [now - t for t in self._last_hb]
        return {
            "followers": len(ages),
            "max_heartbeat_age_s": round(max(ages, default=0.0), 3),
            "stale": [i for i, a in enumerate(ages) if a > stale_after_s],
        }

    def broadcast(self, op: str, payload: dict) -> None:
        # Serialize ONCE: insert_kv payloads carry whole KV tensors.
        data = pickle.dumps((op, payload), protocol=pickle.HIGHEST_PROTOCOL)
        framed = struct.pack(">I", len(data)) + data
        with self._lock:
            for conn in self._conns:
                conn.sendall(framed)

    def close(self) -> None:
        with self._lock:
            for conn in self._conns:
                try:
                    _send_msg(conn, ("stop", {}))
                except OSError:
                    pass
                conn.close()
            self._conns.clear()
        self._srv.close()


class DispatchFollower:
    """Follower side: mirrors the leader's dispatches onto a local engine.

    Holds the transient cross-op state the leader keeps in locals (the last
    prefill's KV) and executes each op with this process's own device state.
    """

    def __init__(self, engine, leader_host: str, port: int,
                 connect_timeout_s: float = 120.0):
        import jax

        self.engine = engine
        self._jax = jax
        # Pipelined decode replay: the follower threads its OWN device
        # state between "decode_pipe" ops (the leader cannot broadcast
        # token values it never fetched); a fresh op re-seeds it.
        self._pipe_state = None
        self._pipe_cols = None
        secret = _secret()
        deadline = time.monotonic() + connect_timeout_s
        while True:
            try:
                self._sock = socket.create_connection((leader_host, port),
                                                      timeout=5)
                # Mutual handshake: prove the gang secret, then require the
                # leader's derived ack — never unpickle bytes from an
                # unauthenticated peer (a port squatter could otherwise
                # feed arbitrary pickles = code execution).
                self._sock.settimeout(10)
                self._sock.sendall(hashlib.sha256(secret).digest())
                ack = _recv_exact(self._sock, 32)
                if ack != _leader_ack(secret):
                    raise ConnectionError("leader failed gang-secret handshake")
                self._sock.settimeout(None)
                break
            except OSError:
                sock = getattr(self, "_sock", None)
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.5)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _hb_loop(self, interval_s: float) -> None:
        """Send liveness beats on the channel's return direction.  A
        separate thread from the dispatch loop ON PURPOSE: a worker wedged
        inside a dispatch (deadlocked collective, stuck DMA) keeps its
        socket open but stops beating only if the whole process stops —
        SIGSTOP, OOM-thrash, runaway GC — which is exactly the "hung, not
        dead" class the leader's wedge monitor exists for.  jit compiles
        and device waits release the GIL, so beats flow through them."""
        while not self._hb_stop.is_set():
            try:
                with self._send_lock:
                    _send_msg(self._sock, ("hb", {}))
            except (OSError, ConnectionError):
                return
            self._hb_stop.wait(interval_s)

    def run(self) -> None:
        """Dispatch loop; returns when the leader sends stop/disconnects."""
        import jax
        import jax.numpy as jnp

        from arks_tpu.engine import sampler as sampler_mod

        eng = self.engine
        self._hb_stop = threading.Event()
        self._send_lock = threading.Lock()
        threading.Thread(
            target=self._hb_loop,
            args=(knobs.get_float("ARKS_GANG_HB_INTERVAL"),),
            name="dispatch-hb", daemon=True).start()
        try:
            self._run_inner(eng, jax, jnp)
        finally:
            self._hb_stop.set()

    def _run_inner(self, eng, jax, jnp) -> None:
        while True:
            try:
                op, p = _recv_msg(self._sock)
            except (ConnectionError, OSError):
                log.info("dispatch channel closed; follower exiting")
                return
            if op == "stop":
                return
            try:
                self._apply(eng, jax, jnp, op, p)
            except Exception as e:
                # A deterministic device fault raises here AND on the
                # leader; the leader's recovery broadcasts "recover" +
                # "reset" next, which rebuilds this process's device state
                # too.  (A follower-only fault diverges instead — the next
                # collective then hangs and jax's coordination service
                # kills the gang, which the driver restarts.)
                from arks_tpu.engine import faults as faults_mod
                faults_mod.swallowed("follower_dispatch", e)
                log.exception("dispatch op %r failed; awaiting reset", op)

    @staticmethod
    def _shape_args(p: dict, jnp, sampler_mod, eng):
        """Follower-side (bias_ids, bias_vals, sup_ids, min_first, guide,
        guide_row, guide_tables) jnp args from an emit payload, defaulting
        to the empty columns — ONE definition, or leader/follower replay
        diverges per op."""
        import numpy as _np
        nb = sampler_mod.LOGIT_BIAS_MAX
        ns = sampler_mod.SUPPRESS_MAX
        return (
            jnp.asarray(p.get("bias_ids", _np.full((nb,), -1, _np.int32))),
            jnp.asarray(p.get("bias_vals", _np.zeros((nb,), _np.float32))),
            jnp.asarray(p.get("sup_ids", _np.full((ns,), -1, _np.int32))),
            jnp.asarray(p.get("min_first", 0), jnp.int32),
            jnp.asarray(p.get("guide", -1), jnp.int32),
            jnp.asarray(p.get("guide_row", 0), jnp.int32),
            eng._guide_dev)

    def _apply(self, eng, jax, jnp, op: str, p: dict) -> None:
        from arks_tpu.engine import sampler as sampler_mod

        if op in ("admit_batch", "admit_batch_lp"):
            # Fused batched admission: prefill + sample + insert + set_slot
            # for M prompts in one dispatch (mirrors the leader's
            # _admit_fn exactly).  Paged engines receive the page rows by
            # value — the allocator runs on the leader only.
            import numpy as _np
            keys = jnp.asarray(_np.stack(
                [sampler_mod.np_prng_key(s) for s in p["seeds"]]))
            fn = (eng._admit_lp_fn if op == "admit_batch_lp"
                  else eng._admit_fn)
            pages = p.get("pages")
            out = fn(eng.params, eng._cache, eng._sampling,
                     jnp.asarray(p["tokens"]),
                     jnp.asarray(p["lengths"], jnp.int32),
                     jnp.asarray(p["slots"], jnp.int32),
                     None if pages is None else jnp.asarray(pages),
                     None if pages is None else jnp.asarray(
                         p["n_pages"], jnp.int32),
                     jnp.asarray(p["temperature"], jnp.float32),
                     jnp.asarray(p["top_p"], jnp.float32),
                     jnp.asarray(p["top_k"], jnp.int32), keys,
                     jnp.asarray(p["presence"], jnp.float32),
                     jnp.asarray(p["frequency"], jnp.float32),
                     jnp.asarray(p["bias_ids"], jnp.int32),
                     jnp.asarray(p["bias_vals"], jnp.float32),
                     jnp.asarray(p["sup_ids"], jnp.int32),
                     jnp.asarray(p["min_first"], jnp.int32),
                     jnp.asarray(p["min_until"], jnp.int32),
                     jnp.asarray(p.get("guide",
                                       _np.full((len(p["seeds"]),), -1,
                                                _np.int32)), jnp.int32),
                     jnp.asarray(p.get("guide_row",
                                       _np.zeros((len(p["seeds"]),),
                                                 _np.int32)), jnp.int32),
                     eng._guide_dev)
            eng._cache, eng._sampling = out[-4], out[-3]
        elif op == "chunk_paged":
            _logits, eng._cache = eng._chunk_fn(
                eng.params, eng._cache, jnp.asarray(p["tables_row"]),
                jnp.asarray(p["tokens"]),
                jnp.asarray(p["start"], jnp.int32),
                jnp.asarray(p["valid"], jnp.int32))
            self._last_logits = _logits
        elif op == "insert_pages":
            eng._cache = eng._insert_pages_fn(
                eng._cache, jnp.asarray(p["k"]), jnp.asarray(p["v"]),
                jnp.asarray(p["pages"]),
                jnp.asarray(p["n_pages"], jnp.int32))
        elif op in ("prefill_detached", "prefill_detached_lp"):
            # Disaggregated prefill on a gang: mirror the replicated-KV
            # prefill program (the leader materializes the full block for
            # the wire transfer; followers just keep collectives aligned).
            key = jnp.asarray(sampler_mod.np_prng_key(p["seed"]))
            fn = (eng._prefill_detached_lp_fn if op.endswith("_lp")
                  else eng._prefill_detached_fn)
            out = fn(eng.params, jnp.asarray(p["tokens"]),
                     jnp.asarray([p["length"]], jnp.int32),
                     jnp.float32(p["temperature"]),
                     jnp.float32(p["top_p"]),
                     jnp.int32(p["top_k"]), key,
                     *self._shape_args(p, jnp, sampler_mod, eng))
            jax.block_until_ready(out[0])
        elif op == "insert_kv":
            # Disaggregated decode: KV arrives by value (the leader got
            # it over the wire, not from a local prefill).
            eng._cache = eng._insert_fn(
                eng._cache, jnp.asarray(p["k"]), jnp.asarray(p["v"]),
                jnp.asarray(p["slot"]))
        elif op == "set_slot":
            from arks_tpu.engine.types import SamplingParams

            key = jnp.asarray(sampler_mod.np_prng_key(p["seed"]))
            params = SamplingParams(
                temperature=p["temperature"], top_p=p["top_p"],
                top_k=p["top_k"],
                presence_penalty=p.get("presence", 0.0),
                frequency_penalty=p.get("frequency", 0.0),
                logit_bias=tuple((int(t), float(b))
                                 for t, b in p.get("logit_bias", ())),
                min_tokens=p.get("min_tokens", 0),
                stop_token_ids=tuple(p.get("stop_ids", ())),
                ignore_eos=p.get("ignore_eos", False))
            eng._apply_set_slot(p["slot"], params,
                                self._jax.random.fold_in(key, 1),
                                num_prompt=p.get("num_prompt", 0),
                                guide=p.get("guide", -1),
                                guide_row=p.get("guide_row", 0))
        elif op == "recover":
            # Leader entered fault recovery: log the surviving-request
            # manifest (the streams about to be replayed through ordinary
            # chunk/set_slot ops) and drop the threaded pipeline state —
            # the next decode_pipe op after a recovery is always fresh.
            self._pipe_state = None
            self._pipe_cols = None
            log.warning(
                "leader fault recovery (phase=%s kind=%s): replaying %d "
                "surviving request(s): %s", p.get("phase"), p.get("kind"),
                len(p.get("manifest", ())),
                [rid for rid, _, _ in p.get("manifest", ())])
        elif op == "clear_penalties":
            eng._sampling = eng._clear_pen_fn(
                eng._sampling, jnp.asarray(p["slot"], jnp.int32))
        elif op == "chunk":
            _logits, eng._cache = eng._chunk_fn(
                eng.params, eng._cache, jnp.asarray(p["slot"], jnp.int32),
                jnp.asarray(p["tokens"]),
                jnp.asarray(p["start"], jnp.int32),
                jnp.asarray(p["valid"], jnp.int32))
            self._last_logits = _logits
        elif op in ("sample_one", "sample_one_lp"):
            key = jnp.asarray(sampler_mod.np_prng_key(p["seed"]))
            fn = (eng._sample_one_lp_fn if op == "sample_one_lp"
                  else eng._sample_one_fn)
            fn(self._last_logits,
               jnp.float32(p["temperature"]),
               jnp.float32(p["top_p"]),
               jnp.int32(p["top_k"]), key,
               *self._shape_args(p, jnp, sampler_mod, eng))
        elif op == "decode":
            fn = eng._decode_lp_fn if p.get("lp") else eng._decode_fn
            tables = p.get("tables")
            eng._cache, eng._sampling, toks = fn(
                eng.params, eng._cache, jnp.asarray(p["tokens"]),
                jnp.asarray(p["lengths"]), eng._sampling,
                None if tables is None else jnp.asarray(tables),
                eng._guide_dev)
            # Host-sync like the leader, but via block_until_ready —
            # a follower may not address every shard of toks.
            jax.block_until_ready(toks)
        elif op == "decode_pipe":
            # Pipelined decode (ARKS_PIPELINE_DEPTH): the op stream carries
            # NO host token values — a fresh op ships the host-built state
            # (pipeline entry), every later op consumes this process's own
            # device arrays threaded from the previous dispatch, exactly
            # like the leader.  No host sync either: lockstep rides the
            # collectives inside the program, and blocking here would
            # re-introduce on the follower the per-step stall the pipeline
            # exists to remove.
            if p.get("fresh"):
                self._pipe_state = (jnp.asarray(p["tokens"]),
                                    jnp.asarray(p["lengths"], jnp.int32),
                                    jnp.asarray(p["alive"]))
                cols = [jnp.asarray(p["stop_ids"]),
                        jnp.asarray(p["dead_len"], jnp.int32)]
                if "spec_enable" in p:
                    cols.append(jnp.asarray(p["spec_enable"]))
                self._pipe_cols = tuple(cols)
            elif self._pipe_state is None:
                raise RuntimeError(
                    "decode_pipe without fresh state: leader/follower "
                    "pipeline streams diverged")
            tables = p.get("tables")
            tables = None if tables is None else jnp.asarray(tables)
            # Same program resolution as the leader (_pipe_call prefers
            # this process's warmed executable when one exists).
            if eng._draft_cfg is not None:
                # Spec engines thread the draft cache too; the program
                # returns (cache, dcache, sampling, ...).
                out = eng._pipe_call(bool(p.get("lp")), eng.params,
                                     eng._draft_params, eng._cache,
                                     eng._draft_cache, *self._pipe_state,
                                     *self._pipe_cols, eng._sampling,
                                     tables, eng._guide_dev)
                eng._cache, eng._draft_cache, eng._sampling = \
                    out[0], out[1], out[2]
            else:
                out = eng._pipe_call(bool(p.get("lp")), eng.params,
                                     eng._cache, *self._pipe_state,
                                     *self._pipe_cols, eng._sampling,
                                     tables, eng._guide_dev)
                eng._cache, eng._sampling = out[0], out[1]
            self._pipe_state = out[-3:]
        elif op == "mixed":
            # Unified mixed prefill+decode dispatch (ARKS_MIXED_STEP): the
            # whole batch description arrives by value — followers never
            # need the leader's scheduler state, only the identical jit
            # call (override keys included, so gang sampling stays in
            # lockstep without the guide/seed registries).
            fn = eng._mixed_lp_fn if p.get("lp") else eng._mixed_fn
            out = fn(eng.params, eng._cache, eng._sampling,
                     jnp.asarray(p["tokens"]), jnp.asarray(p["token_slot"]),
                     jnp.asarray(p["token_pos"]), jnp.asarray(p["tables"]),
                     jnp.asarray(p["feed_tokens"]),
                     jnp.asarray(p["feed_active"]),
                     jnp.asarray(p["lengths"]),
                     jnp.asarray(p["sample_src"]),
                     jnp.asarray(p["seq_q_start"]),
                     jnp.asarray(p["seq_q_len"]),
                     jnp.asarray(p["seq_pos_start"]),
                     jnp.asarray(p["ov_mask"]), jnp.asarray(p["ov_temp"]),
                     jnp.asarray(p["ov_top_p"]), jnp.asarray(p["ov_top_k"]),
                     jnp.asarray(p["ov_key"]),
                     jnp.asarray(p["ov_bias_ids"]),
                     jnp.asarray(p["ov_bias_vals"]),
                     jnp.asarray(p["ov_sup"]),
                     jnp.asarray(p["ov_min_until"]),
                     jnp.asarray(p["ov_guide"]),
                     jnp.asarray(p["ov_guide_row"]), eng._guide_dev)
            eng._cache, eng._sampling = out[-2], out[-1]
            jax.block_until_ready(out[0])
        elif op == "draft_prefill":
            # Speculative decoding: the draft cache mirrors the leader's
            # (identical draft params: same spec + same seed/shards).
            eng._draft_cache = eng._draft_prefill_fn(
                eng._draft_params, eng._draft_cache,
                jnp.asarray(p["tokens"]),
                jnp.asarray([p["length"]], jnp.int32),
                jnp.asarray(p["slot"]))
        elif op == "spec_mixed":
            # Spec-mixed dispatch (draft propose + ragged verify + accept
            # inside the mixed program): the whole batch description
            # arrives by value like "mixed"; key lockstep rides the shared
            # _sampling state, which both sides evolve with the kernel's
            # deterministic splits.
            fn = (eng._spec_mixed_lp_fn if p.get("lp")
                  else eng._spec_mixed_fn)
            out = fn(eng.params, eng._draft_params, eng._cache,
                     eng._draft_cache, eng._sampling,
                     jnp.asarray(p["tokens"]), jnp.asarray(p["token_slot"]),
                     jnp.asarray(p["token_pos"]), jnp.asarray(p["tables"]),
                     jnp.asarray(p["feed_tokens"]),
                     jnp.asarray(p["feed_active"]),
                     jnp.asarray(p["lengths"]),
                     jnp.asarray(p["sample_src"]),
                     jnp.asarray(p["seq_q_start"]),
                     jnp.asarray(p["seq_q_len"]),
                     jnp.asarray(p["seq_pos_start"]),
                     jnp.asarray(p["spec_enable"]),
                     jnp.asarray(p["ov_mask"]), jnp.asarray(p["ov_temp"]),
                     jnp.asarray(p["ov_top_p"]), jnp.asarray(p["ov_top_k"]),
                     jnp.asarray(p["ov_key"]),
                     jnp.asarray(p["ov_bias_ids"]),
                     jnp.asarray(p["ov_bias_vals"]),
                     jnp.asarray(p["ov_sup"]),
                     jnp.asarray(p["ov_min_until"]),
                     jnp.asarray(p["ov_guide"]),
                     jnp.asarray(p["ov_guide_row"]), eng._guide_dev)
            eng._cache, eng._draft_cache, eng._sampling = \
                out[-3], out[-2], out[-1]
            jax.block_until_ready(out[1])
        elif op == "guides":
            # Guide-table sync: load the leader's host tables and refresh
            # the device copies NOW — ops after this one in the channel
            # may reference the new rows.
            eng.guides.load_state(p["class_ids"], p["trans"], p["version"])
            eng._guide_dev = (jnp.asarray(eng.guides.class_ids),
                              jnp.asarray(eng.guides.trans))
            eng._guide_ver = eng.guides.version
        elif op == "reset":
            eng._reset_device_state()
        else:
            log.warning("unknown dispatch op %r", op)
