"""Host-side page allocator for the paged device KV cache.

The device side is arks_tpu.ops.paged_attention (pool + block tables);
this is the authority over which pool page holds what:

- **Free list + refcounts**: a page is free (refcount 0), private (held by
  one slot), or shared (held by several slots and/or the prefix index).
- **Prefix index**: chained content digests (same scheme as
  engine.prefix_cache) -> page id, LRU-ordered.  Registering a prompt's
  full pages costs NOTHING on the device — the pages are already there;
  a later prompt with the same prefix just points its table at them.
  This replaces the host-resident PrefixKVCache's device->host harvest
  copies and PCIe re-upload on hits, and because pages/tables are plain
  dispatch arguments, it works on multi-host gangs (the old design's
  single-host restriction — VERDICT round 2 item 2).
- **Eviction**: allocation prefers the free list; under pressure it evicts
  LRU index-retained pages (refcount held only by the index).  The pool is
  sized so active slots can always allocate: slots*pages_per_slot worst
  case is reserved, retention rides the surplus + an explicit extra.

Thread-safety: engine thread only (like the rest of the scheduler state);
the disaggregated prefill path never touches the allocator.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

# THE one hash-chaining implementation lives in arks_tpu.prefix_sketch
# (jax-free, so the router can share it for tokenize-free scoring); the
# allocator's prefix index and the host PrefixKVCache keep keying the
# same bytes through these re-exports.
from arks_tpu.prefix_sketch import chain_digests, iter_chain_digests

__all__ = ["OutOfPagesError", "iter_chain_digests", "chain_digests",
           "pages_needed", "mixed_grid_steps", "mixed_kv_bytes",
           "PageAllocator"]


class OutOfPagesError(RuntimeError):
    pass


def pages_needed(length: int, rows: int, page: int, max_pages: int) -> int:
    """Block-table entries a slot needs before a dispatch burst writing
    ``rows`` rows from position ``length`` (rows = K per fused dispatch x
    the in-flight pipeline depth: with ARKS_PIPELINE_DEPTH dispatches
    issued ahead of host resolution, the host must pre-own pages for
    EVERY in-flight dispatch's write window, not just the next one).

    Clamped to ``max_pages``: near the cache cap the host's lagged view
    can overshoot the window, but the device's dead_len mask retires the
    slot before any write lands past max_cache_len — growing the table
    beyond its row width would corrupt the neighbouring slot's row."""
    return min((length + rows - 1) // page + 1, max_pages)


def mixed_grid_steps(pos_start, q_len, *, page: int, block_q: int,
                     num_qb: int, max_pages: int) -> tuple[int, int]:
    """(ideal, dense) page-compute step counts for one mixed dispatch —
    the host-side numpy mirror of ops.paged_attention.build_mixed_work_list.

    ``ideal`` is what the ragged work-list grid executes: each active
    (seq, q_block) item visits exactly its own causal page count, q_len=0
    lanes and padding items visit zero.  ``dense`` is the legacy grid's
    S * num_qb * max_pages (every lane pays the worst case).  The counter
    pair metrics these feed (mixed_grid_steps_total vs _ideal_total)
    describes the grid PLAN, so it is meaningful under either
    ARKS_MIXED_GRID mode and either attention impl.

    Inputs must already be host numpy arrays (the engine's issue path
    holds them that way) — no device fetches happen here; the hot-path
    guard covers this function."""
    pos = pos_start.astype(np.int64, copy=False)
    ql = q_len.astype(np.int64, copy=False)
    q_lo = (np.arange(num_qb, dtype=np.int64) * block_q)[None, :]
    active = q_lo < ql[:, None]
    kv_end = np.where(active, pos[:, None] + np.minimum(q_lo + block_q,
                                                        ql[:, None]), 0)
    pages = np.minimum(-(-kv_end // page), max_pages)
    ideal = int(pages.sum())
    dense = int(pos.shape[0]) * num_qb * max_pages
    return ideal, dense


def mixed_kv_bytes(pos_start, q_len, *, page: int, block_q: int,
                   num_qb: int, max_pages: int, hkv: int,
                   page_head_bytes: int) -> tuple[int, int]:
    """(actual, ideal) KV bytes one mixed dispatch streams from HBM — the
    host-side mirror of the ragged kernel's DMA schedule, feeding the
    mixed_kv_bytes_total / _ideal_total counter pair.

    ``actual``: every active (seq, q_block) item re-streams its own
    causal page prefix, and the head-group split is a pure partition of
    the head axis (n_groups x head_group == hkv), so the grouped and
    ungrouped schedules move the same bytes AT EQUAL block_q — the
    grouped win arrives entirely through the larger tuned block_q
    (fewer q-blocks, fewer prefix re-streams), which is why this mirror
    takes the PLAN's block_q/num_qb rather than a head-group count.

    ``ideal``: each distinct causal page crosses the wire exactly once
    per dispatch (what a perfect cross-q-block-sharing schedule would
    move).  actual/ideal is the waste ratio docs/monitoring.md alerts
    on.

    ``page_head_bytes``: bytes one (page, head) KV block moves — K + V
    (+ scale rows when quantized); the engine derives it from the pool
    dtypes so int4 packing halves it automatically."""
    pos = pos_start.astype(np.int64, copy=False)
    ql = q_len.astype(np.int64, copy=False)
    q_lo = (np.arange(num_qb, dtype=np.int64) * block_q)[None, :]
    active = q_lo < ql[:, None]
    kv_end = np.where(active, pos[:, None] + np.minimum(q_lo + block_q,
                                                        ql[:, None]), 0)
    pages = np.minimum(-(-kv_end // page), max_pages)
    actual = int(pages.sum()) * hkv * page_head_bytes
    seq_end = np.where(ql > 0, pos + ql, 0)
    seq_pages = np.minimum(-(-seq_end // page), max_pages)
    ideal = int(seq_pages.sum()) * hkv * page_head_bytes
    return actual, ideal


class PageAllocator:
    def __init__(self, num_pages: int, page: int, on_evict=None) -> None:
        self.page = page
        self.num_pages = num_pages
        self._free = list(range(num_pages - 1, -1, -1))
        self._ref = [0] * num_pages
        # digest -> page id; LRU order (oldest first).  The index holds ONE
        # reference on each registered page.
        self._index: "OrderedDict[bytes, int]" = OrderedDict()
        self._page_digest: dict[int, bytes] = {}
        # Spill hook: called as on_evict(digest, page) the moment an
        # index-retained page is evicted, BEFORE the page can reach the
        # free list — the engine uses it to queue the page for an async
        # D2H spill into the host-RAM prefix tier while its content is
        # still guaranteed un-overwritten on device.  Must not raise and
        # must not call back into the allocator (it runs mid-alloc).
        self.on_evict = on_evict
        # Membership mirror for the routing sketch: server threads need a
        # consistent view of WHICH digests are indexed, while _index stays
        # engine-thread-only.  The mirror tracks membership changes
        # (register/evict), not recency touches — so the hot decode path
        # (match's move_to_end) never takes the lock, and the version only
        # moves when an exported sketch would actually change.
        self._mirror_lock = threading.Lock()
        self._mirror: "OrderedDict[bytes, None]" = OrderedDict()
        self.index_version = 0
        # Stats (mirrored into EngineMetrics by the engine).
        self.hit_tokens = 0
        self.query_tokens = 0

    # -- allocation ----------------------------------------------------

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def retained_pages(self) -> int:
        return len(self._index)

    def alloc(self, n: int) -> list[int]:
        """n fresh pages (refcount 1 each).  Evicts LRU retained pages as
        needed; raises OutOfPagesError when even eviction cannot satisfy
        (pool mis-sized)."""
        while len(self._free) < n and self._index:
            self._evict_lru()
        if len(self._free) < n:
            raise OutOfPagesError(
                f"need {n} pages, {len(self._free)} free and nothing "
                "evictable — pool too small for the active slots")
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._ref[p] = 1
        return out

    def _evict_lru(self) -> None:
        digest, pg = self._index.popitem(last=False)
        del self._page_digest[pg]
        with self._mirror_lock:
            self._mirror.pop(digest, None)
            self.index_version += 1
        if self.on_evict is not None:
            self.on_evict(digest, pg)
        self._ref[pg] -= 1
        if self._ref[pg] == 0:
            self._free.append(pg)

    def incref(self, pages) -> None:
        for p in pages:
            self._ref[p] += 1

    def decref(self, pages) -> None:
        for p in pages:
            self._ref[p] -= 1
            if self._ref[p] == 0:
                self._free.append(p)
            elif self._ref[p] < 0:
                raise AssertionError(f"page {p} refcount underflow")

    # -- prefix index --------------------------------------------------

    def match(self, digests: list[bytes]) -> list[int]:
        """Pages for the longest indexed digest-chain prefix; each matched
        page gets a caller reference (incref) and an LRU touch."""
        pages = []
        for d in digests:
            pg = self._index.get(d)
            if pg is None:
                break
            self._index.move_to_end(d)
            self._ref[pg] += 1
            pages.append(pg)
        return pages

    def register(self, digests: list[bytes], pages: list[int]) -> None:
        """Put (digest, page) pairs into the index.  The index takes ONE
        reference per newly-registered page; already-indexed digests keep
        their existing page (the caller's duplicate page stays owned by the
        caller alone and is freed on its decref).  A page already indexed
        under a DIFFERENT digest is skipped: _page_digest is a one-to-one
        reverse map, and overwriting it would leave the old digest's index
        entry stale — evicting either digest would then delete the other's
        reverse entry and a later eviction would KeyError mid-alloc (and
        the refcount held for the old entry would leak)."""
        for d, pg in zip(digests, pages):
            if d in self._index:
                self._index.move_to_end(d)
                continue
            if self._page_digest.get(pg, d) != d:
                continue
            self._index[d] = pg
            self._page_digest[pg] = d
            self._ref[pg] += 1
            with self._mirror_lock:
                self._mirror[d] = None
                self._mirror.move_to_end(d)
                self.index_version += 1

    def index_snapshot(self) -> tuple[list[bytes], int]:
        """Indexed digests (registration order, oldest first) plus the
        membership version — the tier-0 input to the routing sketch.
        Safe from any thread; the engine thread only pays the mirror lock
        on membership changes, never per match."""
        with self._mirror_lock:
            return list(self._mirror), self.index_version

    # -- stats ---------------------------------------------------------

    def record_query(self, num_tokens: int, hit: int) -> None:
        self.query_tokens += num_tokens
        self.hit_tokens += hit

    @property
    def hit_rate(self) -> float:
        return self.hit_tokens / self.query_tokens if self.query_tokens else 0.0
