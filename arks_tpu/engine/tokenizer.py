"""Tokenizer adapters + incremental stream decoding.

Real deployments load the HuggingFace tokenizer shipped in the ArksModel
storage volume (the reference mounts the same /models PVC into runtime
containers — /root/reference/internal/controller/arksmodel_controller.go:377).
Tests and CPU rigs use ByteTokenizer, which needs no assets.

Each tokenizer provides ``make_stream_decoder()`` returning an object with
``push(ids) -> str`` / ``flush() -> str`` that emits text incrementally in
amortized O(tokens) total (NOT re-decoding the full history per chunk):

- ByteTokenizer: exact, via codecs' incremental UTF-8 decoder.
- HFTokenizer: the convert_ids_to_tokens / convert_tokens_to_string
  prefix-window algorithm (the standard trick for BPE/SentencePiece, where
  decode(a+b) != decode(a)+decode(b) because of leading-space handling).
"""

from __future__ import annotations

import codecs
import logging
from typing import Protocol, Sequence


class StreamDecoder(Protocol):
    def push(self, ids: Sequence[int]) -> str: ...
    def flush(self) -> str: ...


class Tokenizer(Protocol):
    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...
    def apply_chat_template(self, messages: list[dict],
                            tools: list | None = None) -> list[int]: ...
    def make_stream_decoder(self) -> StreamDecoder: ...
    @property
    def eos_token_ids(self) -> tuple[int, ...]: ...


# ---------------------------------------------------------------------------
# Byte-level tokenizer (tests / no-asset rigs)
# ---------------------------------------------------------------------------


class ByteTokenizer:
    """Bytes + a few specials. Vocab: 0=eos/pad, 1=bos, 2..257 = bytes."""

    OFFSET = 2

    def __init__(self) -> None:
        self.vocab_size = 258

    @property
    def eos_token_ids(self) -> tuple[int, ...]:
        return (0,)

    def encode(self, text: str) -> list[int]:
        return [b + self.OFFSET for b in text.encode("utf-8")]

    def decode(self, ids: Sequence[int]) -> str:
        # Total over any id: random-weight test models emit ids beyond the
        # byte range; wrap them instead of raising.
        data = bytes((i - self.OFFSET) % 256 for i in ids if i >= self.OFFSET)
        return data.decode("utf-8", errors="replace")

    def apply_chat_template(self, messages: list[dict],
                            tools: list | None = None) -> list[int]:
        parts = []
        if tools:
            from arks_tpu.server.tools import tools_system_text
            parts.append(f"<system>{tools_system_text(tools)}</system>")
        for m in messages:
            body = m.get("content") or ""
            for tc in m.get("tool_calls") or ():
                fn = tc.get("function", {})
                body += (f"<tool_call>{{\"name\": \"{fn.get('name')}\", "
                         f"\"arguments\": {fn.get('arguments')}}}"
                         "</tool_call>")
            parts.append(f"<{m['role']}>{body}</{m['role']}>")
        return [1] + self.encode("".join(parts))

    def make_stream_decoder(self) -> StreamDecoder:
        return _ByteStreamDecoder(self)


class _ByteStreamDecoder:
    """Exact incremental UTF-8 decode; O(1) state."""

    def __init__(self, tok: ByteTokenizer) -> None:
        self._tok = tok
        self._dec = codecs.getincrementaldecoder("utf-8")("replace")

    def push(self, ids: Sequence[int]) -> str:
        data = bytes((i - ByteTokenizer.OFFSET) % 256
                     for i in ids if i >= ByteTokenizer.OFFSET)
        return self._dec.decode(data, final=False)

    def flush(self) -> str:
        return self._dec.decode(b"", final=True)


# ---------------------------------------------------------------------------
# HuggingFace tokenizer
# ---------------------------------------------------------------------------


class HFTokenizer:
    """transformers.AutoTokenizer adapter (loaded from the model volume)."""

    def __init__(self, path: str) -> None:
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path)

    @property
    def eos_token_ids(self) -> tuple[int, ...]:
        ids = []
        if self._tok.eos_token_id is not None:
            ids.append(self._tok.eos_token_id)
        return tuple(ids)

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(self, messages: list[dict],
                            tools: list | None = None) -> list[int]:
        if tools:
            try:
                # Modern templates (Qwen2.5, Llama-3.1, Hermes) render
                # tools natively.
                return self._tok.apply_chat_template(
                    messages, tools=tools, add_generation_prompt=True)
            except Exception as e:
                # Template without tools support: declare them in a system
                # message using the hermes convention the parser expects.
                from arks_tpu.engine.faults import swallowed
                swallowed("chat_template_tools", e)
                from arks_tpu.server.tools import tools_system_text
                messages = ([{"role": "system",
                              "content": tools_system_text(tools)}]
                            + list(messages))
        return self._tok.apply_chat_template(messages, add_generation_prompt=True)

    def make_stream_decoder(self) -> StreamDecoder:
        return _HFStreamDecoder(self._tok)


class _HFStreamDecoder:
    """Prefix-window incremental detokenization.

    Keeps token strings (not ids) and two offsets: ``prefix`` marks text
    already emitted; ``read`` trails by a small window so multi-token
    characters/leading-space merges resolve before emission.  Per push, only
    the window (not the whole history) is re-stringified — amortized O(1)
    per token.
    """

    def __init__(self, tok) -> None:
        self._tok = tok
        # transformers recomputes all_special_tokens per access; cache it —
        # this runs once per streamed chunk on the hot path.
        self._special = set(tok.all_special_tokens)
        self._tokens: list[str] = []
        self._prefix = 0  # token index: everything before is emitted
        self._emitted_in_window = 0  # chars of window text already emitted

    def _window_text(self) -> str:
        return self._tok.convert_tokens_to_string(self._tokens[self._prefix:])

    def push(self, ids: Sequence[int]) -> str:
        if not ids:
            return ""
        new = self._tok.convert_ids_to_tokens(list(ids))
        self._tokens.extend(t for t in new if t not in self._special)
        text = self._window_text()
        safe_end = len(text) - 1 if text.endswith("�") else len(text)
        out = text[self._emitted_in_window:safe_end]
        self._emitted_in_window = max(self._emitted_in_window, safe_end)
        # Advance the window once it's large and cleanly decoded, so each
        # push re-stringifies a bounded number of tokens.
        if len(self._tokens) - self._prefix > 16 and not text.endswith("�"):
            self._prefix = len(self._tokens)
            self._emitted_in_window = 0
        return out

    def flush(self) -> str:
        text = self._window_text()
        out = text[self._emitted_in_window:]
        self._emitted_in_window = len(text)
        return out


def load_tokenizer(path: str | None, strict: bool = False) -> Tokenizer:
    """Best available tokenizer for a model dir (same policy as
    ``weights.load_params``: real assets > byte-level fallback).

    A directory with real weights but no tokenizer assets is usually a
    misconfiguration (wrong mount, partial download); pass ``strict=True``
    to fail instead of falling back.
    """
    if path is None:
        return ByteTokenizer()
    import os

    probed = ("tokenizer.json", "tokenizer_config.json", "tokenizer.model")
    if any(os.path.exists(os.path.join(path, f)) for f in probed):
        return HFTokenizer(path)
    msg = (f"no tokenizer assets in {path!r} "
           f"(looked for {', '.join(probed)})")
    if strict:
        raise FileNotFoundError(msg)
    logging.getLogger("arks_tpu.tokenizer").warning(
        "%s — falling back to byte-level tokenizer", msg)
    return ByteTokenizer()


class IncrementalDetokenizer:
    """Convenience wrapper: one stream decoder bound to a tokenizer."""

    def __init__(self, tokenizer: Tokenizer) -> None:
        self._dec = tokenizer.make_stream_decoder()

    def push(self, ids: Sequence[int]) -> str:
        return self._dec.push(ids)

    def flush(self) -> str:
        return self._dec.flush()
