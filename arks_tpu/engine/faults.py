"""Fault taxonomy, injection, and escalation for the serving engine.

DeepServe (PAPERS.md, arxiv 2501.14417) treats fast request-preserving
recovery as a first-class serving requirement; this module is the engine's
vocabulary for it:

- **StepFault**: the typed wrapper every scheduler-phase fault is raised
  as.  It carries *blast-radius attribution* — which request(s) the
  failing operation was doing work for (``culprits``) — plus any request
  state that would otherwise be stranded in locals when the stack unwinds
  (``survivors``).  The engine's recovery loop quarantines only the
  culprits (bounded per-request retry budget, ``ARKS_FAULT_RETRIES``) and
  token-replays everyone else.
- **FaultInjector**: the ``ARKS_FAULT_INJECT`` chaos hook.  Spec:
  comma-separated ``phase:nth:kind`` entries (``decode:3:runtime`` = raise
  a RuntimeError at the 3rd decode-dispatch issue).  Threaded through
  every dispatch/resolve/alloc point so the chaos suite can kill any
  scheduler phase deterministically.  Phases: ``decode`` (any
  decode-carrying model dispatch issue, incl. pipelined and mixed),
  ``resolve`` (their host-sync tails), ``admit`` / ``admit_resolve``
  (fused admissions), ``chunk`` (chunked-prefill dispatch), ``replay``
  (recovery re-admission), ``pages`` (page-table growth/alloc),
  ``guide`` (guide-table upload), ``spec`` (speculative dispatch),
  ``preempt`` (preemptive-swap spill issue/harvest and victim resume —
  culprit is the preempted/resuming request only), ``disk_spill``
  (tier-2 disk spill issue — serves no request, so nobody's retry
  budget burns), ``peer_fetch`` (disk/peer prefix-block fetch resolve —
  culprit is the fetching request only), ``residency`` (windowed-
  residency span step: engage/spill/prefetch/forward — culprits are
  the window-engaged requests only), ``resize`` (elastic topology
  resize seams: drain / reshard / resume — fired at a fully drained
  boundary after every stream was preempted to the host, so NOBODY is
  quarantined; drain/reshard faults recover at the old shape, a
  resume fault at the new one).
  Kinds: ``runtime``, ``value``, ``oom`` (RESOURCE_EXHAUSTED-shaped
  RuntimeError), ``hang`` (sleeps ``ARKS_FAULT_HANG_S``, default 3600 —
  the watchdog-escalation fixture).
- **Watchdog**: detects a wedged device dispatch — a ``step()`` that has
  not returned within ``ARKS_DISPATCH_DEADLINE_S`` — flips the engine
  state to ``wedged`` (readiness then 503s), dumps the in-flight
  diagnostics, and escalates to ``os._exit(70)`` so the pod supervisor
  restarts the process (the same shared-fate policy as a broken gang
  dispatch channel, engine._emit).  Disabled at 0 (the default): the
  deadline must be set ABOVE the worst first-dispatch jit compile, which
  also runs inside step().
- **swallowed()**: the sanctioned route for the few handlers that
  intentionally swallow an exception (platform capability probes, debug
  introspection).  tests/test_fault_guard.py statically REQUIRES every
  ``except Exception`` under arks_tpu/engine/ to re-raise or call into
  this module — a silent swallow cannot merge.
"""

from __future__ import annotations

import logging
import os
import threading
import time

from arks_tpu.utils import knobs

log = logging.getLogger("arks_tpu.faults")

# Engine state codes surfaced by the engine_state gauge (docs/monitoring.md).
STATE_SERVING = 0
STATE_RECOVERING = 1
STATE_WEDGED = 2
STATE_CODES = {"serving": STATE_SERVING, "recovering": STATE_RECOVERING,
               "wedged": STATE_WEDGED}


class StepFault(Exception):
    """A scheduler-phase fault with blast-radius attribution.

    ``phase``     the scheduler phase that faulted (metric label).
    ``kind``      coarse failure class (metric label; see classify()).
    ``culprits``  request ids the failing operation was doing work FOR —
                  the quarantine set (retry-budget accounting).
    ``survivors`` request-state descriptors (engine._Survivor) that only
                  lived in the failing frame's locals: un-registered
                  admissions, not-yet-replayed recovery snapshots.  The
                  recovery loop re-admits them; without this they would be
                  stranded (client blocks forever).
    """

    def __init__(self, phase: str, kind: str, culprits=(), survivors=(),
                 message: str = ""):
        super().__init__(message or f"engine fault in phase {phase!r} ({kind})")
        self.phase = phase
        self.kind = kind
        self.culprits = tuple(culprits)
        self.survivors = list(survivors)


class InjectedFault(RuntimeError):
    """Raised by FaultInjector.fire(); distinguishable in logs/tests."""


def classify(exc: BaseException) -> str:
    """Coarse fault kind for the engine_faults_total metric label.
    Deliberately low-cardinality: dashboards alert on (phase, kind), and
    one label value per exception class would explode the family."""
    if isinstance(exc, StepFault):
        return exc.kind
    msg = f"{type(exc).__name__}: {exc}"
    if "RESOURCE_EXHAUSTED" in msg or isinstance(exc, MemoryError):
        return "oom"
    if isinstance(exc, InjectedFault):
        return "injected"
    if isinstance(exc, (ValueError, TypeError, KeyError, IndexError)):
        return "value"
    if isinstance(exc, OSError):
        return "io"
    return "runtime"


_KINDS = ("runtime", "value", "oom", "hang")


class FaultInjector:
    """ARKS_FAULT_INJECT chaos hook: ``phase:nth:kind[,phase:nth:kind...]``.

    ``nth`` is the 1-based occurrence of ``fire(phase)`` calls for that
    phase; each spec entry fires at most once.  Engine-thread only (the
    counters are unsynchronized on purpose — all fire sites run on the
    scheduler thread)."""

    def __init__(self, spec: str | None = None):
        self._specs: list[list] = []   # [phase, nth, kind, armed]
        self._counts: dict[str, int] = {}
        spec = (knobs.get_str("ARKS_FAULT_INJECT", fallback="") or ""
                ) if spec is None else spec
        if spec:
            for entry in spec.split(","):
                self.arm(entry)

    def arm(self, entry: str) -> None:
        """Add one ``phase:nth:kind`` spec (env parsing and the
        bench/chaos harness's programmatic injection)."""
        entry = entry.strip()
        if not entry:
            return
        parts = entry.split(":")
        if len(parts) != 3:
            raise ValueError(
                f"ARKS_FAULT_INJECT entry {entry!r}: expected phase:nth:kind")
        phase, nth_s, kind = parts
        try:
            nth = int(nth_s)
        except ValueError:
            raise ValueError(
                f"ARKS_FAULT_INJECT entry {entry!r}: nth must be an integer")
        if nth < 1:
            raise ValueError(
                f"ARKS_FAULT_INJECT entry {entry!r}: nth must be >= 1")
        if kind not in _KINDS:
            raise ValueError(
                f"ARKS_FAULT_INJECT entry {entry!r}: kind must be one of "
                f"{_KINDS}")
        self._specs.append([phase, nth, kind, True])

    @property
    def active(self) -> bool:
        return bool(self._specs)

    def fire(self, phase: str) -> None:
        """Count one occurrence of ``phase``; raise if a spec matches."""
        if not self._specs:
            return
        n = self._counts.get(phase, 0) + 1
        self._counts[phase] = n
        for spec in self._specs:
            if spec[3] and spec[0] == phase and spec[1] == n:
                spec[3] = False
                kind = spec[2]
                log.warning("fault injection: phase=%s nth=%d kind=%s",
                            phase, n, kind)
                if kind == "hang":
                    time.sleep(knobs.get_float("ARKS_FAULT_HANG_S"))
                    return
                if kind == "oom":
                    raise InjectedFault(
                        f"RESOURCE_EXHAUSTED (injected at {phase}:{n})")
                if kind == "value":
                    raise ValueError(f"injected fault at {phase}:{n}")
                raise InjectedFault(f"injected fault at {phase}:{n}")


def swallowed(site: str, exc: BaseException | None = None) -> None:
    """Record an INTENTIONALLY swallowed exception (capability probes,
    best-effort introspection).  The one sanctioned alternative to
    re-raising under arks_tpu/engine/ (tests/test_fault_guard.py): the
    debug log keeps the swallow observable without turning a benign probe
    failure into a serving fault."""
    log.debug("swallowed exception at %s: %s", site, exc, exc_info=exc)


class Watchdog:
    """Wedged-dispatch detector: ``heartbeat()`` returns (phase, t0) of
    the in-flight scheduler step (None when idle); if a step overruns the
    deadline the watchdog calls ``on_wedged()`` (flip state/readiness,
    dump diagnostics) and escalates through ``exit_fn(70)`` so the pod
    supervisor restarts the process.  ``exit_fn`` is injectable for
    tests; production uses os._exit — a wedged device call cannot be
    cancelled from Python, so a clean shutdown is not on the table."""

    def __init__(self, deadline_s: float, heartbeat, on_wedged,
                 exit_fn=os._exit):
        self.deadline_s = deadline_s
        self._heartbeat = heartbeat
        self._on_wedged = on_wedged
        self._exit_fn = exit_fn
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="watchdog",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        poll = max(min(self.deadline_s / 4.0, 1.0), 0.02)
        while not self._stop.wait(poll):
            hb = self._heartbeat()
            if hb is None:
                continue
            phase, t0 = hb
            age = time.monotonic() - t0
            if age <= self.deadline_s:
                continue
            log.critical(
                "engine step wedged for %.1fs (> ARKS_DISPATCH_DEADLINE_S="
                "%.1fs) in phase %r; flipping readiness and exiting 70 so "
                "the supervisor restarts the pod", age, self.deadline_s,
                phase)
            try:
                self._on_wedged(phase, age)
            except Exception as e:  # the escalation must not be derailed
                swallowed("watchdog.on_wedged", e)
            self._exit_fn(70)
            return
