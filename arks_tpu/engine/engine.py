"""Continuous-batching inference engine.

This is the component the reference outsources entirely to vLLM/SGLang
containers (it only writes their command lines —
/root/reference/internal/controller/arksapplication_controller.go:941-1014).
Here it is TPU-native:

- **Slot model**: a fixed decode batch of ``num_slots`` sequences, each
  owning a stretch of the slotted KV cache.  Prompts are prefilled one at a
  time into bucketed-length compiled programs, then inserted into a free
  slot; decode advances all slots together.
- **Fused dispatch**: ``steps_per_dispatch`` decode steps + on-device
  sampling run inside ONE jitted ``lax.scan`` per dispatch, and only the
  sampled ids [K, B] come back to the host.  On a tunneled PJRT platform
  per-dispatch overhead is ~10ms, so this is the difference between 70 and
  3000+ tok/s.
- **Host-authoritative scheduling**: lengths/last-token mirrors live on the
  host; device state is params + cache + sampler keys.  The scheduler
  decides admission, stopping, and slot reuse between dispatches.

All jax work happens on the engine thread; the server talks to it via
thread-safe queues (Request.outputs).
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import os
import queue
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from arks_tpu.engine import fairqueue
from arks_tpu.engine import faults as faults_mod
from arks_tpu.engine import sampler as sampler_mod
from arks_tpu.engine.faults import StepFault
from arks_tpu.engine.guides import GuideError
from arks_tpu.engine.model_pool import LoadTicket, ModelPool, PoolFullError
from arks_tpu.engine.tokenizer import Tokenizer
from arks_tpu.engine.types import (PrefilledState, Request, RequestOutput,
                                   SamplingParams)
from arks_tpu.models.config import ModelConfig
from arks_tpu.models import transformer as tf
from arks_tpu.obs import logctx
from arks_tpu.obs import profiler as prof_mod
from arks_tpu.obs import trace as trace_mod
from arks_tpu.utils import knobs
from arks_tpu.utils import metrics as prom
from arks_tpu import slo as slo_mod
from arks_tpu import tenancy

log = logging.getLogger("arks_tpu.engine")
logctx.install(log)


class ContextLengthExceededError(ValueError):
    """Prompt does not fit the serving window.  OpenAI-compatible servers
    must surface this as HTTP 400 with code ``context_length_exceeded`` —
    silently truncating would corrupt long-context results and billing."""


@dataclasses.dataclass
class EngineConfig:
    model: str = "tiny"
    num_slots: int = 8
    max_cache_len: int = 1024
    prefill_buckets: tuple[int, ...] = (32, 64, 128, 256, 512, 1024)
    steps_per_dispatch: int = 4
    # Chunked prefill: prompts longer than the largest one-shot bucket are
    # processed in chunks of this many tokens, one chunk per scheduler step,
    # INTERLEAVED with decode dispatches — a burst of long prompts no longer
    # freezes every decoding slot.  None disables (long prompts then 400).
    prefill_chunk: int | None = 256
    # Parallelism: when a mesh isn't passed to InferenceEngine explicitly,
    # one is built from these over all visible devices (tp defaults to
    # devices/dp). All 1 (or 1 visible device) → no mesh, single-chip path.
    # context_parallel > 1 shards prefill's T over the 'seq' axis and runs
    # ring attention — the long-context serving path (prompts beyond one
    # chip's prefill budget; decode replicates over the seq axis, so cp
    # belongs on prefill-heavy tiers, e.g. the disaggregated prefill role).
    tensor_parallel: int | None = None
    data_parallel: int = 1
    context_parallel: int = 1
    # pipeline_parallel > 1 shards LAYERS (and each layer's KV) over the
    # 'stage' axis: HBM capacity scales with stages, for models whose
    # weights+KV exceed one chip.  Decode pipelines microbatches of slots
    # across stages (parallel.pipeline.pp_decode_step); prefill runs
    # one-shot through the stages.  Mutually exclusive with tp/dp/cp in the
    # engine (compose via multi-group replicas instead); chunked prefill
    # and the prefix cache are disabled under pp (their dynamic layer
    # indexing would gather the stage-sharded cache).
    pipeline_parallel: int = 1
    # Speculative decoding: a small draft model proposes draft_len-1 tokens
    # per dispatch and the target verifies them as RAGGED q_len=draft_len
    # rows of the SAME mixed dispatch that carries decode feeds and
    # prefill chunks (transformer.mixed_step / paged_mixed_attention) —
    # draft propose + verify + acceptance run inside ONE program per
    # scheduler iteration, and the spec engine keeps the mixed engine's
    # pipelining, guided decoding, and token-replay fault recovery.
    # Greedy slots keep the longest argmax-matching prefix plus one bonus
    # token — emitted tokens IDENTICAL to target-only greedy decoding.
    # Sampled slots use rejection sampling (sampler.speculative_accept) —
    # exact in DISTRIBUTION against the engine's own effective sampling
    # dist.  Requires the mixed scheduler (paged KV layout + chunked
    # prefill); dp/pp-exclusive.  Multi-host gangs mirror the
    # draft-prefill and spec_mixed dispatches like any other op.
    draft_model: str | None = None
    draft_len: int = 4
    dtype: str | None = None   # default: model config dtype
    # "auto"|"bf16"|"int8"|"int4": int8 halves KV HBM traffic and doubles
    # cache capacity (per-token scales, dequantized inside the attention
    # kernel).  int4 packs token pairs into one byte (same per-token scale
    # stripes) — half the page bytes again; requires the paged layout
    # (dequant is fused on the mixed kernel's page stream; there is no
    # int4 slot-cache kernel).  auto = int8 on real TPU (the production
    # default bench.py measures), engine dtype elsewhere (CPU tests stay
    # full-width).
    kv_cache_dtype: str = "auto"
    # "bf16"|"int8"|"int4": weight-only quantization (models.quant).
    # int8 = w8a16 (per-output-channel scales, dequant fused into the
    # matmuls) — how 7B-class models fit a 16GB v5e chip, and it halves
    # decode weight reads.  int4 = w4a16 (per-128-row-group scales,
    # embedding stays int8) — halves weight bytes again: 13B-class
    # single-chip, or the freed HBM becomes KV pages.
    weight_dtype: str = "bf16"
    # "auto"|"slot"|"paged": device KV layout.  "paged" = block-table pool
    # (ops.paged_attention) with zero-copy on-device prefix sharing —
    # measured FASTER than the slot cache at production shapes
    # (tools/bench_kernels.py: 0.96x int8 b192, 0.78x bf16 b96) and it
    # works on multi-host gangs.  "auto" = paged on TPU whenever the
    # engine shape allows (no pp / dp, lane-aligned head_dim,
    # chunk == page alignment); slot elsewhere — the slot layout remains
    # the fallback for those paths.  Speculative decoding REQUIRES paged
    # (verify blocks are ragged rows of the mixed dispatch; the draft
    # mirror stays slot-contiguous — it is num_slots x draft-model sized,
    # where paging buys nothing), so "auto" resolves to paged for draft
    # engines on every backend whose shape allows it.
    # Context parallelism pages too (one-shot prefill rides the ring;
    # the pool is seq-replicated, so tables/pages are unaffected — chunk
    # tails run unsharded over seq, as they do on the slot layout).
    # Pipeline parallelism pages too: the pool shards over 'stage' on its
    # layer dim and decode pipelines microbatches through the block
    # tables (parallel.pipeline.pp_decode_step_paged) — page-granular
    # allocation instead of per-slot max_cache_len reservations, the HBM
    # lever pp exists for (chunking/prefix reuse stay off under pp).
    # dp stays slot by design: the pool has no batch dim to shard and
    # per-dp-shard pools would fragment the prefix index.
    kv_layout: str = "auto"
    # Host-RAM budget for the prefix KV cache (0 disables).  Shared prompt
    # prefixes (system prompts, few-shot preambles, multi-turn history)
    # skip recomputation: cached blocks are inserted and only the tail is
    # prefilled.  Requires prefill_chunk (reuse lands on chunk boundaries);
    # single-host only (harvest needs fully-addressable arrays).
    prefix_cache_mb: int = 256
    seed: int = 0

    def resolve_kv_cache_dtype(self) -> str:
        """Returns 'int8' | 'int4' | 'bf16' | 'engine' (= engine dtype)."""
        if self.kv_cache_dtype not in ("auto", "bf16", "int8", "int4"):
            raise ValueError(f"kv_cache_dtype={self.kv_cache_dtype!r}")
        if self.kv_cache_dtype == "auto":
            import jax
            return "int8" if jax.default_backend() == "tpu" else "engine"
        return self.kv_cache_dtype

    @property
    def kv_quantized(self) -> bool:
        return self.resolve_kv_cache_dtype() in ("int8", "int4")

    @property
    def kv_bits(self) -> int:
        """Stored bits per KV element: 4 / 8 / 16."""
        kvd = self.resolve_kv_cache_dtype()
        return {"int4": 4, "int8": 8}.get(kvd, 16)

    def resolve_buckets(self) -> list[int]:
        """Prefill buckets clamped to the cache; never empty."""
        buckets = sorted(b for b in self.prefill_buckets if b <= self.max_cache_len)
        if not buckets:
            buckets = [self.max_cache_len]
        elif buckets[-1] < self.max_cache_len and not self.prefill_chunk:
            # No chunked path: the one-shot buckets must cover full-cache-
            # length prompts.  (With chunking, prompts beyond the largest
            # bucket run chunked — appending a full-length bucket here would
            # make every long prompt monolithic again.)
            buckets.append(self.max_cache_len)
        return buckets

    def cache_len_alignment(self) -> int:
        """Required max_cache_len alignment for the Pallas decode path.

        The in-place cache-update kernels DMA along S in fixed tiles (16 for
        bf16, 128 for the int8 per-token scales) and the ragged attention
        grid needs S % min(block_s, S) == 0 (block_s = ARKS_ATTN_BLOCK_S,
        default 256) — so any cache length ≥ block_s must be a multiple of
        block_s (block_s is itself tile-aligned), and shorter caches a
        multiple of the update tile.
        """
        from arks_tpu.ops.attention import default_decode_impl
        if default_decode_impl() != "pallas":
            return 1
        block_s = knobs.get_int("ARKS_ATTN_BLOCK_S")
        if self.max_cache_len >= block_s:
            return block_s
        return 128 if self.kv_quantized else 16

    def align_cache_len(self) -> None:
        """Round max_cache_len up to the kernel alignment (warn if changed).

        Called at engine startup so a misconfigured --max-model-len fails
        (or self-corrects) immediately instead of raising a ValueError deep
        inside the first decode dispatch.
        """
        align = self.cache_len_alignment()
        rounded = -(-self.max_cache_len // align) * align
        if rounded != self.max_cache_len:
            log.warning(
                "max_cache_len=%d is not %d-aligned for the Pallas decode "
                "kernels (kv=%s); rounding up to %d",
                self.max_cache_len, align, self.resolve_kv_cache_dtype(),
                rounded)
            self.max_cache_len = rounded


@dataclasses.dataclass
class _Slot:
    request: Request
    num_prompt: int
    generated: list[int] = dataclasses.field(default_factory=list)
    num_emitted: int = 0  # tokens already streamed to the request queue
    first_token_time: float | None = None
    # Speculative decoding: the draft cache mirrors this slot's rows
    # (prompt draft-prefilled at registration).  The spec-mixed dispatch
    # feeds the draft the REAL last token every step, so the mirror stays
    # in sync for the slot's whole life whether or not it speculates.
    draft_synced: bool = False
    # Spec eligibility, frozen at registration (pure function of the
    # request): draft-synced, penalty-free, no logprobs/bias/min_tokens.
    # Guided slots ARE eligible — verify-aware DFA advancement
    # (sampler.speculative_accept) keeps the grammar exact.  Frozen
    # eligibility is what makes spec engines replay-safe: a lane's PRNG
    # key advances by the same per-dispatch structure on every re-run.
    spec_ok: bool = False
    # Per-token logprob entries parallel to ``generated`` (only populated
    # when the request asked for logprobs): (chosen_lp, [(id, lp), ...]).
    logprobs: list = dataclasses.field(default_factory=list)
    # Pipelined decode (ARKS_PIPELINE_DEPTH): the device stop column for
    # this slot (None = stop set exceeds sampler.STOP_IDS_MAX, slot rides
    # the sequential path) and the absolute length at which the device
    # must stop dispatching it (min of the max_tokens cutoff and the
    # cache-cap margin) — both frozen at registration.
    stop_col: object = None   # np.ndarray [STOP_IDS_MAX] | None
    dead_len: int = 0
    # Sampling seed (request seed or the engine-assigned one) — fault
    # recovery reconstructs the slot's key stream from it (advance_key).
    seed: int = 0


@dataclasses.dataclass
class _ChunkState:
    """A chunked prefill in progress (slot reserved, not yet decoding)."""

    request: Request
    ids: list[int]
    pos: int      # tokens already prefilled
    seed: int     # sampling seed (key = PRNGKey(seed))
    key: jax.Array  # base sampling key (PRNGKey(seed))
    # Paged layout: the prompt's chained page digests (computed at match
    # time), registered into the allocator's prefix index at promote.
    digests: list | None = None


@dataclasses.dataclass
class _RestoreState:
    """A tier-1 (host-RAM) prefix restore in flight: the request parks
    here (mirroring the guide_wait park) while its H2D scatter dispatch
    rides the device stream behind the pipelined decode; once the marker
    resolves, only the un-hit prompt tail goes through chunked prefill."""

    request: Request
    ids: list[int]
    digests: list        # full prompt digest chain (computed at match)
    shared: list[int]    # tier-0 device pages (caller refs held by us)
    pages: list[int]     # freshly-allocated pages the scatter writes
    marker: object       # device scalar from the last scatter dispatch
    seed: int
    t0: float


@dataclasses.dataclass
class _FetchState:
    """A tier-2 / peer prefix fetch in flight: the request parks here
    while a worker thread stages the missing blocks from the local disk
    tier (DiskPrefixTier.get) and/or a peer replica (GET
    /v1/cache/blocks/{digest}) INTO THE HOST TIER.  No device pages are
    held across the park — _resolve_fetches re-runs the admission match
    from scratch, so the unparked request rides the existing tier-1
    restore path (or plain chunked prefill if the fetch came up empty).
    The worker writes only `done`/`fetched_*`; the engine thread owns
    membership in _awaiting_fetch."""

    request: Request
    ids: list[int]
    digests: list          # full prompt digest chain (computed at match)
    start: int             # first uncovered digest index at park time
    peer: str | None       # hinted peer base address ("host:port")
    seed: int
    t0: float
    done: bool = False
    fetched_disk: int = 0  # blocks staged from the local disk tier
    fetched_peer: int = 0  # blocks staged from the peer


@dataclasses.dataclass
class _SwapRecord:
    """A preempted request's host-side slot snapshot (ARKS_PREEMPT):
    everything `_finish_resume` needs to rebuild the victim's `_Slot` and
    host mirrors byte-identically once its KV pages scatter back.  The
    device-side halves (KV page blocks, sampler row) live in the
    SwapStore entry keyed by the same request id."""

    request: Request
    num_prompt: int
    generated: list
    num_emitted: int
    logprobs: list
    first_token_time: float | None
    seed: int
    length: int       # host lengths mirror at preempt (valid KV rows)
    last_token: int   # host last-token mirror at preempt
    stop_col: object
    dead_len: int
    n_pages: int      # pool pages covering rows [0, length)
    priority: int
    t0: float         # preempt issue time (preempt_swap_seconds)


@dataclasses.dataclass
class _SwapState:
    """An in-flight preempt spill: the victim's slot is already freed
    (stream order guarantees the gathers below read pre-reuse bytes) and
    these D2H copies are draining."""

    rec: _SwapRecord
    staged: list   # [(n_valid, gather outputs)] per spill group
    row: tuple     # (key[2], counts[V], guide_row) device arrays


@dataclasses.dataclass
class _ResumeState:
    """A preempt-swap restore in flight: the resumed request holds
    ``slot`` (popped from _free) while its page blocks scatter back; it
    parks in ``_awaiting_restore`` beside the prefix ``_RestoreState``s
    and lands via ``_finish_resume`` once the marker resolves — no
    prefill, no first-token output, the stream just continues."""

    rec: _SwapRecord
    slot: int
    pages: list[int]
    marker: object
    t0: float

    @property
    def request(self) -> Request:
        return self.rec.request

    @property
    def ids(self) -> list[int]:
        return self.rec.request.prompt_ids


@dataclasses.dataclass
class _ResizeRequest:
    """A pending live-topology resize, posted by ``request_resize`` from
    any thread and serviced by the engine thread's elastic state machine
    (drain -> reshard -> rebuild -> resume).  ``event`` fires when the
    resize completes, is rejected, or faults; ``outcome``/``error``
    carry the result."""

    tensor_parallel: int
    data_parallel: int
    event: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    t0: float = dataclasses.field(default_factory=time.monotonic)
    active: bool = False
    drain_t0: float = 0.0
    outcome: str | None = None    # "ok" | "rejected" | "error"
    error: str | None = None
    seconds: float = 0.0

    def wait(self, timeout: float | None = None) -> bool:
        return self.event.wait(timeout)


class _WarmupSink:
    """Output sink for engine-issued warm-up requests: tokens go nowhere
    (the point is compiling/priming the new shape, not the text)."""

    def put(self, item) -> None:
        pass


@dataclasses.dataclass
class _Survivor:
    """An in-flight request's replayable state, snapshotted at a step
    fault (engine._recover_from_fault).  ``generated`` empty = the request
    had emitted nothing (queued/prefilling/deferred admission) and simply
    re-queues; non-empty = token-replay resume (deterministic
    re-execution behind a _ReplayGate — see that class)."""

    request: Request
    seed: int
    num_prompt: int
    generated: list = dataclasses.field(default_factory=list)
    num_emitted: int = 0
    logprobs: list = dataclasses.field(default_factory=list)
    first_token_time: float | None = None


class _ReplayGate:
    """Token-replay resume by DETERMINISTIC RE-EXECUTION (fault recovery).

    A surviving stream is re-admitted through its ORIGINAL schedule — the
    same admission path, the same compiled programs, the same pinned seed
    — so every regenerated token is byte-identical to the recorded stream
    by run-to-run determinism.  (The alternative, re-prefilling the
    generated tokens and restoring sampler state, recomputes KV rows with
    DIFFERENT program shapes than the original decode wrote them — the
    ulp-level drift occasionally flips a sampled token several steps after
    resume, which is exactly the silent corruption replay must never
    produce.)

    The gate wraps the request's output queue for the re-run:

    - **suppression**: regenerated tokens the client already received
      (the first ``client_total``) are dropped, so the resumed stream has
      no duplicates;
    - **verification**: every regenerated token is checked against the
      recorded stream; a mismatch (broken determinism) fails THIS request
      with an engine_fault error instead of splicing a divergent tail
      onto the client's stream — byte-identity is enforced, not assumed;
    - **re-entrancy**: a second fault during the re-run just restarts the
      cursor (``restart``); ``client_total`` survives, so suppression
      stays exact across nested recoveries.

    put() runs on the engine thread; get() is the server side's
    pass-through to the original queue.
    """

    def __init__(self, inner, engine, request_id: str, expect: list,
                 client_total: int):
        self._inner = inner
        self._engine = engine
        self._rid = request_id
        self.expect = [int(t) for t in expect]
        self.pos = 0              # regenerated tokens seen this run
        self.client_total = client_total  # tokens the client has received
        self.failed = False

    def restart(self, expect: list | None = None) -> None:
        self.pos = 0
        if expect and len(expect) > len(self.expect):
            self.expect = [int(t) for t in expect]

    def get(self, *args, **kwargs):
        return self._inner.get(*args, **kwargs)

    def put(self, out: RequestOutput) -> None:
        if self.failed:
            # The client already saw the divergence error; drop the rest
            # of the doomed re-run (its abort tail included).
            return
        toks = list(out.token_ids)
        start = self.pos
        n_check = min(len(toks), len(self.expect) - start)
        if toks[:n_check] != self.expect[start:start + n_check]:
            self.failed = True
            self._engine.abort(self._rid)
            self._inner.put(RequestOutput(
                request_id=self._rid, token_ids=[], finished=True,
                finish_reason="error",
                error="engine_fault: replay_diverged",
                num_prompt_tokens=out.num_prompt_tokens))
            log.error("replay of %s diverged from the recorded stream at "
                      "token %d; failing the request", self._rid,
                      start + 1)
            return
        self.pos += len(toks)
        skip = max(0, min(self.client_total - start, len(toks)))
        fwd = toks[skip:]
        lps = out.logprobs[skip:] if out.logprobs else None
        if not fwd and not out.finished:
            return  # entirely inside the already-delivered prefix
        self.client_total = max(self.client_total, self.pos)
        self._inner.put(dataclasses.replace(
            out, token_ids=fwd, logprobs=lps, ttft_s=None))


class EngineMetrics:
    """Normalized runtime metric names (what the reference's runtime
    ServiceMonitor relabels vLLM/SGLang names into —
    /root/reference/config/prometheus/monitor-runtime.yaml:13-44)."""

    def __init__(self, registry: prom.Registry | None = None):
        self.registry = registry or prom.Registry()
        r = self.registry
        self.num_requests_running = r.gauge(
            "num_requests_running", "Requests currently decoding")
        self.num_requests_waiting = r.gauge(
            "num_requests_waiting", "Requests queued for admission")
        self.prompt_tokens_total = r.counter(
            "prompt_tokens_total", "Prefilled prompt tokens")
        self.generation_tokens_total = r.counter(
            "generation_tokens_total", "Generated tokens")
        self.time_to_first_token_seconds = r.histogram(
            "time_to_first_token_seconds", "TTFT",
            buckets=[0.01, 0.025, 0.05, 0.1, 0.2, 0.4, 0.8, 1.6, 3.2, 6.4, 12.8])
        self.time_per_output_token_seconds = r.histogram(
            "time_per_output_token_seconds", "TPOT",
            buckets=[0.002, 0.005, 0.01, 0.02, 0.04, 0.08, 0.16, 0.32, 0.64])
        self.e2e_request_latency_seconds = r.histogram(
            "e2e_request_latency_seconds", "End-to-end request latency",
            buckets=[0.1, 0.25, 0.5, 1, 2.5, 5, 10, 20, 40, 80, 160])
        self.request_success_total = r.counter(
            "request_success_total", "Finished requests by reason")
        # Prefix-cache family (reference dashboard's cache hit-rate panel —
        # docs/monitoring.md:118-144 — normalized like the other names).
        self.prefix_cache_query_tokens_total = r.counter(
            "prefix_cache_query_tokens_total",
            "Prompt tokens checked against the prefix cache")
        self.prefix_cache_hit_tokens_total = r.counter(
            "prefix_cache_hit_tokens_total",
            "Prompt tokens served from the prefix cache")
        self.prefix_cache_usage_bytes = r.gauge(
            "prefix_cache_usage_bytes",
            "Bytes held by the prefix cache, by tier (device = retained "
            "pool pages, host = host-RAM blocks)")
        self.prefix_cache_hit_rate = r.gauge(
            "prefix_cache_hit_rate", "Lifetime prefix-cache token hit rate")
        # Hierarchical prefix cache (paged engines): tier 0 is the
        # allocator's on-device page index, tier 1 the host-RAM spill
        # store — the families that make HBM-pressure thrash (spill storm)
        # and restore latency visible on a dashboard.
        self.prefix_spill_blocks_total = r.counter(
            "prefix_spill_blocks_total",
            "KV pages spilled from the device prefix index to the host tier")
        self.prefix_restore_blocks_total = r.counter(
            "prefix_restore_blocks_total",
            "KV pages restored from the host tier into fresh pool pages")
        self.prefix_restore_seconds = r.histogram(
            "prefix_restore_seconds",
            "Host-tier restore latency (scatter issue -> request unparked)",
            buckets=[0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1, 2.5])
        # Tier 2 (DiskPrefixTier) + fleet peer fetch: the families that
        # make disk-budget churn, a poisoned disk tier (corrupt reads),
        # and a peer fetch that lost to re-prefill visible on a dashboard.
        self.prefix_disk_evictions_total = r.counter(
            "prefix_disk_evictions_total",
            "KV page blocks LRU-evicted from the tier-2 disk store past "
            "its byte budget")
        self.prefix_disk_corrupt_total = r.counter(
            "prefix_disk_corrupt_total",
            "Tier-2 block files rejected on read (corrupt, truncated, or "
            "stale-epoch) and deleted")
        self.prefix_peer_fetch_blocks_total = r.counter(
            "prefix_peer_fetch_blocks_total",
            "Prefix KV blocks fetched into the host tier, by source "
            "(disk = local tier 2, peer = remote replica)")
        self.prefix_peer_fetch_seconds = r.histogram(
            "prefix_peer_fetch_seconds",
            "Disk/peer prefix fetch latency (park -> blocks staged in "
            "the host tier)",
            buckets=[0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1,
                     2.5, 5, 10])
        self.guided_requests_total = r.counter(
            "guided_requests_total",
            "Admitted guided-decoding requests by guide kind")
        # Guide compile pipeline (engine.guides): async worker-pool
        # compiles + LRU registry — the families that make a cold-compile
        # stall or an eviction storm visible on a dashboard.
        self.guide_compile_seconds = r.histogram(
            "guide_compile_seconds",
            "Guided-decoding DFA compile latency (worker-pool threads)",
            buckets=[0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120])
        self.guide_cache_hits_total = r.counter(
            "guide_cache_hits_total",
            "Guide requests served from the compiled registry")
        self.guide_cache_misses_total = r.counter(
            "guide_cache_misses_total",
            "Guide requests that scheduled a cold compile")
        self.guide_cache_evictions_total = r.counter(
            "guide_cache_evictions_total",
            "Guides evicted from the registry (LRU, no active slot)")
        self.guide_registry_guides_in_use = r.gauge(
            "guide_registry_guides_in_use",
            "Guides currently packed in the registry")
        self.guide_registry_rows_in_use = r.gauge(
            "guide_registry_rows_in_use",
            "DFA rows currently packed in the transition table")
        self.spec_decode_proposed_tokens_total = r.counter(
            "spec_decode_proposed_tokens_total",
            "Draft tokens proposed to the verifier")
        self.spec_decode_accepted_tokens_total = r.counter(
            "spec_decode_accepted_tokens_total",
            "Draft tokens accepted by the verifier")
        self.spec_decode_acceptance_rate = r.gauge(
            "spec_decode_acceptance_rate",
            "Lifetime draft-token acceptance rate")
        # Per-dispatch accepted-block length (1 = nothing accepted, just
        # the normally-sampled token; draft_len = full block + bonus).
        # The distribution — not just the lifetime rate — is what shows an
        # acceptance COLLAPSE (histogram mass sliding to 1) before
        # throughput falls over (docs/monitoring.md).
        self.spec_decode_accepted_length = r.histogram(
            "spec_decode_accepted_length",
            "Tokens landed per speculating request per spec dispatch",
            buckets=[1, 2, 3, 4, 6, 8, 12, 16])
        # Mixed-step scheduling (ARKS_MIXED_STEP): one token-budget dispatch
        # per iteration carrying decode tokens + prefill-chunk tokens.
        self.mixed_batch_tokens = r.histogram(
            "mixed_batch_tokens",
            "Valid tokens per mixed dispatch (decode + chunk)",
            buckets=[1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048])
        self.mixed_chunk_tokens_total = r.counter(
            "mixed_chunk_tokens_total",
            "Prefill-chunk tokens processed inside mixed dispatches")
        # Ragged-grid padding waste (ops.paged_attention ragged work list):
        # steps_total counts the page-compute steps the ACTIVE grid mode
        # executes per mixed dispatch; ideal_total counts the per-sequence
        # causal minimum (what the ragged work list runs).  Their ratio is
        # the padding-waste factor — 1.0 under ARKS_MIXED_GRID=ragged,
        # up to S*num_qb*max_pages/ideal under the dense fallback
        # (docs/monitoring.md has the alert row).
        self.mixed_grid_steps_total = r.counter(
            "mixed_grid_steps_total",
            "Page-compute grid steps executed by mixed dispatches")
        self.mixed_grid_steps_ideal_total = r.counter(
            "mixed_grid_steps_ideal_total",
            "Per-sequence causal minimum page-compute steps for the same "
            "mixed dispatches")
        # KV bytes-moved pair (engine/paged.mixed_kv_bytes): bytes_total
        # mirrors the ragged kernel's actual DMA schedule (every q-block
        # re-streams its causal page prefix at the PLAN's block_q — the
        # GQA head-grouped autotune entries earn their keep by raising
        # block_q, which this counter shows directly); ideal_total counts
        # each distinct causal page once per dispatch.  The ratio is the
        # KV streaming waste factor (docs/monitoring.md alert).
        self.mixed_kv_bytes_total = r.counter(
            "mixed_kv_bytes_total",
            "KV bytes streamed from HBM by mixed dispatches (plan mirror)")
        self.mixed_kv_bytes_ideal_total = r.counter(
            "mixed_kv_bytes_ideal_total",
            "KV bytes a perfect once-per-page schedule would stream for "
            "the same mixed dispatches")
        # Windowed-residency decode (ARKS_RESIDENCY_WINDOW_PAGES): spans
        # attended and cold pages prefetched for contexts larger than the
        # device page pool.
        self.residency_spans_total = r.counter(
            "residency_spans_total",
            "Windowed-residency attention spans attended")
        self.residency_prefetch_pages_total = r.counter(
            "residency_prefetch_pages_total",
            "Cold KV pages restored into staging by residency prefetch")
        self.sampler_fused_dispatch_total = r.counter(
            "sampler_fused_dispatch_total",
            "Steady-state decode dispatches issued through the fused "
            "attention+sampler program (ARKS_SAMPLER_FUSE) with zero "
            "host-side prep arrays")
        # Scheduler phase breakdown (seconds of engine-thread wall time):
        # where a serving cycle actually goes — the counters bench_serving
        # scrapes to attribute throughput loss (admit vs chunk vs decode).
        self.scheduler_seconds_total = r.counter(
            "scheduler_seconds_total",
            "Engine-thread wall seconds by scheduler phase")
        self.decode_resolve_wait_seconds_total = r.counter(
            "decode_resolve_wait_seconds_total",
            "Seconds blocked fetching decode results (pure device-stream "
            "wait, unpolluted by overlapped host work), split by "
            "mode=pipelined|sequential")
        # Pipelined decode (ARKS_PIPELINE_DEPTH): in-flight dispatches
        # after each issue.  At depth N steady state this sits at N — a
        # histogram stuck at 1 means the engine keeps leaving the
        # pipelined path (admission churn, aborts, oversized stop sets).
        self.pipeline_depth_occupancy = r.histogram(
            "pipeline_depth_occupancy",
            "In-flight decode dispatches after each pipelined issue",
            buckets=[1, 2, 3, 4, 6, 8])
        # Fault isolation / recovery (engine.faults + _recover_from_fault):
        # the observability DeepServe-style request-preserving recovery
        # needs — who faulted (phase, kind), who survived, who was
        # quarantined, and how long the replay took.
        self.engine_faults_total = r.counter(
            "engine_faults_total",
            "Scheduler-step faults by phase and kind")
        self.requests_recovered_total = r.counter(
            "requests_recovered_total",
            "In-flight requests restored to serving after an engine fault "
            "(token-replay resume or re-queued admission)")
        self.requests_quarantined_total = r.counter(
            "requests_quarantined_total",
            "Culprit requests failed alone after exhausting "
            "ARKS_FAULT_RETRIES")
        self.engine_recovery_seconds = r.histogram(
            "engine_recovery_seconds",
            "Fault-to-resumed-decoding recovery latency",
            buckets=[0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120])
        # 0=serving 1=recovering 2=wedged (faults.STATE_CODES); /readiness
        # reports 503 "recovering"/"wedged" while nonzero.
        self.engine_state = r.gauge(
            "engine_state",
            "Engine serving state (0=serving, 1=recovering, 2=wedged)")
        # Resolved-config info gauge (value always 1, config as labels —
        # the kube-state-metrics "_info" idiom): which KV layout / decode
        # impl / overlap mode a replica ACTUALLY runs, so an operator can
        # tell the perf envelope from /metrics instead of reading logs.
        self.engine_config_info = r.gauge(
            "engine_config_info",
            "Resolved engine configuration (labels; value is always 1)")
        # ---- Multi-model pool (engine.model_pool) ----------------------
        self.model_pool_resident_bytes = r.gauge(
            "model_pool_resident_bytes",
            "Device weight bytes per pool model (0 while evicted)")
        self.model_switch_seconds = r.histogram(
            "model_switch_seconds",
            "Model switch latency: first request parked for the model to "
            "the model serving (includes the overlapped weight load)",
            buckets=[0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                     60.0, 120.0])
        self.model_cold_starts_total = r.counter(
            "model_cold_starts_total",
            "Pool model loads from cold (weights not resident)")
        self.requests_parked = r.gauge(
            "requests_parked",
            "Requests parked by reason: guide compile, host-tier KV "
            "restore, a pending model switch, or a preemptive KV swap")
        # ---- Elastic parallelism (live resize / scale-from-zero) -------
        self.engine_resizes_total = r.counter(
            "engine_resizes_total",
            "Live topology resizes by mode (resize|scale_to_zero|rearm) "
            "and outcome (ok|error|rejected)")
        self.resize_seconds = r.histogram(
            "resize_seconds",
            "Live resize latency: drain boundary reached to serving at "
            "the new shape (reshard + rebuild + survivor resume issue)",
            buckets=[0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                     60.0, 120.0])
        self.scale_from_zero_seconds = r.histogram(
            "scale_from_zero_seconds",
            "Scale-from-zero re-arm latency: demand arrival to serving "
            "(weight stream + cache/program rebuild + warm-up issue)",
            buckets=[0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                     60.0, 120.0])
        # ---- SLO tiers + preemptive KV swap (arks_tpu.slo, ARKS_PREEMPT)
        # Per-tier latency families carry the tier NAME as a label so one
        # dashboard row per rung of the ladder can alert on its own
        # target (docs/monitoring.md); without ARKS_SLO_TIERS everything
        # lands in tier="default" and the families mirror the global
        # TTFT/TPOT histograms.
        self.ttft_seconds = r.histogram(
            "ttft_seconds", "TTFT by SLO tier",
            buckets=[0.01, 0.025, 0.05, 0.1, 0.2, 0.4, 0.8, 1.6, 3.2, 6.4, 12.8])
        self.tpot_seconds = r.histogram(
            "tpot_seconds", "TPOT by SLO tier",
            buckets=[0.002, 0.005, 0.01, 0.02, 0.04, 0.08, 0.16, 0.32, 0.64])
        self.requests_preempted_total = r.counter(
            "requests_preempted_total",
            "Running requests preempted for a higher tier, by victim tier")
        self.preempt_swap_seconds = r.histogram(
            "preempt_swap_seconds",
            "Preemptive-swap leg latency (issue -> host copy landed, and "
            "resume issue -> slot live again)",
            buckets=[0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1, 2.5])
        # ---- Tenant-fair admission + overload ladder (engine.fairqueue)
        # The tenant label rides through TenantLabels (first-K tenants
        # keep their id, the rest share "other") so hostile key churn
        # cannot mint unbounded series — tests/test_metrics_conformance
        # enforces the bound.
        self.requests_shed_total = r.counter(
            "requests_shed_total",
            "Requests rejected by the overload ladder, by reason "
            "(queue_full|tenant_cap|deadline), tier, and bounded tenant "
            "label")
        self.admission_queue_depth = r.gauge(
            "admission_queue_depth",
            "Admission-queue depth across all tiers and tenants (compare "
            "against ARKS_QUEUE_MAX for the saturation fraction)")


def _scoped(phase: str):
    """Fault-context decorator for scheduler phases: any exception leaving
    the wrapped method is re-raised as a StepFault tagged with the phase
    and the culprit request ids (blast-radius attribution — the recovery
    loop's quarantine input).  Inner StepFaults (narrower attribution from
    a per-request handler) pass through untouched."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            hb = self._step_hb
            if hb is not None:
                self._step_hb = (phase, hb[1])
            self.trace.evt("", "phase." + phase, "B")
            try:
                return fn(self, *args, **kwargs)
            except StepFault:
                raise
            except Exception as e:
                raise StepFault(phase, faults_mod.classify(e),
                                culprits=self._phase_culprits(phase)) from e
            finally:
                self.trace.evt("", "phase." + phase, "E")
        return wrapper
    return deco


class InferenceEngine:
    def __init__(
        self,
        cfg: ModelConfig,
        engine_cfg: EngineConfig,
        tokenizer: Tokenizer,
        params: tf.Params | None = None,
        mesh=None,
        registry: prom.Registry | None = None,
        draft_params: tf.Params | None = None,
        draft_cfg: ModelConfig | None = None,
        pool=None,
    ) -> None:
        self.tokenizer = tokenizer
        if engine_cfg.pipeline_parallel > 1 and (
                (engine_cfg.tensor_parallel or 1) * engine_cfg.data_parallel
                * engine_cfg.context_parallel > 1):
            raise ValueError(
                "pipeline_parallel cannot combine with tp/dp/cp in one "
                "engine; scale those via replica groups")
        if mesh is None and ((engine_cfg.tensor_parallel or 1)
                             * engine_cfg.data_parallel
                             * engine_cfg.context_parallel
                             * engine_cfg.pipeline_parallel > 1):
            from arks_tpu.parallel.mesh import make_mesh
            mesh = make_mesh(tensor_parallel=engine_cfg.tensor_parallel,
                             data_parallel=engine_cfg.data_parallel,
                             context_parallel=engine_cfg.context_parallel,
                             pipeline_parallel=engine_cfg.pipeline_parallel)
        self.mesh = mesh
        self.metrics = EngineMetrics(registry)
        # Effective parallelism comes from the MESH's axes (an explicitly
        # passed mesh wins over the config — keying off the config here
        # while _build_programs keys off the mesh would let them disagree).
        self._cp = mesh.shape.get("seq", 1) if mesh is not None else 1
        self._pp = mesh.shape.get("stage", 1) if mesh is not None else 1

        # ---- Engine-global (model-independent) machinery ---------------
        # Everything from here to the _init_model_state call survives a
        # model switch untouched: admission queue, abort/fault state,
        # deferred-admit plumbing, pipeline depth, and the model pool
        # itself.  Per-model state (weights, caches, mirrors, compiled
        # programs) is built by _init_model_state and swapped WHOLESALE on
        # switch — a saved context is byte-for-byte the state a
        # single-model engine of that model would hold.
        from collections import deque

        # Admission queue: tier-ordered (lower value first), weighted
        # deficit round-robin across tenants within a tier, FIFO within a
        # (tier, tenant) via a monotonic tiebreak — Request objects are
        # never compared.  Bounded (ARKS_QUEUE_MAX / ARKS_QUEUE_TENANT_MAX)
        # on the external add_request path only; with a single tenant the
        # pick order is exactly the old PriorityQueue order.
        self._queue = fairqueue.FairQueue()
        self._queue_seq = 0
        self._queued_rids: set[str] = set()
        # Deadline-aware shedding (ARKS_SHED_DEADLINE): a popped request
        # whose queue wait already exceeds factor x its tier's ttft_ms
        # budget is rejected at _preadmit instead of wasting prefill on a
        # stream its client has given up on.  0 = off.  Replay, swap-
        # resume, and disagg-prefilled requests are exempt.
        shed_factor = knobs.get_float("ARKS_SHED_DEADLINE")
        if shed_factor < 0:
            raise ValueError(
                f"ARKS_SHED_DEADLINE={shed_factor}: must be >= 0")
        self._shed_deadline_factor = shed_factor
        # Bounded tenant metric labels (ARKS_TENANT_LABEL_MAX): tenant ids
        # are unbounded user input; label cardinality must not be.
        self._tenant_labels = tenancy.TenantLabels()
        self._aborted: set[str] = set()
        self._abort_lock = threading.Lock()
        # Detached prefill (disaggregated mode) runs on server threads, not
        # the engine thread; serialize device access.
        self._prefill_lock = threading.Lock()
        self._running = False
        self._thread: threading.Thread | None = None
        self._request_seed = engine_cfg.seed
        # ---- Fault isolation (engine.faults) ---------------------------
        # Injector (ARKS_FAULT_INJECT chaos hook), per-request fault
        # counts (the quarantine budget), and the serving/recovering/
        # wedged state machine /readiness reports.
        self._faults = faults_mod.FaultInjector()
        self._fault_retries = knobs.get_int("ARKS_FAULT_RETRIES")
        if self._fault_retries < 0:
            raise ValueError(
                f"ARKS_FAULT_RETRIES={self._fault_retries}: must be >= 0")
        self._fault_counts: dict[str, int] = {}
        self._consec_faults = 0
        # Request ids currently replaying (re-executing behind a
        # _ReplayGate) after a fault; the recovery window closes when the
        # last one re-registers (or dies).  Engine-thread-only.
        self._replaying: set[str] = set()
        self._state = "serving"
        self.metrics.engine_state.set(faults_mod.STATE_SERVING)
        self._recover_t0 = 0.0
        # Watchdog heartbeat: (phase, t0) of the in-flight scheduler step,
        # None while idle.  Written by the engine thread, read by the
        # watchdog thread (a torn read degrades to one missed poll).
        self._step_hb: tuple[str, float] | None = None
        self._watchdog: faults_mod.Watchdog | None = None
        # Deferred admissions: issued batches whose first tokens haven't
        # been fetched yet (FIFO).  Resolving lazily (is_ready polling in
        # step) keeps the engine thread issuing decode dispatches instead
        # of blocking on every admit program's round-trip — the r04 bench
        # measured 92% of engine wall in blocking admit resolves at
        # saturation.
        self._pending_admits: "deque" = deque()
        # Request count across the deque, maintained by the engine thread
        # at every mutation: num_running reads it cross-thread (iterating
        # the deque there would race popleft/extend).
        self._pending_n = 0
        self._defer_admits = True
        # Decode/admission overlap: issue the decode dispatch async and do
        # admission host work while the device computes.  Pays off where
        # device compute and host logistics are truly parallel (TPU);
        # on CPU the "device" shares the host's cores, so the reorder only
        # delays new slots' first decode — sequential there.
        # ARKS_OVERLAP_DECODE=0/1 overrides.
        _ov = knobs.get_str("ARKS_OVERLAP_DECODE")
        self._overlap = (_ov == "1" or
                         (_ov != "0" and jax.default_backend() == "tpu"))
        # Multi-host: a DispatchLeader when this engine drives follower
        # processes (arks_tpu.engine.multihost); None single-host.
        self.dispatcher = None

        # ---- Pipelined decode depth (ARKS_PIPELINE_DEPTH) --------------
        # Parsed once per process (model-independent); the per-model pipe
        # state itself lives in _init_model_state.
        pipe_depth = knobs.get_int("ARKS_PIPELINE_DEPTH")
        if pipe_depth < 0:
            raise ValueError(
                f"ARKS_PIPELINE_DEPTH={pipe_depth}: must be >= 0")
        self._pipe_depth = pipe_depth
        # Depth-0 sampler fusion (ARKS_SAMPLER_FUSE): steady-state decode
        # issues the fused attention+sampler pipe program with immediate
        # resolve instead of the classic host-prepped mixed batch.
        self._sampler_fuse = knobs.get_str("ARKS_SAMPLER_FUSE") != "0"

        # ---- SLO tiers + preemptive KV swap (ARKS_PREEMPT) -------------
        # Tier ladder (metric labels + admission semantics; arks_tpu.slo)
        # and the preemption knobs, all engine-global: a queued request
        # whose (aged) priority strictly outranks the lowest running tier
        # may seize that victim's slot by swapping its full decode state
        # to host RAM.  Default OFF — priority stays pure queue ordering.
        self._slo = slo_mod.from_env()
        # Per-tier SLO burn tracker: the engine thread appends one
        # (time, violated) sample per first token for tiers that declare
        # a ttft_ms target; slo_burn() folds the rolling window into
        # violation_fraction / ARKS_SLO_ERROR_BUDGET for /readiness and
        # the signals-mode autoscaler (control.autoscaler).
        self._slo_burn_window_s = knobs.get_float("ARKS_SLO_BURN_WINDOW_S")
        self._slo_error_budget = max(
            knobs.get_float("ARKS_SLO_ERROR_BUDGET"), 1e-6)
        self._slo_events: dict[str, list] = {}
        # ---- End-to-end tracing + profiler windows (arks_tpu.obs) ------
        # The tracer records span events from the scheduler seams into
        # per-thread rings (ARKS_TRACE=0 disables; the step loop may only
        # call trace.evt — tests/test_hotpath_guard.py enforces it) and
        # doubles as the flight recorder the watchdog/fault dumps attach.
        self.trace = trace_mod.Tracer()
        self.profiler = prof_mod.ProfilerWindows()
        self._pipe_seq = 0   # pipelined issue->resolve span pairing
        self._preempt_on = knobs.get_bool("ARKS_PREEMPT")
        preempt_max = knobs.get_int("ARKS_PREEMPT_MAX_INFLIGHT")
        if preempt_max < 1:
            raise ValueError(
                f"ARKS_PREEMPT_MAX_INFLIGHT={preempt_max}: must be >= 1")
        self._preempt_max = preempt_max
        preempt_cooldown = knobs.get_float("ARKS_PREEMPT_COOLDOWN_S")
        if preempt_cooldown < 0:
            raise ValueError(
                f"ARKS_PREEMPT_COOLDOWN_S={preempt_cooldown}: must be >= 0")
        self._preempt_cooldown_s = preempt_cooldown
        # Anti-thrash ledger: rid -> last preempt time; a victim inside
        # the cooldown window is skipped so two tiers can't ping-pong one
        # slot (swap-storm livelock).
        self._preempt_last: dict[str, float] = {}
        # Preempt-resumed rids mid-flight through replay-mode resume (the
        # re-queue path): _register_slot suppresses their TTFT — the
        # client saw the real first token long ago.
        self._resuming: set[str] = set()
        # ---- Priority-queue aging (ARKS_QUEUE_AGING_S) -----------------
        # A queued request's EFFECTIVE priority decays by one tier per
        # aging window, so sustained high-tier load cannot starve the
        # batch tier forever.  0 = off.
        queue_aging = knobs.get_float("ARKS_QUEUE_AGING_S")
        if queue_aging < 0:
            raise ValueError(
                f"ARKS_QUEUE_AGING_S={queue_aging}: must be >= 0")
        self._queue_aging_s = queue_aging
        self._queue_age_last = 0.0

        # ---- Multi-model pool (arks_tpu.engine.model_pool) -------------
        # Requests carry a model id; ones targeting a non-active pool
        # model park in _awaiting_model (mirroring guide_wait /
        # awaiting_restore — same abort/drain/recovery discipline) while
        # the pool streams the weights in the background, then the
        # scheduler switches contexts at a drained boundary
        # ("model_switch" fault phase).
        self.pool = pool
        self._awaiting_model: list[tuple[Request, str, float]] = []
        self._model_loads: dict[str, object] = {}   # name -> LoadTicket
        # Cold-start prefetch hints: add_request drops the model name here
        # so the load kicks the moment demand ARRIVES — a queued request
        # behind busy slots must not delay the weight stream until it
        # parks (GIL-atomic set ops; server threads write, engine reads).
        self._model_prefetch: set[str] = set()
        self._model_ctxs: dict[str, dict] = {}      # saved per-model state
        self._switch_target: str | None = None
        self._switch_policy = knobs.get_str("ARKS_MODEL_SWITCH_POLICY")
        switch_quantum = knobs.get_float("ARKS_MODEL_SWITCH_QUANTUM_S")
        if switch_quantum <= 0:
            raise ValueError(
                f"ARKS_MODEL_SWITCH_QUANTUM_S={switch_quantum}: must be > 0")
        self._switch_quantum = switch_quantum
        self._slice_t0 = time.monotonic()   # active model's timeslice epoch
        self._switch_t0: dict[str, float] = {}   # first-park time per model
        # Dispatch accounting while a model load is in flight: proves the
        # resident model kept full pipeline depth during the overlap
        # (bench --workload multi-model asserts on this).
        self._switch_stats = {"dispatches": 0, "max_depth": 0}
        self.last_switch_stats: dict | None = None

        # ---- Elastic parallelism (live resize / scale-from-zero) -------
        # A posted _ResizeRequest drives the drain -> reshard -> resume
        # state machine from the step loop; scale-to-zero disarms a fully
        # idle engine (weights + device KV dropped, host/disk prefix
        # tiers and swapped victims kept) until demand re-arms it.  All
        # engine-global: a resize outlives any one model context.
        self._resize_req: _ResizeRequest | None = None
        self._resize_active = False
        self._armed = True
        self._zero_t0 = 0.0
        self._idle_since: float | None = None
        self._rearm_loader = None   # optional (cfg, mesh) -> params
        idle_zero = knobs.get_float("ARKS_ELASTIC_IDLE_ZERO_S")
        if idle_zero < 0:
            raise ValueError(
                f"ARKS_ELASTIC_IDLE_ZERO_S={idle_zero}: must be >= 0")
        self._idle_zero_s = idle_zero
        self._elastic_warmup = knobs.get_bool("ARKS_ELASTIC_WARMUP")
        self._warmup_seq = 0
        self._rearm_fail_t = -1e9   # last failed re-arm (retry backoff)
        self._rearm_wake = threading.Event()   # interrupts the backoff
        self.last_resize_stats: dict | None = None
        self.last_rearm_stats: dict | None = None

        pre = set(vars(self))
        self._init_model_state(cfg, engine_cfg, params=params,
                               draft_params=draft_params, draft_cfg=draft_cfg)
        # Every per-model attribute name (weights, caches, mirrors, AND the
        # jit program objects _build_programs hangs on self): _switch_to
        # swaps exactly these, wholesale, between saved model contexts.
        self._model_attr_names = tuple(sorted(set(vars(self)) - pre))
        self._primary_model = cfg.name
        self._primary_ecfg = engine_cfg
        # Prefix-digest sketch exporter (cache-aware routing): summarizes
        # tier-0/tier-1 digest membership for GET /v1/cache/sketch.  One
        # per engine PROCESS, not per model — its epoch tracks this
        # engine's boot/reset lifecycle, which is what routers key sketch
        # staleness on.  Deliberately outside the _model_attr_names diff:
        # a model switch must not resurrect a pre-switch epoch.
        self._sketch = None
        if self._paged and self._chunk:
            from arks_tpu.prefix_sketch import SketchExporter
            self._sketch = SketchExporter(self._page_size())
        if self.pool is not None:
            from types import SimpleNamespace as _NS
            self.pool.adopt(cfg.name, cfg, self.params, pinned=True)
            self.pool.acquire(cfg.name)   # active-model ref, held until switch
            if self._draft_cfg is not None:
                # Satellite of ROADMAP item 3: the draft rides the shared
                # pool (pinned co-resident with the flagship) instead of a
                # second free-floating load_params tree.
                self.pool.adopt(self._draft_cfg.name, self._draft_cfg,
                                self._draft_params, pinned=True)
            if self.pool.metrics is None:
                self.pool.metrics = _NS(
                    resident_bytes=self.metrics.model_pool_resident_bytes,
                    cold_starts=self.metrics.model_cold_starts_total)
                self.pool._publish_metrics()
            # Eviction must drop the saved context too — it holds a params
            # reference, so the HBM would not actually free.
            self.pool.on_evict = lambda n: self._model_ctxs.pop(n, None)

    def _init_model_state(self, cfg: ModelConfig, engine_cfg: EngineConfig,
                          params: tf.Params | None = None,
                          draft_params: tf.Params | None = None,
                          draft_cfg: ModelConfig | None = None,
                          keep_tiers: dict | None = None) -> None:
        """Build ALL per-model engine state: weights, KV cache/allocator,
        sampling state, guide registry, host mirrors, prefix tiers, draft
        state, mixed/pipe scheduling state, and the compiled programs.

        Called from __init__ for the primary model and from _switch_to for
        each cold activation of a pool model.  Every attribute assigned
        here (captured by the __init__ vars() diff) is saved/restored
        wholesale on model switch — which is only legal because switches
        happen at FULLY DRAINED boundaries, where the mutable scheduling
        members are at their empty state.

        ``keep_tiers`` (elastic resize / scale-from-zero re-arm, same
        model, possibly a new mesh): reuse the caller-snapshotted host/
        disk/swap tiers and their worker threads instead of building
        fresh ones.  Tier blocks are full logical host arrays keyed by a
        layout epoch that excludes the mesh shape, so warm prefixes and
        swapped-out victims survive the new topology verbatim — and the
        already-running writer/fetch threads keep their queues (a fresh
        spawn would orphan both)."""
        mesh = self.mesh
        tokenizer = self.tokenizer
        self.cfg = cfg
        self.ecfg = engine_cfg
        # Per-model KV dtype preference: a checkpoint that ships
        # kv_cache_dtype in its ModelConfig wins over the engine's "auto"
        # (an explicit EngineConfig setting still overrides the model).
        if (engine_cfg.kv_cache_dtype == "auto"
                and getattr(cfg, "kv_cache_dtype", "auto") != "auto"):
            engine_cfg.kv_cache_dtype = cfg.kv_cache_dtype
            log.info("kv_cache_dtype=%s from the model config",
                     cfg.kv_cache_dtype)
        # Under pp, chunked prefill (and with it the prefix cache) is off:
        # its dynamic layer indexing would gather the stage-sharded cache.
        # Derived locally — the caller's EngineConfig is not mutated.
        self._chunk_cfg = engine_cfg.prefill_chunk if self._pp == 1 else None
        if self._pp > 1 and engine_cfg.prefill_chunk:
            log.info("pipeline parallelism: chunked prefill and the prefix "
                     "cache are disabled for this engine")
        engine_cfg.align_cache_len()
        self._buckets = engine_cfg.resolve_buckets()
        if self._pp > 1 and self._buckets[-1] < engine_cfg.max_cache_len:
            # No chunked path under pp: one-shot buckets must cover the
            # window (mirrors resolve_buckets' no-chunk behavior).
            self._buckets.append(engine_cfg.max_cache_len)
        if self._cp > 1:
            # Ring prefill shards T over 'seq': buckets must divide evenly.
            kept = [b for b in self._buckets if b % self._cp == 0]
            if not kept:
                raise ValueError(
                    f"no prefill bucket in {self._buckets} is divisible by "
                    f"the mesh seq axis ({self._cp})")
            # The whole point of cp is prompts beyond one chip's prefill
            # budget: extend the one-shot buckets to the full cache window
            # (doubling) so long prompts ride the ring instead of falling
            # into the unsharded chunked path.  Chunked prefill still serves
            # prefix-cache tails; whole-prompt chunking is pointless when
            # the ring makes one-shot prefill cp-times faster.
            while kept[-1] < engine_cfg.max_cache_len:
                nxt = min(kept[-1] * 2, engine_cfg.max_cache_len)
                if nxt % self._cp:
                    break
                kept.append(nxt)
            self._buckets = kept
        dtype = jnp.dtype(engine_cfg.dtype or cfg.dtype)

        from arks_tpu.models.quant import weight_bits
        wbits = weight_bits(engine_cfg.weight_dtype)
        tp_shards = mesh.shape.get(tf.AXIS_MODEL, 1) if mesh is not None else 1
        if params is None:
            if wbits:
                # Direct quantized init: a full-width init of an HBM-limited
                # model would OOM before quantization could shrink it.
                from arks_tpu.models import quant
                params = quant.init_params_quantized(
                    cfg, jax.random.PRNGKey(engine_cfg.seed), dtype,
                    bits=wbits, shards=tp_shards)
            else:
                params = tf.init_params(cfg, jax.random.PRNGKey(engine_cfg.seed), dtype)
        elif wbits:
            from arks_tpu.models import quant
            if not quant.is_quantized(params["layers"].get("wq")):
                params = quant.quantize_params(params, bits=wbits,
                                               shards=tp_shards)
        if mesh is not None:
            if self._pp > 1:
                from arks_tpu.parallel.pipeline import shard_params_pp
                params = shard_params_pp(params, mesh)
            else:
                params = tf.shard_params(params, cfg, mesh)
        self.params = params

        # KV cache built below, once the chunk size (= page size for the
        # paged layout) is known.
        self._sampling = sampler_mod.init_sampling_state(
            engine_cfg.num_slots, engine_cfg.seed,
            vocab_size=cfg.vocab_size)

        # Guided decoding: compiler owns the host tables; fixed-budget
        # device copies are allocated up front so compiling a guide later
        # never changes program shapes (no mid-serving retrace).  The
        # engine thread re-uploads CONTENTS when the version bumps.
        from types import SimpleNamespace

        from arks_tpu.engine.guides import GuideCompiler
        eos_all = tuple(dict.fromkeys(
            list(cfg.eos_token_ids) + list(tokenizer.eos_token_ids)))
        self.guides = GuideCompiler(
            tokenizer, cfg.vocab_size, eos_all,
            metrics=SimpleNamespace(
                compile_seconds=self.metrics.guide_compile_seconds,
                hits=self.metrics.guide_cache_hits_total,
                misses=self.metrics.guide_cache_misses_total,
                evictions=self.metrics.guide_cache_evictions_total,
                guides_in_use=self.metrics.guide_registry_guides_in_use,
                rows_in_use=self.metrics.guide_registry_rows_in_use))
        self._guide_dev = (jnp.asarray(self.guides.class_ids),
                           jnp.asarray(self.guides.trans))
        self._guide_ver = self.guides.version
        # Requests whose guide is still compiling on the worker pool, each
        # with its CompileTicket: the scheduler re-checks them every step
        # (guide_wait phase) and re-queues/fails them — the engine thread
        # itself NEVER waits on a compile.  Engine-thread-only.
        self._awaiting_guide: list = []
        # request_id -> guide key for requests holding a registry pin
        # (acquired at admission, released at every end-of-life path);
        # pinned guides are never evicted.  Engine-thread-only.
        self._guide_pins: dict[str, tuple[str, str]] = {}

        # Host-authoritative mirrors.
        self._lengths = np.zeros((engine_cfg.num_slots,), np.int32)
        self._last_token = np.zeros((engine_cfg.num_slots,), np.int32)
        self._slots: dict[int, _Slot] = {}
        self._free: list[int] = list(range(engine_cfg.num_slots))
        # Chunked prefills in progress: slot -> _ChunkState (insertion order
        # = FIFO processing).  These slots are reserved but not yet decoding.
        self._prefilling: dict[int, _ChunkState] = {}

        # Effective chunk size: the largest divisor of the cache length not
        # exceeding the configured chunk.  Chunk starts are multiples of the
        # chunk size, so divisibility guarantees every chunk's write window
        # [start, start+C) stays inside the cache (dynamic_update_slice
        # would otherwise clamp the start and corrupt earlier rows).
        self._chunk = 0
        if self._chunk_cfg:
            c = min(self._chunk_cfg, engine_cfg.max_cache_len)
            while engine_cfg.max_cache_len % c:
                c -= 1
            self._chunk = c

        # ---- KV layout: paged pool or slot-contiguous cache ------------
        self._paged = self._resolve_kv_layout()
        self._residency_window = 0
        self._residency = None
        self._alloc = None
        self._tables = None
        self._slot_pages: dict[int, list[int]] = {}
        if self._paged:
            from arks_tpu.engine.paged import PageAllocator
            page = self._page_size()
            max_pages = engine_cfg.max_cache_len // page
            self._max_pages = max_pages
            # Worst case (every slot full) always fits; the prefix budget
            # adds retention headroom on top.
            kv_bits = (engine_cfg.kv_bits if engine_cfg.kv_quantized
                       else jnp.dtype(self._cache_dtype(dtype)).itemsize * 8)
            d_store = tf.cache_head_dim(cfg, self._pad_head())
            page_bytes = (cfg.num_layers * cfg.num_kv_heads * page
                          * d_store * kv_bits // 8 * 2)
            if engine_cfg.kv_quantized:
                page_bytes += cfg.num_layers * cfg.num_kv_heads * page * 4 * 2
            extra = 0
            # Retention pages only help when prefix sharing can actually
            # register/match them, which rides the chunk path — under pp
            # (chunking off) they would be permanently dead HBM.
            if engine_cfg.prefix_cache_mb and self._chunk:
                extra = max(engine_cfg.prefix_cache_mb * 2**20 // page_bytes, 0)
                # The byte budget is tuned for 7B-class pools; cap by
                # proportion so tiny test models don't allocate huge pools.
                extra = min(extra, engine_cfg.num_slots * max_pages * 4)
            # Windowed residency (ARKS_RESIDENCY_WINDOW_PAGES): bound the
            # RESIDENT per-slot page budget below the logical table width
            # — slots whose context outgrows the window engage the
            # span-streaming decode path (engine/residency.py) instead of
            # holding their whole KV on device.  The logical tables keep
            # the full max_cache_len width; only the pool shrinks.
            window = knobs.get_int("ARKS_RESIDENCY_WINDOW_PAGES")
            if window < 0:
                raise ValueError(
                    f"ARKS_RESIDENCY_WINDOW_PAGES={window}: must be >= 0")
            per_slot = max_pages
            if window and window < max_pages:
                if window < 4:
                    raise ValueError(
                        f"ARKS_RESIDENCY_WINDOW_PAGES={window}: the window "
                        "must cover 2 hot-tail pages + 2 staging halves "
                        "(>= 4)")
                per_slot = window
                self._residency_window = window
            num_pages = engine_cfg.num_slots * per_slot + extra
            self._page_bytes = page_bytes
            self._cache = tf.init_paged_cache(
                cfg, num_pages, page, self._cache_dtype(dtype),
                quantized=engine_cfg.kv_quantized,
                pad_head=self._pad_head(),
                kv_bits=min(engine_cfg.kv_bits, 8))
            if mesh is not None:
                self._cache = self._shard_paged(self._cache)
            self._alloc = PageAllocator(num_pages, page)
            self._tables = np.zeros((engine_cfg.num_slots, max_pages),
                                    np.int32)
            # Free slots park at the coverage sentinel: their garbage
            # dispatch rows are dropped by the kernels instead of landing
            # in (possibly shared) pages.
            self._lengths[:] = self._park_sentinel()
            log.info("paged KV: %d pages x %d tokens (%d retention extra)",
                     num_pages, page, extra)
        else:
            self._max_pages = 0
            self._page_bytes = 0
            self._cache = tf.init_cache(cfg, engine_cfg.num_slots,
                                        engine_cfg.max_cache_len,
                                        self._cache_dtype(dtype),
                                        quantized=engine_cfg.kv_quantized,
                                        pad_head=self._pad_head())
            if mesh is not None:
                self._cache = self._shard_cache(self._cache)

        # Host-resident prefix KV cache (slot layout only — the paged pool
        # shares pages ON DEVICE through the allocator's index instead).
        self._prefix = None
        if engine_cfg.prefix_cache_mb and self._chunk and not self._paged:
            from arks_tpu.engine.prefix_cache import PrefixKVCache
            self._prefix = PrefixKVCache(
                self._chunk, engine_cfg.prefix_cache_mb * 2**20)

        # ---- Host-RAM spill tier behind the paged prefix index ---------
        # Tier 0 = the allocator's on-device page index (zero-copy hits);
        # tier 1 = HostPrefixTier, fed by ASYNC spills of pages the index
        # evicts under pool pressure (the pool used to DESTROY them) and
        # consulted at admission: a tier-1 hit restores the blocks with
        # one H2D scatter dispatch instead of re-prefilling them, while
        # the request parks in awaiting_restore.  Host RAM is 10-100x
        # HBM, so the shared-prefix working set a production fleet sees
        # (system prompts, few-shot preambles, multi-turn histories)
        # survives far beyond the pool's retention surplus.
        from collections import deque as _deque
        self._host = None
        self._spill_victims: list = []      # (digest, page) since last flush
        self._spills: "_deque" = _deque()   # in-flight D2H spill records
        self._awaiting_restore: list[_RestoreState] = []
        host_mb = knobs.get_int("ARKS_PREFIX_HOST_MB")
        if host_mb < 0:
            raise ValueError(
                f"ARKS_PREFIX_HOST_MB={host_mb}: must be >= 0")
        self._host_mb = host_mb if (self._paged and self._chunk
                                    and host_mb) else 0
        if keep_tiers is not None:
            # Elastic rebuild: adopt the surviving tier-1 store (blocks
            # are full logical host arrays — mesh-shape-independent).
            self._host = keep_tiers["host"]
            if self._host is not None:
                self._alloc.on_evict = self._note_evicted
        elif self._host_mb:
            from arks_tpu.engine.prefix_cache import HostPrefixTier
            self._host = HostPrefixTier(self._page_size(),
                                        self._host_mb * 2**20)
            self._alloc.on_evict = self._note_evicted
        # Fixed spill/restore group sizes: each is ONE compiled program
        # shape (short groups pad), keeping the variant budget flat.
        self._spill_group = min(8, max(self._max_pages, 1))
        self._restore_group = min(8, max(self._max_pages, 1))

        # ---- Tier-2 disk block store + fleet peer fetch ----------------
        # Tier 2 = DiskPrefixTier: a byte-budgeted local-disk store fed
        # ASYNCHRONOUSLY from tier-1 LRU evictions (host.on_evict queues
        # the victim block; a writer thread does the file IO — the step
        # loop only drains the queue).  Same chain-digest keys, same
        # pool-native blocks, so warm prefixes survive an engine restart.
        # Peer fetch makes the tiers fleet-wide: an admission miss whose
        # prefix a peer replica advertises (router X-Arks-Peer-Hint, or
        # the ARKS_PEER_ADDRS probe list) parks in _awaiting_fetch while
        # a worker pulls the raw AKV1 blocks into the host tier — the
        # unpark then rides the ordinary tier-1 restore path.
        self._disk = None
        self._disk_spill_pending: "_deque" = _deque()   # (digest, block)
        self._awaiting_fetch: list[_FetchState] = []
        self._disk_write_queue = None
        self._disk_writer = None
        self._fetch_queue = None
        self._kv_epoch = self._kv_layout_epoch()
        self._disk_stats_lock = threading.Lock()
        self._disk_evict_seen = 0
        self._disk_corrupt_seen = 0
        disk_mb = knobs.get_int("ARKS_PREFIX_DISK_MB")
        if disk_mb < 0:
            raise ValueError(
                f"ARKS_PREFIX_DISK_MB={disk_mb}: must be >= 0")
        self._peer_timeout = knobs.get_float("ARKS_PEER_FETCH_TIMEOUT_S")
        if self._peer_timeout <= 0:
            raise ValueError(
                f"ARKS_PEER_FETCH_TIMEOUT_S={self._peer_timeout}: "
                "must be > 0")
        self._peer_addrs = [a.strip() for a in knobs.get_list(
            "ARKS_PEER_ADDRS") if a.strip()]
        self._peer_fetch = (knobs.get_bool("ARKS_PEER_FETCH")
                            and self._host is not None
                            and self.dispatcher is None)
        if keep_tiers is not None:
            # Elastic rebuild: the tier-2 store, its writer/fetch worker
            # threads, and their queues all survive as-is — the threads
            # captured their queues at spawn, so fresh ones here would
            # leave the old workers consuming orphaned queues forever.
            self._disk = keep_tiers["disk"]
            self._disk_write_queue = keep_tiers["disk_write_queue"]
            self._disk_writer = keep_tiers["disk_writer"]
            self._fetch_queue = keep_tiers["fetch_queue"]
            self._disk_stats_lock = keep_tiers["disk_stats_lock"]
            self._disk_evict_seen = keep_tiers["disk_evict_seen"]
            self._disk_corrupt_seen = keep_tiers["disk_corrupt_seen"]
            if self._disk is not None and self._host is not None:
                self._host.on_evict = self._note_host_evicted
        elif disk_mb and self._host is not None and self.dispatcher is None:
            import tempfile

            from arks_tpu.engine.prefix_cache import DiskPrefixTier
            ddir = knobs.get_str("ARKS_PREFIX_DISK_DIR") or os.path.join(
                tempfile.gettempdir(), "arks-prefix-disk")
            self._disk = DiskPrefixTier(
                self._page_size(), disk_mb * 2**20, ddir,
                self._kv_layout_epoch())
            self._host.on_evict = self._note_host_evicted
            # Bounded: a spill storm drops blocks (best-effort warmth)
            # instead of growing an unbounded host-RAM backlog.
            self._disk_write_queue = queue.Queue(maxsize=256)
            self._disk_writer = threading.Thread(
                target=self._disk_write_loop, name="disk-spill",
                daemon=True)
            self._disk_writer.start()
        if keep_tiers is None and (self._disk is not None
                                   or self._peer_fetch):
            self._fetch_queue = queue.Queue()
            threading.Thread(target=self._fetch_loop,
                             name="prefix-fetch", daemon=True).start()

        # ---- Preemptive KV swap state (ARKS_PREEMPT) -------------------
        # Victim decode state (KV page blocks + sampler row) parks in a
        # keyed SwapStore sharing the host tier's byte budget; swap-mode
        # preemption therefore requires the host tier.  Engines without
        # it (slot layout, pp>1, host tier off) and spec engines (the
        # draft cache mirror has no cheap snapshot) preempt in REPLAY
        # mode instead: the victim re-queues behind a _ReplayGate and
        # deterministically re-executes (docs/application-usage.md has
        # the fallback matrix).
        self._swap = None
        if keep_tiers is not None:
            # Elastic rebuild: swapped-out victims' KV blocks are full
            # logical host pages — they resume byte-identically into the
            # new topology's pool via the ordinary restore path.
            self._swap = keep_tiers["swap"]
            self._swapped = keep_tiers["swapped"]
        elif self._host is not None:
            from arks_tpu.engine.prefix_cache import SwapStore
            self._swap = SwapStore(self._host)
        if keep_tiers is None:
            self._swapped: dict[str, _SwapRecord] = {}  # rid -> victim
        self._swap_pending: list[_SwapState] = []   # in-flight D2H swaps

        # Speculative decoding: draft model params + its own slot cache.
        self._draft_cfg = None
        self._draft_params = None
        self._draft_cache = None
        if engine_cfg.draft_model:
            if self._pp > 1:
                raise ValueError(
                    "speculative decoding is incompatible with pipeline_parallel")
            if tf.batch_axis_for(mesh) is not None:
                raise ValueError(
                    "speculative decoding requires data_parallel == 1 "
                    "(and no slice axis)")
            if engine_cfg.draft_len < 2:
                raise ValueError("draft_len must be >= 2")
            from arks_tpu.models import get_config
            dcfg = draft_cfg or get_config(engine_cfg.draft_model)
            if dcfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {dcfg.vocab_size} != target {cfg.vocab_size}"
                    " — the draft must share the target's tokenizer")
            self._draft_cfg = dcfg
            dparams = draft_params
            if dparams is None:
                dparams = tf.init_params(
                    dcfg, jax.random.PRNGKey(engine_cfg.seed + 1), dtype)
            if mesh is not None:
                dparams = tf.shard_params(dparams, dcfg, mesh)
            self._draft_params = dparams
            self._draft_cache = tf.init_cache(
                dcfg, engine_cfg.num_slots, engine_cfg.max_cache_len,
                self._cache_dtype(dtype), quantized=engine_cfg.kv_quantized,
                pad_head=self._pad_head())
            if mesh is not None:
                self._draft_cache = tf.shard_cache(self._draft_cache, dcfg, mesh)

        self._spec_proposed = 0
        self._spec_accepted = 0

        # ---- Mixed prefill+decode step (ARKS_MIXED_STEP) ---------------
        # ONE token-budget dispatch per scheduler iteration: every decoding
        # slot's next token plus up to ARKS_MIXED_CHUNK_TOKENS prefill-chunk
        # tokens spread round-robin across ALL prefilling sequences, sampled
        # in the same program.  Replaces the admit_batch x chunk_step x
        # decode_loop program family for paged engines — default ON where
        # supported; non-paged and no-chunk (pp) engines stay on the legacy
        # paths.  Speculative engines RIDE the mixed step (verify lanes are
        # q_len=draft_len rows of the same dispatch) and nothing else.
        _mx = knobs.get_str("ARKS_MIXED_STEP")
        mixed_capable = self._paged and bool(self._chunk)
        self._mixed = mixed_capable and _mx != "0"
        if _mx == "1" and not mixed_capable:
            log.warning(
                "ARKS_MIXED_STEP=1 requested but unsupported here "
                "(paged=%s chunk=%s); staying on the legacy scheduler",
                self._paged, self._chunk)
        if engine_cfg.draft_model and not self._mixed:
            raise ValueError(
                "speculative decoding rides the mixed scheduler and "
                "requires the paged KV layout with chunked prefill "
                f"(resolved kv_layout={'paged' if self._paged else 'slot'}, "
                f"prefill_chunk={self._chunk or None}, "
                f"ARKS_MIXED_STEP={_mx})")
        self._mixed_budget = 0
        # Per-qmax grid plans memoized for the padding-waste counters
        # (_mixed_grid_counters): the plan is static per engine shape, so
        # the issue path pays one dict hit per dispatch.
        self._grid_plans: dict[int, dict] = {}
        if self._mixed:
            budget = knobs.get_int("ARKS_MIXED_CHUNK_TOKENS",
                                   fallback=self._chunk)
            if budget < 1:
                raise ValueError(
                    f"ARKS_MIXED_CHUNK_TOKENS={budget}: must be >= 1")
            self._mixed_budget = min(budget, engine_cfg.max_cache_len)

        # ---- Windowed residency (ARKS_RESIDENCY_WINDOW_PAGES) ----------
        # Created only once the mixed scheduler is resolved: the manager's
        # jitted helpers replicate the mixed program's batch shapes, and
        # the span chain needs the Pallas ragged kernel (the XLA oracle
        # attend cannot carry online-softmax state across page spans).
        if self._residency_window:
            if not self._mixed:
                raise ValueError(
                    "ARKS_RESIDENCY_WINDOW_PAGES requires the mixed "
                    "scheduler (paged KV + chunked prefill, "
                    "ARKS_MIXED_STEP!=0)")
            if self._draft_cfg is not None:
                raise ValueError(
                    "ARKS_RESIDENCY_WINDOW_PAGES is incompatible with "
                    "speculative decoding (spec verify blocks never ride "
                    "the span-streaming path)")
            from arks_tpu.ops.attention import default_decode_impl
            if default_decode_impl() != "pallas":
                raise ValueError(
                    "ARKS_RESIDENCY_WINDOW_PAGES requires "
                    "ARKS_ATTN_IMPL=pallas — the span chain carries "
                    "online-softmax state through the ragged kernel; the "
                    "XLA oracle attend is one-shot")
            from arks_tpu.engine.residency import ResidencyManager
            self._residency = ResidencyManager(self, self._residency_window)
            log.info("windowed residency: %d-page window (2x%d staging), "
                     "%d-page logical tables", self._residency_window,
                     self._residency.chunk, self._max_pages)

        # ---- Pipelined decode (ARKS_PIPELINE_DEPTH) --------------------
        # Steady-state decoding free of blocking host syncs: the decode
        # state (last token / lengths / liveness) lives ON DEVICE and each
        # dispatch consumes the previous dispatch's arrays, so up to
        # ``depth`` dispatches ride the stream while results drain through
        # async copies and resolve one full pipeline slot later.  Dead
        # slots self-mask (pad token, KV writes dropped at the slot
        # sentinel) until the host retires them at resolve.  0 disables
        # (pure sequential issue/resolve).  Speculative engines pipeline
        # too: the spec_pipe program threads accepted-length/last-token
        # state on device (draft propose + ragged verify + accept inside
        # every in-flight dispatch), so the draft's propose dispatches
        # fill the bubble the resolve queue exposes instead of forcing
        # depth 0.  (The depth itself is parsed once in __init__ — it is
        # model-independent.)
        # Rows a pipelined dispatch writes per slot: spec engines write a
        # draft_len verify block, mixed engines pipeline their own
        # one-token mixed step (kernel parity across the pipeline
        # boundary), legacy engines the K-step fused loop.  Also the
        # cache-cap margin for dead_len.
        if self._draft_cfg is not None:
            self._pipe_rows = engine_cfg.draft_len
        else:
            self._pipe_rows = (1 if self._mixed
                               else engine_cfg.steps_per_dispatch)
        # In-flight dispatch records (FIFO), the threaded device state,
        # and the per-run device stop columns.  Engine-thread-only.
        self._pipe_inflight: "_deque" = _deque()
        self._pipe_state = None       # (tokens, lengths, alive) on device
        self._pipe_cols = None        # (stop_ids, dead_len) on device
        self._pipe_cols_np = None     # host copies for follower payloads
        self._pipe_last_resolve = None
        # Off-thread warmup of the pipe programs: jit's dispatch cache is
        # NOT populated by AOT lower/compile on this jax, so the warmed
        # executables are kept and called directly.  Until they exist the
        # engine stays on the (already warm) sequential path — a first
        # steady-state entry must never freeze live token streams behind
        # an inline compile.
        self._pipe_exec: dict = {}    # want_lp -> AOT-compiled executable
        self._pipe_warm_state = None  # None|"compiling"|"ready"|"failed"
        self._pipe_warm_thread = None
        # Slot registration generations: a pipelined dispatch snapshots
        # (slot, gen) pairs, so a resolve arriving after the slot was
        # retired AND re-admitted can never fan overshoot tokens into the
        # new request's stream.
        self._slot_gen = np.zeros((engine_cfg.num_slots,), np.int64)

        # Surface the RESOLVED configuration — the auto decisions, not the
        # requested ones — as an _info gauge and one startup log line, so
        # bench_serving / Grafana / an operator can tell which perf
        # envelope this replica actually runs (round-3 verdict: the
        # kv_layout=auto decision was logged-only and invisible outside).
        from arks_tpu.ops.attention import default_decode_impl
        from arks_tpu.ops import autotune
        from arks_tpu.ops.paged_attention import mixed_grid_mode
        self._admit_sizes = self._admit_batch_sizes()
        self.resolved_config = {
            "kv_layout": "paged" if self._paged else "slot",
            "decode_impl": default_decode_impl(),
            "admit_batch_sizes": ",".join(map(str, self._admit_sizes)),
            "pad_head": str(bool(self._pad_head())).lower(),
            "overlap": str(bool(self._overlap)).lower(),
            "kv_cache_dtype": self.ecfg.resolve_kv_cache_dtype(),
            "kv_dtype": self.ecfg.resolve_kv_cache_dtype(),
            "kernel_tune": autotune.mode(),
            "mixed_grid": mixed_grid_mode(),
            "weight_dtype": self.ecfg.weight_dtype or "native",
            "model": self.ecfg.model,
            "mixed_step": str(bool(self._mixed)).lower(),
            "pipeline_depth": str(self._pipe_depth),
            "prefix_host_mb": str(self._host_mb),
            # Spec engines run draft+verify inside the mixed dispatch (the
            # legacy fused spec loop is gone) — "true" whenever a draft
            # model is configured, since the mixed scheduler is a hard
            # requirement for speculation.
            "spec_mixed": str(self._draft_cfg is not None).lower(),
            # "swap" = preemption spills victim decode state to host RAM;
            # "replay" = victims re-queue and re-execute; "off" = priority
            # is pure queue ordering (the fallback matrix in
            # docs/application-usage.md).
            "preempt": ("off" if not self._preempt_on else
                        "swap" if self._preempt_swap_capable() else
                        "replay"),
            # Live topology (elastic resize rewrites these in place): the
            # mesh axes actually populated, not the requested config.
            "tensor_parallel": str(
                self.mesh.shape.get(tf.AXIS_MODEL, 1)
                if self.mesh is not None else 1),
            "data_parallel": str(
                self.mesh.shape.get("data", 1)
                if self.mesh is not None else 1),
        }
        self.metrics.engine_config_info.set(1, **self.resolved_config)
        log.info("engine resolved config: %s",
                 " ".join(f"{k}={v}" for k, v in
                          sorted(self.resolved_config.items())))

        # ARKS_KERNEL_TUNE=sweep benchmarks candidate kernel blocks for
        # THIS shape now, so _build_programs (and every later dispatch)
        # resolves tuned statics by pure table lookup only.
        self._warm_autotune()
        self._build_programs()

    def _warm_autotune(self) -> None:
        """ARKS_KERNEL_TUNE=sweep warm-up: benchmark the mixed kernel's
        (block_q, dma_depth) candidates at THIS engine's shape and persist
        the winner (ops.autotune.sweep).  Runs once, before any program is
        built — the serving step loop can only reach autotune.lookup (the
        hot-path guard asserts this split), and the table entry resolves
        to the same statics every time, so a persisted winner costs zero
        extra compiled variants."""
        from arks_tpu.ops import autotune
        if autotune.mode() != "sweep" or not self._paged or not self._mixed:
            return
        from arks_tpu.ops.paged_attention import paged_mixed_attention
        cfg = self.cfg
        hkv = cfg.num_kv_heads
        g = cfg.num_heads // hkv
        d = tf.cache_head_dim(cfg, self._pad_head())
        page = self._page_size()
        qmax = self._mixed_budget + 1
        kvd = self.ecfg.resolve_kv_cache_dtype()
        kv = kvd if kvd in ("int8", "int4") else str(self._cache.k.dtype)
        sig = autotune.mixed_signature(hkv=hkv, g=g, d=d, page=page,
                                       qmax=qmax, kv=kv)
        if autotune.lookup("paged_mixed", sig) is not None:
            return
        s = self.ecfg.num_slots
        # Representative traffic on the engine's own (zeroed) pool: one
        # full prefill chunk + decode lanes, tables pointing at real pages.
        q = jnp.ones((s, hkv, g, qmax, d), jnp.float32)
        tables = jnp.zeros((s, self._max_pages), jnp.int32)
        pos = np.full((s,), page // 2, np.int32)
        ql = np.ones((s,), np.int32)
        ql[0] = qmax
        pos[0] = 0
        pos_j, ql_j = jnp.asarray(pos), jnp.asarray(ql)
        layer = jnp.asarray(0, jnp.int32)
        interpret = jax.default_backend() != "tpu"

        def bench(block_q: int, dma_depth: int,
                  head_group: int = hkv) -> None:
            out = paged_mixed_attention(
                q, self._cache.k, self._cache.v, tables, pos_j, ql_j,
                layer, self._cache.k_scale, self._cache.v_scale,
                block_q=block_q, interpret=interpret, dma_depth=dma_depth,
                head_group=head_group)
            np.asarray(out)  # block until the kernel actually ran

        # GQA head grouping shrinks per-item VMEM by hkv/head_group, so
        # grouped candidates may afford proportionally larger q blocks —
        # the block_q growth is where the bytes-moved win comes from.
        hgs = sorted({h for h in (1, 2, hkv) if hkv % h == 0})
        cands = [{"block_q": min(bq * (hkv // hg), qmax), "dma_depth": dd,
                  "head_group": hg}
                 for bq in (8, 16, 32)
                 for dd in (2, 4)
                 for hg in hgs]
        # De-dup candidates that clamp to the same statics.
        cands = [dict(t) for t in
                 sorted({tuple(sorted(c.items())) for c in cands})]
        autotune.sweep("paged_mixed", sig, cands, bench)

    # ------------------------------------------------------------------
    # Compiled programs
    # ------------------------------------------------------------------

    def _build_programs(self) -> None:
        cfg, mesh = self.cfg, self.mesh
        batch_axis = tf.batch_axis_for(mesh)  # ("slice","data") on multislice
        # Context parallelism: prefill's T shards over 'seq' and attention
        # runs as a ring (parallel.ring) — serving reaches the same
        # long-context path the trainer and dryrun exercise.
        seq_axis = "seq" if self._cp > 1 else None
        K = self.ecfg.steps_per_dispatch
        # Pipeline parallelism: stage-sharded prefill/decode programs with
        # microbatch overlap when slots divide evenly (else M=1, a plain
        # sequential pipeline — still correct, no overlap).
        if self._pp > 1:
            from arks_tpu.parallel import pipeline as pp_mod
            num_mb = self._pp if self.ecfg.num_slots % self._pp == 0 else 1

            def model_prefill(params, tokens, length):
                return pp_mod.pp_prefill(params, cfg, tokens, length, mesh)

            def model_decode(params, cache, tokens, lengths, tables=None):
                if tables is not None:
                    return pp_mod.pp_decode_step_paged(
                        params, cfg, cache, tables, tokens, lengths, mesh,
                        num_mb)
                return pp_mod.pp_decode_step(params, cfg, cache, tokens,
                                             lengths, mesh, num_mb)
        else:
            def model_prefill(params, tokens, length):
                return tf.prefill(params, cfg, tokens, length, mesh,
                                  seq_axis=seq_axis)

            def model_decode(params, cache, tokens, lengths, tables=None):
                return tf.decode_step(params, cfg, cache, tokens, lengths,
                                      mesh, batch_axis, tables=tables)

        # Detached (disaggregated) prefill: same math, but the KV comes
        # back REPLICATED over the mesh — on a multi-host gang the leader
        # must materialize the full [L,1,T,Hkv,D] block for the wire
        # transfer, and sharded outputs are not addressable across hosts.
        # (No-op constraint single-host.)
        def _replicate(x):
            if mesh is None or mesh.size == 1:
                return x
            from jax.sharding import NamedSharding, PartitionSpec
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, PartitionSpec()))

        def prefill_detached_prog(params, tokens, length, temperature,
                                  top_p, top_k, key, bias_ids, bias_vals,
                                  sup_ids, min_first, guide, guide_row,
                                  gtables, want_lp: bool):
            logits, ks, vs = model_prefill(params, tokens, length)
            state = sampler_mod.transient_state(
                temperature, top_p, top_k, key, cfg.vocab_size,
                bias_ids, bias_vals, sup_ids, min_first,
                guide=guide, guide_row=guide_row)
            ids, _ = sampler_mod.sample(logits, state, guide_tables=gtables)
            ks, vs = _replicate(ks), _replicate(vs)
            if want_lp:
                clp, vals, lids = sampler_mod.top_logprobs(logits, ids)
                return ids[0], clp[0], vals[0], lids[0], ks, vs
            return ids[0], ks, vs

        self._prefill_detached_fn = jax.jit(
            functools.partial(prefill_detached_prog, want_lp=False))
        self._prefill_detached_lp_fn = jax.jit(
            functools.partial(prefill_detached_prog, want_lp=True))
        # Lambda wrapper (here and for the other module-level tf.* jits
        # below): jit's trace cache is keyed on the underlying callable,
        # so a bare jax.jit(tf.insert) would share one process-wide cache
        # across engines and leak other engines' shape variants into
        # compiled_program_variants().
        self._insert_fn = jax.jit(lambda *a: tf.insert(*a),
                                  donate_argnums=(0,))

        # Fused BATCHED admission: M queued prompts prefill + sample +
        # insert + set_slot in ONE dispatch.  Under churn admissions were
        # 71% of engine wall time as single dispatches (bench_serving.py's
        # scheduler_seconds_total breakdown); batching amortizes the
        # per-dispatch round-trip AND raises prefill MXU utilization.  One
        # compiled program per (bucket, M, lp) combination — M is drawn
        # from _admit_batch_sizes() so the variant count stays bounded.
        def admit_batch(params, cache, sampling, tokens, lengths, slots,
                        pages, n_pages, temps, top_ps, top_ks, keys, pres,
                        freqs, bias_ids, bias_vals, sup_ids, min_first,
                        min_until, guide, guide_row, gtables, want_lp: bool):
            logits, ks, vs = model_prefill(params, tokens, lengths)
            tstate = sampler_mod.transient_state_batch(
                temps, top_ps, top_ks, keys, cfg.vocab_size,
                bias_ids, bias_vals, sup_ids, min_first,
                guide=guide, guide_row=guide_row)
            ids, tstate = sampler_mod.sample(logits, tstate,
                                             guide_tables=gtables)
            if self._paged:
                # Buckets smaller than a page: pad T up so the page-insert
                # loop can slice whole pages (tail rows masked by length).
                pad = (-ks.shape[2]) % self._page_size()
                if pad:
                    width = ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0))
                    ks_in = jnp.pad(ks, width)
                    vs_in = jnp.pad(vs, width)
                else:
                    ks_in, vs_in = ks, vs
                cache = tf.insert_pages_batch(cache, ks_in, vs_in, pages,
                                              n_pages)
            else:
                cache = tf.insert_batch(cache, ks, vs, slots)
            fold = jax.vmap(lambda k: jax.random.fold_in(k, 1))(keys)
            # tstate's guide_row was advanced by the first sampled token —
            # the decode loop continues the DFA from there.
            sampling = sampler_mod.set_slots(
                sampling, slots, temps, top_ps, top_ks, fold, pres, freqs,
                bias_ids, bias_vals, sup_ids, min_until,
                guide=guide, guide_row=tstate.guide_row)
            if want_lp:
                clp, vals, lids = sampler_mod.top_logprobs(logits, ids)
                return ids, clp, vals, lids, cache, sampling, ks, vs
            return ids, cache, sampling, ks, vs

        self._admit_fn = jax.jit(functools.partial(admit_batch, want_lp=False),
                                 donate_argnums=(1, 2))
        self._admit_lp_fn = jax.jit(functools.partial(admit_batch, want_lp=True),
                                    donate_argnums=(1, 2))

        if self._paged:
            def chunk_step(params, cache, tables_row, tokens, start, valid):
                return tf.prefill_chunk_paged(params, cfg, cache, tables_row,
                                              tokens, start, valid, mesh)
        else:
            def chunk_step(params, cache, slot, tokens, start, valid):
                return tf.prefill_chunk(params, cfg, cache, slot, tokens,
                                        start, valid, mesh)

        self._chunk_fn = jax.jit(chunk_step, donate_argnums=(1,))
        if self._paged:
            self._insert_pages_fn = jax.jit(
                lambda *a: tf.insert_pages(*a), donate_argnums=(0,))
            # Host-tier spill/restore: gather evicted pages into a D2H
            # staging block; scatter host blocks back into fresh pool
            # pages.  The restore returns a marker READ FROM the written
            # pool, so marker.is_ready() == "the scatter landed" (a
            # passed-through input would alias and read ready instantly).
            self._spill_gather_fn = jax.jit(
                lambda *a, **kw: tf.gather_pool_pages(*a, **kw))

            def restore_scatter(cache, kb, vb, ksb, vsb, pages, n_valid):
                cache = tf.scatter_pool_pages(cache, kb, vb, pages, n_valid,
                                              k_scale=ksb, v_scale=vsb)
                return cache, cache.k[0, 0, 0, 0, 0]

            self._restore_fn = jax.jit(restore_scatter, donate_argnums=(0,))

            # Preemptive swap (ARKS_PREEMPT): one victim slot's sampler
            # row out (the D2H decode-state snapshot: PRNG key, penalty
            # counts, DFA row — everything sample() evolves per slot) and
            # its counts back on resume (key/guide_row ride set_slot,
            # which RESETS counts — hence the separate restore).
            self._sampler_row_fn = jax.jit(
                lambda st, slot: (st.key[slot], st.counts[slot],
                                  st.guide_row[slot]))
            self._restore_counts_fn = jax.jit(
                lambda st, slot, row: st._replace(
                    counts=st.counts.at[slot].set(row)),
                donate_argnums=(0,))

        def sample_one(logits, temperature, top_p, top_k, key,
                       bias_ids, bias_vals, sup_ids, min_first,
                       guide, guide_row, gtables):
            state = sampler_mod.transient_state(
                temperature, top_p, top_k, key, cfg.vocab_size,
                bias_ids, bias_vals, sup_ids, min_first,
                guide=guide, guide_row=guide_row)
            ids, _ = sampler_mod.sample(logits, state, guide_tables=gtables)
            return ids[0]

        self._sample_one_fn = jax.jit(sample_one)

        def sample_one_lp(logits, temperature, top_p, top_k, key,
                          bias_ids, bias_vals, sup_ids, min_first,
                          guide, guide_row, gtables):
            state = sampler_mod.transient_state(
                temperature, top_p, top_k, key, cfg.vocab_size,
                bias_ids, bias_vals, sup_ids, min_first,
                guide=guide, guide_row=guide_row)
            ids, _ = sampler_mod.sample(logits, state, guide_tables=gtables)
            clp, vals, lids = sampler_mod.top_logprobs(logits, ids)
            return ids[0], clp[0], vals[0], lids[0]

        self._sample_one_lp_fn = jax.jit(sample_one_lp)

        dtype = jnp.dtype(self.ecfg.dtype or cfg.dtype)
        self._extract_fn = jax.jit(
            lambda cache, slot: tf.extract(cache, slot, dtype))

        # Donated slot-state writes: eager .at[].set() would copy the whole
        # [num_slots, vocab] penalty-counts buffer on EVERY admission
        # (~117MB at 192 slots x 152k vocab); donation updates in place.
        # Per-engine lambda wrappers: jax.jit's trace cache is keyed on the
        # underlying callable, so jitting the module-level functions
        # directly would share one process-wide cache across engines and
        # make compiled_program_variants() report shapes traced by OTHER
        # engines (order-dependent compile-budget counts under pytest).
        self._set_slot_fn = jax.jit(
            lambda *a, **kw: sampler_mod.set_slot(*a, **kw),
            donate_argnums=(0,))
        self._clear_pen_fn = jax.jit(
            lambda *a, **kw: sampler_mod.clear_slot_penalties(*a, **kw),
            donate_argnums=(0,))

        # Free/pending slots park their lengths at this write-drop value;
        # the fused loop derives the active mask from it so PRNG keys and
        # penalty counts only advance for REGISTERED slots (deferred
        # admissions put decode dispatches between a slot's admit program
        # and its registration — see _drain_ready_admits).
        sentinel = self._park_sentinel()

        def decode_loop(params, cache, tokens, lengths, sstate, tables,
                        gtables):
            def body(carry, _):
                cache, tokens, lengths, sstate = carry
                active = lengths < sentinel
                # Feed-time counting: every generated token is fed exactly
                # once, which keeps the presence/frequency counts right
                # across the one-shot, chunked, and disagg admission paths.
                sstate = sampler_mod.count_tokens(sstate, tokens, active)
                logits, cache = model_decode(params, cache, tokens, lengths,
                                             tables)
                nxt, sstate = sampler_mod.sample(logits, sstate, active,
                                                 lengths,
                                                 guide_tables=gtables)
                return (cache, nxt, lengths + 1, sstate), nxt

            (cache, tokens, lengths, sstate), toks = jax.lax.scan(
                body, (cache, tokens, lengths, sstate), None, length=K)
            return cache, sstate, toks  # toks [K, B]

        self._decode_fn = jax.jit(decode_loop, donate_argnums=(1, 4))

        def decode_loop_lp(params, cache, tokens, lengths, sstate, tables,
                           gtables):
            # The logprob variant: selected per dispatch when any live slot
            # asked for logprobs (separate compiled program — the common
            # case never pays the full-vocab log-softmax).
            def body(carry, _):
                cache, tokens, lengths, sstate = carry
                active = lengths < sentinel
                sstate = sampler_mod.count_tokens(sstate, tokens, active)
                logits, cache = model_decode(params, cache, tokens, lengths,
                                             tables)
                nxt, sstate = sampler_mod.sample(logits, sstate, active,
                                                 lengths,
                                                 guide_tables=gtables)
                clp, vals, lids = sampler_mod.top_logprobs(logits, nxt)
                return (cache, nxt, lengths + 1, sstate), (nxt, clp, vals, lids)

            (cache, tokens, lengths, sstate), outs = jax.lax.scan(
                body, (cache, tokens, lengths, sstate), None, length=K)
            return cache, sstate, outs  # ([K,B], [K,B], [K,B,L], [K,B,L])

        self._decode_lp_fn = jax.jit(decode_loop_lp, donate_argnums=(1, 4))

        # Pipelined decode program (ARKS_PIPELINE_DEPTH): the fused loop
        # with DEVICE-RESIDENT state — tokens/lengths/liveness come in as
        # arrays threaded from the PREVIOUS dispatch and go back out
        # updated, so the next dispatch needs no host values at all.  Dead
        # slots run masked at the park sentinel (pad fed, KV writes
        # dropped, keys/penalties frozen) and end-of-dispatch liveness
        # replicates the host's retire condition exactly
        # (sampler.advance_liveness) — which is what keeps token streams
        # byte-identical to the sequential path at any depth.
        if self._pp > 1:
            def model_decode_state(params, cache, tokens, lengths, alive,
                                   tables=None):
                eff = jnp.where(alive, lengths, jnp.int32(sentinel))
                return model_decode(params, cache, tokens, eff, tables)
        else:
            def model_decode_state(params, cache, tokens, lengths, alive,
                                   tables=None):
                return tf.decode_state_step(params, cfg, cache, tokens,
                                            lengths, alive, sentinel, mesh,
                                            batch_axis, tables=tables)

        def decode_pipe(params, cache, tokens, lengths, alive, stop_ids,
                        dead_len, sstate, tables, gtables, want_lp: bool):
            def body(carry, _):
                cache, tokens, lengths, sstate = carry
                eff = jnp.where(alive, lengths, jnp.int32(sentinel))
                active = eff < sentinel
                sstate = sampler_mod.count_tokens(sstate, tokens, active)
                logits, cache = model_decode_state(params, cache, tokens,
                                                   lengths, alive, tables)
                nxt, sstate = sampler_mod.sample(logits, sstate, active,
                                                 eff, guide_tables=gtables)
                nxt = jnp.where(alive, nxt, jnp.int32(0))
                if want_lp:
                    clp, vals, lids = sampler_mod.top_logprobs(logits, nxt)
                    out = (nxt, clp, vals, lids)
                else:
                    out = nxt
                return (cache, nxt, lengths + 1, sstate), out

            (cache, tokens, lengths, sstate), outs = jax.lax.scan(
                body, (cache, tokens, lengths, sstate), None, length=K)
            toks = outs[0] if want_lp else outs          # [K, B]
            alive = sampler_mod.advance_liveness(toks, alive, lengths,
                                                 stop_ids, dead_len)
            tokens = jnp.where(alive, tokens, jnp.int32(0))
            if want_lp:
                return (cache, sstate, toks, outs[1], outs[2], outs[3],
                        tokens, lengths, alive)
            return cache, sstate, toks, tokens, lengths, alive

        self._decode_pipe_fn = jax.jit(
            functools.partial(decode_pipe, want_lp=False),
            donate_argnums=(1, 2, 3, 4, 7))
        self._decode_pipe_lp_fn = jax.jit(
            functools.partial(decode_pipe, want_lp=True),
            donate_argnums=(1, 2, 3, 4, 7))

        if self._mixed:
            # The unified mixed prefill+decode program: count the decode
            # feed, run ONE model forward over the flat token batch, then
            # ONE sampler.sample over every lane — persistent rows for
            # decoding slots, transient override columns (packed per lane)
            # for sequences whose prompt completes this step.  Only key and
            # guide-row advances of DECODE lanes merge back into the
            # persistent state; completion lanes are written by the host's
            # set_slot at registration, exactly like the legacy chunk path.
            def mixed_prog(params, cache, sampling, tokens, token_slot,
                           token_pos, tables, feed_tokens, feed_active,
                           lengths, sample_src, seq_q_start, seq_q_len,
                           seq_pos_start, ov_mask, ov_temp, ov_top_p,
                           ov_top_k, ov_key, ov_bias_ids, ov_bias_vals,
                           ov_sup, ov_min_until, ov_guide, ov_guide_row,
                           gtables, want_lp: bool):
                sampling = sampler_mod.count_tokens(sampling, feed_tokens,
                                                    feed_active)
                logits, cache = tf.mixed_step(
                    params, cfg, cache, tables, tokens, token_slot,
                    token_pos, sample_src, seq_q_start, seq_q_len,
                    seq_pos_start, mesh)
                ovc = ov_mask[:, None]
                # Completion lanes sample with transient first-token
                # semantics: penalties are identity (their output is
                # empty — counts don't matter once presence/frequency are
                # zeroed), bias/suppression/guide come from the override
                # columns, and min_until is pre-shifted by the host so
                # ``lengths < min_until`` reads as the min_first flag.
                eff = sampling._replace(
                    temperature=jnp.where(ov_mask, ov_temp,
                                          sampling.temperature),
                    top_p=jnp.where(ov_mask, ov_top_p, sampling.top_p),
                    top_k=jnp.where(ov_mask, ov_top_k, sampling.top_k),
                    key=jnp.where(ovc, ov_key, sampling.key),
                    presence=jnp.where(ov_mask, 0.0, sampling.presence),
                    frequency=jnp.where(ov_mask, 0.0, sampling.frequency),
                    bias_ids=jnp.where(ovc, ov_bias_ids, sampling.bias_ids),
                    bias_vals=jnp.where(ovc, ov_bias_vals,
                                        sampling.bias_vals),
                    suppress_ids=jnp.where(ovc, ov_sup,
                                           sampling.suppress_ids),
                    min_until=jnp.where(ov_mask, ov_min_until,
                                        sampling.min_until),
                    guide=jnp.where(ov_mask, ov_guide, sampling.guide),
                    guide_row=jnp.where(ov_mask, ov_guide_row,
                                        sampling.guide_row))
                ids, eff2 = sampler_mod.sample(logits, eff, feed_active,
                                               lengths,
                                               guide_tables=gtables)
                sampling = sampling._replace(
                    key=jnp.where(feed_active[:, None], eff2.key,
                                  sampling.key),
                    guide_row=jnp.where(feed_active, eff2.guide_row,
                                        sampling.guide_row))
                if want_lp:
                    clp, vals, lids = sampler_mod.top_logprobs(logits, ids)
                    return ids, clp, vals, lids, cache, sampling
                return ids, cache, sampling

            self._mixed_fn = jax.jit(
                functools.partial(mixed_prog, want_lp=False),
                donate_argnums=(1, 2))
            self._mixed_lp_fn = jax.jit(
                functools.partial(mixed_prog, want_lp=True),
                donate_argnums=(1, 2))

            # Device-state mixed variant (ARKS_PIPELINE_DEPTH): the
            # steady-state (decode-only) mixed step consuming threaded
            # token/length/liveness arrays.  ONE token per dispatch like
            # every mixed dispatch, and the SAME mixed kernel — the fused
            # K-step loop is mathematically equal but not bitwise equal
            # (fp reassociation), and a kernel switch at the pipeline
            # boundary would let sampled streams diverge across depths.
            B = self.ecfg.num_slots
            lane = jnp.arange(B, dtype=jnp.int32)

            def mixed_pipe(params, cache, tokens, lengths, alive, stop_ids,
                           dead_len, sstate, tables, gtables, want_lp: bool):
                eff = jnp.where(alive, lengths, jnp.int32(sentinel))
                sstate = sampler_mod.count_tokens(sstate, tokens, alive)
                # Decode-only flat batch, lane t == slot t: dead lanes
                # park at the sentinel position (writes dropped, nothing
                # attended) exactly like the host-built batch's padding.
                logits, cache = tf.mixed_step(
                    params, cfg, cache, tables, tokens,
                    jnp.where(alive, lane, jnp.int32(-1)), eff,
                    lane, lane, alive.astype(jnp.int32), eff, mesh)
                nxt, sstate = sampler_mod.sample(logits, sstate, alive,
                                                 eff, guide_tables=gtables)
                nxt = jnp.where(alive, nxt, jnp.int32(0))
                lengths = lengths + 1
                alive = sampler_mod.advance_liveness(
                    nxt[None], alive, lengths, stop_ids, dead_len)
                tokens_out = jnp.where(alive, nxt, jnp.int32(0))
                if want_lp:
                    clp, vals, lids = sampler_mod.top_logprobs(logits, nxt)
                    # [1, B]-shaped outputs so the resolve fanout shares
                    # the K-step record format.
                    return (cache, sstate, nxt[None], clp[None],
                            vals[None], lids[None], tokens_out, lengths,
                            alive)
                return (cache, sstate, nxt[None], tokens_out, lengths,
                        alive)

            self._mixed_pipe_fn = jax.jit(
                functools.partial(mixed_pipe, want_lp=False),
                donate_argnums=(1, 2, 3, 4, 7))
            self._mixed_pipe_lp_fn = jax.jit(
                functools.partial(mixed_pipe, want_lp=True),
                donate_argnums=(1, 2, 3, 4, 7))

        if self._draft_cfg is not None:
            dcfg = self._draft_cfg
            DK = self.ecfg.draft_len
            B = self.ecfg.num_slots
            lane = jnp.arange(B, dtype=jnp.int32)
            blk = jnp.arange(DK, dtype=jnp.int32)

            def draft_prefill_insert(dparams, dcache, tokens, length, slot):
                _, ks, vs = tf.prefill(dparams, dcfg, tokens, length, mesh)
                return tf.insert(dcache, ks, vs, slot)

            self._draft_prefill_fn = jax.jit(draft_prefill_insert,
                                             donate_argnums=(1,))

            def draft_propose(dparams, dcache, tokens, lengths, sstate):
                """DK-step draft scan: propose DK-1 tokens per lane (greedy
                lanes argmax, sampled lanes draw from their effective
                filtered distribution).  DK steps, not DK-1: the extra
                step writes the LAST draft token's KV row, so after a
                fully-accepted block the next dispatch's draft attends a
                complete prefix (without it, row L+DK-1 is garbage and
                the draft mispredicts every DK-th token even when
                draft == target).  Parked lanes (lengths at the sentinel)
                drop their slot-cache writes like any other decode."""
                def body(carry, _):
                    dcache, tok, ln, keys = carry
                    logits, dcache = tf.decode_step(dparams, dcfg, dcache,
                                                    tok, ln, mesh)
                    tok, q, qp, qi, keys = sampler_mod.draft_sample(
                        logits, sstate, keys)
                    return (dcache, tok, ln + 1, keys), (tok, q, qp, qi)

                (dcache, _, _, keys), (toks, qs, qps, qis) = jax.lax.scan(
                    body, (dcache, tokens, lengths, sstate.key), None,
                    length=DK)
                drafts = jnp.swapaxes(toks, 0, 1)[:, : DK - 1]   # [B, DK-1]
                q_sel = jnp.swapaxes(qs, 0, 1)[:, : DK - 1]
                q_probs = jnp.swapaxes(qps, 0, 1)[:, : DK - 1]   # [B,DK-1,W]
                q_idx = jnp.swapaxes(qis, 0, 1)[:, : DK - 1]
                return dcache, drafts, q_sel, q_probs, q_idx, keys

            # Ragged spec-mixed program: draft propose + multi-token
            # verify + acceptance INSIDE the one mixed dispatch that also
            # carries prefill chunks.  Every decoding lane owns a fixed
            # q_len=DK verify block (rows [b*DK, (b+1)*DK) of the flat
            # batch — row 0 its last token, rows 1.. the draft's
            # proposals, scattered in ON DEVICE so no host sync touches
            # them); the chunk region starts at B*DK.  Verify logits are
            # just DK extra sample positions of the same tf.mixed_step
            # call — the per-spec verify program family is gone.
            spec_rows = (lane[:, None] * DK + 1
                         + jnp.arange(DK - 1, dtype=jnp.int32)[None, :]
                         ).reshape(-1)
            vsrc = jnp.arange(B * DK, dtype=jnp.int32)

            def spec_mixed_prog(params, dparams, cache, dcache, sampling,
                                tokens, token_slot, token_pos, tables,
                                feed_tokens, feed_active, lengths,
                                sample_src, seq_q_start, seq_q_len,
                                seq_pos_start, spec_enable, ov_mask,
                                ov_temp, ov_top_p, ov_top_k, ov_key,
                                ov_bias_ids, ov_bias_vals, ov_sup,
                                ov_min_until, ov_guide, ov_guide_row,
                                gtables, want_lp: bool):
                # Feed-time counting: spec-DISABLED penalized lanes
                # advance one normally-sampled token per dispatch, so
                # their counts must evolve; eligible lanes are
                # penalty-free and reset at slot reuse.
                sampling = sampler_mod.count_tokens(sampling, feed_tokens,
                                                    feed_active)
                dcache, drafts, q_sel, q_probs, q_idx, dkeys = \
                    draft_propose(dparams, dcache, feed_tokens, lengths,
                                  sampling)
                # Proposals land in every lane's verify block; lanes that
                # are not decoding this step keep padding rows
                # (token_slot=-1), so the scattered values write nothing.
                tokens = tokens.at[spec_rows].set(drafts.reshape(-1))
                src = jnp.concatenate([vsrc, sample_src])
                logits_all, cache = tf.mixed_step(
                    params, cfg, cache, tables, tokens, token_slot,
                    token_pos, src, seq_q_start, seq_q_len, seq_pos_start,
                    mesh)
                vlogits = logits_all[: B * DK].reshape(B, DK, -1)
                samp_logits = logits_all[B * DK:]               # [B, V]
                # Prompt-completing lanes: transient first-token sampling
                # with the override columns — identical semantics to the
                # plain mixed program (their persistent rows are written
                # by set_slot at registration).
                ovc = ov_mask[:, None]
                eff = sampling._replace(
                    temperature=jnp.where(ov_mask, ov_temp,
                                          sampling.temperature),
                    top_p=jnp.where(ov_mask, ov_top_p, sampling.top_p),
                    top_k=jnp.where(ov_mask, ov_top_k, sampling.top_k),
                    key=jnp.where(ovc, ov_key, sampling.key),
                    presence=jnp.where(ov_mask, 0.0, sampling.presence),
                    frequency=jnp.where(ov_mask, 0.0, sampling.frequency),
                    bias_ids=jnp.where(ovc, ov_bias_ids, sampling.bias_ids),
                    bias_vals=jnp.where(ovc, ov_bias_vals,
                                        sampling.bias_vals),
                    suppress_ids=jnp.where(ovc, ov_sup,
                                           sampling.suppress_ids),
                    min_until=jnp.where(ov_mask, ov_min_until,
                                        sampling.min_until),
                    guide=jnp.where(ov_mask, ov_guide, sampling.guide),
                    guide_row=jnp.where(ov_mask, ov_guide_row,
                                        sampling.guide_row))
                comp_ids, _ = sampler_mod.sample(samp_logits, eff, ov_mask,
                                                 lengths,
                                                 guide_tables=gtables)
                # Decoding lanes (enabled AND disabled) advance through
                # the rejection kernel — verify-aware guide advancement
                # included, so guided lanes speculate instead of being
                # carved out.
                out, counts, carry_keys, grow = \
                    sampler_mod.speculative_accept(
                        drafts, q_sel, q_probs, q_idx, vlogits, sampling,
                        dkeys, enable=spec_enable, lengths=lengths,
                        guide_tables=gtables)
                sampling = sampling._replace(
                    key=jnp.where(feed_active[:, None], carry_keys,
                                  sampling.key),
                    guide_row=jnp.where(feed_active, grow,
                                        sampling.guide_row))
                counts = jnp.maximum(counts, 1)
                if want_lp:
                    # Raw-distribution logprobs for the ONE token each
                    # disabled lp lane advanced (enabled lanes never carry
                    # logprobs — eligibility excludes them) and for
                    # completing lanes' first tokens, in one call.
                    lane_logits = jnp.where(ovc, samp_logits,
                                            vlogits[:, 0])
                    chosen = jnp.where(ov_mask, comp_ids, out[:, 0])
                    clp, vals, lids = sampler_mod.top_logprobs(lane_logits,
                                                               chosen)
                    return (out, counts, comp_ids, clp, vals, lids, cache,
                            dcache, sampling)
                return out, counts, comp_ids, cache, dcache, sampling

            self._spec_mixed_fn = jax.jit(
                functools.partial(spec_mixed_prog, want_lp=False),
                donate_argnums=(2, 3, 4))
            self._spec_mixed_lp_fn = jax.jit(
                functools.partial(spec_mixed_prog, want_lp=True),
                donate_argnums=(2, 3, 4))

            # Device-state spec variant (ARKS_PIPELINE_DEPTH): the
            # steady-state (decode-only) spec step consuming threaded
            # token/length/liveness arrays — draft propose + ragged verify
            # + accept per dispatch with NO host values, so the draft's
            # propose work fills the resolve-queue bubble instead of
            # forcing spec engines sequential.  Same tf.mixed_step kernel
            # as the fresh-entry program (per-row math is lane-local, so
            # streams stay byte-identical across depths).
            def spec_pipe(params, dparams, cache, dcache, tokens, lengths,
                          alive, stop_ids, dead_len, spec_col, sstate,
                          tables, gtables, want_lp: bool):
                eff = jnp.where(alive, lengths, jnp.int32(sentinel))
                sstate = sampler_mod.count_tokens(sstate, tokens, alive)
                dcache, drafts, q_sel, q_probs, q_idx, dkeys = \
                    draft_propose(dparams, dcache, tokens, eff, sstate)
                block = jnp.concatenate([tokens[:, None], drafts], axis=1)
                flat_slot = jnp.repeat(
                    jnp.where(alive, lane, jnp.int32(-1)), DK)
                flat_pos = (eff[:, None] + blk[None, :]).reshape(-1)
                src = jnp.concatenate([vsrc, lane * DK])
                logits_all, cache = tf.mixed_step(
                    params, cfg, cache, tables, block.reshape(-1),
                    flat_slot, flat_pos, src, lane * DK,
                    jnp.where(alive, DK, 0).astype(jnp.int32), eff, mesh)
                vlogits = logits_all[: B * DK].reshape(B, DK, -1)
                out, counts, carry_keys, grow = \
                    sampler_mod.speculative_accept(
                        drafts, q_sel, q_probs, q_idx, vlogits, sstate,
                        dkeys, enable=spec_col & alive, lengths=eff,
                        guide_tables=gtables)
                sstate = sstate._replace(
                    key=jnp.where(alive[:, None], carry_keys, sstate.key),
                    guide_row=jnp.where(alive, grow, sstate.guide_row))
                counts = jnp.maximum(counts, 1)
                # Liveness over the ACCEPTED prefix only: tokens past
                # counts are rejected drafts the host never sees — they
                # must not trip the stop check.
                valid = blk[None, :] < counts[:, None]
                masked = jnp.where(valid & alive[:, None], out,
                                   jnp.int32(-1))
                lengths = lengths + jnp.where(alive, counts, jnp.int32(1))
                alive = sampler_mod.advance_liveness(
                    jnp.swapaxes(masked, 0, 1), alive, lengths, stop_ids,
                    dead_len)
                last = jnp.take_along_axis(out, (counts - 1)[:, None],
                                           axis=1)[:, 0]
                tokens_out = jnp.where(alive, last, jnp.int32(0))
                toks = jnp.swapaxes(out, 0, 1)              # [DK, B]
                if want_lp:
                    clp, vals, lids = sampler_mod.top_logprobs(
                        vlogits[:, 0], out[:, 0])
                    # [1, B]-shaped so the resolve fanout shares the
                    # K-step record format (lp lanes always land c == 1).
                    return (cache, dcache, sstate, toks, counts,
                            clp[None], vals[None], lids[None], tokens_out,
                            lengths, alive)
                return (cache, dcache, sstate, toks, counts, tokens_out,
                        lengths, alive)

            self._spec_pipe_fn = jax.jit(
                functools.partial(spec_pipe, want_lp=False),
                donate_argnums=(2, 3, 4, 5, 6, 10))
            self._spec_pipe_lp_fn = jax.jit(
                functools.partial(spec_pipe, want_lp=True),
                donate_argnums=(2, 3, 4, 5, 6, 10))

    # ------------------------------------------------------------------
    # Public API
    # ------------------------------------------------------------------

    def min_tokens_suppress_ids(self, p) -> list[int]:
        """Deduped token ids suppressed on device while a request is below
        min_tokens (eos unless ignore_eos, plus stop_token_ids).  The ONE
        definition shared by admission validation, _shape_cols, and the
        HTTP validator — divergence would let np_suppress_col raise on the
        engine thread, tripping _run's blanket fault handler."""
        if p.min_tokens <= 0:
            return []
        stop: list[int] = []
        if not p.ignore_eos:
            stop += list(self.cfg.eos_token_ids)
            stop += list(self.tokenizer.eos_token_ids)
        stop += list(p.stop_token_ids)
        return list(dict.fromkeys(stop))

    def add_request(self, request: Request) -> None:
        # Validate the min_tokens suppress set HERE, on the caller's
        # thread: np_suppress_col raising inside the scheduler would trip
        # _run's blanket fault handler and abort every in-flight request,
        # while a ValueError here fails only the offender (the HTTP layer
        # 400s the same condition before it ever reaches the engine).
        sampler_mod.np_suppress_col(
            self.min_tokens_suppress_ids(request.params))
        if request.params.guide is not None:
            # Cheap syntactic validation on the CALLER's thread: malformed
            # patterns raise GuideError (ValueError -> HTTP 400) here.
            # The seconds-scale DFA build is handed to the compiler's
            # worker pool (ensure) — this call never blocks, and the
            # scheduler parks the request until the guide publishes
            # (compile failure -> per-request "error" output, not a
            # dropped stream).
            if self.guides.lookup(*request.params.guide) is None:
                self.guides.validate(*request.params.guide)
            # Only kick the background compile when the request targets
            # the ACTIVE model: guide registries are per-model context, so
            # compiling into the current model's tables for a request that
            # will park on a model switch would waste a registry row (the
            # guide gate re-ensures after the switch).  Racy read of
            # self.cfg across a switch degrades to exactly that waste.
            want = request.model or getattr(self, "_primary_model", None)
            if want in (None, self.cfg.name):
                self.guides.ensure(*request.params.guide)
            self.metrics.guided_requests_total.inc(
                1, kind=request.params.guide[0])
        if (request.model is not None and self.pool is not None
                and request.model != self.cfg.name
                and self.pool.has(request.model)):
            # Cold-start prefetch: start streaming this model's weights
            # NOW — a queued request behind busy slots would otherwise
            # only kick the load once it parks.  Racy read of self.cfg
            # across a switch at worst hints the active model; the
            # scheduler drops stale hints.
            self._model_prefetch.add(request.model)
        if self.trace.enabled:
            # Register the trace context (caller's thread — locking is
            # fine here) and open the queue span.
            self.trace.register(
                request.request_id, ctx=request.trace,
                tier=self._slo.tier_of(request.params.priority)
                if self._slo else None)
            self.trace.evt(request.request_id, "queue", "B")
            with logctx.bound(request.request_id,
                              request.trace.trace_id
                              if request.trace is not None else None):
                log.debug("request queued: %d prompt tokens, priority %d",
                          len(request.prompt_ids), request.params.priority)
        self.metrics.num_requests_waiting.inc(1)
        with self._abort_lock:
            self._queued_rids.add(request.request_id)
            self._queue_seq += 1
            seq = self._queue_seq
        try:
            # Bounded put: external admissions hit the overload ladder's
            # first rung HERE, on the caller's (server) thread — the
            # QueueFullError carries a drain-rate-derived Retry-After the
            # HTTP layer maps to 429 (tenant cap) / 503 (total cap).
            self._queue.put((request.params.priority, seq, request),
                            bounded=True)
        except fairqueue.QueueFullError as e:
            with self._abort_lock:
                self._queued_rids.discard(request.request_id)
            self.metrics.num_requests_waiting.inc(-1)
            self.metrics.requests_shed_total.inc(
                1,
                reason="queue_full" if e.scope == "queue" else "tenant_cap",
                tier=self._slo.tier_of(request.params.priority),
                tenant=self._tenant_labels.label(request.tenant))
            raise
        self.metrics.admission_queue_depth.set(self._queue.qsize())

    def abort(self, request_id: str) -> None:
        """Free the request's slot at the next scheduler boundary (client
        disconnect, stop-string hit in the server, etc.)."""
        with self._abort_lock:
            self._aborted.add(request_id)

    def start(self) -> None:
        self._running = True
        self.trace.start()
        deadline = knobs.get_float("ARKS_DISPATCH_DEADLINE_S", fallback=0.0)
        if deadline > 0:
            # Wedged-dispatch escalation: a device call that never returns
            # (hung DMA, deadlocked collective) cannot be cancelled from
            # Python — flip state (readiness 503s), dump diagnostics, exit
            # 70 so the supervisor restarts the pod.  The deadline must
            # exceed the worst in-step jit compile (docs/runbook.md).
            self._watchdog = faults_mod.Watchdog(
                deadline, lambda: self._step_hb, self._on_wedged)
            self._watchdog.start()
        self._thread = threading.Thread(target=self._run, name="engine", daemon=True)
        self._thread.start()

    def _on_wedged(self, phase: str, age_s: float) -> None:
        """Watchdog callback: record the wedged state (readiness reads it)
        and log the in-flight picture an operator needs post-mortem."""
        self._set_state("wedged")
        log.critical(
            "wedged dispatch diagnostics: phase=%s age=%.1fs slots=%s "
            "prefilling=%s pending_admits=%d pipe_inflight=%d queue=%d",
            phase, age_s,
            {s: st.request.request_id for s, st in self._slots.items()},
            {s: cs.request.request_id for s, cs in self._prefilling.items()},
            self._pending_n, len(self._pipe_inflight), self._queue.qsize())
        # Flight recorder: the wedge dump ships its own timeline — the
        # last N span events across every thread ring (this runs on the
        # watchdog thread; the wedged step loop never pays for it).
        tail = self.trace.tail()
        if tail:
            log.critical("flight recorder (last %d events): %s", len(tail),
                         "; ".join(
                             f"{e['t']:.3f} {e['rid'] or '<engine>'} "
                             f"{e['name']}/{e['ph']}" for e in tail))

    def _set_state(self, state: str) -> None:
        self._state = state
        self.metrics.engine_state.set(faults_mod.STATE_CODES[state])

    @property
    def state(self) -> str:
        """"serving" | "recovering" | "wedged" — the /readiness gate."""
        return self._state

    def stop(self) -> None:
        self._running = False
        self.trace.stop()
        if self._watchdog is not None:
            self._watchdog.stop()
        if self._thread is not None:
            self._thread.join(timeout=120.0)
            if self._thread.is_alive():
                # Engine thread wedged (e.g. a hung device call inside
                # _resolve_admit_batch): _pending_admits/_pending_n/_free
                # are engine-thread-owned, so touching them here would
                # race a thread that may still wake up.  _run()'s finally
                # aborts the deferred admissions itself if it ever exits.
                log.warning(
                    "engine thread did not exit within 120s; it aborts "
                    "deferred admissions itself on exit")
        # Graceful-stop persistence: publish the warm prefixes still
        # resident on-device / in tier 1 into the disk store BEFORE the
        # writer gets its exit sentinel, so a relaunch on the same
        # ARKS_PREFIX_DISK_DIR re-serves them without re-prefilling.
        if self._disk is not None:
            try:
                self._flush_warm_to_disk()
            except Exception as e:  # best-effort: warmth, not shutdown
                faults_mod.swallowed("disk_tier.flush", e)
        # Disk-spill writer / prefix-fetch workers: daemon threads, but
        # hand them their exit sentinel so a clean stop doesn't leave
        # them blocked on an empty queue.
        for wq in (self._disk_write_queue, self._fetch_queue):
            if wq is not None:
                try:
                    wq.put_nowait(None)
                except queue.Full:
                    pass
        if self._disk_writer is not None:
            # Queued spill writes land before the process exits.
            self._disk_writer.join(timeout=30.0)
        # Deferred admissions are drained by _run()'s finally on the
        # engine thread itself; a never-started engine has none.

    @property
    def num_running(self) -> int:
        # Deferred admit batches hold slots too — external drivers poll
        # this to detect completion, and a pending admission is running
        # work in every sense that matters to them.
        return len(self._slots) + self._pending_n

    def compiled_program_variants(self) -> dict[str, int]:
        """Program name -> number of compiled variants, for every jitted
        function this engine owns.  The compile-budget regression surface:
        the mixed scheduler exists partly to collapse the (bucket, M, lp)
        admit-program family into ONE budget-shaped program, and a future
        scheduler edit that silently reintroduces per-shape retraces shows
        up here long before it shows up as TPU compile stalls."""
        out: dict[str, int] = {}
        for name, fn in vars(self).items():
            size = getattr(fn, "_cache_size", None)
            if callable(size):
                try:
                    out[name] = int(size())
                except Exception as e:  # jax internals may shift across versions
                    faults_mod.swallowed("compiled_program_variants", e)
                    continue
        return out

    @property
    def idle(self) -> bool:
        """No decoding slots, no queued admissions, no chunked prefills,
        deferred admit batches, or requests parked on a guide compile,
        host-tier restore, or model switch — the drain gate (servers must
        not poke at privates)."""
        return (not self._slots and self._queue.empty()
                and not self._prefilling and not self._pending_admits
                and not self._awaiting_guide
                and not self._awaiting_restore
                and not self._awaiting_fetch
                and not self._awaiting_model
                and not self._swap_pending and not self._swapped)

    # ------------------------------------------------------------------
    # Scheduler loop
    # ------------------------------------------------------------------

    def _cache_dtype(self, engine_dtype):
        kvd = self.ecfg.resolve_kv_cache_dtype()
        return jnp.bfloat16 if kvd == "bf16" else engine_dtype

    def _pad_head(self) -> bool:
        """Lane-pad the stored KV head dim to 128 for d<128 models so they
        ride the compiled Pallas decode kernels instead of the XLA
        fallback (exact math — zero K lanes add 0 to scores, padded V
        columns are sliced off; ops/attention prescales q).  Costs
        128/head_dim x KV HBM; ARKS_PAD_HEAD_DIM=0 opts out."""
        if not knobs.get_bool("ARKS_PAD_HEAD_DIM"):
            return False
        from arks_tpu.ops.attention import default_decode_impl
        return (jax.default_backend() == "tpu"
                and default_decode_impl() == "pallas"
                and self.cfg.head_dim % 128 != 0
                and self._pp == 1)

    def _park_sentinel(self) -> int:
        """Write-drop length for parked (free/pending) slots: cache ops
        drop KV writes at/beyond it, and the fused decode loop's active
        mask freezes PRNG keys + penalty counts there.  ONE definition —
        the mask is only correct while every parking site agrees."""
        return (self._max_pages * self._page_size() if self._paged
                else self.ecfg.max_cache_len)

    def _page_size(self) -> int:
        """Page size = chunk size (a reused prefix then ends exactly where
        the tail chunk prefill starts), or 256 when chunking is off —
        capped by the cache window so small configs (pp disables chunking)
        still page."""
        return self._chunk or min(256, self.ecfg.max_cache_len)

    def _page_align(self) -> int:
        """Kernel alignment for the page size (compiled TPU only): int8
        scale RMW chunks are 128-wide, bf16 row chunks 16-wide."""
        if jax.default_backend() != "tpu":
            return 1
        return 128 if self.ecfg.kv_quantized else 16

    def _grow_slot_pages(self, rows_per_slot: int, ahead: int = 0) -> None:
        """Paged layout: before a dispatch that writes ``rows_per_slot``
        rows per active slot (K for the fused decode loop, draft_len for a
        speculative verify), extend each slot's block table to cover them.
        ``ahead`` counts dispatches already in flight (pipelined decode):
        the host's lagged lengths must pre-own pages for EVERY unresolved
        dispatch's write window, not just the next one.  Host-only
        bookkeeping; the pool is sized so allocation cannot fail for
        active slots (pages_needed clamps at the per-slot table width —
        the device's dead_len mask retires a slot before any write could
        land past it)."""
        from arks_tpu.engine.paged import pages_needed
        self._faults.fire("pages")
        page = self._page_size()
        rows = rows_per_slot * (ahead + 1)
        for slot in self._slots:
            if self._residency is not None and slot in self._residency.slots:
                # Engaged slots own staging + hot-tail pages only; the
                # residency manager grows their tail itself.
                continue
            need = pages_needed(int(self._lengths[slot]), rows, page,
                                self._max_pages)
            row = self._slot_pages[slot]
            if len(row) < need:
                new = self._alloc.alloc(need - len(row))
                self._tables[slot, len(row): len(row) + len(new)] = new
                row.extend(new)
        # Any eviction the allocations caused must spill BEFORE the
        # caller's dispatch can write the recycled pages (stream order).
        self._spill_flush()

    def _resolve_kv_layout(self) -> bool:
        layout = self.ecfg.kv_layout
        if layout not in ("auto", "slot", "paged"):
            raise ValueError(f"kv_layout={layout!r}")
        int4 = self.ecfg.kv_bits == 4
        if layout == "slot":
            if int4:
                raise ValueError(
                    "kv_cache_dtype=int4 requires the paged KV layout "
                    "(packed pages + fused dequant live in the paged mixed "
                    "kernel; there is no int4 slot cache)")
            return False
        from arks_tpu.parallel.mesh import AXIS_SLICE
        dp = (self.mesh.shape.get(tf.AXIS_DATA, 1)
              * self.mesh.shape.get(AXIS_SLICE, 1)) \
            if self.mesh is not None else 1
        blockers = []
        if dp > 1:
            blockers.append("data parallelism")
        if (jax.default_backend() == "tpu"
                and self.cfg.head_dim % 128 != 0
                and not self._pad_head()):
            blockers.append("head_dim not 128-lane aligned (and lane "
                            "padding disabled)")
        page = self._page_size()
        if page % self._page_align() != 0:
            blockers.append(f"page size {page} not {self._page_align()}-aligned")
        if self.ecfg.max_cache_len % page != 0:
            blockers.append(f"max_cache_len not a multiple of page {page}")
        if layout == "paged":
            if blockers:
                raise ValueError(
                    "kv_layout=paged is incompatible with: "
                    + ", ".join(blockers))
            return True
        # auto: paged wherever supported — it measured faster than the
        # slot layout at production shapes and adds on-device prefix
        # sharing (tools/bench_kernels.py).  CPU stays on the slot layout
        # (interpret-mode kernels are test-only) EXCEPT for draft engines:
        # speculation requires the mixed scheduler, whose CPU path runs
        # the XLA oracle — resolving slot there would turn a valid spec
        # config into an init error.
        if blockers:
            if int4:
                raise ValueError(
                    "kv_cache_dtype=int4 requires the paged KV layout, "
                    "which this shape cannot use: " + ", ".join(blockers))
            return False
        if jax.default_backend() != "tpu":
            # int4 forces paged wherever the shape allows it (there is no
            # int4 slot cache — see the kv_cache_dtype=int4 ValueError).
            return (int4 or (self.ecfg.draft_model is not None
                             and bool(self._chunk)))
        return True

    def _shard_cache(self, cache):
        if self._pp > 1:
            from arks_tpu.parallel.pipeline import shard_cache_pp
            return shard_cache_pp(cache, self.mesh)
        return tf.shard_cache(cache, self.cfg, self.mesh)

    def _shard_paged(self, cache):
        """Paged-pool sharding, pp-aware — used by BOTH engine init and
        _reset_device_state (a reset that replicated a stage-sized pool
        onto every stage device would OOM inside the recovery path)."""
        if self._pp > 1:
            from arks_tpu.parallel.pipeline import shard_paged_cache_pp
            return shard_paged_cache_pp(cache, self.mesh)
        return tf.shard_paged_cache(cache, self.cfg, self.mesh)

    @_scoped("guide")
    def _ensure_guides_uploaded(self) -> None:
        """Refresh the device guide tables when the compiler's version
        bumped (server threads compile guides on THEIR threads; only the
        upload happens here, on the engine thread, between dispatches).
        Multi-host: the leader replicates the host tables first so
        followers re-upload the same contents before mirroring the next
        dispatch."""
        if self._guide_ver == self.guides.version:
            return
        self._faults.fire("guide")
        cls_host, trans_host, ver = self.guides.snapshot()
        self._emit("guides", class_ids=cls_host, trans=trans_host,
                   version=ver)
        self._guide_dev = (jnp.asarray(cls_host), jnp.asarray(trans_host))
        self._guide_ver = ver

    def _emit(self, op: str, **payload) -> None:
        """Broadcast a device dispatch to follower processes (multi-host);
        no-op single-host.  MUST precede the local dispatch at every site —
        followers replay the identical jit sequence, which is what keeps
        the gang's collectives in lockstep.

        A broken dispatch channel is fatal to the whole gang: without it the
        followers stop mirroring and the next collective hangs forever, with
        every process alive — invisible to the gang driver's liveness checks.
        Exit instead, so the driver restarts the group (the same policy
        jax's own coordination service applies when a peer dies)."""
        if self.dispatcher is None:
            return
        try:
            self.dispatcher.broadcast(op, payload)
        except OSError:
            log.critical(
                "dispatch channel to followers broke; exiting so the gang "
                "driver restarts the whole group", exc_info=True)
            os._exit(70)

    def _run(self) -> None:
        try:
            self._run_loop()
        finally:
            # Loop exit (stop(), or a late wake-up after a wedged device
            # call outlived stop()'s join window): no scheduler remains to
            # resolve deferred admissions, so fail their clients here ON
            # the engine thread — the only thread allowed to touch
            # _pending_admits/_pending_n/_free.
            self._abort_pending_admits()
            self._abort_awaiting_guide()
            self._abort_awaiting_restores()
            self._abort_awaiting_fetches()
            self._abort_awaiting_model()
            self._abort_swapped()

    def _run_loop(self) -> None:
        prof = self.profiler
        while self._running:
            t0 = time.monotonic()
            self._step_hb = ("step", t0)
            try:
                if prof.active:
                    # Stamp the live span ids into the device timeline so
                    # the profile correlates back to the trace store.
                    with prof.annotate("arks_step", self.trace.live_ids()):
                        progressed = self.step()
                else:
                    progressed = self.step()
                self._consec_faults = 0
            except Exception as e:
                # Fault-isolated recovery (engine.faults): quarantine the
                # culprit request(s), REBUILD the device state (the
                # dispatch donated cache+sampler buffers, so they may
                # already be invalidated), and token-replay every other
                # in-flight request so its stream resumes byte-identically.
                progressed = self._recover_from_fault(e)
            finally:
                self._step_hb = None
            # Auto-arm hook: a step whose wall time jumps past
            # ARKS_PROF_AUTO_ARM x the trailing median opens a profiler
            # window by itself (closed after ARKS_PROF_WINDOW_S).
            prof.on_step(time.monotonic() - t0)
            if not progressed:
                time.sleep(0.001)

    # ------------------------------------------------------------------
    # Fault-isolated recovery
    # ------------------------------------------------------------------

    def _recover_from_fault(self, exc: Exception) -> bool:
        """Top-level fault handler: attempt quarantine + token-replay
        recovery, escalating to the blanket abort-everything path only
        when recovery itself keeps faulting (crash-loop guard)."""
        self._set_state("recovering")
        self._recover_t0 = time.monotonic()
        attempts = max(self._fault_retries + 2, 3)
        for _ in range(attempts):
            try:
                self._do_recovery(exc)
                return True
            except Exception as e:  # routed back into _do_recovery
                exc = e
        log.error("recovery kept faulting after %d attempts; falling back "
                  "to abort-everything", attempts)
        self._blanket_abort(exc)
        return True

    def _do_recovery(self, exc: Exception) -> None:
        """One recovery round: attribute, quarantine culprits over budget,
        snapshot every other in-flight request, rebuild the device state,
        and re-admit the survivors (token-replay for streams that already
        emitted, plain re-queue for the rest)."""
        if isinstance(exc, StepFault):
            phase, kind = exc.phase, exc.kind
            culprits = set(exc.culprits)
            survivors: list[_Survivor] = list(exc.survivors)
            cause = exc.__cause__ or exc
        else:
            phase, kind = "step", faults_mod.classify(exc)
            culprits, survivors = set(), []
            cause = exc
        self._consec_faults += 1
        self.metrics.engine_faults_total.inc(1, phase=phase, kind=kind)
        log.error("engine fault in phase %r (kind=%s, culprits=%s, "
                  "consecutive=%d); recovering",
                  phase, kind, sorted(culprits) or "-", self._consec_faults,
                  exc_info=cause)
        # Flight recorder: snapshot the ring tail ONCE and pin it onto
        # every culprit's eventual trace; the fault dump ships its own
        # timeline.  (Recovery is a slow path — assembly is allowed here.)
        self.trace.evt("", "recover", "B", f"{phase}/{kind}")
        flight_tail = self.trace.tail()
        if flight_tail:
            log.error("flight recorder (last %d events): %s",
                      len(flight_tail), "; ".join(
                          f"{e['t']:.3f} {e['rid'] or '<engine>'} "
                          f"{e['name']}/{e['ph']}" for e in flight_tail))
        for rid in culprits:
            self._fault_counts[rid] = self._fault_counts.get(rid, 0) + 1
            self.trace.evt(rid, "fault", "I", f"{phase}/{kind}")
            self.trace.attach_tail(rid, flight_tail)
        if self._consec_faults > max(self._fault_retries + 1, 2):
            # Unattributed (or mis-attributed) fault storm: per-request
            # budgets cannot bound it — stop the crash loop.
            raise RuntimeError(
                f"{self._consec_faults} consecutive step faults") from cause

        # ---- snapshot every in-flight request --------------------------
        for st in self._slots.values():
            survivors.append(_Survivor(
                request=st.request, seed=st.seed, num_prompt=st.num_prompt,
                generated=list(st.generated), num_emitted=st.num_emitted,
                logprobs=list(st.logprobs),
                first_token_time=st.first_token_time))
        for cs in self._prefilling.values():
            # Mid-prefill sequences re-run from the top (nothing emitted);
            # a replaying one keeps its gate — _do_recovery's re-admit
            # detects it on the request and restarts the cursor.
            survivors.append(_Survivor(
                request=cs.request, seed=cs.seed, num_prompt=len(cs.ids)))
        for rec in self._pending_admits:
            for req, ids, _ in rec[0]:
                survivors.append(_Survivor(
                    request=req, seed=self._resolve_seed(req),
                    num_prompt=len(ids)))
        for rst in self._awaiting_restore:
            self.metrics.num_requests_waiting.inc(-1)
            if isinstance(rst, _ResumeState):
                # A mid-restore preempt resume replays like any decoding
                # survivor — its generated prefix re-executes behind the
                # gate (the safe backstop when the swap path itself may
                # be what faulted).
                survivors.append(self._swap_survivor(rst.rec))
            else:
                # Restore-parked requests emitted nothing: plain
                # re-queue.  The host tier SURVIVES the device reset, so
                # the re-run's admission hits tier 1 again instead of
                # re-prefilling.
                survivors.append(_Survivor(
                    request=rst.request, seed=rst.seed,
                    num_prompt=len(rst.ids)))
        self._awaiting_restore = []
        for fs in self._awaiting_fetch:
            # Fetch-parked requests emitted nothing and hold no pages:
            # plain re-queue.  The host tier survives the reset, so any
            # blocks the worker already staged still pay off on the
            # re-run's admission; a worker still mid-fetch harmlessly
            # finishes against the surviving tiers.
            self.metrics.num_requests_waiting.inc(-1)
            survivors.append(_Survivor(
                request=fs.request, seed=fs.seed,
                num_prompt=len(fs.ids)))
        self._awaiting_fetch = []
        # Preempted victims (spill in flight or parked in host RAM):
        # token-replay instead of trusting a snapshot that may share the
        # fault's poisoned stream.  Their SwapStore bytes come back.
        for sw in self._swap_pending:
            self.metrics.num_requests_waiting.inc(-1)
            survivors.append(self._swap_survivor(sw.rec))
        self._swap_pending = []
        for rid_sw, rec_sw in self._swapped.items():
            self.metrics.num_requests_waiting.inc(-1)
            if self._swap is not None:
                self._swap.discard(rid_sw)
            survivors.append(self._swap_survivor(rec_sw))
        self._swapped.clear()
        if self._swap is not None:
            self.metrics.prefix_cache_usage_bytes.set(
                self._swap.bytes_used, tier="swap")
        self._slots.clear()
        self._prefilling.clear()
        self._pending_admits.clear()
        self._pending_n = 0
        self.metrics.num_requests_running.set(0)

        # ---- quarantine / abort / keep ---------------------------------
        with self._abort_lock:
            aborted = set(self._aborted)
        keep: list[_Survivor] = []
        seen: set[str] = set()
        err = f"engine_fault: {phase}/{kind}"
        for sv in survivors:
            rid = sv.request.request_id
            if rid in seen:
                continue
            seen.add(rid)
            if rid in aborted:
                # Abort raced the fault: honor it instead of replaying.
                with self._abort_lock:
                    self._aborted.discard(rid)
                self._fail_survivor(sv, "abort", None)
                continue
            if self._fault_counts.get(rid, 0) > self._fault_retries:
                # The culprit fails ALONE: finish_reason="error" maps to
                # an OpenAI-style 500 at the HTTP layer.
                self.metrics.requests_quarantined_total.inc(1)
                with logctx.bound(rid):
                    log.warning("quarantining %s after %d faults (%s)", rid,
                                self._fault_counts[rid], err)
                self.trace.attach_tail(rid, flight_tail)
                self.trace.evt(rid, "quarantined", "I", err)
                self._fail_survivor(sv, "error", err)
                continue
            keep.append(sv)

        # ---- re-admit survivors ----------------------------------------
        # BEFORE the device reset: the admission queue is untouched by a
        # reset, so if the rebuild itself faults the survivors ride the
        # queue into the next recovery round instead of vanishing with
        # this frame's locals (clients blocked forever).  Nothing admits
        # until recovery returns, so ordering is otherwise free.
        replay_n = 0
        for sv in keep:
            req = sv.request
            rid = req.request_id
            gate = (req.outputs
                    if isinstance(req.outputs, _ReplayGate) else None)
            if sv.generated or gate is not None:
                # Token-replay resume by deterministic re-execution: wrap
                # (or restart) the emission gate, then re-run the request
                # through its ORIGINAL admission path with its pinned
                # seed — the same compiled programs that produced the
                # recorded stream reproduce it bitwise, the gate
                # suppresses the already-delivered prefix and verifies
                # every regenerated token.  Replayers jump the admission
                # queue: they were already decoding before the fault.
                if gate is None:
                    req.outputs = _ReplayGate(req.outputs, self, rid,
                                              sv.generated, sv.num_emitted)
                else:
                    gate.restart(sv.generated)
                self._replaying.add(rid)
                self.trace.evt(rid, "replay", "I", len(sv.generated))
                prio = req.params.priority - (1 << 20)
                replay_n += 1
            else:
                # Nothing emitted yet: plain re-queue (the pinned seed
                # makes the re-run byte-identical to a fault-free
                # admission).
                prio = req.params.priority
                self.metrics.requests_recovered_total.inc(1)
            with self._abort_lock:
                self._queued_rids.add(rid)
                self._queue_seq += 1
                seq = self._queue_seq
            self.metrics.num_requests_waiting.inc(1)
            self._queue.put((prio, seq, req))

        # ---- rebuild device state; tell followers ----------------------
        self._emit("recover", manifest=[
            (sv.request.request_id, sv.num_prompt, len(sv.generated))
            for sv in keep], phase=phase, kind=kind)
        self._reset_device_state()
        self.trace.evt("", "recover", "E")
        # Assemble NOW so quarantined timelines are retained even if the
        # process dies before the collector's next pass.
        self.trace.flush()
        if not replay_n:
            self._finish_recovery()

    def _phase_culprits(self, phase: str):
        """Blast-radius attribution for a phase-scoped fault: the requests
        the failing operation was doing work for.  Guide-table uploads
        serve no specific request — nobody's retry budget burns for one."""
        if phase in ("guide", "disk_spill"):
            # Guide-table uploads and tier-2 spill drains serve no
            # specific request — nobody's retry budget burns for one.
            return ()
        if phase == "peer_fetch":
            # Fetch faults are raised with the explicit fetching request
            # at every fire site; an unattributed one can only be the
            # park bookkeeping — blame the parked fetches, not the
            # decoding slots.
            return [st.request.request_id for st in self._awaiting_fetch]
        if phase == "model_switch":
            # The switch serves the requests parked for the target model;
            # nobody else was in flight (switches run fully drained).
            return [req.request_id for req, want, _ in self._awaiting_model
                    if want == self._switch_target]
        if phase == "resize":
            # A topology resize serves no specific request: it runs at a
            # fully drained boundary, and every in-flight stream was
            # already moved to the host (swap entry or replay requeue)
            # before the first seam — those survive a resize fault in
            # layout-independent form, so nobody's retry budget burns.
            return ()
        if phase == "preempt":
            # Preempt faults are raised with explicit single-victim
            # culprits at every fire site; an unattributed one can only
            # be host-side scheduling code — blame the in-flight swap
            # traffic, not the decoding slots.
            return ([sw.rec.request.request_id for sw in self._swap_pending]
                    + list(self._swapped)
                    + [r.request.request_id for r in self._awaiting_restore
                       if isinstance(r, _ResumeState)])
        if phase == "residency":
            # The span-streaming step only does work for ENGAGED slots —
            # co-resident classic-path slots never touch its dispatches.
            if self._residency is not None:
                return [self._slots[s].request.request_id
                        for s in self._residency.slots if s in self._slots]
            return ()
        rids = [st.request.request_id for st in self._slots.values()]
        if phase == "mixed":
            rids += [cs.request.request_id
                     for cs in self._prefilling.values()]
        return rids

    def _live_rids(self) -> set:
        """Request ids somewhere in the engine's in-flight structures
        (everything except the admission queue) — the abort-purge and
        replay-liveness universe."""
        live = {st.request.request_id for st in self._slots.values()}
        live |= {st.request.request_id for st in self._prefilling.values()}
        live |= {req.request_id for rec in self._pending_admits
                 for req, _, _ in rec[0]}
        live |= {req.request_id for req, _ in self._awaiting_guide}
        live |= {rec.request.request_id for rec in self._awaiting_restore}
        live |= {req.request_id for req, _, _ in self._awaiting_model}
        live |= {sw.rec.request.request_id for sw in self._swap_pending}
        live |= set(self._swapped)
        return live

    def _purge_stale_aborts(self, consumed=()) -> None:
        """Drop abort flags that no live request can ever consume.  Aborts
        for requests still waiting in the admission queue stay until
        _preadmit consumes them; anything else (request already finished,
        or never existed) is garbage — without this, an abort racing
        _finish would sit in the set forever (and the set could grow
        without bound under abort-heavy clients)."""
        active = self._live_rids()
        with self._abort_lock:
            self._aborted -= set(consumed)
            self._aborted &= active | self._queued_rids

    def _fail_survivor(self, sv: "_Survivor", reason: str,
                       error: str | None) -> None:
        self._unpin_guide(sv.request)
        self._fault_counts.pop(sv.request.request_id, None)
        sv.request.outputs.put(RequestOutput(
            request_id=sv.request.request_id, token_ids=[], finished=True,
            finish_reason=reason, error=error,
            num_prompt_tokens=sv.num_prompt,
            num_generated_tokens=len(sv.generated)))
        if reason == "error":
            self.metrics.request_success_total.inc(reason="error")

    def _finish_recovery(self) -> None:
        self.metrics.engine_recovery_seconds.observe(
            time.monotonic() - self._recover_t0)
        self._set_state("serving")
        log.info("recovery complete in %.3fs",
                 time.monotonic() - self._recover_t0)

    def _maybe_finish_recovery(self) -> None:
        """Close the recovery window once the last replaying request has
        re-registered into a decoding slot (or died on the way):
        engine_recovery_seconds measures fault -> every surviving stream
        decoding again."""
        if self._state != "recovering":
            return
        if self._replaying:
            # Drop replayers that went terminal without re-registering
            # (an abort or per-request rejection raced the re-run).
            live = self._live_rids()
            with self._abort_lock:
                live |= self._queued_rids
            self._replaying &= live
            if self._replaying:
                return
        self._finish_recovery()

    def _blanket_abort(self, exc: Exception) -> None:
        """Last-resort path (recovery crash loop): fail EVERY in-flight
        request and rebuild — the pre-recovery behavior, kept as the
        backstop so an unattributable fault storm cannot spin forever."""
        log.exception("engine step failed; aborting in-flight requests",
                      exc_info=exc)
        for slot in list(self._slots):
            self._finish(slot, "abort")
        for slot, st in list(self._prefilling.items()):
            self._unpin_guide(st.request)
            st.request.outputs.put(RequestOutput(
                request_id=st.request.request_id, token_ids=[],
                finished=True, finish_reason="abort",
                num_prompt_tokens=len(st.ids)))
        self._prefilling.clear()
        self._abort_pending_admits()
        self._abort_awaiting_restores()
        self._abort_awaiting_fetches()
        self._abort_awaiting_model()
        # Preempted victims fail too, and their SwapStore entries go with
        # them — swapped-out KV may carry the poison back on resume.
        self._abort_swapped()
        if self._prefix is not None:
            # Deep clean: cached prefix KV may itself be the poison.
            self._prefix.clear()
        if self._host is not None:
            # Same deep clean for the host tier: spilled blocks may carry
            # the poisoned KV back on the next restore.
            self._host.clear()
            self.metrics.prefix_cache_usage_bytes.set(0, tier="host")
        if self._disk is not None:
            # The disk tier goes with it — AND its files, or the poison
            # would resurrect on the next boot's directory scan.
            self._disk_spill_pending.clear()
            self._disk.clear()
            self.metrics.prefix_cache_usage_bytes.set(0, tier="disk")
        self._fault_counts.clear()
        self._consec_faults = 0
        self._reset_device_state()
        self._finish_recovery()

    def _reset_device_state(self) -> None:
        # Pipelined decode: in-flight records reference donated-away device
        # buffers; drop them rather than resolve (their requests were
        # already aborted by the fault path).
        self._pipe_reset()
        # In-flight spill gathers may share the fault's poisoned stream;
        # drop them (losing a spill costs one future re-prefill).  The
        # host tier itself SURVIVES the reset — that is the "warm across
        # restarts" property the tier exists for.
        self._spill_victims.clear()
        self._spills.clear()
        # In-flight preempt swaps reference the same stream; their
        # victims were snapshotted as replay survivors by _do_recovery
        # (or aborted by _blanket_abort) — drop the device refs.
        self._swap_pending = []
        # The rebuilt allocator starts with an EMPTY tier-0 index: move
        # the sketch epoch so routers drop the pre-reset sketch the
        # moment they next poll, instead of keeping this backend winning
        # placement on membership it no longer holds.
        if self._sketch is not None:
            self._sketch.bump_epoch()
        # Followers rebuild too (their _run path never sees the exception).
        if self.dispatcher is not None:
            self._emit("reset")
        dtype = jnp.dtype(self.ecfg.dtype or self.cfg.dtype)
        if self._paged:
            from arks_tpu.engine.paged import PageAllocator
            page = self._page_size()
            self._cache = tf.init_paged_cache(
                self.cfg, self._alloc.num_pages, page,
                self._cache_dtype(dtype), quantized=self.ecfg.kv_quantized,
                pad_head=self._pad_head(),
                kv_bits=min(self.ecfg.kv_bits, 8))
            if self.mesh is not None:
                self._cache = self._shard_paged(self._cache)
            self._alloc = PageAllocator(self._alloc.num_pages, page)
            if self._host is not None:
                self._alloc.on_evict = self._note_evicted
            self._tables[:] = 0
            self._slot_pages.clear()
            if self._residency is not None:
                # Windowed slots' host stores reference the pre-reset
                # stream; their requests token-replay from the top, so the
                # windowed state drops wholesale (the staging/tail pages
                # died with the rebuilt allocator).
                self._residency.slots.clear()
        else:
            self._cache = tf.init_cache(self.cfg, self.ecfg.num_slots,
                                        self.ecfg.max_cache_len,
                                        self._cache_dtype(dtype),
                                        quantized=self.ecfg.kv_quantized,
                                        pad_head=self._pad_head())
            if self.mesh is not None:
                self._cache = self._shard_cache(self._cache)
        self._sampling = sampler_mod.init_sampling_state(
            self.ecfg.num_slots, self.ecfg.seed,
            vocab_size=self.cfg.vocab_size)
        if self._draft_cfg is not None:
            self._draft_cache = tf.init_cache(
                self._draft_cfg, self.ecfg.num_slots, self.ecfg.max_cache_len,
                self._cache_dtype(dtype), quantized=self.ecfg.kv_quantized,
                pad_head=self._pad_head())
            if self.mesh is not None:
                self._draft_cache = tf.shard_cache(
                    self._draft_cache, self._draft_cfg, self.mesh)
        # Paged: park every slot at the sentinel.  Slot layout: empty
        # slots start at 0 (their pre-insert garbage rows are private).
        self._lengths[:] = self._park_sentinel() if self._paged else 0
        self._last_token[:] = 0
        # A fault between _free.pop() and slot registration would otherwise
        # leak the slot index permanently.
        self._free = [s for s in range(self.ecfg.num_slots)
                      if s not in self._slots]

    def step(self, block_s: float = 0.05) -> bool:
        """One scheduler iteration: issue ONE decode dispatch (async),
        admit pending requests and advance at most one prefill chunk WHILE
        it computes, then fan the decode results out.  The overlap hides
        admission host work (numpy packing, digests, page allocation, the
        dispatch-issue latency) behind decode compute; device work still
        executes in issue order on the stream.  The chunk/decode interleave
        bounds how long a long-prompt burst can stall decoding slots: one
        chunk dispatch, not one whole prefill.  Returns True if any work
        was done.

        Speculative engines ride the mixed branch like any other mixed
        engine — their dispatch is the spec-mixed program (draft propose +
        ragged verify + accept), issued async and resolved after the
        overlapped admission work exactly like a plain mixed dispatch.
        Phase-seconds note: with the overlap, waits on the shared device
        stream land in whichever phase fetches first — the breakdown
        attributes WALL time, not device time."""
        t0 = time.monotonic()
        self._maybe_finish_recovery()
        if not self._armed:
            # Scaled to zero: no device state exists — the only work is
            # re-arming on demand (a queue arrival or a posted resize).
            return self._step_disarmed(block_s)
        worked = False
        if self._resize_req is not None or self._idle_zero_s:
            # Elastic servicing: progress a posted resize's drain ->
            # reshard -> resume machine, or scale a long-idle engine to
            # zero.  Cheap no-op when neither condition holds.
            worked = self._service_elastic()
            if not self._armed:
                # This step scaled the engine to zero; nothing below may
                # touch the dropped device state.
                return True
            te = time.monotonic()
            if te - t0 > 1e-4:
                self.metrics.scheduler_seconds_total.inc(te - t0,
                                                         phase="elastic")
                t0 = te
        self._ensure_guides_uploaded()
        if self._awaiting_guide:
            # Requests parked on a worker-pool guide compile: re-queue the
            # ones whose guide published, fail the ones whose compile
            # failed, keep waiting on the rest.  Never blocks — a step
            # with only parked requests falls through to the idle sleep.
            worked = self._service_awaiting_guides() or worked
            tg = time.monotonic()
            self.metrics.scheduler_seconds_total.inc(tg - t0,
                                                     phase="guide_wait")
            t0 = tg
        if self._awaiting_model or self._model_loads or self._model_prefetch:
            # Multi-model park servicing: kick/poll the next model's
            # background weight load, fail/abort dead parked requests, and
            # switch contexts once the target is resident AND the engine
            # is fully drained.  Cheap and non-blocking — while the load
            # is in flight the RESIDENT model keeps pipelining at full
            # depth (the fast path below still runs every step).
            worked = self._issue_model_load() or worked
            tm = time.monotonic()
            self.metrics.scheduler_seconds_total.inc(tm - t0,
                                                     phase="model_wait")
            t0 = tm
        if self._pipe_ready():
            # Steady-state pipelined decoding: exactly ONE dispatch issued
            # per iteration, up to ARKS_PIPELINE_DEPTH in flight; the
            # oldest resolves (lagged host view) only once the pipeline is
            # full, so the device never waits on Python between
            # dispatches.
            self._step_pipelined()
            self.metrics.scheduler_seconds_total.inc(
                time.monotonic() - t0, phase="decode")
            return True
        if self._pipe_inflight or self._pipe_state is not None:
            # Leaving steady state (admission possible, abort raised,
            # prefill work, or a slot's stop set outgrew the device
            # column): resolve every in-flight dispatch so the host
            # mirrors are authoritative again before any host-side
            # mutation touches scheduler state.
            self._pipe_drain()
            worked = True
            td = time.monotonic()
            self.metrics.scheduler_seconds_total.inc(td - t0, phase="decode")
            t0 = td
        if self._fuse_ready():
            # Depth-0 sampler fusion: steady-state pure decode rides the
            # fused attention+sampler program with an immediate resolve —
            # one device program per step, no host-side sampler prep.
            self._step_fused()
            self.metrics.scheduler_seconds_total.inc(
                time.monotonic() - t0, phase="mixed")
            return True
        if self._residency_active():
            # Windowed-residency slots: span-by-span decode on the host
            # loop (cold pages stream through staging while resident
            # spans attend).  Runs before the classic mixed dispatch so
            # windowed slots never enter its lanes.
            worked = self._residency_step() or worked
            tw = time.monotonic()
            self.metrics.scheduler_seconds_total.inc(tw - t0,
                                                     phase="residency")
            t0 = tw
        if self._awaiting_restore:
            # Host-tier restores whose scatter landed unpark into the
            # chunked-tail path (needs authoritative mirrors — the
            # pipeline drained above); in-flight ones stay parked.
            worked = self._resolve_restores() or worked
            tr = time.monotonic()
            self.metrics.scheduler_seconds_total.inc(tr - t0,
                                                     phase="restore")
            t0 = tr
        if self._awaiting_fetch:
            # Disk/peer fetch parks whose worker finished re-enter the
            # admission match; in-flight ones stay parked (the worker
            # thread owns them — the step loop never blocks on IO).
            worked = self._resolve_fetches() or worked
            tq = time.monotonic()
            self.metrics.scheduler_seconds_total.inc(tq - t0,
                                                     phase="fetch")
            t0 = tq
        if self._spills:
            worked = self._resolve_spills() or worked
        if self._disk_spill_pending:
            worked = self._drain_disk_spills() or worked
        if self._swap_pending or self._swapped or self._preempt_on:
            # Preemptive KV swap: harvest landed victim spills into the
            # SwapStore, serve aborts / schedule resumes for swapped-out
            # victims, then seize slots for outranking queued requests —
            # all BEFORE the issue block, so a freed slot admits (and a
            # resumed scatter dispatches) in this same step.
            tp = time.monotonic()
            self._queue_age_tick()
            if self._swap_pending:
                worked = self._resolve_preempt_swaps() or worked
            # During a resize drain, swapped victims stay parked (resuming
            # one would fight the eviction) and natural preemption pauses;
            # both resume at the new shape.
            if self._swapped and not self._resize_active:
                worked = self._service_swapped() or worked
            if not self._resize_active:
                worked = self._maybe_preempt() or worked
            dt = time.monotonic() - tp
            if dt > 1e-4:
                self.metrics.scheduler_seconds_total.inc(dt, phase="preempt")
        elif self._queue_aging_s:
            self._queue_age_tick()
        pending = None
        issued = False
        if self._mixed:
            # Mixed scheduling: ONE model dispatch per iteration carries
            # every decoding slot's next token AND all prefilling
            # sequences' chunk tokens — admission host work overlaps the
            # in-flight dispatch exactly as in the legacy issue/resolve
            # split.
            spec = self._draft_cfg is not None
            phase = "spec" if spec else "mixed"
            if self._slots or self._prefilling:
                pending = (self._issue_spec_mixed() if spec
                           else self._issue_mixed())
                issued = pending is not None
            t1 = time.monotonic()
            if issued:
                self.metrics.scheduler_seconds_total.inc(t1 - t0,
                                                         phase=phase)
            worked = self._admit() or worked or issued
            t2 = time.monotonic()
            if t2 - t1 > 1e-4:
                self.metrics.scheduler_seconds_total.inc(t2 - t1,
                                                         phase="admit")
            if pending is not None:
                if spec:
                    self._resolve_spec_mixed(pending, exclude_s=t2 - t1)
                else:
                    self._resolve_mixed(pending, exclude_s=t2 - t1)
                self.metrics.scheduler_seconds_total.inc(
                    time.monotonic() - t2, phase=phase)
        else:
            if self._slots and self._overlap:
                pending = self._issue_decode()  # may retire/abort even if None
                issued = True
            t1 = time.monotonic()
            if issued:
                self.metrics.scheduler_seconds_total.inc(t1 - t0, phase="decode")
            worked = self._admit() or worked or issued
            t2 = time.monotonic()
            if t2 - t1 > 1e-4:
                self.metrics.scheduler_seconds_total.inc(t2 - t1, phase="admit")
            if self._prefilling:
                self._process_chunk()
                t3 = time.monotonic()
                self.metrics.scheduler_seconds_total.inc(t3 - t2, phase="chunk")
                t2 = t3
                worked = True
            if pending is not None:
                self._resolve_decode(pending, exclude_s=t2 - t1)
                self.metrics.scheduler_seconds_total.inc(
                    time.monotonic() - t2, phase="decode")
            elif self._slots and not self._overlap:
                # Sequential order: platforms where the overlap cannot pay
                # (see _overlap above).
                self._decode_dispatch()
                self.metrics.scheduler_seconds_total.inc(
                    time.monotonic() - t2, phase="decode")
                worked = True
        if self._pending_admits:
            # Deferred admissions: resolve whatever the device finished
            # while this step ran (the decode resolve above usually means
            # earlier admit programs are done too).  When nothing else
            # made progress, BLOCK on the oldest — a pending admission
            # must never starve behind an empty queue.
            t4 = time.monotonic()
            worked = self._drain_ready_admits(force_one=not worked) or worked
            self.metrics.scheduler_seconds_total.inc(
                time.monotonic() - t4, phase="admit")
        if not worked and (self._awaiting_restore or self._spills
                           or self._awaiting_fetch
                           or self._disk_spill_pending
                           or self._swap_pending or self._swapped
                           or self._awaiting_model or self._model_loads
                           or self._resize_req is not None):
            # Parked restores / in-flight spills / pending model loads
            # resolve on DEVICE (or loader-thread) time, not queue
            # arrivals: poll again shortly instead of blocking on the
            # admission queue for block_s.
            time.sleep(0.001)
            return True
        if not worked:
            # Idle housekeeping: an abort that raced _finish (or targeted
            # a request that never existed) must not linger in the set
            # forever — the busy-path purges only run while slots exist.
            self._purge_stale_aborts()
            # Idle: wait briefly for a request, then try admission again.
            try:
                _, _, req = self._queue.get(timeout=block_s)
            except queue.Empty:
                return False
            pre = self._preadmit(req)
            if pre is not None:
                self._resolve_admit_batch(
                    self._issue_admit_batch([pre], pre[0].params.logprobs
                                            is not None))
        return True

    @staticmethod
    def _admit_batch_sizes() -> tuple[int, ...]:
        """Admission batch sizes (largest-first greedy fill).  Each size is
        one compiled program per (bucket, lp); the cap keeps variants
        bounded.  ARKS_ADMIT_BATCH_SIZES overrides (comma-separated) so
        the serving sweep can probe bigger fills (e.g. "16,8,4,2,1" — at
        b192 with ~24 finishes per dispatch cycle, deeper batches may
        amortize more of the per-dispatch round-trip) without a code
        change.  Normalized descending; 1 is always present (the greedy
        fill's floor)."""
        raw = knobs.raw("ARKS_ADMIT_BATCH_SIZES") or "8,4,2,1"
        try:
            sizes = {int(x) for x in raw.split(",") if x.strip()}
        except ValueError as e:
            raise ValueError(
                f"ARKS_ADMIT_BATCH_SIZES={raw!r}: expected comma-separated "
                "integers (e.g. \"16,8,4,2,1\")") from e
        if any(s < 1 for s in sizes):
            raise ValueError(
                f"ARKS_ADMIT_BATCH_SIZES={raw!r}: sizes must be >= 1")
        return tuple(sorted(sizes | {1}, reverse=True))

    def _admit(self) -> bool:
        """Admit waiting requests.  One-shot prompts are GROUPED by
        (prefill bucket, logprobs) and issued as fused batch dispatches —
        all batches go out back-to-back (async); first tokens are fetched
        DEFERRED (self._pending_admits, resolved by step() as they become
        ready) so the engine thread never blocks on an admit program's
        device round-trip while decode work is available."""
        if self._resize_active:
            # Resize drain: new admissions wait in the queue until the
            # engine resumes at its new shape.
            return False
        admitted = False
        groups: dict[tuple[int, bool], list] = {}
        recs = []
        try:
            # The grouping loop sits INSIDE the try: _preadmit can re-raise
            # after failing only its own request (_admit_prefilled dispatch
            # error, _start_chunked page-alloc failure), and any one-shot
            # requests already collected in ``groups`` hold no slot and are
            # invisible to _run's recovery — the handler below must abort
            # them or their clients block forever.
            while self._free and self._queue.qsize() > 0:
                n_grouped = sum(len(v) for v in groups.values())
                if n_grouped >= len(self._free):
                    break
                try:
                    _, _, req = self._queue.get_nowait()
                except queue.Empty:
                    break
                try:
                    # Chaos hook at the WDRR pick point: the popped
                    # request is the sole culprit (its retry budget
                    # burns; over budget it quarantines alone) AND a
                    # survivor (nothing was emitted — recovery plain-
                    # requeues it through the fair queue again).
                    self._faults.fire("admit_fair")
                except Exception as e:
                    self.metrics.num_requests_waiting.inc(-1)
                    with self._abort_lock:
                        self._queued_rids.discard(req.request_id)
                    raise StepFault(
                        "admit_fair", faults_mod.classify(e),
                        culprits=[req.request_id],
                        survivors=[_Survivor(
                            request=req, seed=self._resolve_seed(req),
                            num_prompt=len(req.prompt_ids))]) from e
                admitted = True
                pre = self._preadmit(req)
                if pre is not None:
                    req, ids, padded = pre
                    key = (padded.shape[1], req.params.logprobs is not None)
                    groups.setdefault(key, []).append(pre)
            for (bucket, want_lp), items in groups.items():
                while items:
                    m = next(s for s in self._admit_sizes
                             if s <= len(items))
                    # Detach BEFORE issuing: _issue_admit_batch fails its
                    # own items on error, and the handler below must not
                    # abort them a second time.
                    batch = items[:m]
                    del items[:m]
                    recs.append(self._issue_admit_batch(batch, want_lp))
            if self._defer_admits:
                # Hand the issued batches to the deferred queue; step()
                # resolves them as their first tokens become ready, so the
                # engine thread goes back to issuing decode dispatches
                # instead of blocking here.  (Anything already computed
                # resolves immediately — the no-load TTFT path.)
                self._pending_n += sum(len(r[0]) for r in recs)
                self._pending_admits.extend(recs)
                recs = []
                self._drain_ready_admits()
            else:
                while recs:
                    self._resolve_admit_batch(recs.pop(0))
        except Exception as e:
            # A failing batch must not strand its SIBLINGS: un-issued items
            # and unresolved already-issued batches hold no registered slot
            # (invisible to the recovery snapshot) — carry them as
            # survivors on the StepFault so recovery re-queues them.  (The
            # failing operation's own requests ride its inner StepFault.)
            survivors = []
            for sib_items in groups.values():
                for req, ids, _ in sib_items:
                    survivors.append(_Survivor(
                        request=req, seed=self._resolve_seed(req),
                        num_prompt=len(ids)))
            for rec in recs:
                for (req, ids, _), slot in zip(rec[0], rec[1]):
                    if slot not in self._slots:
                        self._free.append(slot)
                    survivors.append(_Survivor(
                        request=req, seed=self._resolve_seed(req),
                        num_prompt=len(ids)))
            if isinstance(e, StepFault):
                e.survivors.extend(survivors)
                raise
            raise StepFault("admit", faults_mod.classify(e),
                            survivors=survivors) from e
        return admitted

    def _drain_ready_admits(self, force_one: bool = False) -> bool:
        """Resolve deferred admission batches whose first tokens are ready
        (FIFO — emission order matches issue order).  ``force_one`` blocks
        on the oldest batch even if unready: the idle path uses it so a
        pending admission can never starve behind an empty queue.  Returns
        True if anything resolved."""
        did = False
        while self._pending_admits:
            rec = self._pending_admits[0]
            if not (force_one and not did) and not rec[2].is_ready():
                break
            self._pending_admits.popleft()
            self._pending_n -= len(rec[0])
            self._resolve_admit_batch(rec)
            did = True
        return did

    def _abort_pending_admits(self) -> None:
        """Fail every deferred admission batch (fault/stop paths): their
        requests hold popped slots but are registered nowhere, so no other
        recovery can reach them."""
        while self._pending_admits:
            items, slots_l = self._pending_admits.popleft()[:2]
            self._pending_n -= len(items)
            for (req, ids, _), slot in zip(items, slots_l):
                if slot not in self._slots:
                    self._release_slot_pages(slot)
                    self._free.append(slot)
                self._unpin_guide(req)
                req.outputs.put(RequestOutput(
                    request_id=req.request_id, token_ids=[], finished=True,
                    finish_reason="abort", num_prompt_tokens=len(ids)))

    def _resolve_seed(self, req: Request) -> int:
        """The request's sampling seed, assigned ONCE per request: an
        explicit params.seed wins; otherwise the engine counter value is
        pinned on the request (fault recovery re-admits with the identical
        key stream instead of drawing a fresh counter value)."""
        if req.params.seed is not None:
            return req.params.seed
        if req.assigned_seed is None:
            self._request_seed += 1
            req.assigned_seed = self._request_seed
        return req.assigned_seed

    def _preadmit(self, req: Request):
        """Admission front half: aborts, disagg-transferred KV, rejects,
        and the chunked/prefix paths are handled HERE (individually);
        one-shot prompts return (req, ids, padded) for batch grouping."""
        self.metrics.num_requests_waiting.inc(-1)
        self.metrics.admission_queue_depth.set(self._queue.qsize())
        with self._abort_lock:
            self._queued_rids.discard(req.request_id)
            if req.request_id in self._aborted:
                self._aborted.discard(req.request_id)
                self._unpin_guide(req)
                req.outputs.put(RequestOutput(
                    request_id=req.request_id, token_ids=[], finished=True,
                    finish_reason="abort"))
                return
        if self._shed_due(req):
            # Deadline-aware shedding: the queue wait already burned the
            # tier's whole TTFT budget (x ARKS_SHED_DEADLINE) — prefill
            # would be wasted on a stream the client has written off.
            # Reject with a machine-readable code; the server maps it to
            # 503 + Retry-After.  Exempt: replayers/swap-resumes (already
            # decoding before their fault/preemption — shedding them
            # breaks the byte-identity contract) and disagg-prefilled
            # requests (the expensive half is already paid for).
            waited = time.monotonic() - req.arrival_time
            tier = self._slo.tier_of(req.params.priority)
            self._unpin_guide(req)
            self.metrics.requests_shed_total.inc(
                1, reason="deadline", tier=tier,
                tenant=self._tenant_labels.label(req.tenant))
            self.trace.evt(req.request_id, "shed", "I", round(waited, 3))
            req.outputs.put(RequestOutput(
                request_id=req.request_id, token_ids=[], finished=True,
                finish_reason="error",
                error=(f"shed_deadline: queued {waited:.2f}s, tier "
                       f"{tier} ttft budget already unmeetable"),
                num_prompt_tokens=len(req.prompt_ids)))
            return
        if isinstance(req.outputs, _ReplayGate):
            # Fault-recovery re-admission: a per-request injectable point
            # ("replay" phase) so the chaos suite can kill one survivor's
            # resume specifically — the StepFault attributes the fault to
            # THIS request alone and carries its replay state.
            try:
                self._faults.fire("replay")
            except Exception as e:
                raise StepFault(
                    "replay", faults_mod.classify(e),
                    culprits=[req.request_id],
                    survivors=[_Survivor(
                        request=req, seed=self._resolve_seed(req),
                        num_prompt=len(req.prompt_ids),
                        generated=list(req.outputs.expect),
                        num_emitted=req.outputs.client_total)]) from e
        want = getattr(req, "model", None) or self._primary_model
        if want != self.cfg.name or (self._switch_target is not None
                                     and self._switch_target != self.cfg.name):
            # Multi-model routing: the request targets a pool model that is
            # not active — or a switch away from the active model is
            # already committed, in which case even active-model requests
            # park (admitting them would keep the drain from converging).
            # Parked BEFORE the guide gate: guide registries are per-model
            # context, so a pin taken here would reference the wrong
            # model's tables after the switch.
            return self._park_awaiting_model(req, want)
        if req.params.guide is not None:
            # Cold-guide gate: park the request while its guide compiles
            # on the worker pool (the scheduler never blocks on
            # compilation); fail it on compile error; PIN the published
            # guide for the request's lifetime so eviction can't repack
            # the rows its slot decodes against.
            gate = self._gate_guide(req)
            if gate == "park":
                return
            if gate is not None:
                req.outputs.put(RequestOutput(
                    request_id=req.request_id, token_ids=[], finished=True,
                    finish_reason="error",
                    error=f"guide_compile_failed: {gate}",
                    num_prompt_tokens=len(req.prompt_ids)))
                log.info("rejected %s: guide compile failed: %s",
                         req.request_id, gate)
                return
        if req.prefilled is not None:
            return self._admit_prefilled(req)
        try:
            ids, padded = self._prepare_prompt(req.prompt_ids)
        except ContextLengthExceededError as e:
            self._unpin_guide(req)
            req.outputs.put(RequestOutput(
                request_id=req.request_id, token_ids=[], finished=True,
                finish_reason="error", error="context_length_exceeded",
                num_prompt_tokens=len(req.prompt_ids)))
            log.info("rejected %s: %s", req.request_id, e)
            return

        # Prefix reuse.  Paged layout: the allocator's digest index maps
        # shared prefixes to pages already ON DEVICE — the new slot's table
        # points at them (zero copies, works on multi-host gangs since the
        # pages travel as dispatch args) and only the tail is chunk-
        # prefilled.  Slot layout: host-resident blocks are re-uploaded
        # (single-host only).  At least one tail token is always computed —
        # its logits feed first-token sampling.
        if self._paged and self._chunk:
            from arks_tpu.engine.paged import chain_digests
            page = self._page_size()
            nfull = (len(ids) - 1) // page
            digests = chain_digests(ids, page, nfull) if nfull else []
            shared = self._alloc.match(digests)
            plen = len(shared) * page
            # Tier 1: blocks beyond the device hit that survive in host
            # RAM (spilled on eviction, or published by a disagg prefill
            # peer) — restored asynchronously instead of re-prefilled.
            host_blocks: list = []
            if self._host_tier_on() and len(shared) < nfull:
                host_blocks = self._host.match_blocks(digests, len(shared))
            hlen = len(host_blocks) * page
            self._alloc.record_query(len(ids), plen + hlen)
            self.metrics.prefix_cache_query_tokens_total.inc(len(ids))
            if plen:
                self.metrics.prefix_cache_hit_tokens_total.inc(
                    plen, tier="device")
            if hlen:
                self.metrics.prefix_cache_hit_tokens_total.inc(
                    hlen, tier="host")
            self.metrics.prefix_cache_hit_rate.set(self._alloc.hit_rate)
            covered = len(shared) + len(host_blocks)
            if covered < nfull and self._fetch_candidate(req, digests,
                                                         covered):
                # Tier 2 / fleet: the uncovered span exists on local
                # disk or (per the router's hint) on a peer replica —
                # park for an async fetch into the host tier instead of
                # re-prefilling it.  Shared device refs are RELEASED
                # across the park (the resolve re-matches from scratch),
                # so no page bookkeeping outlives this frame.
                self._alloc.decref(shared)
                return self._issue_fetch(req, ids, digests, covered)
            if host_blocks:
                return self._issue_restore(req, ids, digests, shared,
                                           host_blocks)
            if plen:
                return self._start_chunked(req, ids, prefix_len=plen,
                                           prefix_pages=shared,
                                           digests=digests)
        elif self._prefix is not None and self.dispatcher is None:
            plen = min(self._prefix.match(ids),
                       (len(ids) - 1) // self._chunk * self._chunk)
            self._prefix.record_query(len(ids), plen)
            self.metrics.prefix_cache_query_tokens_total.inc(len(ids))
            self.metrics.prefix_cache_hit_tokens_total.inc(plen, tier="host")
            self.metrics.prefix_cache_hit_rate.set(self._prefix.hit_rate)
            if plen:
                return self._start_chunked(req, ids, prefix_len=plen)

        if padded is None or self._mixed:
            # Mixed scheduling: EVERY prompt rides the chunked path — its
            # tokens reach the model through mixed dispatches, so the
            # bucketed one-shot admit programs never compile (the variant
            # family collapses to one budget-shaped program).
            return self._start_chunked(req, ids)

        return (req, ids, padded)

    def _issue_admit_batch(self, items: list, want_lp: bool):
        """Issue ONE fused dispatch admitting ``len(items)`` one-shot
        prompts (same bucket).  Returns the pending record for
        _resolve_admit_batch."""
        # Guides compile on SERVER threads: a request added after this
        # step's top-of-loop table refresh would otherwise run its admit
        # with the pre-compile tables (everything masked -> instant eos).
        self._ensure_guides_uploaded()
        m = len(items)
        page = self._page_size() if self._paged else 0
        tokens = np.concatenate([padded for _, _, padded in items], axis=0)
        lengths = np.asarray([len(ids) for _, ids, _ in items], np.int32)
        slots_l, seeds, keys = [], [], []
        pages_rows = np.zeros((m, self._max_pages or 1), np.int32)
        n_pages = np.zeros((m,), np.int32)
        params_cols = {f: np.zeros((m,), np.float32)
                       for f in ("temperature", "top_p", "presence", "frequency")}
        top_ks = np.zeros((m,), np.int32)
        bias_ids = np.full((m, sampler_mod.LOGIT_BIAS_MAX), -1, np.int32)
        bias_vals = np.zeros((m, sampler_mod.LOGIT_BIAS_MAX), np.float32)
        sup_ids = np.full((m, sampler_mod.SUPPRESS_MAX), -1, np.int32)
        min_first = np.zeros((m,), np.int32)
        min_until = np.zeros((m,), np.int32)
        guide_col = np.full((m,), -1, np.int32)
        guide_row_col = np.zeros((m,), np.int32)
        try:
            self._faults.fire("admit")
            for i, (req, ids, _) in enumerate(items):
                p = req.params
                seed = self._resolve_seed(req)
                seeds.append(seed)
                keys.append(sampler_mod.np_prng_key(seed))
                slot = self._free.pop()
                slots_l.append(slot)
                # Park the slot at the write-drop sentinel until its
                # registration: with deferred resolution, decode dispatches
                # can land between this admit program (which inserts the
                # prompt KV) and _register_slot — a stale length here would
                # let those dispatches overwrite the inserted rows.
                self._lengths[slot] = self._park_sentinel()
                if self._paged:
                    n_alloc = -(-len(ids) // page)
                    pages_rows[i] = self._assign_slot_pages(slot, n_alloc)
                    n_pages[i] = n_alloc
                params_cols["temperature"][i] = p.temperature
                params_cols["top_p"][i] = p.top_p
                params_cols["presence"][i] = p.presence_penalty
                params_cols["frequency"][i] = p.frequency_penalty
                top_ks[i] = p.top_k
                if p.logit_bias or p.min_tokens:
                    (bias_ids[i], bias_vals[i], sup_ids[i], min_first[i],
                     min_until[i]) = self._shape_cols(p, len(ids))
                guide_col[i], guide_row_col[i] = self._guide_cols(p)
            slots = np.asarray(slots_l, np.int32)
            self._emit("admit_batch_lp" if want_lp else "admit_batch",
                       tokens=tokens, lengths=lengths, slots=slots,
                       pages=pages_rows if self._paged else None,
                       n_pages=n_pages if self._paged else None,
                       seeds=list(seeds),
                       temperature=params_cols["temperature"],
                       top_p=params_cols["top_p"], top_k=top_ks,
                       presence=params_cols["presence"],
                       frequency=params_cols["frequency"],
                       bias_ids=bias_ids, bias_vals=bias_vals,
                       sup_ids=sup_ids, min_first=min_first,
                       min_until=min_until, guide=guide_col,
                       guide_row=guide_row_col)
            args = (self.params, self._cache, self._sampling,
                    jnp.asarray(tokens), jnp.asarray(lengths),
                    jnp.asarray(slots),
                    jnp.asarray(pages_rows) if self._paged else None,
                    jnp.asarray(n_pages) if self._paged else None,
                    jnp.asarray(params_cols["temperature"]),
                    jnp.asarray(params_cols["top_p"]),
                    jnp.asarray(top_ks),
                    jnp.asarray(np.stack(keys)),
                    jnp.asarray(params_cols["presence"]),
                    jnp.asarray(params_cols["frequency"]),
                    jnp.asarray(bias_ids), jnp.asarray(bias_vals),
                    jnp.asarray(sup_ids), jnp.asarray(min_first),
                    jnp.asarray(min_until), jnp.asarray(guide_col),
                    jnp.asarray(guide_row_col), self._guide_dev)
            if want_lp:
                (first_ids, clps, valss, lidss, self._cache, self._sampling,
                 ks, vs) = self._admit_lp_fn(*args)
                lp_out = (clps, valss, lidss)
            else:
                first_ids, self._cache, self._sampling, ks, vs = \
                    self._admit_fn(*args)
                lp_out = None
        except Exception as e:
            # None of the requests holds a REGISTERED slot yet, so _run's
            # recovery snapshot can't see them — carry them as survivors
            # on the StepFault (they re-queue with their pinned seeds) or
            # their clients block forever.  (Slot and page bookkeeping are
            # rebuilt by the recovery reset.)
            survivors = [_Survivor(request=req, seed=self._resolve_seed(req),
                                   num_prompt=len(ids))
                         for req, ids, _ in items]
            if isinstance(e, StepFault):
                e.survivors.extend(survivors)
                raise
            raise StepFault(
                "admit", faults_mod.classify(e),
                culprits=[req.request_id for req, _, _ in items],
                survivors=survivors) from e
        # Only the slot-layout single-prompt prefix harvest reads ks/vs at
        # resolve; everywhere else, keeping them in the record would pin
        # the batch's full prompt KV in HBM for the deferral window.
        if self._paged or self._prefix is None or m > 1:
            ks = vs = None
        for req, ids, _ in items:
            self.trace.evt(req.request_id, "queue", "E")
            self.trace.evt(req.request_id, "prefill", "B", len(ids))
        return (items, slots_l, first_ids, lp_out, ks, vs)

    def _resolve_admit_batch(self, rec) -> None:
        """Host-sync tail of a fused admission batch: fetch the first
        tokens, register the slots, emit, and harvest prefixes."""
        items, slots_l, first_ids, lp_out, ks, vs = rec
        try:
            self._faults.fire("admit_resolve")
            firsts = np.asarray(first_ids).tolist()  # device round-trip
            if lp_out is not None:
                clps = np.asarray(lp_out[0])
                valss = np.asarray(lp_out[1])
                lidss = np.asarray(lp_out[2])
        except Exception as e:
            # Dispatch failed asynchronously; the requests hold slots the
            # recovery snapshot will not see (not registered) — carry them
            # as survivors so they re-queue with their pinned seeds.
            for (req, ids, _), slot in zip(items, slots_l):
                if slot not in self._slots:
                    self._free.append(slot)
            raise StepFault(
                "admit_resolve", faults_mod.classify(e),
                culprits=[req.request_id for req, _, _ in items],
                survivors=[_Survivor(request=req,
                                     seed=self._resolve_seed(req),
                                     num_prompt=len(ids))
                           for req, ids, _ in items]) from e
        for i, ((req, ids, _), slot) in enumerate(zip(items, slots_l)):
            # Aborts raised between issue and this (deferred) resolve:
            # honor them here instead of registering a dead slot for one
            # more dispatch cycle.
            with self._abort_lock:
                was_aborted = req.request_id in self._aborted
                self._aborted.discard(req.request_id)
            if was_aborted:
                self._release_slot_pages(slot)
                self._free.append(slot)
                self._unpin_guide(req)
                p = req.params
                if (p.presence_penalty or p.frequency_penalty
                        or p.logit_bias or p.min_tokens
                        or p.guide is not None):
                    # Re-arm shaped()'s fast paths (same as _finish): the
                    # admit program already wrote this slot's shaping rows.
                    self._emit("clear_penalties", slot=slot)
                    self._sampling = self._clear_pen_fn(
                        self._sampling, jnp.asarray(slot, jnp.int32))
                req.outputs.put(RequestOutput(
                    request_id=req.request_id, token_ids=[], finished=True,
                    finish_reason="abort", num_prompt_tokens=len(ids)))
                continue
            first_lp = None
            if lp_out is not None and req.params.logprobs is not None:
                first_lp = self._lp_entry(clps[i], valss[i], lidss[i],
                                          req.params.logprobs)
            self._register_slot(req, slot, firsts[i], len(ids),
                                first_lp=first_lp,
                                seed=self._resolve_seed(req))
            if self._paged and self._chunk:
                # Zero-cost harvest: the prompt's full pages are already in
                # the pool — register their digests so later prompts share
                # them on device.  (Only pages entirely covered by the
                # prompt: decode writes start at position len(ids).)
                self._register_prompt_pages(ids,
                                            self._slot_pages.get(slot, []))
            # Slot layout: harvest into the host prefix cache — but NOT
            # under admission pressure: the device->host KV copy (tens of
            # MB per prompt) would starve waiting admissions.  (ks is None
            # whenever the issue path decided no harvest could apply.)
            elif (self._prefix is not None and self.dispatcher is None
                    and ks is not None
                    and len(items) == 1 and self._queue.empty()):
                nfull = len(ids) // self._chunk * self._chunk
                if nfull and self._prefix.missing_blocks(ids, nfull):
                    self._prefix.put(ids, np.asarray(ks[:, :, :nfull]),
                                     np.asarray(vs[:, :, :nfull]), nfull)
                    self.metrics.prefix_cache_usage_bytes.set(
                        self._prefix.bytes_used, tier="host")

    def _assign_slot_pages(self, slot: int, total: int,
                           head_pages=()) -> np.ndarray:
        """Allocate a slot's pages (optionally headed by already-incref'd
        shared prefix pages), record them in _slot_pages, and write the
        zero-padded table row — THE one place the row/ownership invariant
        lives.  Returns the table row."""
        pages = list(head_pages) + self._alloc.alloc(total - len(head_pages))
        self._slot_pages[slot] = pages
        row = np.zeros((self._max_pages,), np.int32)
        row[: len(pages)] = pages
        self._tables[slot] = row
        # Evictions the alloc caused spill before the caller's dispatch
        # can write the recycled pages (stream order).
        self._spill_flush()
        return row

    def _register_prompt_pages(self, ids, pages, digests=None) -> None:
        from arks_tpu.engine.paged import chain_digests
        page = self._page_size()
        nreg = min(len(ids) // page, len(pages))
        if nreg:
            if digests is None or len(digests) < nreg:
                digests = chain_digests(ids, page, nreg)
            self._alloc.register(digests[:nreg], pages[:nreg])
            self.metrics.prefix_cache_usage_bytes.set(
                self._alloc.retained_pages * self._page_bytes, tier="device")

    # ------------------------------------------------------------------
    # Prefix-digest sketch export (cache-aware routing)
    # ------------------------------------------------------------------

    def cache_sketch(self) -> dict:
        """The prefix-digest sketch payload for ``GET /v1/cache/sketch``.
        Server threads only.  Reads host-side membership snapshots (the
        allocator's locked mirror, the host tier's map under its own
        lock) and host counters — never device data — so an export can
        never add a blocking fetch to the dispatch stream; the build
        itself is cached inside the exporter until tier membership (or
        the epoch) actually changes."""
        sk = self._sketch
        alloc = self._alloc
        if sk is None:
            return {"enabled": False}
        # Scaled to zero: the allocator (and the device prefix tier with
        # it) is gone — advertise an empty tier 0 but keep host/disk
        # visible; peers may still pull warm blocks from this replica.
        device: list = []
        dver = -1
        akey = 0
        if alloc is not None:
            device, dver = alloc.index_snapshot()
            akey = id(alloc)
        host_list: list = []
        hver = -1
        host = self._host
        if host is not None:
            host_list, hver = host.snapshot()
        disk_list: list = []
        dkver = -1
        disk = self._disk
        if disk is not None:
            disk_list, dkver = disk.snapshot()
        # id(alloc) keys the build cache across resets/model switches,
        # where a FRESH allocator restarts its version counter.
        hits = self.metrics.prefix_cache_hit_tokens_total
        return sk.build(
            device, (akey, dver), host_list, hver,
            disk=disk_list, disk_key=dkver,
            hit_tokens={"device": hits.get(tier="device"),
                        "host": hits.get(tier="host"),
                        "disk": hits.get(tier="disk")},
            query_tokens=self.metrics.prefix_cache_query_tokens_total.total(),
            extra={"model": self.cfg.name})

    def note_prompt_text(self, body: dict, ids) -> None:
        """Record one request's text->token digest alignment in the
        sketch exporter's ledger (the text-domain side of tokenize-free
        router scoring).  Server threads; pure host hashing."""
        sk = self._sketch
        if sk is None:
            return
        from arks_tpu.prefix_sketch import canonical_prompt_text
        text = canonical_prompt_text(body)
        if text:
            sk.link(text, ids)

    # ------------------------------------------------------------------
    # Hierarchical prefix cache: host-RAM spill tier (tier 1)
    # ------------------------------------------------------------------

    def _host_tier_on(self) -> bool:
        """Tier 1 active: paged+chunk engine with an ARKS_PREFIX_HOST_MB
        budget on a SINGLE host.  Followers would need the spill/restore
        dispatches mirrored for no benefit — the blocks are host-side
        state only the leader consults — so a dispatcher turns it off
        (same restriction as the legacy slot-layout host cache)."""
        return self._host is not None and self.dispatcher is None

    def _note_evicted(self, digest: bytes, page: int) -> None:
        """PageAllocator.on_evict hook: queue the victim for an async D2H
        spill.  Runs mid-alloc on the engine thread — bookkeeping only;
        _spill_flush issues the gather before any dispatch can reuse the
        page."""
        self._spill_victims.append((digest, page))

    def _spill_flush(self) -> None:
        """Issue spill gathers for every page evicted since the last
        flush: gather the victim pages into a device staging block and
        start the D2H drain (copy_to_host_async) — the engine thread
        never waits; _resolve_spills harvests the bytes one lagged step
        later.  MUST run after the evicting alloc and before the next
        dispatch that could write the recycled pages: both order on the
        device stream, so the gather reads the pre-overwrite bytes."""
        if not self._spill_victims:
            return
        victims, self._spill_victims = self._spill_victims, []
        if not self._host_tier_on():
            return
        victims = [(d, p) for d, p in victims if not self._host.has(d)]
        self.trace.evt("", "spill", "I", len(victims))
        G = self._spill_group
        for i in range(0, len(victims), G):
            grp = victims[i: i + G]
            self._faults.fire("spill")
            # Short groups pad by repeating a real page (one compiled
            # shape); the host side drops the padded entries.
            pages = [p for _, p in grp] + [grp[0][1]] * (G - len(grp))
            out = self._spill_gather_fn(self._cache,
                                        jnp.asarray(pages, jnp.int32))
            for arr in out:
                if arr is None:
                    continue
                try:
                    arr.copy_to_host_async()
                except Exception as e:  # platform without async host copies
                    faults_mod.swallowed("copy_to_host_async", e)
            self._spills.append(([d for d, _ in grp], out))

    @staticmethod
    def _dev_ready(arr) -> bool:
        try:
            return arr.is_ready()
        except AttributeError:  # platform without readiness polling
            return True

    def _resolve_spills(self, force: bool = False) -> bool:
        """Harvest completed spill gathers into the host tier (FIFO;
        non-blocking unless forced).  Spills are best-effort cache
        warmth: a failed gather is dropped via the fault API, never
        escalated — losing a spill costs one future re-prefill, while
        faulting the engine for it would cost every in-flight stream a
        recovery round."""
        did = False
        while self._spills:
            digests, out = self._spills[0]
            if not force and not self._dev_ready(out[0]):
                break
            self._spills.popleft()
            did = True
            try:
                k, v, ks, vs = [None if a is None else np.asarray(a)
                                for a in out]
            except Exception as e:
                faults_mod.swallowed("spill_resolve", e)
                continue
            stored = 0
            for j, d in enumerate(digests):
                # Contiguous copies: a view would pin the whole staging
                # block in host RAM for the lifetime of one page entry.
                blk = {"k": np.ascontiguousarray(k[:, j]),
                       "v": np.ascontiguousarray(v[:, j])}
                if ks is not None:
                    blk["k_scale"] = np.ascontiguousarray(ks[:, j])
                    blk["v_scale"] = np.ascontiguousarray(vs[:, j])
                if self._host.put(d, blk):
                    stored += 1
            if stored:
                self.metrics.prefix_spill_blocks_total.inc(stored)
            self.metrics.prefix_cache_usage_bytes.set(
                self._host.bytes_used, tier="host")
        return did

    def _issue_restore(self, req: Request, ids: list[int], digests: list,
                       shared: list[int], blocks: list) -> None:
        """Tier-1 hit at admission: allocate fresh pool pages for the
        host blocks and issue the H2D scatter-into-pool dispatch(es)
        ASYNCHRONOUSLY — just another dispatch on the stream, so decode
        pipelining keeps its full depth while the restore is in flight.
        The request parks in awaiting_restore (mirroring the guide_wait
        park); _resolve_restores unparks it into the ordinary
        chunked-tail path once the marker lands."""
        seed = self._resolve_seed(req)
        try:
            self._faults.fire("restore")
            pages = self._alloc.alloc(len(blocks))
            # The alloc may have evicted tier-0 pages; their spill
            # gathers must precede our scatter (which may write those
            # very pages).
            self._spill_flush()
            marker = None
            G = self._restore_group
            for i in range(0, len(blocks), G):
                marker = self._dispatch_restore_group(
                    blocks[i: i + G], pages[i: i + G], G)
        except Exception as e:
            # Page/alloc state is rebuilt wholesale by the recovery
            # reset; the survivor re-queues with its pinned seed and
            # retries admission (the host tier survives the reset, so
            # the retry hits tier 1 again).
            if isinstance(e, StepFault):
                raise
            raise StepFault(
                "restore", faults_mod.classify(e),
                culprits=[req.request_id],
                survivors=[_Survivor(request=req, seed=seed,
                                     num_prompt=len(ids))]) from e
        self._awaiting_restore.append(_RestoreState(
            request=req, ids=ids, digests=digests, shared=shared,
            pages=pages, marker=marker, seed=seed, t0=time.monotonic()))
        self.metrics.num_requests_waiting.inc(1)
        self.trace.evt(req.request_id, "park.restore", "B", len(blocks))

    def _dispatch_restore_group(self, blocks: list, pages: list[int],
                                G: int):
        """One scatter dispatch: stack up to G host blocks into the
        padded staging shape (ONE compiled program) and write them into
        ``pages``.  Returns the dispatch's readiness marker."""
        nb = len(blocks)

        def staged(field):
            first = blocks[0][field]
            out = np.zeros((first.shape[0], G) + first.shape[1:],
                           first.dtype)
            for j, b in enumerate(blocks):
                out[:, j] = b[field]
            return jnp.asarray(out)

        ksb = vsb = None
        if "k_scale" in blocks[0]:
            ksb, vsb = staged("k_scale"), staged("v_scale")
        pg = list(pages) + [pages[0]] * (G - nb)
        self._cache, marker = self._restore_fn(
            self._cache, staged("k"), staged("v"), ksb, vsb,
            jnp.asarray(pg, jnp.int32), jnp.asarray(nb, jnp.int32))
        return marker

    def _restore_ready_any(self) -> bool:
        return any(self._dev_ready(rec.marker)
                   for rec in self._awaiting_restore
                   if not isinstance(rec, _ResumeState))

    def _resume_ready_any(self) -> bool:
        """A preempt-swap resume's scatter landed.  Unlike a prefix
        restore it needs NO free slot — the resumed request already holds
        one — so the pipelined fast path must drain for it even when
        _free is empty."""
        return any(self._dev_ready(rec.marker)
                   for rec in self._awaiting_restore
                   if isinstance(rec, _ResumeState))

    def _swap_ready_any(self) -> bool:
        """The oldest in-flight preempt spill's D2H copies landed (FIFO —
        _resolve_preempt_swaps only ever harvests the head)."""
        if not self._swap_pending:
            return False
        sw = self._swap_pending[0]
        marker = sw.staged[-1][1][0] if sw.staged else sw.row[1]
        return self._dev_ready(marker) and self._dev_ready(sw.row[1])

    def _resolve_restores(self) -> bool:
        """Unpark restore-parked requests whose scatter landed (and a
        free slot exists): register the restored digests into the device
        index (tier-1 hits repopulate tier 0) and continue through the
        ordinary chunked-tail path.  Aborts raised while parked release
        the pages; a failed restore dispatch faults the restoring
        request ALONE (phase "restore")."""
        did = False
        pending = self._awaiting_restore
        i = 0
        while i < len(pending):
            rec = pending[i]
            rid = rec.request.request_id
            with self._abort_lock:
                was_aborted = rid in self._aborted
                if was_aborted:
                    self._aborted.discard(rid)
            if isinstance(rec, _ResumeState):
                # Preempt-swap resume: the request holds its slot already;
                # only the scatter marker gates it (no free-slot wait).
                if was_aborted:
                    pending.pop(i)
                    did = True
                    self.metrics.num_requests_waiting.inc(-1)
                    self._alloc.decref(rec.pages)
                    self._free.append(rec.slot)
                    self._unpin_guide(rec.request)
                    rec.request.outputs.put(RequestOutput(
                        request_id=rid, token_ids=[], finished=True,
                        finish_reason="abort",
                        num_prompt_tokens=rec.rec.num_prompt,
                        num_generated_tokens=len(rec.rec.generated)))
                    self._update_parked()
                    continue
                if not self._dev_ready(rec.marker):
                    i += 1
                    continue
                pending.pop(i)
                did = True
                self.metrics.num_requests_waiting.inc(-1)
                try:
                    self._faults.fire("preempt")
                    np.asarray(rec.marker)  # surfaces dispatch failures
                except Exception as e:
                    self._free.append(rec.slot)
                    if isinstance(e, StepFault):
                        raise
                    raise StepFault(
                        "preempt", faults_mod.classify(e), culprits=[rid],
                        survivors=[self._swap_survivor(rec.rec)]) from e
                self._finish_resume(rec)
                self._update_parked()
                continue
            if was_aborted:
                pending.pop(i)
                did = True
                self.metrics.num_requests_waiting.inc(-1)
                # The scatter may still be in flight toward these pages;
                # freeing them is safe — any re-allocation's write
                # dispatch queues behind our scatter on the stream.
                self._alloc.decref(rec.shared)
                self._alloc.decref(rec.pages)
                self._unpin_guide(rec.request)
                rec.request.outputs.put(RequestOutput(
                    request_id=rid, token_ids=[], finished=True,
                    finish_reason="abort", num_prompt_tokens=len(rec.ids)))
                continue
            if not self._free or not self._dev_ready(rec.marker):
                i += 1
                continue
            pending.pop(i)  # before any fault path, so recovery cannot
            did = True      # double-count the record as a survivor
            self.metrics.num_requests_waiting.inc(-1)
            try:
                self._faults.fire("restore")
                np.asarray(rec.marker)  # surfaces async dispatch failures
            except Exception as e:
                raise StepFault(
                    "restore", faults_mod.classify(e),
                    culprits=[rid],
                    survivors=[_Survivor(request=rec.request, seed=rec.seed,
                                         num_prompt=len(rec.ids))]) from e
            page = self._page_size()
            start = len(rec.shared)
            # Register BEFORE _start_chunked: if the tail alloc faults,
            # its cleanup decrefs only our caller refs and the restored
            # pages survive as index-retained.
            self._alloc.register(
                rec.digests[start: start + len(rec.pages)], rec.pages)
            if self._host is not None:
                self._host.restored_blocks += len(rec.pages)
            self.metrics.prefix_restore_blocks_total.inc(len(rec.pages))
            self.metrics.prefix_restore_seconds.observe(
                time.monotonic() - rec.t0)
            self.metrics.prefix_cache_usage_bytes.set(
                self._alloc.retained_pages * self._page_bytes,
                tier="device")
            self.trace.evt(rid, "park.restore", "E")
            self._start_chunked(
                rec.request, rec.ids,
                prefix_len=(start + len(rec.pages)) * page,
                prefix_pages=rec.shared + rec.pages,
                digests=rec.digests)
        return did

    def _abort_awaiting_restores(self) -> None:
        """Fail every restore-parked request (engine exit / blanket
        abort): no scheduler remains to unpark them.  Page bookkeeping is
        moot — both callers precede a device reset or process exit."""
        for rec in self._awaiting_restore:
            self.metrics.num_requests_waiting.inc(-1)
            self._unpin_guide(rec.request)
            rec.request.outputs.put(RequestOutput(
                request_id=rec.request.request_id, token_ids=[],
                finished=True, finish_reason="abort",
                num_prompt_tokens=len(rec.ids)))
        self._awaiting_restore = []

    # ------------------------------------------------------------------
    # Tier-2 disk block store + fleet peer fetch
    # ------------------------------------------------------------------

    def _kv_layout_epoch(self) -> str:
        """Pool layout signature digest.  Chain digests are content-only
        (token ids) — NOT keyed by model or pool geometry — so every
        tier-2 block file and every peer-fetched wire block carries this
        stamp, and a reader on any other layout rejects the bytes
        instead of reinterpreting them."""
        import hashlib
        sig = "|".join(str(x) for x in (
            self.cfg.name, self._page_size(), self.cfg.num_layers,
            self.cfg.num_kv_heads, self._page_bytes,
            self.ecfg.kv_quantized, self.ecfg.kv_bits,
            self.ecfg.resolve_kv_cache_dtype()))
        return hashlib.sha1(sig.encode()).hexdigest()[:16]

    @property
    def kv_epoch(self) -> str:
        """The layout epoch peers validate fetched blocks against (the
        server's block-export path packs with this)."""
        return self._kv_epoch

    def _note_host_evicted(self, digest: bytes, block: dict) -> None:
        """HostPrefixTier.on_evict hook: queue a tier-1 evictee for the
        async disk spill.  Called outside the tier lock, from whichever
        thread triggered the eviction (engine spill harvest, disagg
        publish) — bookkeeping only; the step loop drains the queue and
        a writer thread does the file IO.  Bounded: a spill storm drops
        blocks (cache warmth is best-effort) rather than growing an
        unbounded backlog of host RAM the LRU just decided to free."""
        if self._disk is None or len(self._disk_spill_pending) >= 1024:
            return
        self._disk_spill_pending.append((digest, block))

    def _drain_disk_spills(self) -> bool:
        """Hand queued tier-1 evictees to the disk writer thread (engine
        thread; no file IO here).  Phase "disk_spill" raises with NO
        culprits — a spill serves no request, so a fault replays every
        in-flight stream and burns nobody's retry budget."""
        if self._disk is None or not self._disk_spill_pending:
            return False
        try:
            self._faults.fire("disk_spill")
        except Exception as e:
            if isinstance(e, StepFault):
                raise
            raise StepFault("disk_spill", faults_mod.classify(e)) from e
        n = 0
        while self._disk_spill_pending:
            digest, blk = self._disk_spill_pending.popleft()
            if self._disk.has(digest):
                continue
            try:
                self._disk_write_queue.put_nowait((digest, blk))
            except queue.Full:
                # Best-effort: losing a spill costs one future
                # re-prefill; blocking the step loop would cost every
                # in-flight stream.
                self._disk_spill_pending.clear()
                break
            n += 1
        if n:
            self.trace.evt("", "disk_spill", "I", n)
        return n > 0

    def _disk_write_loop(self) -> None:
        """Writer thread: persist queued blocks (tmp+rename inside the
        tier) and mirror the tier's gauges.  Failures are swallowed —
        the disk tier is warmth, never correctness."""
        q = self._disk_write_queue
        while True:
            item = q.get()
            if item is None:
                return
            digest, blk = item
            try:
                self._disk.put(digest, blk)
            except Exception as e:
                faults_mod.swallowed("disk_spill.write", e)
            self._mirror_disk_metrics()

    def _mirror_disk_metrics(self) -> None:
        """Mirror the disk tier's internal counters into EngineMetrics
        (called from the writer/fetch threads after tier mutations)."""
        d = self._disk
        if d is None:
            return
        m = self.metrics
        m.prefix_cache_usage_bytes.set(d.bytes_used, tier="disk")
        with self._disk_stats_lock:
            ev, co = d.evicted_blocks, d.corrupt_blocks
            if ev > self._disk_evict_seen:
                m.prefix_disk_evictions_total.inc(ev - self._disk_evict_seen)
                self._disk_evict_seen = ev
            if co > self._disk_corrupt_seen:
                m.prefix_disk_corrupt_total.inc(co - self._disk_corrupt_seen)
                self._disk_corrupt_seen = co

    def _flush_warm_to_disk(self) -> None:
        """Graceful-stop persistence (stop(), engine thread already
        joined): gather every prefix block still resident in the device
        index with the spill path's own grouped gather, and copy every
        tier-1 block, into the disk store — synchronously; blocking D2H
        is fine once the step loop is gone.  Best-effort throughout: a
        failed gather or write costs restart warmth, never the
        shutdown."""
        disk, host, alloc = self._disk, self._host, self._alloc
        gather = getattr(self, "_spill_gather_fn", None)
        if alloc is not None and gather is not None and \
                self._cache is not None:
            with alloc._mirror_lock:
                resident = list(alloc._index.items())  # digest -> page
            victims = [(d, p) for d, p in resident if not disk.has(d)]
            G = self._spill_group
            for i in range(0, len(victims), G):
                grp = victims[i: i + G]
                pages = [p for _, p in grp] + [grp[0][1]] * (G - len(grp))
                try:
                    out = gather(self._cache,
                                 jnp.asarray(pages, jnp.int32))
                    k, v, ks, vs = [None if a is None else np.asarray(a)
                                    for a in out]
                except Exception as e:
                    faults_mod.swallowed("disk_tier.flush", e)
                    continue
                for j, (d, _) in enumerate(grp):
                    blk = {"k": np.ascontiguousarray(k[:, j]),
                           "v": np.ascontiguousarray(v[:, j])}
                    if ks is not None:
                        blk["k_scale"] = np.ascontiguousarray(ks[:, j])
                        blk["v_scale"] = np.ascontiguousarray(vs[:, j])
                    disk.put(d, blk)
        if host is not None:
            digests, _ver = host.snapshot()
            for d in digests:
                if disk.has(d):
                    continue
                blk = host.peek(d)
                if blk is not None:
                    disk.put(d, blk)
        self._mirror_disk_metrics()

    def _fetch_candidate(self, req: Request, digests: list,
                         covered: int) -> bool:
        """Can tier 2 or a peer extend this admission's coverage?  Pure
        host probes (the disk check is an in-memory index hit): True
        parks the request in _awaiting_fetch instead of re-prefilling
        the uncovered span."""
        if self._fetch_queue is None or not self._host_tier_on():
            return False
        if self._disk is not None and \
                self._disk.match_digests(digests, covered):
            return True
        return self._peer_fetch and bool(req.peer_hint or self._peer_addrs)

    def _issue_fetch(self, req: Request, ids: list[int], digests: list,
                     start: int) -> None:
        """Park an admission miss whose uncovered digests the disk tier
        (or a hinted peer) can supply.  No device pages are held across
        the park — the resolve re-runs the match from scratch — so abort
        and recovery need no page bookkeeping for this state."""
        seed = self._resolve_seed(req)
        st = _FetchState(request=req, ids=ids, digests=digests,
                         start=start, peer=(req.peer_hint or None),
                         seed=seed, t0=time.monotonic())
        self._awaiting_fetch.append(st)
        self._fetch_queue.put(st)
        self.metrics.num_requests_waiting.inc(1)
        self.trace.evt(req.request_id, "park.fetch", "B",
                       len(digests) - start)

    def _fetch_loop(self) -> None:
        """Fetch worker thread: stage parked requests' missing blocks
        into the host tier.  Every failure mode degrades to `done` with
        whatever run was staged — the resolve then restores the partial
        run and chunk-prefills the rest (mid-fetch peer death costs
        latency, never correctness)."""
        q = self._fetch_queue
        while True:
            st = q.get()
            if st is None:
                return
            try:
                self._fetch_one(st)
            except Exception as e:
                faults_mod.swallowed("prefix_fetch", e)
            st.done = True

    def _fetch_one(self, st: _FetchState) -> None:
        """Stage st's uncovered digest run: local disk first (cheaper),
        then the hinted peer, then the static ARKS_PEER_ADDRS list.
        Consecutive-only — a gap stops the run, because a restore needs
        a contiguous prefix."""
        peers = [a for a in ([st.peer] if st.peer else [])
                 + self._peer_addrs if a]
        for d in st.digests[st.start:]:
            if self._host.has(d):
                continue
            blk = self._disk.get(d) if self._disk is not None else None
            src = "disk"
            if blk is None and peers:
                blk = self._fetch_from_peers(peers, d)
                src = "peer"
            if blk is None:
                break
            if not self._host.put(d, blk) and not self._host.has(d):
                break   # host budget cannot hold the staged run
            if src == "disk":
                st.fetched_disk += 1
            else:
                st.fetched_peer += 1
        self._mirror_disk_metrics()

    def _fetch_from_peers(self, peers: list[str], digest: bytes):
        """One block from the first peer that has it, validated against
        the local layout epoch (a peer on another pool layout 404s or is
        rejected — never reinterpreted)."""
        from arks_tpu.engine import kv_transfer
        for addr in peers:
            buf = self._peer_block_get(addr, digest)
            if buf is None:
                continue
            try:
                blk = kv_transfer.unpack_block(buf, digest, self._kv_epoch)
            except ValueError as e:
                faults_mod.swallowed("peer_fetch.unpack", e)
                continue
            return {k: np.ascontiguousarray(v) for k, v in blk.items()}
        return None

    def _peer_block_get(self, addr: str, digest: bytes) -> bytes | None:
        """GET /v1/cache/blocks/{digest} from one peer; None on any
        failure (timeout, refused, 404, mid-body death) — the caller
        falls back to the next peer or to re-prefill."""
        import http.client
        addr = addr.split("//", 1)[-1].rstrip("/")
        host, _, port = addr.rpartition(":")
        try:
            conn = http.client.HTTPConnection(
                host or addr, int(port) if port else 80,
                timeout=self._peer_timeout)
            try:
                conn.request("GET", f"/v1/cache/blocks/{digest.hex()}")
                resp = conn.getresponse()
                if resp.status != 200:
                    return None
                return resp.read()
            finally:
                conn.close()
        except Exception as e:
            faults_mod.swallowed("peer_fetch.http", e)
            return None

    def _fetch_ready_any(self) -> bool:
        return bool(self._free) and any(st.done
                                        for st in self._awaiting_fetch)

    def _resolve_fetches(self) -> bool:
        """Unpark fetch-parked requests whose worker finished: re-run
        the admission match (the staged blocks now sit in the host tier)
        and continue through the ordinary tier-1 restore / chunked-tail
        path.  A resolve fault culprits the fetching request ALONE
        (phase "peer_fetch"); aborts raised while parked just fail the
        request — no pages were held across the park."""
        did = False
        pending = self._awaiting_fetch
        i = 0
        while i < len(pending):
            st = pending[i]
            rid = st.request.request_id
            with self._abort_lock:
                was_aborted = rid in self._aborted
                if was_aborted:
                    self._aborted.discard(rid)
            if was_aborted:
                pending.pop(i)
                did = True
                self.metrics.num_requests_waiting.inc(-1)
                self._unpin_guide(st.request)
                st.request.outputs.put(RequestOutput(
                    request_id=rid, token_ids=[], finished=True,
                    finish_reason="abort", num_prompt_tokens=len(st.ids)))
                continue
            if not st.done or not self._free:
                i += 1
                continue
            pending.pop(i)  # before the fault fire, so recovery cannot
            did = True      # double-count the record as a survivor
            self.metrics.num_requests_waiting.inc(-1)
            try:
                self._faults.fire("peer_fetch")
            except Exception as e:
                if isinstance(e, StepFault):
                    raise
                raise StepFault(
                    "peer_fetch", faults_mod.classify(e), culprits=[rid],
                    survivors=[_Survivor(request=st.request, seed=st.seed,
                                         num_prompt=len(st.ids))]) from e
            page = self._page_size()
            if st.fetched_disk:
                self.metrics.prefix_peer_fetch_blocks_total.inc(
                    st.fetched_disk, source="disk")
                self.metrics.prefix_cache_hit_tokens_total.inc(
                    st.fetched_disk * page, tier="disk")
            if st.fetched_peer:
                self.metrics.prefix_peer_fetch_blocks_total.inc(
                    st.fetched_peer, source="peer")
                self.metrics.prefix_cache_hit_tokens_total.inc(
                    st.fetched_peer * page, tier="peer")
            if st.fetched_disk or st.fetched_peer:
                self.metrics.prefix_peer_fetch_seconds.observe(
                    time.monotonic() - st.t0)
            self.trace.evt(rid, "park.fetch", "E",
                           st.fetched_disk + st.fetched_peer)
            self._admit_after_fetch(st)
        return did

    def _admit_after_fetch(self, st: _FetchState) -> None:
        """Route an unparked fetch through the standard admission match:
        device run (may have changed while parked), then host tier (now
        holding the staged blocks), then the chunked tail.  An empty
        fetch degrades to plain chunked prefill — the no-worse-than-
        re-prefill guarantee."""
        req, ids, digests = st.request, st.ids, st.digests
        page = self._page_size()
        shared = self._alloc.match(digests)
        plen = len(shared) * page
        host_blocks: list = []
        if self._host_tier_on() and len(shared) < len(digests):
            host_blocks = self._host.match_blocks(digests, len(shared))
        if host_blocks:
            return self._issue_restore(req, ids, digests, shared,
                                       host_blocks)
        if plen:
            return self._start_chunked(req, ids, prefix_len=plen,
                                       prefix_pages=shared,
                                       digests=digests)
        self._alloc.decref(shared)
        self._start_chunked(req, ids)

    def block_for_export(self, digest: bytes) -> dict | None:
        """One prefix block for a peer's GET /v1/cache/blocks/{digest}.
        Server threads.  Host tier first (peek — a remote reader must
        not distort this replica's own recency order), then disk; None
        maps to 404 at the HTTP layer."""
        host = self._host
        if host is not None:
            blk = host.peek(digest)
            if blk is not None:
                return blk
        disk = self._disk
        if disk is not None:
            return disk.get(digest)
        return None

    def _abort_awaiting_fetches(self) -> None:
        """Fail every fetch-parked request (engine exit / blanket
        abort): no scheduler remains to unpark them."""
        for st in self._awaiting_fetch:
            self.metrics.num_requests_waiting.inc(-1)
            self._unpin_guide(st.request)
            st.request.outputs.put(RequestOutput(
                request_id=st.request.request_id, token_ids=[],
                finished=True, finish_reason="abort",
                num_prompt_tokens=len(st.ids)))
        self._awaiting_fetch = []

    # ------------------------------------------------------------------
    # SLO-tiered preemptive KV swap (ARKS_PREEMPT)
    # ------------------------------------------------------------------
    # Priority stops being mere queue ordering: when a queued request's
    # (aged) priority strictly outranks the lowest running tier and no
    # slot is free, the scheduler seizes a victim slot.  Two modes:
    #
    # - SWAP (paged + chunked + host tier, single-host, non-spec): the
    #   victim's FULL decode state leaves the device — KV pages through
    #   the same gather/stage path the prefix spill uses, plus the
    #   sampler row (PRNG key, penalty counts, DFA row) — and parks in
    #   the SwapStore.  Resume scatters it all back into a fresh slot and
    #   the stream continues byte-identically: the key snapshot re-enters
    #   the per-slot split chain exactly where sample() left it, the
    #   counts row reproduces the penalty state, and pool pages are
    #   byte-exact round trips (the PR 5 bit-exactness argument).
    # - REPLAY (everything else): the victim re-queues behind a
    #   _ReplayGate and deterministically re-executes — the PR 4 recovery
    #   discipline, which also backstops swap mode when the host budget
    #   is full.  docs/application-usage.md carries the fallback matrix.
    #
    # Freeing the victim's slot in the SAME step as the gathers is safe
    # for the same reason _spill_flush is: every device op enqueues in
    # order on one stream, so the gathers read pre-reuse bytes no matter
    # when the next admission's dispatch lands.

    def _preempt_swap_capable(self) -> bool:
        """Swap-mode eligibility (engine-wide, decided at init): needs
        the paged+chunk engine with the host tier on (the SwapStore
        shares its budget) and no draft model — a spec victim's draft
        cache mirror has no cheap snapshot, so spec engines preempt in
        replay mode."""
        return (self._host_tier_on() and self._swap is not None
                and self._draft_cfg is None)

    def _preempt_capable(self) -> bool:
        """Preemption on at all: ARKS_PREEMPT=1 and single-host (the
        follower dispatch protocol has no preempt op)."""
        return self._preempt_on and self.dispatcher is None

    @staticmethod
    def _swap_survivor(rec: _SwapRecord) -> _Survivor:
        """A swapped victim's replayable snapshot — any fault on the swap
        path downgrades it to ordinary token-replay recovery."""
        return _Survivor(request=rec.request, seed=rec.seed,
                         num_prompt=rec.num_prompt,
                         generated=list(rec.generated),
                         num_emitted=rec.num_emitted,
                         logprobs=list(rec.logprobs),
                         first_token_time=rec.first_token_time)

    def _queue_head_prio(self):
        """Effective priority of the admission-queue head (None when
        empty) — delegated to the FairQueue, which knows its own lanes
        (urgent heap first, then the best non-empty tier)."""
        return self._queue.head_prio()

    def _shed_due(self, req: Request) -> bool:
        """Should this just-popped request be deadline-shed?  True only
        when shedding is on, the request's tier declares a ttft_ms
        target, the wait already exceeds factor x that budget, and the
        request is not exempt (replay / swap-resume / disagg-prefilled)."""
        if not self._shed_deadline_factor or not self._slo:
            return False
        if (isinstance(req.outputs, _ReplayGate)
                or req.request_id in self._resuming
                or req.prefilled is not None):
            return False
        tier = self._slo.get(self._slo.tier_of(req.params.priority))
        if tier is None or not tier.ttft_ms:
            return False
        budget_s = tier.ttft_ms / 1000.0 * self._shed_deadline_factor
        return (time.monotonic() - req.arrival_time) > budget_s

    def saturation(self) -> dict:
        """Admission-queue overload signal (depth, caps, waiting tenants,
        drain rate, 0-1 saturation fraction) — exported via /readiness
        and the x-arks-saturation header on shed responses."""
        return self._queue.saturation()

    def queue_retry_after(self) -> int:
        """Drain-rate-derived backoff (seconds) for shed responses."""
        return self._queue.retry_after()

    def _slo_burn_record(self, priority: int, ttft_s: float) -> None:
        """One first-token sample for the rolling burn tracker (engine
        thread only; tiers without a ttft_ms target record nothing)."""
        if not self._slo:
            return
        name = self._slo.tier_of(priority)
        tier = self._slo.get(name)
        if tier is None or not tier.ttft_ms:
            return
        ev = self._slo_events.setdefault(name, [])
        ev.append((time.monotonic(), ttft_s * 1000.0 > tier.ttft_ms))
        if len(ev) > 1024:
            del ev[:len(ev) - 512]

    def slo_burn(self) -> dict:
        """Per-tier SLO burn rate over ARKS_SLO_BURN_WINDOW_S: the
        fraction of first tokens that missed the tier's ttft_ms target,
        divided by ARKS_SLO_ERROR_BUDGET (1.0 = burning exactly at
        budget).  Exported via /readiness; the signals-mode autoscaler
        scales up when any tier crosses ARKS_ELASTIC_BURN_HI.  Any
        thread — appends happen engine-side, the slice copies."""
        now = time.monotonic()
        cutoff = now - self._slo_burn_window_s
        out: dict[str, float] = {}
        for name, ev in list(self._slo_events.items()):
            recent = [v for (t, v) in ev[-1024:] if t >= cutoff]
            if recent:
                frac = sum(recent) / len(recent)
                out[name] = round(frac / self._slo_error_budget, 4)
        return out

    def _queue_age_tick(self) -> None:
        """Priority-queue aging (ARKS_QUEUE_AGING_S): re-derive queued
        entries' effective tier as ``base - elapsed/aging_s`` (floored
        at 0) so a starved batch request climbs one tier per window and
        eventually admits under sustained latency-tier load.  The aging
        itself is per-(tier, tenant) inside the FairQueue (promotions
        keep each tenant's FIFO order); replay re-queues (priority -
        2**20) ride the urgent lane and never age.  Throttled to a
        fraction of the window so the rebucketing cost stays off the
        per-step path."""
        if not self._queue_aging_s:
            return
        now = time.monotonic()
        if now - self._queue_age_last < min(1.0, self._queue_aging_s / 4):
            return
        self._queue_age_last = now
        self._queue.age_tick(now, self._queue_aging_s)

    def _preempt_inflight(self) -> int:
        """Victims preempted and not yet back in a slot, across both
        modes — the ARKS_PREEMPT_MAX_INFLIGHT budget's denominator."""
        if self._resuming:
            # Replay-mode victims leave _resuming at re-registration;
            # ones that died queued (abort/quarantine) must not pin the
            # budget forever.
            live = self._live_rids()
            with self._abort_lock:
                live |= self._queued_rids
            self._resuming &= live
        return (len(self._swap_pending) + len(self._swapped)
                + sum(1 for r in self._awaiting_restore
                      if isinstance(r, _ResumeState))
                + len(self._resuming))

    def _preempt_victims(self) -> list[int]:
        """Victim slots, best-first: strictly lower tier than the queue
        head (aged), lowest tier first, least progress within a tier
        (cheapest swap, most re-usable work preserved), most recent
        arrival on ties.  Never a replaying/resumed slot (their streams
        are mid-verification), never one inside the anti-thrash cooldown
        window."""
        head = self._queue_head_prio()
        if head is None:
            return []
        now = time.monotonic()
        cands = []
        for slot, st in self._slots.items():
            prio = st.request.params.priority
            if prio <= head:
                continue
            rid = st.request.request_id
            if rid in self._replaying or rid in self._resuming:
                continue
            if self._residency is not None and slot in self._residency.slots:
                # An engaged slot's KV is split across host store +
                # staging + tail — the swap harvest has no single page
                # list to gather.  Windowed slots finish in place.
                continue
            if now - self._preempt_last.get(rid, -1e9) < self._preempt_cooldown_s:
                continue
            cands.append((-prio, len(st.generated),
                          -st.request.arrival_time, slot))
        cands.sort()
        return [c[-1] for c in cands]

    def _preempt_wanted(self) -> bool:
        """Cheap host-only check, safe on the pipelined fast path: a
        queued request outranks a running victim, no free slot, budget
        available.  The queue-empty test short-circuits the common case
        to one attribute read."""
        if self._queue.empty() or self._free or not self._slots:
            return False
        if not self._preempt_capable() or self._state != "serving":
            return False
        if self._preempt_inflight() >= self._preempt_max:
            return False
        return bool(self._preempt_victims())

    def _maybe_preempt(self) -> bool:
        """Seize slots for outranking queued requests (one victim per
        queued seizer, capped by the in-flight budget).  Runs between
        resolves and the issue block, so every freed slot admits in the
        SAME scheduler step."""
        if not self._preempt_wanted():
            return False
        budget = self._preempt_max - self._preempt_inflight()
        n = min(budget, self._queue.qsize())
        did = False
        for slot in self._preempt_victims()[:n]:
            if self._preempt_swap_capable() and self._slot_pages.get(slot):
                self._issue_preempt_swap(slot)
            else:
                self._preempt_replay(slot)
            did = True
        if did:
            self._update_parked()
        return did

    def _issue_preempt_swap(self, slot: int) -> None:
        """Swap-mode preemption, issue side: gather the victim's valid KV
        pages and its sampler row into device staging blocks, start the
        D2H drain (copy_to_host_async — never a host wait), then free the
        slot immediately (stream order keeps the gathers pre-reuse).
        _resolve_preempt_swaps harvests the bytes into the SwapStore a
        lagged step later."""
        st = self._slots[slot]
        rid = st.request.request_id
        p = st.request.params
        page = self._page_size()
        length = int(self._lengths[slot])
        pages_all = self._slot_pages.get(slot, [])
        n_pages = min(-(-length // page), len(pages_all))
        rec = _SwapRecord(
            request=st.request, num_prompt=st.num_prompt,
            generated=list(st.generated), num_emitted=st.num_emitted,
            logprobs=list(st.logprobs),
            first_token_time=st.first_token_time, seed=st.seed,
            length=length, last_token=int(self._last_token[slot]),
            stop_col=st.stop_col, dead_len=st.dead_len, n_pages=n_pages,
            priority=p.priority, t0=time.monotonic())
        try:
            self._faults.fire("preempt")
            staged = []
            G = self._spill_group
            victim_pages = pages_all[:n_pages]
            for i in range(0, n_pages, G):
                grp = victim_pages[i: i + G]
                pg = grp + [grp[0]] * (G - len(grp))
                out = self._spill_gather_fn(self._cache,
                                            jnp.asarray(pg, jnp.int32))
                for arr in out:
                    if arr is None:
                        continue
                    try:
                        arr.copy_to_host_async()
                    except Exception as e:
                        faults_mod.swallowed("copy_to_host_async", e)
                staged.append((len(grp), out))
            row = self._sampler_row_fn(self._sampling,
                                       jnp.asarray(slot, jnp.int32))
            for arr in row:
                try:
                    arr.copy_to_host_async()
                except Exception as e:
                    faults_mod.swallowed("copy_to_host_async", e)
        except Exception as e:
            # Victim still registered: recovery snapshots it from _slots
            # and token-replay preserves its stream.
            if isinstance(e, StepFault):
                raise
            raise StepFault("preempt", faults_mod.classify(e),
                            culprits=[rid]) from e
        # Gathers are on the stream — the slot can be reused now.  The
        # guide pin is deliberately KEPT: the snapshotted DFA row must
        # stay valid until resume.
        self._slots.pop(slot)
        self._release_slot_pages(slot)
        self._free.append(slot)
        if (p.presence_penalty or p.frequency_penalty or p.logit_bias
                or p.min_tokens or p.guide is not None):
            self._emit("clear_penalties", slot=slot)
            self._sampling = self._clear_pen_fn(self._sampling,
                                                jnp.asarray(slot, jnp.int32))
        self._swap_pending.append(_SwapState(rec=rec, staged=staged, row=row))
        self._preempt_last[rid] = time.monotonic()
        self.trace.evt(rid, "park.preempt", "B", n_pages)
        self.metrics.requests_preempted_total.inc(
            1, tier=self._slo.tier_of(p.priority))
        self.metrics.num_requests_running.set(len(self._slots))
        self.metrics.num_requests_waiting.inc(1)
        log.info("preempted %s (tier=%s, %d pages) for a higher tier",
                 rid, self._slo.tier_of(p.priority), n_pages)

    def _preempt_replay(self, slot: int) -> None:
        """Replay-mode preemption (the fallback matrix rows): free the
        victim's slot and re-queue it behind a _ReplayGate for
        deterministic re-execution — no host KV needed; the cost is
        re-prefilling and re-decoding the generated prefix on resume."""
        st = self._slots[slot]
        rid = st.request.request_id
        p = st.request.params
        try:
            self._faults.fire("preempt")
        except Exception as e:
            # Victim untouched: recovery snapshots it from _slots.
            raise StepFault("preempt", faults_mod.classify(e),
                            culprits=[rid]) from e
        rec = _SwapRecord(
            request=st.request, num_prompt=st.num_prompt,
            generated=list(st.generated), num_emitted=st.num_emitted,
            logprobs=list(st.logprobs),
            first_token_time=st.first_token_time, seed=st.seed,
            length=int(self._lengths[slot]) if self._paged else 0,
            last_token=int(self._last_token[slot]),
            stop_col=st.stop_col, dead_len=st.dead_len, n_pages=0,
            priority=p.priority, t0=time.monotonic())
        self._slots.pop(slot)
        self._release_slot_pages(slot)
        self._free.append(slot)
        self._unpin_guide(st.request)
        if (p.presence_penalty or p.frequency_penalty or p.logit_bias
                or p.min_tokens or p.guide is not None):
            self._emit("clear_penalties", slot=slot)
            self._sampling = self._clear_pen_fn(self._sampling,
                                                jnp.asarray(slot, jnp.int32))
        self._preempt_last[rid] = time.monotonic()
        self.trace.evt(rid, "park.preempt", "B", "replay")
        self.metrics.requests_preempted_total.inc(
            1, tier=self._slo.tier_of(p.priority))
        self.metrics.num_requests_running.set(len(self._slots))
        self._preempt_requeue_replay(rec)
        log.info("preempted %s (tier=%s) in replay mode",
                 rid, self._slo.tier_of(p.priority))

    def _preempt_requeue_replay(self, rec: _SwapRecord) -> None:
        """Re-queue a preempted victim for deterministic re-execution at
        its OWN priority (unlike fault replayers it is not urgent — it
        was just outranked).  The gate suppresses the already-delivered
        prefix and verifies byte-identity of the re-run."""
        req = rec.request
        rid = req.request_id
        gate = req.outputs if isinstance(req.outputs, _ReplayGate) else None
        if gate is None:
            req.outputs = _ReplayGate(req.outputs, self, rid,
                                      rec.generated, rec.num_emitted)
        else:
            gate.restart(rec.generated)
        self._resuming.add(rid)
        with self._abort_lock:
            self._queued_rids.add(rid)
            self._queue_seq += 1
            seq = self._queue_seq
        self.metrics.num_requests_waiting.inc(1)
        self._queue.put((req.params.priority, seq, req))

    def _resolve_preempt_swaps(self, force: bool = False) -> bool:
        """Harvest completed preempt spills into the SwapStore (FIFO,
        non-blocking unless forced).  Unlike prefix spills these are NOT
        best-effort — the victim's only KV copy is in these staging
        blocks — so a harvest failure faults the victim alone and
        token-replay rebuilds its stream; a SwapStore refusal (budget
        full) downgrades to replay mode without a fault."""
        did = False
        while self._swap_pending:
            sw = self._swap_pending[0]
            marker = sw.staged[-1][1][0] if sw.staged else sw.row[1]
            if not force and not (self._dev_ready(marker)
                                  and self._dev_ready(sw.row[1])):
                break
            self._swap_pending.pop(0)
            did = True
            rec = sw.rec
            rid = rec.request.request_id
            with self._abort_lock:
                was_aborted = rid in self._aborted
                if was_aborted:
                    self._aborted.discard(rid)
            if was_aborted:
                self._finish_swapped_abort(rec)
                self._update_parked()
                continue
            try:
                self._faults.fire("preempt")
                blocks = []
                for n, out in sw.staged:
                    k, v, ks, vs = [None if a is None else np.asarray(a)
                                    for a in out]
                    for j in range(n):
                        blk = {"k": np.ascontiguousarray(k[:, j]),
                               "v": np.ascontiguousarray(v[:, j])}
                        if ks is not None:
                            blk["k_scale"] = np.ascontiguousarray(ks[:, j])
                            blk["v_scale"] = np.ascontiguousarray(vs[:, j])
                        blocks.append(blk)
                entry = {"blocks": blocks,
                         "key": np.asarray(sw.row[0]),
                         "counts": np.asarray(sw.row[1]),
                         "guide_row": int(np.asarray(sw.row[2]))}
            except Exception as e:
                self.metrics.num_requests_waiting.inc(-1)
                if isinstance(e, StepFault):
                    raise
                raise StepFault("preempt", faults_mod.classify(e),
                                culprits=[rid],
                                survivors=[self._swap_survivor(rec)]) from e
            if self._swap is not None and self._swap.put(rid, entry):
                self._swapped[rid] = rec
                self.metrics.preempt_swap_seconds.observe(
                    time.monotonic() - rec.t0)
                self.metrics.prefix_cache_usage_bytes.set(
                    self._swap.bytes_used, tier="swap")
            else:
                # Host budget cannot hold the snapshot — fall back to
                # replay-mode resume (drop the bytes, re-execute later).
                log.warning("swap store refused %s (%d blocks); falling "
                            "back to replay-mode preemption", rid,
                            len(entry["blocks"]))
                self.metrics.num_requests_waiting.inc(-1)
                self._unpin_guide(rec.request)
                self._preempt_requeue_replay(rec)
            self._update_parked()
        return did

    def _service_swapped(self) -> bool:
        """Swapped-out victims: serve aborts (host bytes come straight
        back) and schedule resumes — best victim first (highest tier,
        earliest preempt), but only while the queue head does not
        STRICTLY outrank it (admission wins ties are not allowed to
        starve a victim of the same tier that already burned a prefill)."""
        did = False
        if not self._swapped:
            return False
        with self._abort_lock:
            hit = [rid for rid in self._swapped if rid in self._aborted]
            for rid in hit:
                self._aborted.discard(rid)
        for rid in hit:
            rec = self._swapped.pop(rid)
            if self._swap is not None:
                self._swap.discard(rid)
                self.metrics.prefix_cache_usage_bytes.set(
                    self._swap.bytes_used, tier="swap")
            self._finish_swapped_abort(rec)
            did = True
        while self._swapped and self._free:
            rid, rec = min(self._swapped.items(),
                           key=lambda kv: (kv[1].priority, kv[1].t0))
            head = self._queue_head_prio()
            if head is not None and head < rec.priority:
                break
            if (self._alloc.free_pages + self._alloc.retained_pages
                    < rec.n_pages):
                break  # pool pressure: wait for pages, don't fault
            self._resume_swapped(rid)
            did = True
        if did:
            self._update_parked()
        return did

    def _resume_swapped(self, rid: str) -> None:
        """Swap-mode resume, issue side: take a free slot, scatter the
        victim's page blocks back (async, padded restore groups — the
        same program as prefix restores) and rebuild its sampler row
        (snapshot key + DFA row through set_slot, counts through the
        donated restore jit).  The request parks as a _ResumeState in
        awaiting_restore; _finish_resume re-registers the slot once the
        marker lands."""
        rec = self._swapped[rid]
        entry = self._swap.pop(rid) if self._swap is not None else None
        self.metrics.prefix_cache_usage_bytes.set(
            self._swap.bytes_used if self._swap is not None else 0,
            tier="swap")
        if entry is None:
            # Entry vanished (blanket-abort clear raced a re-queue):
            # replay mode still resumes the stream correctly.
            del self._swapped[rid]
            self.metrics.num_requests_waiting.inc(-1)
            self._unpin_guide(rec.request)
            self._preempt_requeue_replay(rec)
            return
        slot = self._free.pop()
        try:
            self._faults.fire("preempt")
            pages = self._alloc.alloc(rec.n_pages)
            # The alloc may have evicted tier-0 pages; their spill
            # gathers must precede our scatter.
            self._spill_flush()
            marker = None
            G = self._restore_group
            blocks = entry["blocks"]
            for i in range(0, len(blocks), G):
                marker = self._dispatch_restore_group(
                    blocks[i: i + G], pages[i: i + G], G)
            gid = -1
            if rec.request.params.guide is not None:
                gid, _ = self._guide_cols(rec.request.params)
            self._apply_set_slot(slot, rec.request.params,
                                 jnp.asarray(entry["key"]),
                                 num_prompt=rec.num_prompt, guide=gid,
                                 guide_row=int(entry["guide_row"]))
            self._sampling = self._restore_counts_fn(
                self._sampling, jnp.asarray(slot, jnp.int32),
                jnp.asarray(entry["counts"]))
        except Exception as e:
            self._free.append(slot)
            del self._swapped[rid]
            self.metrics.num_requests_waiting.inc(-1)
            self._unpin_guide(rec.request)
            if isinstance(e, StepFault):
                raise
            raise StepFault("preempt", faults_mod.classify(e),
                            culprits=[rid],
                            survivors=[self._swap_survivor(rec)]) from e
        del self._swapped[rid]
        self._awaiting_restore.append(_ResumeState(
            rec=rec, slot=slot, pages=pages, marker=marker,
            t0=time.monotonic()))

    def _finish_resume(self, res: _ResumeState) -> None:
        """Swap-mode resume, landing side: the scatter resolved — rebuild
        the victim's _Slot and host mirrors exactly as preempt recorded
        them.  No first-token output, no TTFT: the stream simply
        continues at the next decode dispatch (the restored key/counts/
        DFA row make that continuation byte-identical to the
        never-preempted run)."""
        rec = res.rec
        slot = res.slot
        # One invariant owner for the table row: alloc(0) extra pages,
        # head_pages = everything we restored.
        self._assign_slot_pages(slot, len(res.pages),
                                head_pages=res.pages)
        st = _Slot(request=rec.request, num_prompt=rec.num_prompt,
                   generated=list(rec.generated),
                   num_emitted=rec.num_emitted,
                   first_token_time=rec.first_token_time,
                   draft_synced=False, spec_ok=False,
                   logprobs=list(rec.logprobs), stop_col=rec.stop_col,
                   dead_len=rec.dead_len, seed=rec.seed)
        self._slot_gen[slot] += 1
        self._slots[slot] = st
        self._lengths[slot] = rec.length
        self._last_token[slot] = rec.last_token
        self.metrics.num_requests_waiting.inc(-1)
        self.metrics.num_requests_running.set(len(self._slots))
        self.metrics.preempt_swap_seconds.observe(
            time.monotonic() - res.t0)
        self.trace.evt(rec.request.request_id, "park.preempt", "E")
        log.info("resumed %s after preempt swap (slot %d, %d pages)",
                 rec.request.request_id, slot, len(res.pages))

    def _finish_swapped_abort(self, rec: _SwapRecord) -> None:
        """Terminal abort for a preempted victim (client went away while
        its state was off-device)."""
        self.metrics.num_requests_waiting.inc(-1)
        self._unpin_guide(rec.request)
        rec.request.outputs.put(RequestOutput(
            request_id=rec.request.request_id, token_ids=[],
            finished=True, finish_reason="abort",
            num_prompt_tokens=rec.num_prompt,
            num_generated_tokens=len(rec.generated)))

    def _abort_swapped(self) -> None:
        """Fail every preempted-but-unresumed victim (engine exit /
        blanket abort) and release their host bytes."""
        for sw in self._swap_pending:
            self._finish_swapped_abort(sw.rec)
        self._swap_pending = []
        for rid, rec in list(self._swapped.items()):
            if self._swap is not None:
                self._swap.discard(rid)
            self._finish_swapped_abort(rec)
        self._swapped.clear()
        if self._swap is not None:
            self._swap.clear()
            self.metrics.prefix_cache_usage_bytes.set(0, tier="swap")

    # ------------------------------------------------------------------
    # Multi-model serving (engine.model_pool)
    # ------------------------------------------------------------------

    def served_models(self) -> list[str]:
        """Model names this engine can serve: the primary plus every pool
        registration (the openai server routes the request's ``model``
        field against this)."""
        names = [self._primary_model]
        if self.pool is not None:
            names += [n for n in self.pool.names() if n not in names]
        return names

    def register_model(self, model, model_path: str | None = None,
                       pinned: bool = False) -> None:
        """Register a secondary model with the shared pool.  ``model`` is
        a config name (models.get_config) or a ModelConfig.  The default
        loader streams real weights from ``model_path`` when present
        (weights.load_params_streaming — async per-leaf H2D puts, safe
        under a live engine) and otherwise falls back to the SAME
        deterministic random init a single-model engine of this config
        would boot with (PRNGKey(ecfg.seed), same quantize/shard steps) —
        which is what makes pooled token streams byte-identical to
        single-model baselines.  Secondary models share the engine's
        tokenizer; register models with a foreign tokenizer on their own
        engine instead."""
        if self.pool is None:
            raise RuntimeError("engine has no model pool")
        if self.dispatcher is not None:
            raise RuntimeError("multi-model serving is single-host only")
        if self._pp > 1:
            raise RuntimeError(
                "multi-model serving is unsupported under pipeline_parallel")
        from arks_tpu.models import get_config
        cfg2 = get_config(model) if isinstance(model, str) else model
        ecfg = self._primary_ecfg

        def loader(cfg2=cfg2, model_path=model_path):
            from arks_tpu.models import weights as wmod
            dtype = jnp.dtype(ecfg.dtype or cfg2.dtype)
            if wmod.weights_kind(model_path) is not None:
                return wmod.load_params_streaming(
                    cfg2, model_path, mesh=self.mesh, dtype=dtype,
                    weight_dtype=ecfg.weight_dtype)
            from arks_tpu.models.quant import weight_bits
            wbits = weight_bits(ecfg.weight_dtype)
            if wbits:
                from arks_tpu.models import quant
                shards = (self.mesh.shape.get(tf.AXIS_MODEL, 1)
                          if self.mesh is not None else 1)
                params = quant.init_params_quantized(
                    cfg2, jax.random.PRNGKey(ecfg.seed), dtype,
                    bits=wbits, shards=shards)
            else:
                params = tf.init_params(
                    cfg2, jax.random.PRNGKey(ecfg.seed), dtype)
            if self.mesh is not None:
                params = tf.shard_params(params, cfg2, self.mesh)
            return params

        self.pool.register(cfg2.name, cfg2, model_path=model_path,
                           loader=loader, pinned=pinned)

    def _update_parked(self) -> None:
        """Refresh the requests_parked{reason} gauges from the park lists
        themselves — one authoritative setter instead of inc/dec pairs
        scattered across every park/unpark/abort path."""
        m = self.metrics.requests_parked
        m.set(len(self._awaiting_guide), reason="guide")
        m.set(len([r for r in self._awaiting_restore
                   if not isinstance(r, _ResumeState)]), reason="restore")
        m.set(len(self._awaiting_model), reason="model")
        # Preempted victims: spill in flight, parked in host RAM, or
        # restoring back into a slot.  Set-from-len keeps the gauge
        # non-negative across any abort interleaving (the regression in
        # tests/test_preempt.py).
        m.set(len(self._swap_pending) + len(self._swapped)
              + len([r for r in self._awaiting_restore
                     if isinstance(r, _ResumeState)]), reason="preempt")

    def _park_awaiting_model(self, req: Request, want: str) -> None:
        """Park a request until its model is active (mirrors the guide /
        restore parks: waiting gauge held up, abortable, failed on engine
        exit).  Requests for unknown models — or on engines that cannot
        switch (no pool, multi-host gang) — fail immediately instead."""
        if (self.pool is None or self.dispatcher is not None
                or not (want == self._primary_model or self.pool.has(want))):
            error = ("model_not_found" if self.pool is not None
                     and self.dispatcher is None else "multi_model_unsupported")
            req.outputs.put(RequestOutput(
                request_id=req.request_id, token_ids=[], finished=True,
                finish_reason="error", error=error,
                num_prompt_tokens=len(req.prompt_ids)))
            log.info("rejected %s: %s (model=%r)", req.request_id, error, want)
            return
        self._awaiting_model.append((req, want, time.monotonic()))
        self.metrics.num_requests_waiting.inc(1)
        self.trace.evt(req.request_id, "park.model", "B", want)
        self._switch_t0.setdefault(want, time.monotonic())
        self._update_parked()

    def _abort_awaiting_model(self) -> None:
        """Fail every model-parked request (engine exit / blanket abort):
        no scheduler remains to switch models for them."""
        for req, _want, _t in self._awaiting_model:
            self.metrics.num_requests_waiting.inc(-1)
            req.outputs.put(RequestOutput(
                request_id=req.request_id, token_ids=[], finished=True,
                finish_reason="abort", num_prompt_tokens=len(req.prompt_ids)))
        self._awaiting_model = []
        self._update_parked()

    def _fail_parked_for(self, want: str, error: str) -> None:
        """Fail the parked requests waiting on ``want`` (load failure or
        pool exhaustion); other models' parked requests stay."""
        keep = []
        for req, w, t in self._awaiting_model:
            if w != want:
                keep.append((req, w, t))
                continue
            self.metrics.num_requests_waiting.inc(-1)
            self._fault_counts.pop(req.request_id, None)
            req.outputs.put(RequestOutput(
                request_id=req.request_id, token_ids=[], finished=True,
                finish_reason="error", error=error,
                num_prompt_tokens=len(req.prompt_ids)))
            self.metrics.request_success_total.inc(reason="error")
            log.info("rejected %s: %s", req.request_id, error)
        self._awaiting_model = keep
        self._switch_t0.pop(want, None)
        self._model_loads.pop(want, None)
        if self._switch_target == want:
            self._switch_target = None
        self._update_parked()

    def _switch_due_policy(self, target: str) -> bool:
        """May a switch to ``target`` be COMMITTED now?  drain: as soon as
        the target is ready (in-flight work still runs to completion —
        slots are never preempted).  timeslice: once the active model has
        had its quantum, or has no runnable work left."""
        if self._switch_policy == "drain":
            return True
        return (time.monotonic() - self._slice_t0 >= self._switch_quantum
                or (not self._slots and not self._prefilling
                    and not self._pending_admits and self._queue.empty()))

    def _drained_for_switch(self) -> bool:
        """A switch swaps the per-model context wholesale, which is only
        legal when every mutable scheduling member is at its empty state:
        no slots, prefills, deferred admits, pipelined dispatches,
        in-flight spills/restores, or queued admissions (a committed
        target parks the queue through _preadmit first).  Guide-parked
        requests are re-parked by _switch_to itself."""
        return (not self._slots and not self._prefilling
                and not self._pending_admits and not self._pipe_inflight
                and not self._awaiting_restore and not self._spills
                and self._queue.empty()
                # The pipe-warmup thread writes per-model attrs through
                # ``self``; switching mid-compile would graft this model's
                # executables into the next model's context.
                and self._pipe_warm_state != "compiling")

    def _issue_model_load(self) -> bool:
        """Service the awaiting_model park: consume aborts, kick/poll the
        head-of-line model's background load (pool.ensure — NON-blocking;
        the weights stream on the pool's loader thread as async H2D
        puts), commit a switch target per policy, drain the admission
        queue into parks once committed, and execute the switch at the
        drained boundary.  Never blocks the engine thread."""
        worked = False
        with self._abort_lock:
            dead = {req.request_id for req, _, _ in self._awaiting_model
                    if req.request_id in self._aborted}
            self._aborted -= dead
        if dead:
            keep = []
            for req, want, t in self._awaiting_model:
                if req.request_id not in dead:
                    keep.append((req, want, t))
                    continue
                self.metrics.num_requests_waiting.inc(-1)
                req.outputs.put(RequestOutput(
                    request_id=req.request_id, token_ids=[], finished=True,
                    finish_reason="abort",
                    num_prompt_tokens=len(req.prompt_ids)))
            self._awaiting_model = keep
            self._update_parked()
            worked = True
        # Cold-start prefetch hints from add_request: kick the load while
        # the demanding request is still QUEUED behind busy slots.  Errors
        # are deliberately dropped here — they surface with full reporting
        # when the request parks and the head-of-line path re-ensures.
        while self._model_prefetch:
            name = self._model_prefetch.pop()
            if name == self.cfg.name or not self.pool.has(name):
                continue
            try:
                got = self.pool.ensure(name)
            except (KeyError, PoolFullError):
                continue
            if isinstance(got, LoadTicket) and name not in self._model_loads:
                self._model_loads[name] = got
                self._switch_t0.setdefault(name, got.t0)
                self._switch_stats = {"dispatches": 0, "max_depth": 0}
                worked = True
        if not self._awaiting_model:
            self._switch_target = None
            for name, t in list(self._model_loads.items()):
                if t.event.is_set():
                    self._model_loads.pop(name, None)
                    self._switch_t0.pop(name, None)
            return worked
        target = self._switch_target or self._awaiting_model[0][1]
        if target == self.cfg.name:
            # The target became active (or a stale commit cleared) while
            # these requests were parked: release them back to the queue.
            self._switch_target = None
            self._unpark_for(target)
            return True
        try:
            got = self.pool.ensure(target)
        except KeyError as e:
            self._fail_parked_for(target, f"model_not_found: {e}")
            return True
        except PoolFullError as e:
            self._fail_parked_for(target, f"model_pool_exhausted: {e}")
            return True
        resident = not isinstance(got, LoadTicket)
        if not resident:
            if target not in self._model_loads:
                # Fresh load kicked: reset the overlap accounting the
                # bench asserts on (full depth during the load window).
                self._model_loads[target] = got
                self._switch_t0.setdefault(target, got.t0)
                self._switch_stats = {"dispatches": 0, "max_depth": 0}
                worked = True
            if got.event.is_set():
                self._model_loads.pop(target, None)
                if got.error:
                    code = ("model_pool_exhausted"
                            if "model_pool_exhausted" in got.error
                            else "model_load_failed")
                    self._fail_parked_for(target, f"{code}: {got.error}")
                    return True
                resident = True
        else:
            self._model_loads.pop(target, None)
        if not resident:
            return worked
        if (self._switch_target is None and not self._resize_active
                and self._switch_due_policy(target)):
            self._switch_target = target
            worked = True
        if self._switch_target != target:
            return worked
        # Drain the admission queue through _preadmit: with a committed
        # target every popped request parks (for its own model), so the
        # queue empties instead of deadlocking the drained check below.
        while True:
            try:
                _, _, req = self._queue.get_nowait()
            except queue.Empty:
                break
            pre = self._preadmit(req)
            if pre is not None:
                self._resolve_admit_batch(self._issue_admit_batch(
                    [pre], pre[0].params.logprobs is not None))
            worked = True
        if self._drained_for_switch() and not self._resize_active:
            self._switch_to(target)
            worked = True
        return worked

    def _unpark_for(self, name: str) -> None:
        """Re-queue every parked request waiting on ``name`` (the waiting
        gauge stays up — it was raised at park and _preadmit lowers it,
        matching the guide-unpark discipline)."""
        keep = []
        for req, want, t in self._awaiting_model:
            if want != name:
                keep.append((req, want, t))
                continue
            with self._abort_lock:
                self._queued_rids.add(req.request_id)
                self._queue_seq += 1
                seq = self._queue_seq
            self._queue.put((req.params.priority, seq, req))
            self.trace.evt(req.request_id, "park.model", "E")
        self._awaiting_model = keep
        self._switch_t0.pop(name, None)
        self._update_parked()

    def _switch_fault(self, name: str, e: Exception) -> StepFault:
        """Build the StepFault for a failed switch — callers raise it so
        the routing is visible at the fault site (test_fault_guard).  The
        requests parked for the target are BOTH the culprits (their retry
        budget burns — over budget they quarantine alone) and the
        survivors (nothing was emitted, so recovery plain-requeues them
        and the switch retries on re-park)."""
        self._switch_target = None
        self._switch_t0.pop(name, None)
        survivors, keep = [], []
        for req, want, t in self._awaiting_model:
            if want != name:
                keep.append((req, want, t))
                continue
            self.metrics.num_requests_waiting.inc(-1)
            survivors.append(_Survivor(
                request=req, seed=self._resolve_seed(req),
                num_prompt=len(req.prompt_ids)))
        self._awaiting_model = keep
        self._update_parked()
        return StepFault("model_switch", faults_mod.classify(e),
                         culprits=[sv.request.request_id for sv in survivors],
                         survivors=survivors)

    def _switch_to(self, name: str) -> None:
        """Activate pool model ``name`` at a fully drained boundary: save
        the active model's context (every _model_attr_names attribute,
        wholesale — caches, mirrors, guide registry, compiled programs),
        then restore ``name``'s saved context or build a fresh one from
        the pool's (already device-resident) weights.  A warm switch
        compiles NOTHING — program shapes are per-context and cached
        executables ride the context swap; that is what keeps the compile
        budget flat when the second model comes online."""
        t0 = time.monotonic()
        old = self.cfg.name
        try:
            self._faults.fire("model_switch")
            entry = self.pool.acquire(name)
        except Exception as e:
            raise self._switch_fault(name, e) from e
        # Guide-parked requests belong to the OLD model's compiler: re-park
        # them on the model itself so they re-admit (and re-ensure their
        # guide) after a switch back, instead of stranding inside a saved
        # context nothing ever services.  Waiting gauge: both parks hold
        # +1, so the move is gauge-neutral.
        for req, _ticket in self._awaiting_guide:
            self._awaiting_model.append((req, old, time.monotonic()))
        self._awaiting_guide = []
        ctx = {a: getattr(self, a) for a in self._model_attr_names}
        try:
            saved = self._model_ctxs.pop(name, None)
            if saved is not None:
                for a, v in saved.items():
                    setattr(self, a, v)
                # The pool may have reloaded the weights since an eviction
                # dropped this context: trust the pool's params.
                self.params = entry.params
            elif name == self._primary_model:
                ecfg2 = self._primary_ecfg
                dname = ecfg2.draft_model
                dcfg = self.pool.entry(dname).cfg if dname else None
                dparams = self.pool.params_of(dname) if dname else None
                self._init_model_state(entry.cfg, ecfg2, params=entry.params,
                                       draft_params=dparams, draft_cfg=dcfg)
            else:
                # Secondary models run without their own draft (the spec
                # draft rides the primary's context).
                ecfg2 = dataclasses.replace(self._primary_ecfg, model=name,
                                            draft_model=None)
                self._init_model_state(entry.cfg, ecfg2, params=entry.params)
        except Exception as e:
            # Restore the old context before faulting so recovery rebuilds
            # a coherent (old-model) device state.
            for a, v in ctx.items():
                setattr(self, a, v)
            self.pool.release(name)
            raise self._switch_fault(name, e) from e
        self._model_ctxs[old] = ctx
        self.pool.release(old)
        self._switch_target = None
        self._slice_t0 = time.monotonic()
        dt = time.monotonic() - self._switch_t0.pop(name, t0)
        self.metrics.model_switch_seconds.observe(dt)
        self.last_switch_stats = {
            "model": name, "from": old, "seconds": dt,
            "overlap_dispatches": self._switch_stats["dispatches"],
            "overlap_max_depth": self._switch_stats["max_depth"],
        }
        self.metrics.engine_config_info.set(1, **self.resolved_config)
        self._emit("model_switch", model=name)
        log.info("model switch %s -> %s in %.3fs (overlap: %d dispatches, "
                 "max pipeline depth %d)", old, name, dt,
                 self._switch_stats["dispatches"],
                 self._switch_stats["max_depth"])
        self._unpark_for(name)

    # ------------------------------------------------------------------
    # Elastic parallelism: live topology resize + scale-from-zero
    # ------------------------------------------------------------------
    # A serving engine changes shape without dropping a byte of any
    # stream.  The resize state machine rides the step loop:
    #
    #   drain    — every decoding slot is preempted to the host with the
    #              PR-11 swap machinery (full KV pages + sampler row) or
    #              re-queued for deterministic replay (the PR-4/PR-7
    #              fallback-matrix rows: guided, spec, residency-engaged);
    #              new admissions and swap resumes are gated while
    #              in-flight spills/restores/admits run dry.
    #   reshard  — a per-leaf device_put plan (weights.reshard_plan)
    #              moves the CURRENT params onto the new mesh; no
    #              checkpoint reload, no weight re-init.
    #   resume   — _init_model_state rebuilds the per-model context at
    #              the new shape while keep_tiers carries the host/disk
    #              prefix tiers, the SwapStore, and the swapped victims
    #              across verbatim (their blocks are full logical host
    #              arrays keyed by a layout epoch that excludes the mesh
    #              shape), the sketch epoch bumps so routers drop the
    #              pre-resize membership exactly once, and a warm-up
    #              request compiles the new shape's programs before the
    #              first real token rides them.
    #
    # Each seam is a "resize" chaos phase fire site: a fault at drain or
    # reshard recovers at the OLD shape (the context swap has not
    # committed), one at resume recovers at the NEW shape — in both
    # cases the preempted streams were already host-side in
    # layout-independent form, so recovery replays them with nobody
    # quarantined (_phase_culprits returns () for "resize").
    #
    # Scale-to-zero disarms a fully idle engine: weights and device KV
    # drop (the pool remembers nbytes, so re-arm makes room before
    # streaming), host/disk prefix tiers stay warm, and the first queue
    # arrival — or a posted resize — re-arms via _step_disarmed.

    def request_resize(self, tensor_parallel: int | None = None,
                       data_parallel: int | None = None) -> "_ResizeRequest":
        """Post a live topology resize (any thread).  Returns the request
        holder; ``holder.wait(timeout)`` blocks until the step loop
        finishes it and ``holder.outcome`` is "ok" / "rejected" /
        "error".  Validation beyond cheap shape checks happens on the
        engine thread (_resize_reject_reason) where the scheduler state
        is coherent."""
        tp = self._mesh_tp() if tensor_parallel is None else tensor_parallel
        dp = self._mesh_dp() if data_parallel is None else data_parallel
        if tp < 1 or dp < 1:
            raise ValueError(f"resize to tp={tp} dp={dp}: shapes must be >= 1")
        if self._resize_req is not None:
            raise RuntimeError("a resize is already in flight")
        req = _ResizeRequest(tensor_parallel=tp, data_parallel=dp)
        self._resize_req = req
        self._rearm_wake.set()   # a disarmed engine's backoff wait ends now
        return req

    def set_rearm_loader(self, fn) -> None:
        """Install the scale-from-zero weight source: ``fn(cfg, mesh) ->
        params`` (typically a closure over weights.load_orbax_streaming,
        so re-arm streams the checkpoint host->device without a full
        host-tree materialization).  Without one, re-arm re-initializes
        from the engine seed — deterministic, which is what the tests
        ride, but not the served checkpoint."""
        self._rearm_loader = fn

    @property
    def armed(self) -> bool:
        """False while scaled to zero (no device state exists)."""
        return self._armed

    def elastic_status(self) -> dict:
        """Operator/readiness snapshot of the elastic state (any
        thread; plain attribute reads)."""
        req = self._resize_req
        return {
            "armed": self._armed,
            "shape": self._mesh_shape_str(),
            "resize_inflight": req is not None,
            "last_resize": self.last_resize_stats,
            "last_rearm": self.last_rearm_stats,
        }

    def _mesh_tp(self) -> int:
        return self.mesh.shape.get(tf.AXIS_MODEL, 1) if self.mesh is not None else 1

    def _mesh_dp(self) -> int:
        return self.mesh.shape.get("data", 1) if self.mesh is not None else 1

    def _mesh_shape_str(self) -> str:
        return f"tp{self._mesh_tp()}xdp{self._mesh_dp()}"

    def _service_elastic(self) -> bool:
        """Step-loop elastic hook: progress a posted resize, else check
        the idle scale-to-zero window.  Engine thread only."""
        if self._resize_req is not None:
            return self._service_resize()
        return self._maybe_scale_to_zero()

    def _resize_reject_reason(self, req: "_ResizeRequest") -> str | None:
        """Why this engine cannot live-resize to the requested shape
        (docs/application-usage.md carries the fallback matrix), or None
        when it can."""
        tp, dp = req.tensor_parallel, req.data_parallel
        if self._pp > 1:
            return "pipeline_parallel engines cannot live-resize"
        if self._cp > 1:
            return "context_parallel engines cannot live-resize"
        if self.mesh is not None and self.mesh.shape.get("slice", 1) > 1:
            return "multi-slice engines cannot live-resize"
        if self.dispatcher is not None:
            return "multi-host gang engines cannot live-resize"
        if self._draft_cfg is not None and dp > 1:
            return "speculative engines require data_parallel == 1"
        ndev = len(jax.devices())
        if tp * dp > ndev:
            return f"tp*dp={tp * dp} exceeds {ndev} visible devices"
        return None

    def _service_resize(self) -> bool:
        """One step of the resize state machine: validate/activate, then
        drain (evict every classic decode slot to the host), then
        execute at the drained boundary.  Never blocks — partial drains
        return and the next step continues."""
        req = self._resize_req
        if not req.active:
            if (req.tensor_parallel == self._mesh_tp()
                    and req.data_parallel == self._mesh_dp()):
                # Already at the requested shape: trivially complete.
                self._finish_resize(req, "ok")
                return True
            err = self._resize_reject_reason(req)
            if err is not None:
                self.metrics.engine_resizes_total.inc(
                    1, mode="resize", outcome="rejected")
                log.warning("resize to tp=%d dp=%d rejected: %s",
                            req.tensor_parallel, req.data_parallel, err)
                req.error = err
                self._finish_resize(req, "rejected")
                return True
            if self._switch_target is not None or self._awaiting_model:
                # A model switch is in flight: let it land first (the
                # resize would otherwise race its drained boundary).
                return False
            req.active = True
            req.drain_t0 = time.monotonic()
            self._resize_active = True
            log.info("resize %s -> tp%dxdp%d: draining %d slots",
                     self._mesh_shape_str(), req.tensor_parallel,
                     req.data_parallel, len(self._slots))
        worked = False
        if self._pipe_inflight or self._pipe_state is not None:
            self._pipe_drain()
            worked = True
        worked = self._resize_evict_slots() or worked
        if not self._drained_for_resize():
            return worked
        self._execute_resize(req)
        return True

    def _resize_evict_slots(self) -> bool:
        """Evict every classic decode slot for the drain: swap-capable
        victims take the full-KV swap path (resume is byte-identical by
        the PR-5 round-trip argument), the fallback-matrix rows (guided
        — their saved DFA row indexes the OLD compiler's registry, which
        the rebuild discards —, spec engines, replaying/resuming
        streams, swap-incapable engines) re-queue for deterministic
        replay.  Residency-engaged slots finish in place: their KV is
        split across host store + staging + tail with no single page
        list to gather."""
        did = False
        for slot in list(self._slots):
            st = self._slots.get(slot)
            if st is None:
                continue
            if self._residency is not None and slot in self._residency.slots:
                continue
            rid = st.request.request_id
            use_swap = (self._preempt_swap_capable()
                        and st.request.params.guide is None
                        and bool(self._slot_pages.get(slot))
                        and rid not in self._replaying
                        and rid not in self._resuming)
            if use_swap:
                self._issue_preempt_swap(slot)
            else:
                self._preempt_replay(slot)
            did = True
        if did:
            self._update_parked()
        return did

    def _drained_for_resize(self) -> bool:
        """The resize boundary: like _drained_for_switch but the
        admission queue MAY be non-empty (queued requests simply admit
        at the new shape) and the host-side swap machinery must also be
        quiet — in-flight D2H swap harvests and restore scatters
        reference the old cache's device buffers."""
        return (not self._slots and not self._prefilling
                and not self._pending_admits and not self._pipe_inflight
                and self._pipe_state is None
                and not self._awaiting_restore and not self._spills
                and not self._swap_pending and not self._awaiting_fetch
                and self._pipe_warm_state != "compiling")

    def _requeue_awaiting_guide(self) -> None:
        """Re-queue guide-parked requests before a context rebuild:
        their CompileTickets belong to the compiler the rebuild
        discards; on re-admission they re-ensure (and re-pin) against
        the fresh one.  Gauge-neutral: the park holds waiting +1 and
        _preadmit lowers it, same as _unpark_for."""
        for req, _ticket in self._awaiting_guide:
            with self._abort_lock:
                self._queued_rids.add(req.request_id)
                self._queue_seq += 1
                seq = self._queue_seq
            self._queue.put((req.params.priority, seq, req))
            self.trace.evt(req.request_id, "park.guide", "E")
        self._awaiting_guide = []
        self._update_parked()

    def _snapshot_tiers(self) -> dict:
        """The keep_tiers dict for an elastic _init_model_state rebuild:
        the host/disk prefix tiers, their worker threads + queues, and
        the swap store with its parked victims — everything whose state
        is mesh-shape-independent host data that must survive the new
        topology verbatim."""
        return {
            "host": self._host,
            "disk": self._disk,
            "disk_write_queue": self._disk_write_queue,
            "disk_writer": self._disk_writer,
            "fetch_queue": self._fetch_queue,
            "disk_stats_lock": self._disk_stats_lock,
            "disk_evict_seen": self._disk_evict_seen,
            "disk_corrupt_seen": self._disk_corrupt_seen,
            "swap": self._swap,
            "swapped": self._swapped,
        }

    def _new_mesh_for(self, tp: int, dp: int):
        """The resize target mesh over an explicit device prefix —
        resolve_plan requires the plan to cover its device list exactly,
        so scaling BELOW the full host passes jax.devices()[:tp*dp].
        tp*dp == 1 -> no mesh (the single-chip path)."""
        if tp * dp == 1:
            return None
        from arks_tpu.parallel.mesh import make_mesh
        return make_mesh(tensor_parallel=tp, data_parallel=dp,
                         devices=jax.devices()[: tp * dp])

    def _execute_resize(self, req: "_ResizeRequest") -> None:
        """The drained-boundary commit: reshard params onto the new
        mesh, rebuild the per-model context at the new shape with the
        prefix/swap tiers carried across, bump the sketch epoch, and
        issue the warm-up request.  Fault seams: drain (before the
        reshard), reshard (after the device_put plan ran), resume (after
        the commit) — the first two roll back to the old shape before
        raising, the last recovers at the new one."""
        t0 = time.monotonic()
        tp, dp = req.tensor_parallel, req.data_parallel
        cfg = self.cfg
        draft_cfg = self._draft_cfg
        old_mesh = self.mesh
        old_shape = self._mesh_shape_str()
        n_swapped = len(self._swapped)
        try:
            self._faults.fire("resize")                      # drain seam
            new_mesh = self._new_mesh_for(tp, dp)
            from arks_tpu.models import weights as weights_mod
            new_params = weights_mod.reshard_params_to_mesh(
                cfg, self.params, new_mesh)
            new_draft = None
            if draft_cfg is not None and self._draft_params is not None:
                new_draft = weights_mod.reshard_params_to_mesh(
                    draft_cfg, self._draft_params, new_mesh)
            self._faults.fire("resize")                      # reshard seam
        except Exception as e:
            self._finish_resize(req, "error", e)
            if isinstance(e, StepFault):
                raise
            raise StepFault("resize", faults_mod.classify(e)) from e
        self._requeue_awaiting_guide()
        keep = self._snapshot_tiers()
        ctx = {a: getattr(self, a) for a in self._model_attr_names}
        ecfg2 = dataclasses.replace(self.ecfg, tensor_parallel=tp,
                                    data_parallel=dp)
        self.mesh = new_mesh
        try:
            self._init_model_state(cfg, ecfg2, params=new_params,
                                   draft_params=new_draft,
                                   draft_cfg=draft_cfg, keep_tiers=keep)
        except Exception as e:
            # Roll back to a coherent old-shape context before faulting
            # so recovery rebuilds the device state we still have.
            for a, v in ctx.items():
                setattr(self, a, v)
            self.mesh = old_mesh
            self._finish_resize(req, "error", e)
            raise StepFault("resize", faults_mod.classify(e)) from e
        # Committed: saved per-model contexts reference the OLD mesh's
        # buffers — drop them (a later switch re-inits from the pool).
        self._model_ctxs.clear()
        if self.pool is not None:
            self.pool.adopt(cfg.name, cfg, self.params, pinned=True)
            if draft_cfg is not None and self._draft_params is not None:
                self.pool.adopt(draft_cfg.name, draft_cfg,
                                self._draft_params, pinned=True)
        self._primary_ecfg = dataclasses.replace(
            self._primary_ecfg, tensor_parallel=tp, data_parallel=dp)
        if self._sketch is not None:
            # Routers drop the pre-resize membership exactly once on
            # their next poll (the tier-0 index restarted empty).
            self._sketch.bump_epoch("resize")
        try:
            self._faults.fire("resize")                      # resume seam
        except Exception as e:
            self._finish_resize(req, "error", e)
            raise StepFault("resize", faults_mod.classify(e)) from e
        dt = time.monotonic() - t0
        drain_s = t0 - (req.drain_t0 or t0)
        self.metrics.resize_seconds.observe(dt + drain_s)
        self.metrics.engine_resizes_total.inc(1, mode="resize", outcome="ok")
        self.metrics.engine_config_info.set(1, **self.resolved_config)
        self.last_resize_stats = {
            "from": old_shape, "to": self._mesh_shape_str(),
            "drain_seconds": drain_s, "reshard_seconds": dt,
            "seconds": drain_s + dt, "swapped": n_swapped,
        }
        self._issue_warmup_request()
        self._finish_resize(req, "ok")
        log.info("resized %s -> %s in %.3fs (drain %.3fs, %d streams "
                 "swapped to host)", old_shape, self._mesh_shape_str(),
                 drain_s + dt, drain_s, n_swapped)

    def _finish_resize(self, req: "_ResizeRequest", outcome: str,
                       error: Exception | None = None) -> None:
        """Close out a resize request (every terminal path): record the
        outcome, clear the admission gate, and wake waiters."""
        req.outcome = outcome
        if error is not None:
            req.error = f"{type(error).__name__}: {error}"
        req.seconds = time.monotonic() - req.t0
        self._resize_req = None
        self._resize_active = False
        req.event.set()

    # ---- scale-to-zero / re-arm --------------------------------------

    def _maybe_scale_to_zero(self) -> bool:
        """Track the idle window (ARKS_ELASTIC_IDLE_ZERO_S) and disarm
        once the engine has been COMPLETELY quiet — no parked work, no
        in-flight spills, no background model loads — for the full
        window."""
        quiet = (self.idle and not self._pipe_inflight
                 and self._pipe_state is None and not self._spills
                 and not self._disk_spill_pending and not self._model_loads
                 and self._pipe_warm_state != "compiling"
                 and self._state == "serving")
        if not quiet:
            self._idle_since = None
            return False
        now = time.monotonic()
        if self._idle_since is None:
            self._idle_since = now
            return False
        if now - self._idle_since < self._idle_zero_s:
            return False
        self._scale_to_zero()
        return True

    def _scale_to_zero(self) -> None:
        """Disarm an idle engine: flush warm device prefixes to the disk
        tier (best-effort), drop weights + device KV + sampler state,
        and release the pool residency.  Host/disk prefix tiers stay
        warm; every per-model attribute stays PRESENT (the context
        contract) — re-arm rebuilds the device side via
        _init_model_state(keep_tiers=...)."""
        if self._disk is not None:
            try:
                self._resolve_zero_flush()
            except Exception as e:
                faults_mod.swallowed("scale_to_zero.flush", e)
        self.params = None
        self._cache = None
        self._sampling = None
        self._draft_params = None
        self._draft_cache = None
        # The device prefix index died with the cache: drop the allocator
        # so cache_sketch stops advertising tier-0 membership this
        # replica can no longer serve (host/disk stay advertised — peers
        # may still pull warm blocks from a scaled-to-zero replica).
        self._alloc = None
        self._tables = None
        self._model_ctxs.clear()
        if self.pool is not None:
            try:
                self.pool.scale_to_zero(self.cfg.name)
                if self._draft_cfg is not None:
                    self.pool.scale_to_zero(self._draft_cfg.name)
            except RuntimeError as e:
                faults_mod.swallowed("scale_to_zero.pool", e)
        if self._sketch is not None:
            self._sketch.bump_epoch("scale_to_zero")
        self._armed = False
        self._zero_t0 = time.monotonic()
        self._idle_since = None
        self.metrics.engine_resizes_total.inc(
            1, mode="scale_to_zero", outcome="ok")
        log.info("idle %.0fs: scaled to zero (weights + device KV dropped; "
                 "host/disk prefix tiers stay warm)", self._idle_zero_s)

    def _resolve_zero_flush(self) -> None:
        """Host-sync tail of scale-to-zero: D2H-read the warm device
        blocks into the disk tier before the cache drops.  Runs at a
        fully drained boundary (idle engine, no in-flight streams) —
        the sanctioned _resolve_* sync-tail contract, same as the
        spill/restore resolves."""
        self._flush_warm_to_disk()

    def _step_disarmed(self, block_s: float) -> bool:
        """The step loop while scaled to zero: wait for demand (a queue
        arrival) or a posted resize, then re-arm.  A failed re-arm backs
        off one second and retries on the next demand signal — the
        engine stays disarmed rather than crash-looping the step
        thread."""
        if self._resize_req is not None:
            req = self._resize_req
            err = self._resize_reject_reason(req)
            if err is not None:
                self.metrics.engine_resizes_total.inc(
                    1, mode="resize", outcome="rejected")
                req.error = err
                self._finish_resize(req, "rejected")
                return True
            ok = self._rearm(shape=(req.tensor_parallel, req.data_parallel),
                             resize_req=req)
            return True if ok else False
        try:
            prio, seq, demand = self._queue.get(timeout=block_s)
        except queue.Empty:
            return False
        if time.monotonic() - self._rearm_fail_t < 1.0:
            # Recent re-arm failure: put the demand back and pace the
            # retry on the wake event instead of hot-spinning — a
            # posted resize (request_resize sets the event) interrupts
            # the backoff immediately.
            self._queue.put((prio, seq, demand))
            self._rearm_wake.wait(min(block_s, 0.1))
            self._rearm_wake.clear()
            return False
        self._rearm()
        # Re-queue the demand that woke us at its own priority — whether
        # or not the re-arm succeeded (on failure it simply waits for
        # the next attempt's window).
        with self._abort_lock:
            self._queued_rids.add(demand.request_id)
            self._queue_seq += 1
            seq2 = self._queue_seq
        self._queue.put((prio, seq2, demand))
        return True

    def _rearm(self, shape: tuple[int, int] | None = None,
               resize_req: "_ResizeRequest | None" = None) -> bool:
        """Scale from zero: stream the weights back (the installed
        re-arm loader, typically Orbax streaming — or a deterministic
        seed re-init without one) and rebuild the device context at the
        current (or requested) shape, with the warm host/disk tiers and
        any swapped victims carried across.  Rolls the context back and
        stays disarmed on failure."""
        t0 = time.monotonic()
        cfg = self.cfg
        draft_cfg = self._draft_cfg
        keep = self._snapshot_tiers()
        ctx = {a: getattr(self, a) for a in self._model_attr_names}
        old_mesh = self.mesh
        ecfg2 = self.ecfg
        try:
            if shape is not None:
                tp, dp = shape
                ecfg2 = dataclasses.replace(self.ecfg, tensor_parallel=tp,
                                            data_parallel=dp)
                self.mesh = self._new_mesh_for(tp, dp)
            params = None
            if self._rearm_loader is not None:
                params = self._rearm_loader(cfg, self.mesh)
            self._init_model_state(cfg, ecfg2, params=params,
                                   draft_cfg=draft_cfg, keep_tiers=keep)
        except Exception as e:
            for a, v in ctx.items():
                setattr(self, a, v)
            self.mesh = old_mesh
            self._rearm_fail_t = time.monotonic()
            self.metrics.engine_resizes_total.inc(
                1, mode="rearm", outcome="error")
            if resize_req is not None:
                self._finish_resize(resize_req, "error", e)
            log.error("scale-from-zero re-arm failed: %s: %s",
                      type(e).__name__, e)
            # Intentional swallow: the engine stays DISARMED and retries
            # on the next demand signal — a re-arm failure must not take
            # down the step thread of a replica that is serving nothing.
            faults_mod.swallowed("elastic.rearm", e)
            return False
        self._armed = True
        self._idle_since = None
        if self.pool is not None:
            self.pool.adopt(cfg.name, cfg, self.params, pinned=True)
            if draft_cfg is not None and self._draft_params is not None:
                self.pool.adopt(draft_cfg.name, draft_cfg,
                                self._draft_params, pinned=True)
        if shape is not None:
            self._primary_ecfg = dataclasses.replace(
                self._primary_ecfg, tensor_parallel=shape[0],
                data_parallel=shape[1])
        if self._sketch is not None:
            self._sketch.bump_epoch("rearm")
        dt = time.monotonic() - t0
        self.metrics.scale_from_zero_seconds.observe(dt)
        self.metrics.engine_resizes_total.inc(1, mode="rearm", outcome="ok")
        self.metrics.engine_config_info.set(1, **self.resolved_config)
        self.last_rearm_stats = {
            "seconds": dt, "shape": self._mesh_shape_str(),
            "idle_seconds": t0 - self._zero_t0,
            "streamed": self._rearm_loader is not None,
        }
        self._issue_warmup_request()
        if resize_req is not None:
            self._finish_resize(resize_req, "ok")
        log.info("re-armed from zero at %s in %.3fs (%s weights)",
                 self._mesh_shape_str(), dt,
                 "streamed" if self._rearm_loader is not None else "re-init")
        return True

    def _issue_warmup_request(self) -> bool:
        """Queue one tiny greedy self-request after a resize/re-arm so
        the new shape's programs compile BEFORE the first real token
        rides them (its output sinks into _WarmupSink — no client).
        Replicates add_request's queue-put bookkeeping only: the full
        add_request path is host-heavy and off the step-reachable
        hot-path budget."""
        if not self._elastic_warmup:
            return False
        self._warmup_seq += 1
        req = Request(
            request_id=f"__warmup__{self._warmup_seq}",
            prompt_ids=[min(3, self.cfg.vocab_size - 1)] * 4,
            params=SamplingParams(max_tokens=2, top_k=1),
            outputs=_WarmupSink())
        self.metrics.num_requests_waiting.inc(1)
        with self._abort_lock:
            self._queued_rids.add(req.request_id)
            self._queue_seq += 1
            seq = self._queue_seq
        self._queue.put((req.params.priority, seq, req))
        return True

    def _admit_prefilled(self, req: Request) -> None:
        """Admit a request whose prefill ran on another engine (disaggregated
        decode side): insert the transferred KV, reconstruct the sampling key
        stream, and continue decoding from the first token."""
        pf = req.prefilled
        if req.params.logprobs is not None and pf.first_lp is None:
            # A logprob request whose transferred state carries no
            # first-token logprob data (pre-upgrade prefill peer): serving
            # a partial stream would be silently wrong — reject cleanly.
            self._unpin_guide(req)
            req.outputs.put(RequestOutput(
                request_id=req.request_id, token_ids=[], finished=True,
                finish_reason="error", error="logprobs_unavailable",
                num_prompt_tokens=pf.num_prompt))
            return
        usable = self.ecfg.max_cache_len - self.ecfg.steps_per_dispatch - 1
        k, v = jnp.asarray(pf.k), jnp.asarray(pf.v)
        if pf.num_prompt > usable:
            self._unpin_guide(req)
            req.outputs.put(RequestOutput(
                request_id=req.request_id, token_ids=[], finished=True,
                finish_reason="abort", num_prompt_tokens=pf.num_prompt))
            return
        if k.shape[2] > self.ecfg.max_cache_len:
            k = k[:, :, : self.ecfg.max_cache_len]
            v = v[:, :, : self.ecfg.max_cache_len]
        p = req.params
        key = jnp.asarray(sampler_mod.np_prng_key(pf.seed))
        try:
            slot = self._free.pop()
            if self._paged:
                page = self._page_size()
                n_alloc = -(-pf.num_prompt // page)
                row = self._assign_slot_pages(slot, n_alloc)
                # Pad T to a page multiple so the page-insert loop reads
                # whole pages (the tail rows are masked by length).
                pad_t = n_alloc * page - k.shape[2]
                if pad_t > 0:
                    width = [(0, 0)] * 5
                    width[2] = (0, pad_t)
                    k = jnp.pad(k, width)
                    v = jnp.pad(v, width)
                self._emit("insert_pages", k=np.asarray(k), v=np.asarray(v),
                           pages=row.copy(), n_pages=n_alloc)
                self._cache = self._insert_pages_fn(
                    self._cache, k, v, jnp.asarray(row),
                    jnp.asarray(n_alloc, jnp.int32))
            else:
                self._emit("insert_kv", slot=slot, k=np.asarray(k),
                           v=np.asarray(v))
                self._cache = self._insert_fn(self._cache, k, v,
                                              jnp.asarray(slot))
            gid, start = self._guide_cols(p)
            # Refresh the device tables like every other admission path: a
            # guide published (or evicted+repacked) after this step's
            # top-of-loop refresh would otherwise decode against stale
            # device rows (all -1 -> everything masked -> instant eos).
            self._ensure_guides_uploaded()
            # pf.guide_row is RELATIVE to the guide's start state; rebase
            # onto THIS engine's table (compile orders may differ).
            grow = start + pf.guide_row if gid >= 0 else 0
            self._emit("set_slot", slot=slot, temperature=p.temperature,
                       top_p=p.top_p, top_k=p.top_k, seed=pf.seed,
                       presence=p.presence_penalty,
                       frequency=p.frequency_penalty,
                       logit_bias=list(p.logit_bias),
                       min_tokens=p.min_tokens,
                       stop_ids=list(p.stop_token_ids),
                       ignore_eos=p.ignore_eos,
                       num_prompt=pf.num_prompt, guide=gid, guide_row=grow)
            self._apply_set_slot(slot, p, jax.random.fold_in(key, 1),
                                 num_prompt=pf.num_prompt, guide=gid,
                                 guide_row=grow)
        except Exception as e:
            # The transferred KV lives on the REQUEST (host arrays): the
            # survivor simply re-queues and re-inserts after the reset.
            raise StepFault(
                "admit", faults_mod.classify(e),
                culprits=[req.request_id],
                survivors=[_Survivor(request=req, seed=pf.seed,
                                     num_prompt=pf.num_prompt)]) from e
        self._register_slot(req, slot, pf.first_token, pf.num_prompt,
                            first_lp=pf.first_lp
                            if req.params.logprobs is not None else None,
                            seed=pf.seed)
        if self._paged and self._chunk and pf.prompt_ids:
            # Disaggregated publish: the transferred prefill's pages are
            # now in the pool — register their digests (tier 0, zero
            # cost) and spill them into the host tier, so a decode-side
            # restart (or later eviction) keeps the prefill peer's warm
            # prefixes without another wire transfer.  The spill path
            # reads the pages the insert dispatch just wrote, so the
            # stored bytes are the pool-canonical form (quantization
            # included) — no host-side conversion to drift.
            ids_full = [int(t) for t in pf.prompt_ids]
            pages_row = list(self._slot_pages.get(slot, []))
            self._register_prompt_pages(ids_full, pages_row)
            if self._host_tier_on():
                from arks_tpu.engine.paged import chain_digests
                page = self._page_size()
                nreg = min(len(ids_full) // page, len(pages_row))
                digs = chain_digests(ids_full, page, nreg)
                for d, pg in zip(digs, pages_row[:nreg]):
                    if not self._host.has(d):
                        self._spill_victims.append((d, pg))
                self._spill_flush()

    @staticmethod
    def _lp_entry(clp, vals, lids, n: int):
        """(chosen_logprob, [(token_id, logprob) x min(n, MAX)]) from the
        device outputs of a top_logprobs call."""
        n = min(n, sampler_mod.TOP_LOGPROBS_MAX)
        vals = np.asarray(vals)
        lids = np.asarray(lids)
        return (float(clp),
                [(int(lids[i]), float(vals[i])) for i in range(n)])

    def _shape_cols(self, p, num_prompt: int):
        """Host-side logit_bias / min_tokens columns for one request:
        (bias_ids [NB], bias_vals [NB], suppress [NS], min_first,
        min_until).  min_until is the ABSOLUTE sequence length below which
        suppression holds in the fused loop (the new token at carry length
        L is generated-token number L - num_prompt + 2); min_first is the
        transient first-token flag (sample's lengths=None reading)."""
        bias_ids, bias_vals = sampler_mod.np_bias_cols(p, self.cfg.vocab_size)
        sup = sampler_mod.np_suppress_col(self.min_tokens_suppress_ids(p))
        min_first = 1 if p.min_tokens >= 1 else 0
        min_until = num_prompt + p.min_tokens - 1 if p.min_tokens > 0 else 0
        return bias_ids, bias_vals, sup, min_first, min_until

    def _gate_guide(self, req: Request) -> str | None:
        """Resolve a guided request's guide at admission: None = published
        and PINNED (proceed), "park" = parked on the in-flight compile
        (caller returns), any other string = compile failure message.
        Never blocks on compilation."""
        from arks_tpu.engine.guides import Guide
        if req.request_id in self._guide_pins:
            return None
        for _ in range(3):
            got = self.guides.ensure(*req.params.guide)
            if isinstance(got, Guide):
                try:
                    self._pin_guide(req)
                    return None
                except GuideError:
                    # Evicted between publish and pin (another worker's
                    # publish ran in the gap): re-kick and retry.
                    continue
            if got.event.is_set() and got.error is not None:
                return got.error
            self._awaiting_guide.append((req, got))
            self.metrics.num_requests_waiting.inc(1)
            self.trace.evt(req.request_id, "park.guide", "B")
            return "park"
        return "guide evicted repeatedly during admission"

    def _service_awaiting_guides(self) -> bool:
        """Advance the parked-on-compile requests: aborted ones fail,
        failed compiles produce per-request error outputs, published
        guides send their requests back to the admission queue (this
        step's _admit pops them).  Returns True when anything moved."""
        did = False
        still: list = []
        for req, ticket in self._awaiting_guide:
            with self._abort_lock:
                was_aborted = req.request_id in self._aborted
                self._aborted.discard(req.request_id)
            if was_aborted:
                self.metrics.num_requests_waiting.inc(-1)
                req.outputs.put(RequestOutput(
                    request_id=req.request_id, token_ids=[], finished=True,
                    finish_reason="abort",
                    num_prompt_tokens=len(req.prompt_ids)))
                did = True
                continue
            if not ticket.event.is_set():
                still.append((req, ticket))
                continue
            self.trace.evt(req.request_id, "park.guide", "E")
            if ticket.error is not None:
                self.metrics.num_requests_waiting.inc(-1)
                req.outputs.put(RequestOutput(
                    request_id=req.request_id, token_ids=[], finished=True,
                    finish_reason="error",
                    error=f"guide_compile_failed: {ticket.error}",
                    num_prompt_tokens=len(req.prompt_ids)))
                log.info("rejected %s: guide compile failed: %s",
                         req.request_id, ticket.error)
                did = True
                continue
            # Published: back to the admission queue (the waiting gauge
            # stays up — _preadmit decrements it again on the re-pop).
            with self._abort_lock:
                self._queued_rids.add(req.request_id)
                self._queue_seq += 1
                seq = self._queue_seq
            self._queue.put((req.params.priority, seq, req))
            did = True
        self._awaiting_guide = still
        return did

    def _abort_awaiting_guide(self) -> None:
        """Fail every request parked on a guide compile (engine exit):
        no scheduler remains to unpark them."""
        for req, _ in self._awaiting_guide:
            self.metrics.num_requests_waiting.inc(-1)
            req.outputs.put(RequestOutput(
                request_id=req.request_id, token_ids=[], finished=True,
                finish_reason="abort",
                num_prompt_tokens=len(req.prompt_ids)))
        self._awaiting_guide = []

    def _pin_guide(self, req: Request) -> None:
        """Refcount the request's guide (idempotent per request): pinned
        guides are never evicted, so the absolute rows its slot carries on
        device stay valid from admission through _finish."""
        if req.params.guide is None or req.request_id in self._guide_pins:
            return
        self.guides.acquire(*req.params.guide)
        self._guide_pins[req.request_id] = req.params.guide

    def _unpin_guide(self, req: Request) -> None:
        """Release the request's guide pin (idempotent, no-op when
        unguided) — called on EVERY request end-of-life path."""
        key = self._guide_pins.pop(req.request_id, None)
        if key is not None:
            self.guides.release(*key)

    def _guide_cols(self, p) -> tuple[int, int]:
        """(guide_id, start_row) for a request's guide spec, (-1, 0) when
        unguided.  Admission paths reach here only after _gate_guide
        pinned the published guide, so this is a registry hit; a miss
        means the pin discipline broke — GuideError routes to the
        admission fault path, failing just this request."""
        if p.guide is None:
            return -1, 0
        g = self.guides.lookup(*p.guide)
        if g is None:
            raise GuideError(
                f"guide {p.guide[0]}:{p.guide[1]!r} is not registered "
                "(evicted without a pin?)")
        return g.guide_id, g.start_row

    def _apply_set_slot(self, slot: int, p, key, num_prompt: int = 0,
                        guide: int = -1, guide_row: int = 0) -> None:
        """Write one slot's sampling params through the donated jit (array
        args keep one compiled program across requests; python floats would
        retrace per distinct value).  ``guide_row`` is the POST-first-token
        DFA row (resolved by the caller — followers receive it by value, so
        they never need the leader's guide registry)."""
        bias_ids, bias_vals, sup, _mf, min_until =             self._shape_cols(p, num_prompt)
        self._sampling = self._set_slot_fn(
            self._sampling, jnp.asarray(slot, jnp.int32),
            jnp.asarray(p.temperature, jnp.float32),
            jnp.asarray(p.top_p, jnp.float32),
            jnp.asarray(p.top_k, jnp.int32), key,
            jnp.asarray(p.presence_penalty, jnp.float32),
            jnp.asarray(p.frequency_penalty, jnp.float32),
            jnp.asarray(bias_ids), jnp.asarray(bias_vals),
            jnp.asarray(sup), jnp.asarray(min_until, jnp.int32),
            jnp.asarray(guide, jnp.int32), jnp.asarray(guide_row, jnp.int32))

    def _register_slot(self, req: Request, slot: int, first: int,
                       num_prompt: int, first_lp=None,
                       seed: int = 0) -> None:
        # Draft-cache prompt prefill (speculative decoding).  Skipped when
        # the prompt tokens aren't available (disagg-transferred KV) or the
        # prompt exceeds the one-shot buckets (a monolithic draft prefill
        # would reintroduce the head-of-line stall chunking exists to
        # prevent): the slot then rides the fused loop — still CORRECT, the
        # verifier is exact; only the draft speedup is forfeited.
        draft_synced = False
        if (self._draft_cfg is not None and req.prompt_ids
                and len(req.prompt_ids) <= self._buckets[-1]):
            ids = list(req.prompt_ids)
            padded = self._pad_to_bucket(ids)
            try:
                self._emit("draft_prefill", tokens=padded, length=len(ids),
                           slot=slot)
                self._draft_cache = self._draft_prefill_fn(
                    self._draft_params, self._draft_cache,
                    jnp.asarray(padded),
                    jnp.asarray([len(ids)], jnp.int32), jnp.asarray(slot))
            except Exception as e:
                # Not registered yet, nothing emitted yet: the survivor
                # re-queues and re-admits with its pinned seed (same
                # contract as the pre-registration dispatches).
                self._free.append(slot)
                raise StepFault(
                    "admit", faults_mod.classify(e),
                    culprits=[req.request_id],
                    survivors=[_Survivor(request=req,
                                         seed=self._resolve_seed(req),
                                         num_prompt=num_prompt)]) from e
            draft_synced = True
        now = time.monotonic()
        p_ = req.params
        # Spec eligibility, frozen for the slot's lifetime (see _Slot):
        # per-lane and params-pure, which keeps the key-advance structure
        # schedule-independent — the property token-replay recovery needs.
        spec_ok = (draft_synced
                   and p_.presence_penalty == 0
                   and p_.frequency_penalty == 0
                   and p_.logprobs is None
                   and not p_.logit_bias
                   and p_.min_tokens == 0)
        st = _Slot(request=req, num_prompt=num_prompt,
                   draft_synced=draft_synced, spec_ok=spec_ok, seed=seed)
        self._fault_counts.pop(req.request_id, None)
        replaying = req.request_id in self._replaying
        if replaying:
            # Token-replay re-execution reached a decoding slot again:
            # the stream is live (the gate streams the continuation once
            # the re-run passes the delivered prefix).
            self._replaying.discard(req.request_id)
            self.metrics.requests_recovered_total.inc(1)
        resumed = req.request_id in self._resuming
        if resumed:
            # Replay-mode preempt resume reached a slot again: same
            # suppression as a fault replay (the gate drops the delivered
            # prefix), but it is not a recovery — don't count it as one.
            self._resuming.discard(req.request_id)
            self.trace.evt(req.request_id, "park.preempt", "E")
        st.generated.append(first)
        if first_lp is not None:
            st.logprobs.append(first_lp)
        st.first_token_time = now
        # Pipelined-decode liveness data (device mirrors of _is_stop and
        # the retire conditions), frozen for the slot's lifetime.
        st.stop_col = sampler_mod.np_stop_col(
            self._stop_ids_for(req.params))
        st.dead_len = min(num_prompt + req.params.max_tokens - 1,
                          self.ecfg.max_cache_len - self._pipe_rows)
        self._slot_gen[slot] += 1
        self._slots[slot] = st
        self._lengths[slot] = num_prompt
        self._last_token[slot] = first

        self.metrics.prompt_tokens_total.inc(num_prompt)
        self.metrics.num_requests_running.set(len(self._slots))
        ttft = now - req.arrival_time
        if not replaying and not resumed:
            # A replay re-registration is not a first token — the client
            # got theirs long ago; observing it would poison the TTFT
            # histogram with fault-to-now spans.
            self.metrics.time_to_first_token_seconds.observe(ttft)
            self.metrics.ttft_seconds.observe(
                ttft, tier=self._slo.tier_of(p_.priority))
            self._slo_burn_record(p_.priority, ttft)
        if self.trace.enabled:
            self.trace.evt(req.request_id, "prefill", "E")
            if not replaying and not resumed:
                self.trace.evt(req.request_id, "first_token", "I", ttft)
                tier = (self._slo.get(self._slo.tier_of(p_.priority))
                        if self._slo else None)
                if (tier is not None and tier.ttft_ms is not None
                        and ttft * 1000.0 > tier.ttft_ms):
                    self.trace.evt(req.request_id, "slo_violation", "I",
                                   (ttft * 1000.0, tier.ttft_ms))

        if self._check_finished(slot):
            return
        st.num_emitted = 1
        req.outputs.put(RequestOutput(
            request_id=req.request_id, token_ids=[first],
            num_prompt_tokens=num_prompt, ttft_s=ttft,
            logprobs=list(st.logprobs) if st.logprobs else None))

    # ------------------------------------------------------------------
    # Detached prefill (disaggregated prefill side)
    # ------------------------------------------------------------------

    @property
    def max_prompt_len(self) -> int:
        """Largest admissible prompt (one-dispatch decode reserve kept).
        Servers use this for the pre-queue 400 check."""
        usable = self.ecfg.max_cache_len - self.ecfg.steps_per_dispatch - 1
        if self._chunk:
            return usable
        return min(self._buckets[-1], usable)

    def _one_shot_limit(self) -> int:
        return min(self._buckets[-1],
                   self.ecfg.max_cache_len - self.ecfg.steps_per_dispatch - 1)

    def _insert_pad_len(self, plen: int) -> int:
        """Bucketed insert length for a cached prefix: the next prefill
        bucket, or beyond the largest bucket the next multiple of it —
        bounding distinct compiled insert shapes to
        O(len(buckets) + max_cache_len / last_bucket)."""
        for b in self._buckets:
            if plen <= b:
                return b
        last = self._buckets[-1]
        return min(-(-plen // last) * last, self.ecfg.max_cache_len)

    def _pad_to_bucket(self, ids: list[int]) -> np.ndarray:
        """[1, bucket] zero-padded prompt at the smallest covering bucket —
        the ONE padding implementation (one-shot prefill, draft prefill);
        shape agreement between them rides on this."""
        bucket = next(b for b in self._buckets if b >= len(ids))
        padded = np.zeros((1, bucket), np.int32)
        padded[0, : len(ids)] = ids
        return padded

    def _prepare_prompt(self, prompt_ids: list[int]) -> tuple[list[int], np.ndarray | None]:
        """Pad the prompt to the smallest prefill bucket.  Shared by the
        unified and disaggregated paths — the bit-identity guarantee between
        them depends on this being one implementation.

        Returns (ids, padded) for the one-shot path, (ids, None) when the
        prompt needs chunked prefill, and raises ContextLengthExceededError
        when it cannot be served at all — silent truncation would corrupt
        long-context results and billing."""
        ids = list(prompt_ids)
        if len(ids) > self.max_prompt_len:
            raise ContextLengthExceededError(
                f"prompt has {len(ids)} tokens but the maximum context "
                f"length is {self.max_prompt_len}")
        if self._residency_window:
            # Windowed residency engages on DECODE growth only: the
            # prompt itself must fit the resident budget (prefill chunks
            # attend through gather_pages, which needs every causal page
            # on device).  A window that cannot hold the prompt would
            # fail deep inside the allocator instead.
            limit = self._residency_window * self._page_size()
            if len(ids) > limit:
                raise ContextLengthExceededError(
                    f"prompt has {len(ids)} tokens but "
                    f"ARKS_RESIDENCY_WINDOW_PAGES={self._residency_window} "
                    f"bounds resident prompts to {limit} tokens (windowed "
                    "residency streams DECODE-grown context; prompts must "
                    "fit the window)")
        if len(ids) > self._one_shot_limit():
            return ids, None  # chunked path
        return ids, self._pad_to_bucket(ids)

    # ------------------------------------------------------------------
    # Chunked prefill
    # ------------------------------------------------------------------

    def _start_chunked(self, req: Request, ids: list[int],
                       prefix_len: int = 0, prefix_pages=None,
                       digests=None) -> None:
        p = req.params
        seed = self._resolve_seed(req)
        slot = self._free.pop()
        if self._paged:
            # Pages must cover positions [0, len+K-1]: while this slot
            # chunk-prefills, every interleaved decode dispatch's K-step
            # scan writes garbage rows at len..len+K-1 (device lengths
            # advance per step for ALL batch rows) — they must land in
            # owned pages, never a stale/zero table entry that another
            # sequence's page sits behind.  Shared prefix pages (already
            # incref'd by match) head the table; only the tail is newly
            # allocated.
            from arks_tpu.engine.paged import pages_needed
            page = self._page_size()
            k_steps = self.ecfg.steps_per_dispatch
            # Clamped at the table width: a replayed near-cap stream's
            # ids + K window can overshoot max_cache_len — the device's
            # dead_len mask retires the slot before a write lands there.
            total = pages_needed(len(ids), k_steps, page, self._max_pages)
            shared = list(prefix_pages or [])
            try:
                self._faults.fire("pages")
                self._assign_slot_pages(slot, total, head_pages=shared)
            except Exception as e:
                self._alloc.decref(shared)
                self._free.append(slot)
                raise StepFault(
                    "pages", faults_mod.classify(e),
                    culprits=[req.request_id],
                    survivors=[_Survivor(request=req, seed=seed,
                                         num_prompt=len(ids))]) from e
        elif prefix_len:
            # Cached prefix blocks land in the slot first; chunked prefill
            # then continues from prefix_len (a chunk boundary by
            # construction).  The insert is padded to a BUCKETED length so
            # the jitted program compiles O(buckets) shapes, not one per
            # distinct prefix length (the padding rows are garbage the tail
            # chunks overwrite / the per-slot length masks — same invariant
            # as one-shot bucket padding).
            k, v = self._prefix.get(ids, prefix_len)
            pad = self._insert_pad_len(prefix_len)
            if pad > prefix_len:
                width = [(0, 0)] * 5
                width[2] = (0, pad - prefix_len)
                k = np.pad(k, width)
                v = np.pad(v, width)
            try:
                self._cache = self._insert_fn(
                    self._cache, jnp.asarray(k), jnp.asarray(v),
                    jnp.asarray(slot))
            except Exception as e:
                self._free.append(slot)
                raise StepFault(
                    "chunk", faults_mod.classify(e),
                    culprits=[req.request_id],
                    survivors=[_Survivor(request=req, seed=seed,
                                         num_prompt=len(ids))]) from e
        self._prefilling[slot] = _ChunkState(request=req, ids=ids,
                                             pos=prefix_len, seed=seed,
                                             key=jnp.asarray(
                                                 sampler_mod.np_prng_key(seed)),
                                             digests=digests)
        self.trace.evt(req.request_id, "queue", "E")
        self.trace.evt(req.request_id, "prefill", "B", len(ids))
        # Interleaved decode dispatches write garbage KV rows for every slot
        # at its length index; pointing this slot's length at the FINAL
        # prompt position keeps those writes beyond every masked read until
        # real decode overwrites them.
        self._lengths[slot] = len(ids)
        self._last_token[slot] = 0

    def _process_chunk(self) -> None:
        slot, st = next(iter(self._prefilling.items()))
        rid = st.request.request_id
        with self._abort_lock:
            if rid in self._aborted:
                self._aborted.discard(rid)
                del self._prefilling[slot]
                self._release_slot_pages(slot)
                self._free.append(slot)
                self._unpin_guide(st.request)
                st.request.outputs.put(RequestOutput(
                    request_id=rid, token_ids=[], finished=True,
                    finish_reason="abort", num_prompt_tokens=len(st.ids)))
                return
        c = self._chunk
        chunk = st.ids[st.pos: st.pos + c]
        valid = len(chunk)
        self.trace.evt(rid, "chunk", "I", st.pos)
        padded = np.zeros((c,), np.int32)
        padded[:valid] = chunk
        try:
            self._faults.fire("chunk")
            if self._paged:
                self._emit("chunk_paged", slot=slot, tokens=padded,
                           start=st.pos, valid=valid,
                           tables_row=self._tables[slot].copy())
                logits, self._cache = self._chunk_fn(
                    self.params, self._cache, jnp.asarray(self._tables[slot]),
                    jnp.asarray(padded), jnp.asarray(st.pos, jnp.int32),
                    jnp.asarray(valid, jnp.int32))
            else:
                self._emit("chunk", slot=slot, tokens=padded, start=st.pos,
                           valid=valid)
                logits, self._cache = self._chunk_fn(
                    self.params, self._cache, jnp.asarray(slot, jnp.int32),
                    jnp.asarray(padded), jnp.asarray(st.pos, jnp.int32),
                    jnp.asarray(valid, jnp.int32))
        except Exception as e:
            # Attribute the fault to THIS request (the chunk dispatch does
            # work for exactly one sequence) and carry its replayable
            # state with the StepFault — _run's recovery quarantines it
            # within the retry budget while every other request survives.
            del self._prefilling[slot]
            raise StepFault(
                "chunk", faults_mod.classify(e),
                culprits=[st.request.request_id],
                survivors=[_Survivor(request=st.request, seed=st.seed,
                                     num_prompt=len(st.ids))]) from e
        st.pos += valid
        if st.pos < len(st.ids):
            return
        # Final chunk: sample the first token (same key semantics as the
        # one-shot prefill_and_sample) and promote the slot to decoding.
        p = st.request.params
        bias_ids, bias_vals, sup, min_first, _mu = self._shape_cols(p, 0)
        gid, grow0 = self._guide_cols(p)
        self._ensure_guides_uploaded()  # see _issue_admit_batch
        args = (logits, jnp.float32(p.temperature), jnp.float32(p.top_p),
                jnp.int32(p.top_k), st.key,
                jnp.asarray(bias_ids), jnp.asarray(bias_vals),
                jnp.asarray(sup), jnp.asarray(min_first, jnp.int32),
                jnp.asarray(gid, jnp.int32), jnp.asarray(grow0, jnp.int32),
                self._guide_dev)
        first_lp = None
        if p.logprobs is not None:
            self._emit("sample_one_lp", temperature=p.temperature,
                       top_p=p.top_p, top_k=p.top_k, seed=st.seed,
                       bias_ids=bias_ids, bias_vals=bias_vals,
                       sup_ids=sup, min_first=min_first,
                       guide=gid, guide_row=grow0)
            fid, clp, vals, lids = self._sample_one_lp_fn(*args)
            first = int(fid)
            first_lp = self._lp_entry(clp, vals, lids, p.logprobs)
        else:
            self._emit("sample_one", temperature=p.temperature, top_p=p.top_p,
                       top_k=p.top_k, seed=st.seed,
                       bias_ids=bias_ids, bias_vals=bias_vals,
                       sup_ids=sup, min_first=min_first,
                       guide=gid, guide_row=grow0)
            first = int(self._sample_one_fn(*args))
        del self._prefilling[slot]
        grow1 = self.guides.next_row(grow0, first) if gid >= 0 else 0
        self._emit("set_slot", slot=slot, temperature=p.temperature,
                   top_p=p.top_p, top_k=p.top_k, seed=st.seed,
                   presence=p.presence_penalty, frequency=p.frequency_penalty,
                   logit_bias=list(p.logit_bias), min_tokens=p.min_tokens,
                   stop_ids=list(p.stop_token_ids), ignore_eos=p.ignore_eos,
                   num_prompt=len(st.ids), guide=gid, guide_row=grow1)
        self._apply_set_slot(slot, p, jax.random.fold_in(st.key, 1),
                             num_prompt=len(st.ids), guide=gid,
                             guide_row=grow1)
        self._register_slot(st.request, slot, first, len(st.ids),
                            first_lp=first_lp, seed=st.seed)
        if self._paged and self._chunk:
            # Zero-cost harvest: every full prompt page is now written —
            # register the digest chain so later prompts share on device
            # (st.digests carries the chain computed at match time).
            self._register_prompt_pages(st.ids,
                                        self._slot_pages.get(slot, []),
                                        st.digests)
        # Slot layout: harvest the chunk-prefilled prompt (its KV exists
        # only inside the slotted cache — read it back out before decode
        # grows past it).  Same pressure gate as the one-shot path: the
        # device->host copy must not starve waiting admissions.
        elif (self._prefix is not None and self.dispatcher is None
                and self._queue.empty()):
            nfull = len(st.ids) // self._chunk * self._chunk
            if nfull and self._prefix.missing_blocks(st.ids, nfull):
                k, v = self._extract_fn(self._cache, jnp.asarray(slot, jnp.int32))
                # Slice on device: the host copy is nfull rows, not the whole
                # max_cache_len slot.
                self._prefix.put(st.ids, np.asarray(k[:, :, :nfull]),
                                 np.asarray(v[:, :, :nfull]), nfull)
                self.metrics.prefix_cache_usage_bytes.set(
                    self._prefix.bytes_used, tier="host")

    def prefill_detached(self, prompt_ids: list[int],
                         params) -> PrefilledState:
        """Run prefill + first-token sampling and return the transferable
        state instead of inserting into this engine's cache.  Thread-safe;
        called from server threads on a prefill-only engine (no decode
        loop).  On a multi-host gang the dispatch is mirrored to followers
        like any other op — the prefill lock serializes the emit+dispatch
        pair, and a prefill-only engine runs no scheduler thread to
        interleave with, so followers see the leader's exact order.

        One-shot only: the transferred KV is a single [T] block, so prompts
        beyond the largest bucket are rejected (HTTP 400 at the server)."""
        if len(prompt_ids) > self._one_shot_limit():
            raise ContextLengthExceededError(
                f"prompt has {len(prompt_ids)} tokens but the disaggregated "
                f"prefill limit is {self._one_shot_limit()}")
        ids, padded = self._prepare_prompt(prompt_ids)

        want_lp = getattr(params, "logprobs", None) is not None
        first_lp = None
        pinned = False
        if params.guide is not None:
            # BLOCKING compile on this server thread (deduped against
            # concurrent compiles of the same key), taken OUTSIDE the
            # prefill lock so a cold compile never serializes other
            # prefills; then pin for the dispatch window so an eviction
            # cannot repack the guide's rows under us.
            self.guides.compile(*params.guide)
            self.guides.acquire(*params.guide)
            pinned = True
        try:
            return self._prefill_detached_pinned(ids, padded, params,
                                                 want_lp, first_lp)
        finally:
            if pinned:
                self.guides.release(*params.guide)

    def _prefill_detached_pinned(self, ids, padded, params, want_lp,
                                 first_lp) -> PrefilledState:
        with self._prefill_lock:
            self._request_seed += 1
            seed = params.seed if params.seed is not None else self._request_seed
            key = jnp.asarray(sampler_mod.np_prng_key(seed))
            bias_ids, bias_vals, sup, min_first, _mu = \
                self._shape_cols(params, 0)
            gid, grow0 = self._guide_cols(params)
            self._ensure_guides_uploaded()
            args = (self.params, jnp.asarray(padded),
                    jnp.asarray([len(ids)], jnp.int32),
                    jnp.float32(params.temperature),
                    jnp.float32(params.top_p),
                    jnp.int32(params.top_k), key,
                    jnp.asarray(bias_ids), jnp.asarray(bias_vals),
                    jnp.asarray(sup), jnp.asarray(min_first, jnp.int32),
                    jnp.asarray(gid, jnp.int32),
                    jnp.asarray(grow0, jnp.int32), self._guide_dev)
            if want_lp:
                self._emit("prefill_detached_lp", tokens=padded,
                           length=len(ids), temperature=params.temperature,
                           top_p=params.top_p, top_k=params.top_k, seed=seed,
                           bias_ids=bias_ids, bias_vals=bias_vals,
                           sup_ids=sup, min_first=min_first,
                           guide=gid, guide_row=grow0)
                first_id, clp, vals, lids, ks, vs = \
                    self._prefill_detached_lp_fn(*args)
                first_lp = self._lp_entry(clp, vals, lids, params.logprobs)
            else:
                self._emit("prefill_detached", tokens=padded,
                           length=len(ids), temperature=params.temperature,
                           top_p=params.top_p, top_k=params.top_k, seed=seed,
                           bias_ids=bias_ids, bias_vals=bias_vals,
                           sup_ids=sup, min_first=min_first,
                           guide=gid, guide_row=grow0)
                first_id, ks, vs = self._prefill_detached_fn(*args)
            first = int(first_id)
        self.metrics.prompt_tokens_total.inc(len(ids))
        return PrefilledState(first_token=first, num_prompt=len(ids),
                              seed=seed, k=np.asarray(ks), v=np.asarray(vs),
                              first_lp=first_lp,
                              guide_row=(self.guides.next_row(grow0, first)
                                         - grow0 if gid >= 0 else 0),
                              prompt_ids=list(ids))

    # ------------------------------------------------------------------
    # Pipelined decode (ARKS_PIPELINE_DEPTH)
    # ------------------------------------------------------------------

    def _stop_ids_for(self, p) -> list[int]:
        """The token ids that end a stream for these params — the EXACT
        set _is_stop checks, mirrored onto the device as a stop column so
        pipelined dispatches can compute liveness without the host."""
        if p.ignore_eos:
            return list(p.stop_token_ids)
        return (list(self.cfg.eos_token_ids)
                + list(self.tokenizer.eos_token_ids)
                + list(p.stop_token_ids))

    def _pipe_ready(self) -> bool:
        """True when the next iteration can stay on the zero-host-sync
        pipelined path: live decoding slots, no host-side scheduler work
        pending (admission, chunked prefill, deferred admits), no abort
        aimed at a live slot, and every slot's stop set fits the device
        column.  Anything else drains the pipeline first — host mutations
        need authoritative mirrors.  Requests parked on an in-flight guide
        compile do NOT drain it: the park is pure host bookkeeping, and a
        slow compile must not degrade live decoding to the sequential
        path — step() re-queues the request the moment its guide
        publishes, which the admission check below then catches."""
        if not self._pipe_depth:
            return False
        return self._steady_ready()

    def _fuse_ready(self) -> bool:
        """Depth-0 sampler fusion (ARKS_SAMPLER_FUSE): a steady-state
        pure-decode iteration issues the fused attention+sampler pipe
        program (count_tokens -> mixed_step -> sample -> liveness, one
        device program, ZERO host-side prep arrays) and resolves it
        immediately, instead of packing the classic ~20-array mixed
        batch.  Shares the pipelined path's readiness gates exactly —
        anything host-side (prefill chunks, transient first-token
        override columns, admissions, aborts, oversized stop sets)
        falls back to the classic _issue_mixed/_resolve_mixed pair, as
        do speculative engines (their spec-mixed dispatch carries
        per-slot verify blocks the fused columns don't)."""
        if self._pipe_depth or not self._sampler_fuse or not self._mixed:
            return False
        if self._draft_cfg is not None:
            return False
        return self._steady_ready()

    def _steady_ready(self) -> bool:
        """Shared steady-state gate of the pipelined and fused paths."""
        if not self._slots:
            return False
        if self._residency_active():
            # Windowed-residency slots decode span-by-span on the host
            # loop — neither steady-state device program covers them.
            return False
        if self._prefilling or self._pending_admits:
            return False
        if self._awaiting_restore and self._free \
                and self._restore_ready_any():
            # A host-tier restore LANDED: drain so the unpark can take a
            # slot with authoritative mirrors.  Restores still in flight
            # keep pipelining at full depth — that is the point of
            # issuing them as ordinary stream dispatches.
            return False
        if self._fetch_ready_any():
            # A disk/peer fetch finished staging: drain so the unpark
            # re-enters admission with authoritative mirrors.  In-flight
            # fetches are worker-thread work — full depth continues.
            return False
        if self._free and not self._queue.empty():
            # Admission is possible RIGHT NOW; with no free slot the queue
            # can only wait anyway, so saturation keeps pipelining.
            return False
        if self._swap_ready_any() or self._resume_ready_any():
            # A preempt spill's D2H copies landed (its staging blocks
            # hold the victim's only KV copy — harvest them), or a swap
            # resume's scatter landed (its slot must re-register) — both
            # are host mutations.  In-flight ones keep full depth.
            return False
        if self._preempt_wanted():
            # A queued request outranks a running victim: drain so the
            # preempt swap runs on authoritative host mirrors.
            return False
        if any(st.stop_col is None for st in self._slots.values()):
            return False
        with self._abort_lock:
            if self._aborted:
                live = {st.request.request_id
                        for st in self._slots.values()}
                if self._aborted & live:
                    return False
        if self._pipe_warm_state != "ready":
            # Pipe programs still cold: keep serving on the warm
            # sequential path and compile them off-thread — an inline
            # compile here would freeze every live token stream for the
            # whole build (seconds on CPU, potentially tens on TPU).
            self._pipe_kick_warmup()
            return False
        return True

    # ------------------------------------------------------------------
    # Windowed residency (ARKS_RESIDENCY_WINDOW_PAGES)
    # ------------------------------------------------------------------

    def _residency_active(self) -> bool:
        """True when a slot decodes (or is about to decode) through the
        windowed-residency path.  The margin term drains the pipelined
        path a few tokens BEFORE a slot's page need crosses the window,
        so pipelined grow calls can never allocate past the resident
        budget while dispatches are still in flight."""
        r = self._residency
        if r is None:
            return False
        if r.slots:
            return True
        if not self._slots:
            return False
        from arks_tpu.engine.paged import pages_needed
        page = self._page_size()
        margin = 1 + max(self._pipe_depth, 1)
        return any(
            pages_needed(int(self._lengths[s]), margin, page,
                         self._max_pages) > r.window
            for s in self._slots)

    @_scoped("residency")
    def _residency_step(self) -> bool:
        """Advance every engaged slot one token: the manager runs the
        span-streaming forward (cold pages rotate through staging while
        resident spans attend), the engine runs the mixed program's
        sampler tail on the returned logits and fans the token out
        through the shared per-slot resolve path."""
        r = self._residency
        r.engage_pending()
        if not r.slots:
            return False
        self._faults.fire("residency")
        worked = False
        for slot in list(r.slots):
            st = self._slots.get(slot)
            if st is None:
                r.release(slot)
                continue
            t0 = time.monotonic()
            want_lp = st.request.params.logprobs is not None
            logits = r.forward(slot)
            feed_tokens = np.zeros((self.ecfg.num_slots,), np.int32)
            feed_active = np.zeros((self.ecfg.num_slots,), bool)
            feed_tokens[slot] = self._last_token[slot]
            feed_active[slot] = True
            args = (self._sampling, logits, jnp.asarray(feed_tokens),
                    jnp.asarray(feed_active),
                    jnp.asarray(np.array(self._lengths)), self._guide_dev)
            if want_lp:
                ids, clp, vals, lids, self._sampling = r.sample_lp_fn(*args)
                lp_rows = ([np.asarray(clp)[slot]], [np.asarray(vals)[slot]],
                           [np.asarray(lids)[slot]])
            else:
                ids, self._sampling = r.sample_fn(*args)
                lp_rows = None
            tok = int(np.asarray(ids)[slot])
            self._fanout_decode_tokens(slot, [tok], lp_rows,
                                       max(time.monotonic() - t0, 1e-6))
            worked = True
        return worked

    def _pipe_signature(self):
        """Specimen arguments for AOT-lowering the pipe programs: the
        exact avals+shardings a fresh `_pipe_issue` produces.  Built on
        the calling thread while the referenced arrays are alive (the
        engine thread may donate self._cache away at any later dispatch,
        so the background thread must never touch the arrays — only this
        frozen aval view)."""
        n = self.ecfg.num_slots
        state = (jnp.asarray(np.zeros((n,), np.int32)),
                 jnp.asarray(np.zeros((n,), np.int32)),
                 jnp.asarray(np.zeros((n,), bool)))
        cols = [jnp.asarray(np.full((n, sampler_mod.STOP_IDS_MAX), -1,
                                    np.int32)),
                jnp.asarray(np.zeros((n,), np.int32))]
        if self._draft_cfg is not None:
            cols.append(jnp.asarray(np.zeros((n,), bool)))
        tables = jnp.asarray(self._tables) if self._paged else None
        if self._draft_cfg is not None:
            args = (self.params, self._draft_params, self._cache,
                    self._draft_cache, *state, *cols, self._sampling,
                    tables, self._guide_dev)
        else:
            args = (self.params, self._cache, *state, *cols, self._sampling,
                    tables, self._guide_dev)
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding), args)

    def _pipe_jit_fn(self, want_lp: bool):
        if self._draft_cfg is not None:
            return self._spec_pipe_lp_fn if want_lp else self._spec_pipe_fn
        if self._mixed:
            return self._mixed_pipe_lp_fn if want_lp else self._mixed_pipe_fn
        return self._decode_pipe_lp_fn if want_lp else self._decode_pipe_fn

    def _pipe_kick_warmup(self) -> None:
        """Start the one-shot background compile of both pipe-program
        variants (with/without logprobs).  Idempotent; engine-thread.
        Depth-0 engines warm them too when sampler fusion is on — the
        fused path dispatches the same programs."""
        fuse = (self._sampler_fuse and self._mixed
                and self._draft_cfg is None)
        if self._pipe_warm_state is not None or not (self._pipe_depth
                                                     or fuse):
            return
        self._pipe_warm_state = "compiling"
        sig = self._pipe_signature()
        t = threading.Thread(target=self._pipe_warmup, args=(sig,),
                             name="pipe-warmup", daemon=True)
        self._pipe_warm_thread = t
        t.start()

    def _pipe_warmup(self, sig) -> None:
        try:
            t0 = time.monotonic()
            for lp in (False, True):
                self._pipe_exec[lp] = self._pipe_jit_fn(lp).lower(
                    *sig).compile()
            self._pipe_warm_state = "ready"
            log.info("pipelined decode programs warm in %.1fs "
                     "(depth=%d, %s)", time.monotonic() - t0,
                     self._pipe_depth,
                     "mixed_pipe" if self._mixed else "decode_pipe")
        except Exception as e:
            self._pipe_warm_state = "failed"
            faults_mod.swallowed("pipe_warmup", e)
            log.warning("pipelined decode warmup failed; engine stays on "
                        "the sequential path", exc_info=True)

    def _pipe_warm_wait(self, timeout: float | None = None) -> str | None:
        """Kick the warmup and block until it resolves — tests and
        preflight only; the serving path never blocks on it."""
        self._pipe_kick_warmup()
        t = self._pipe_warm_thread
        if t is not None:
            t.join(timeout)
        return self._pipe_warm_state

    def _pipe_call(self, want_lp: bool, *args):
        """Dispatch one pipe program: the warmed AOT executable when the
        inputs still match its signature, else the jit path (which then
        compiles the drifted variant inline ONCE — e.g. after the guide
        tables grew, or for threaded state whose sharding differs from
        the fresh-entry signature on a meshed engine)."""
        exe = self._pipe_exec.get(bool(want_lp))
        if exe is not None:
            try:
                return exe(*args)
            except (TypeError, ValueError):
                pass  # aval/sharding drift: inputs not consumed, retry jit
        return self._pipe_jit_fn(want_lp)(*args)

    @_scoped("decode")
    def _step_pipelined(self) -> None:
        """One steady-state iteration: issue ONE dispatch (if the pipeline
        has room), then resolve — blocking on the oldest only when the
        pipeline is full, else opportunistically draining whatever the
        device already finished."""
        if len(self._pipe_inflight) < self._pipe_depth:
            self._pipe_issue()
        if len(self._pipe_inflight) >= self._pipe_depth:
            self._pipe_resolve_one()
        else:
            while self._pipe_inflight and self._pipe_rec_ready(
                    self._pipe_inflight[0]):
                self._pipe_resolve_one()
        if self._spills:
            # Harvest landed spill gathers (steady-state evictions come
            # from _pipe_issue's page growth); ready-only, never blocks.
            self._resolve_spills()

    @_scoped("mixed")
    def _step_fused(self) -> None:
        """One depth-0 fused iteration (ARKS_SAMPLER_FUSE): issue the
        attention+sampler pipe program FRESH from the host mirrors and
        resolve it immediately.  The host stays authoritative — the
        threaded device state is dropped after every resolve, so the
        fused path is the classic sequential loop with the host-side
        sampler prep folded into the dispatch, not a hidden pipeline."""
        self._pipe_issue()
        if self._pipe_inflight:
            self.metrics.sampler_fused_dispatch_total.inc()
            self._pipe_resolve_one()
        self._pipe_state = None
        self._pipe_cols = None
        self._pipe_cols_np = None
        self._pipe_last_resolve = None
        if self._spills:
            self._resolve_spills()

    @staticmethod
    def _pipe_rec_ready(rec) -> bool:
        try:
            return rec[2].is_ready()
        except AttributeError:  # platform without readiness polling
            return True

    def _pipe_issue(self) -> None:
        """Issue one pipelined decode dispatch.  Fresh (pipeline cold):
        device state is built from the host mirrors — the ONE host->device
        state upload per run.  Threaded: the previous dispatch's returned
        arrays feed this one untouched; only the block tables (host-owned
        page bookkeeping) travel per dispatch."""
        K = self._pipe_rows
        fresh = self._pipe_state is None
        if fresh:
            # Host-authoritative entry: retire slots whose next dispatch
            # would overflow the cache (same margin dead_len enforces on
            # device for every later dispatch of the run).
            for slot in list(self._slots):
                if int(self._lengths[slot]) >= self.ecfg.max_cache_len - K:
                    self._finish(slot, "length")
            if not self._slots:
                return
        spec = self._draft_cfg is not None
        if self._paged:
            self._grow_slot_pages(K, ahead=len(self._pipe_inflight))
        self._ensure_guides_uploaded()
        self._faults.fire("spec" if spec else "decode")
        if fresh:
            n = self.ecfg.num_slots
            alive = np.zeros((n,), bool)
            stop_ids = np.full((n, sampler_mod.STOP_IDS_MAX), -1, np.int32)
            dead_len = np.zeros((n,), np.int32)
            spec_col = np.zeros((n,), bool)
            for slot, st in self._slots.items():
                alive[slot] = True
                stop_ids[slot] = st.stop_col
                dead_len[slot] = st.dead_len
                spec_col[slot] = st.spec_ok
            state = (jnp.asarray(self._last_token),
                     jnp.asarray(self._lengths), jnp.asarray(alive))
            cols = [jnp.asarray(stop_ids), jnp.asarray(dead_len)]
            cols_np = [stop_ids, dead_len]
            if spec:
                # Spec eligibility is per-slot device data too: the
                # threaded spec_pipe dispatches gate acceptance on it
                # without any host value.
                cols.append(jnp.asarray(spec_col))
                cols_np.append(spec_col)
            self._pipe_cols = tuple(cols)
            self._pipe_cols_np = tuple(cols_np)
        else:
            state = self._pipe_state
        want_lp = any(st.request.params.logprobs is not None
                      for st in self._slots.values())
        tables_arg = jnp.asarray(self._tables) if self._paged else None
        payload = dict(lp=want_lp, fresh=fresh,
                       tables=self._tables.copy() if self._paged else None,
                       occupancy=len(self._pipe_inflight) + 1)
        if fresh:
            payload.update(tokens=np.array(self._last_token),
                           lengths=np.array(self._lengths),
                           alive=alive.copy(),
                           stop_ids=self._pipe_cols_np[0].copy(),
                           dead_len=self._pipe_cols_np[1].copy())
            if spec:
                payload.update(spec_enable=self._pipe_cols_np[2].copy())
        self._emit("decode_pipe", **payload)
        t0 = time.monotonic()
        self._pipe_seq += 1
        self.trace.evt("", "pipe", "B", self._pipe_seq)
        if spec:
            out = self._pipe_call(want_lp, self.params, self._draft_params,
                                  self._cache, self._draft_cache, *state,
                                  *self._pipe_cols, self._sampling,
                                  tables_arg, self._guide_dev)
            if want_lp:
                (self._cache, self._draft_cache, self._sampling, toks,
                 counts, clps, lvals, lids, ntok, nlen, nalive) = out
                lp_devs = (clps, lvals, lids)
            else:
                (self._cache, self._draft_cache, self._sampling, toks,
                 counts, ntok, nlen, nalive) = out
                lp_devs = None
        else:
            counts = None
            out = self._pipe_call(want_lp, self.params, self._cache, *state,
                                  *self._pipe_cols, self._sampling,
                                  tables_arg, self._guide_dev)
            if want_lp:
                (self._cache, self._sampling, toks, clps, lvals, lids,
                 ntok, nlen, nalive) = out
                lp_devs = (clps, lvals, lids)
            else:
                self._cache, self._sampling, toks, ntok, nlen, nalive = out
                lp_devs = None
        self._pipe_state = (ntok, nlen, nalive)
        # Start the device->host copies NOW so the lagged resolve finds
        # them materialized instead of blocking the engine thread.
        for arr in (toks,) + (() if counts is None else (counts,)) \
                + (lp_devs or ()):
            try:
                arr.copy_to_host_async()
            except Exception as e:  # platform without async host copies
                faults_mod.swallowed("copy_to_host_async", e)
        snapshot = [(s, int(self._slot_gen[s])) for s in self._slots]
        self._pipe_inflight.append(
            (snapshot, want_lp, toks, lp_devs, K, t0, counts))
        self.metrics.pipeline_depth_occupancy.observe(
            len(self._pipe_inflight))
        if self._model_loads:
            # Dispatch accounting for the switch-overlap claim: decode
            # dispatches issued while another model's weights stream, and
            # the pipeline depth they sustained (the multi-model bench
            # asserts full depth — plain host counters, no device sync).
            self._switch_stats["dispatches"] += 1
            if len(self._pipe_inflight) > self._switch_stats["max_depth"]:
                self._switch_stats["max_depth"] = len(self._pipe_inflight)

    def _pipe_resolve_one(self) -> None:
        """Resolve the OLDEST in-flight dispatch on the lagged host view:
        fan its tokens out, apply the host-only semantics (stop tokens,
        max_tokens truncation, logprob formatting), and retire finished
        slots — whose overshoot tokens in NEWER in-flight dispatches are
        discarded by the (slot, gen) snapshot guard."""
        (snapshot, want_lp, toks, lp_devs, K, t0,
         counts_dev) = self._pipe_inflight.popleft()
        self._faults.fire("resolve")
        t_wait = time.monotonic()
        toks = np.asarray(toks)  # host sync point (async copy usually done)
        counts = None if counts_dev is None else np.asarray(counts_dev)
        if lp_devs is not None:
            clps = np.asarray(lp_devs[0])    # [K, B]
            lvals = np.asarray(lp_devs[1])   # [K, B, L]
            lids = np.asarray(lp_devs[2])
        now = time.monotonic()
        self.metrics.decode_resolve_wait_seconds_total.inc(
            now - t_wait, mode="pipelined")
        self.trace.evt("", "pipe", "E", len(snapshot))
        # TPOT from resolve interarrival: in steady state one resolve
        # lands per dispatch, so the gap IS the per-dispatch device time —
        # this dispatch's own issue->resolve span covers the whole
        # pipeline depth and would overstate TPOT by ~depth x.
        last = self._pipe_last_resolve
        self._pipe_last_resolve = now
        dt = max(now - (t0 if last is None else last), 1e-6)
        cols = toks.T.tolist()
        n_spec = accepted = 0
        for slot, gen in snapshot:
            st = self._slots.get(slot)
            if st is None or int(self._slot_gen[slot]) != gen:
                continue  # retired at an earlier resolve: overshoot dropped
            col = cols[slot]
            if counts is not None:
                # Spec dispatch: only the accepted prefix of the verify
                # block is real output; the rejected tail is garbage the
                # device also never threaded forward.
                c = max(1, min(int(counts[slot]), K))
                col = col[:c]
                if st.spec_ok:
                    n_spec += 1
                    accepted += c - 1
                    self.metrics.spec_decode_accepted_length.observe(c)
            lp_rows = None
            if want_lp and st.request.params.logprobs is not None:
                lp_rows = (clps[:, slot], lvals[:, slot], lids[:, slot])
            self._fanout_decode_tokens(slot, col, lp_rows, dt)
        if n_spec:
            DK = self.ecfg.draft_len
            self.metrics.spec_decode_proposed_tokens_total.inc(
                (DK - 1) * n_spec)
            self.metrics.spec_decode_accepted_tokens_total.inc(accepted)
            self._spec_proposed += (DK - 1) * n_spec
            self._spec_accepted += accepted
            self.metrics.spec_decode_acceptance_rate.set(
                self._spec_accepted / max(self._spec_proposed, 1))

    @_scoped("decode")
    def _pipe_drain(self) -> None:
        """Resolve every in-flight dispatch and hand authority back to the
        host mirrors (they are exact after the last resolve)."""
        try:
            while self._pipe_inflight:
                self._pipe_resolve_one()
        finally:
            self._pipe_state = None
            self._pipe_cols = None
            self._pipe_cols_np = None
            self._pipe_last_resolve = None

    def _pipe_reset(self) -> None:
        """Fault path: drop in-flight records without resolving (the
        dispatch error already aborted their requests; the device state is
        being rebuilt)."""
        self._pipe_inflight.clear()
        self._pipe_state = None
        self._pipe_cols = None
        self._pipe_cols_np = None
        self._pipe_last_resolve = None

    def _decode_dispatch(self) -> None:
        rec = self._issue_decode()
        if rec is not None:
            self._resolve_decode(rec)

    @_scoped("decode")
    def _issue_decode(self):
        """Decode bookkeeping + ASYNC dispatch.  Returns the pending record
        for _resolve_decode, or None when nothing dispatched (no live
        slots).

        The issue/resolve split lets step() overlap admission host work
        with the in-flight decode: aborted/retired slots free their pages
        BEFORE the dispatch snapshot (their rows carry the write-drop
        sentinel), so pages handed to admissions during the flight cannot
        be written by it, and admissions' device work queues after the
        decode on the stream."""
        K = self.ecfg.steps_per_dispatch
        with self._abort_lock:
            aborted = set(self._aborted)
        consumed = set()
        for slot in list(self._slots):
            rid = self._slots[slot].request.request_id
            if rid in aborted:
                self._finish(slot, "abort")
                consumed.add(rid)
        # Aborts for requests still waiting in the admission queue stay in
        # the set until _preadmit consumes them; deferred admits and
        # guide-parked requests count as live (purging their flags would
        # lose aborts raised between issue and registration).
        self._purge_stale_aborts(consumed)
        # Retire any slot that would overflow its cache this dispatch.
        for slot in list(self._slots):
            if int(self._lengths[slot]) + 1 + K > self.ecfg.max_cache_len:
                self._finish(slot, "length")
        if not self._slots:
            return None

        if self._paged:
            self._grow_slot_pages(K)

        self._faults.fire("decode")
        t0 = time.monotonic()
        # Logprob variant selected per dispatch: only dispatches containing
        # a logprob-bearing slot pay the full-vocab log-softmax.
        want_lp = any(st.request.params.logprobs is not None
                      for st in self._slots.values())
        tables_arg = jnp.asarray(self._tables) if self._paged else None
        self._emit("decode", tokens=np.array(self._last_token),
                   lengths=np.array(self._lengths), lp=want_lp,
                   tables=self._tables.copy() if self._paged else None)
        lp_devs = None
        if want_lp:
            self._cache, self._sampling, (toks, clps, lvals, lids) = \
                self._decode_lp_fn(
                    self.params, self._cache, jnp.asarray(self._last_token),
                    jnp.asarray(self._lengths), self._sampling, tables_arg,
                    self._guide_dev)
            lp_devs = (clps, lvals, lids)
        else:
            self._cache, self._sampling, toks = self._decode_fn(
                self.params, self._cache, jnp.asarray(self._last_token),
                jnp.asarray(self._lengths), self._sampling, tables_arg,
                self._guide_dev)
        # Snapshot the dispatch's slot set: slots admitted while this
        # dispatch is in flight are NOT part of it (their rows carried the
        # free-slot sentinel at issue).
        return (list(self._slots.keys()), want_lp, toks, lp_devs, K, t0)

    @_scoped("decode")
    def _resolve_decode(self, rec, exclude_s: float = 0.0) -> None:
        """Host-sync tail: fetch the dispatch's tokens and fan them out to
        the SNAPSHOT slots.  ``exclude_s`` subtracts the overlapped
        admit/chunk wall time from the TPOT observation — in overlap mode
        issue-to-resolve spans that host work, which is not decode time."""
        snapshot, want_lp, toks, lp_devs, K, t0 = rec
        self._faults.fire("resolve")
        t_wait = time.monotonic()
        toks = np.asarray(toks)  # [K, B] — host sync point
        # Pure device-stream wait, free of overlapped host work: the
        # trustworthy device-bound signal for bench_serving's attribution
        # (the phase-seconds breakdown attributes WALL time, which in
        # overlap mode can land waits in whichever phase fetches first).
        self.metrics.decode_resolve_wait_seconds_total.inc(
            time.monotonic() - t_wait, mode="sequential")
        if lp_devs is not None:
            clps = np.asarray(lp_devs[0])    # [K, B]
            lvals = np.asarray(lp_devs[1])   # [K, B, L]
            lids = np.asarray(lp_devs[2])
        dt = max(time.monotonic() - t0 - exclude_s, 1e-6)
        # One bulk C conversion instead of B*K numpy scalar reads (~6k
        # PyObject boxing calls per dispatch at b192/K32 — measurable host
        # time the GIL shares with the serving threads).
        cols = toks.T.tolist()   # [B][K] python ints

        for slot in snapshot:
            st = self._slots[slot]
            lp_rows = None
            if want_lp and st.request.params.logprobs is not None:
                lp_rows = (clps[:, slot], lvals[:, slot], lids[:, slot])
            self._fanout_decode_tokens(slot, cols[slot], lp_rows, dt)

    def _fanout_decode_tokens(self, slot: int, col: list, lp_rows,
                              dt: float) -> None:
        """Per-slot tail shared by the sequential resolve and the
        pipelined resolve: append the dispatch's K tokens (truncating at
        the first stop token or the max_tokens cutoff — everything past it
        is overshoot the device computed but the client never sees),
        advance the host mirrors, and finish or stream the delta."""
        st = self._slots[slot]
        K = len(col)
        n_lp = st.request.params.logprobs
        finished = False
        new_tokens = 0
        for k in range(K):
            tok = col[k]
            st.generated.append(tok)
            if lp_rows is not None:
                st.logprobs.append(self._lp_entry(
                    lp_rows[0][k], lp_rows[1][k], lp_rows[2][k], n_lp))
            new_tokens += 1
            if self._is_stop(st, tok) or len(st.generated) >= st.request.params.max_tokens:
                finished = True
                break
        self._lengths[slot] += K  # all K KVs were written on device
        self._last_token[slot] = col[K - 1]
        self.metrics.generation_tokens_total.inc(new_tokens)
        self.metrics.time_per_output_token_seconds.observe(dt / K)
        self.metrics.tpot_seconds.observe(
            dt / K, tier=self._slo.tier_of(st.request.params.priority))
        if finished:
            self._finish(slot, self._finish_reason(st))
        else:
            delta = st.generated[st.num_emitted:]
            lp_delta = (st.logprobs[st.num_emitted:]
                        if n_lp is not None else None)
            st.num_emitted = len(st.generated)
            st.request.outputs.put(RequestOutput(
                request_id=st.request.request_id, token_ids=delta,
                num_prompt_tokens=st.num_prompt,
                logprobs=lp_delta))

    # ------------------------------------------------------------------
    # Mixed prefill+decode dispatch (ARKS_MIXED_STEP)
    # ------------------------------------------------------------------

    def _mixed_abort_and_retire(self, rows: int = 1) -> None:
        """Mixed-mode scheduling boundary: honor aborts for decoding AND
        prefilling sequences, purge stale abort flags, and retire slots
        that would overflow the cache this dispatch (``rows`` decode rows
        per slot: 1 for the plain mixed step, draft_len for a spec-mixed
        verify block)."""
        with self._abort_lock:
            aborted = set(self._aborted)
        consumed = set()
        for slot in list(self._slots):
            rid = self._slots[slot].request.request_id
            if rid in aborted:
                self._finish(slot, "abort")
                consumed.add(rid)
        for slot, st in list(self._prefilling.items()):
            rid = st.request.request_id
            if rid in aborted:
                del self._prefilling[slot]
                self._release_slot_pages(slot)
                self._free.append(slot)
                self._unpin_guide(st.request)
                st.request.outputs.put(RequestOutput(
                    request_id=rid, token_ids=[], finished=True,
                    finish_reason="abort", num_prompt_tokens=len(st.ids)))
                consumed.add(rid)
        self._purge_stale_aborts(consumed)
        for slot in list(self._slots):
            if int(self._lengths[slot]) + 1 + rows > self.ecfg.max_cache_len:
                self._finish(slot, "length")

    def _mixed_batch_arrays(self, t_budget: int) -> dict:
        """Empty host-side arrays for one mixed/spec-mixed batch: the flat
        token view, the per-lane sampler view, and the completion-override
        columns — ONE definition, so the plain and spec builders cannot
        drift on padding conventions."""
        num_slots = self.ecfg.num_slots
        sentinel = self._park_sentinel()
        return dict(
            tokens=np.zeros((t_budget,), np.int32),
            token_slot=np.full((t_budget,), -1, np.int32),
            token_pos=np.full((t_budget,), sentinel, np.int32),
            sample_src=np.zeros((num_slots,), np.int32),
            feed_tokens=np.zeros((num_slots,), np.int32),
            feed_active=np.zeros((num_slots,), bool),
            seq_q_start=np.zeros((num_slots,), np.int32),
            seq_q_len=np.zeros((num_slots,), np.int32),
            seq_pos_start=np.zeros((num_slots,), np.int32),
            ov_mask=np.zeros((num_slots,), bool),
            ov_temp=np.zeros((num_slots,), np.float32),
            ov_top_p=np.ones((num_slots,), np.float32),
            ov_top_k=np.zeros((num_slots,), np.int32),
            ov_key=np.zeros((num_slots, 2), np.uint32),
            ov_bias_ids=np.full((num_slots, sampler_mod.LOGIT_BIAS_MAX), -1,
                                np.int32),
            ov_bias_vals=np.zeros((num_slots, sampler_mod.LOGIT_BIAS_MAX),
                                  np.float32),
            ov_sup=np.full((num_slots, sampler_mod.SUPPRESS_MAX), -1,
                           np.int32),
            ov_min_until=np.zeros((num_slots,), np.int32),
            ov_guide=np.full((num_slots,), -1, np.int32),
            ov_guide_row=np.zeros((num_slots,), np.int32))

    def _fill_chunk_lanes(self, a: dict, t: int):
        """Round-robin prefill-chunk fill starting at flat index ``t``: an
        even quota per prefilling sequence first, FIFO greedy for the
        leftover — a burst of long prompts shares the budget instead of
        serializing.  Sequences whose prompt completes inside this batch
        get transient first-token sampling columns packed into their lane
        (same key and shaping semantics as the legacy sample_one).
        Returns (completing, chunk_take, t)."""
        completing: list = []
        chunk_take: list[tuple[int, int]] = []
        pre = list(self._prefilling.items())
        if not pre or not self._mixed_budget:
            return completing, chunk_take, t
        budget = self._mixed_budget
        quota = max(budget // len(pre), 1)
        takes: dict[int, int] = {}
        for slot, st in pre:
            if budget <= 0:
                break
            take = min(len(st.ids) - st.pos, quota, budget)
            if take > 0:
                takes[slot] = take
                budget -= take
        for slot, st in pre:
            if budget <= 0:
                break
            extra = min(len(st.ids) - st.pos - takes.get(slot, 0),
                        budget)
            if extra > 0:
                takes[slot] = takes.get(slot, 0) + extra
                budget -= extra
        for slot, st in pre:
            take = takes.get(slot, 0)
            if not take:
                continue
            a["tokens"][t: t + take] = st.ids[st.pos: st.pos + take]
            a["token_slot"][t: t + take] = slot
            a["token_pos"][t: t + take] = np.arange(st.pos, st.pos + take)
            a["seq_q_start"][slot] = t
            a["seq_q_len"][slot] = take
            a["seq_pos_start"][slot] = st.pos
            chunk_take.append((slot, take))
            if st.pos + take == len(st.ids):
                a["sample_src"][slot] = t + take - 1
                p = st.request.params
                gid, grow0 = self._guide_cols(p)
                bias_ids, bias_vals, sup, min_first, _mu = \
                    self._shape_cols(p, 0)
                a["ov_mask"][slot] = True
                a["ov_temp"][slot] = p.temperature
                a["ov_top_p"][slot] = p.top_p
                a["ov_top_k"][slot] = p.top_k
                a["ov_key"][slot] = np.asarray(st.key)
                a["ov_bias_ids"][slot] = bias_ids
                a["ov_bias_vals"][slot] = bias_vals
                a["ov_sup"][slot] = sup
                # lengths[slot] carries len(ids) while prefilling; +1
                # makes ``lengths < min_until`` read as min_first.
                a["ov_min_until"][slot] = \
                    len(st.ids) + 1 if min_first else 0
                a["ov_guide"][slot] = gid
                a["ov_guide_row"][slot] = grow0
                completing.append((slot, st, gid, grow0))
            t += take
        return completing, chunk_take, t

    def _mixed_grid_counters(self, pos_start, q_len, qmax: int) -> None:
        """Account the padding-waste counter pair for one mixed dispatch:
        mixed_grid_steps_total (what the active grid mode executes) and
        mixed_grid_steps_ideal_total (the per-sequence causal minimum).
        The counters describe the grid PLAN — they are meaningful under
        either attention impl, which is what lets the sparse-batch waste
        test run on the XLA oracle.  Inputs are the host-side numpy batch
        arrays — no device fetches here (hot-path guard covers this)."""
        plan = self._grid_plans.get(qmax)
        if plan is None:
            from arks_tpu.ops.paged_attention import mixed_grid_plan
            kvd = self.ecfg.resolve_kv_cache_dtype()
            kv = kvd if kvd in ("int8", "int4") else str(self._cache.k.dtype)
            plan = mixed_grid_plan(
                qmax, hkv=self.cfg.num_kv_heads,
                g=self.cfg.num_heads // self.cfg.num_kv_heads,
                d=tf.cache_head_dim(self.cfg, self._pad_head()),
                page=self._page_size(), kv=kv)
            self._grid_plans[qmax] = plan
        from arks_tpu.engine.paged import mixed_grid_steps, mixed_kv_bytes
        ideal, dense = mixed_grid_steps(
            pos_start, q_len, page=self._page_size(),
            block_q=plan["block_q"], num_qb=plan["num_qb"],
            max_pages=self._max_pages)
        actual = ideal if plan["grid"] == "ragged" else dense
        self.metrics.mixed_grid_steps_total.inc(actual)
        self.metrics.mixed_grid_steps_ideal_total.inc(ideal)
        b_actual, b_ideal = mixed_kv_bytes(
            pos_start, q_len, page=self._page_size(),
            block_q=plan["block_q"], num_qb=plan["num_qb"],
            max_pages=self._max_pages, hkv=self.cfg.num_kv_heads,
            page_head_bytes=self._page_head_bytes())
        self.metrics.mixed_kv_bytes_total.inc(b_actual)
        self.metrics.mixed_kv_bytes_ideal_total.inc(b_ideal)

    def _page_head_bytes(self) -> int:
        """Bytes one (page, KV head) block moves over the mixed kernel's
        page stream: K + V rows (int4 pools store packed nibble rows, so
        the row count already reflects the halving) plus the f32 scale
        rows for quantized pools."""
        k = self._cache.k
        per = 2 * k.shape[3] * k.shape[4] * k.dtype.itemsize
        if self._cache.k_scale is not None:
            per += 2 * self._cache.k_scale.shape[3] * 4
        return per

    @_scoped("mixed")
    def _issue_mixed(self):
        """Build and issue ONE mixed dispatch: every decoding slot's next
        token plus up to ARKS_MIXED_CHUNK_TOKENS prefill tokens spread
        round-robin across ALL prefilling sequences (each makes progress
        every step — no head-of-line prefill serialization).  Sequences
        whose prompt completes inside this batch get transient first-token
        sampling columns packed into their lane; everything samples in the
        program's single sampler.sample call.  Returns the pending record
        for _resolve_mixed, or None when no sequence needs the model."""
        self._mixed_abort_and_retire()
        if not self._slots and not self._prefilling:
            return None
        self._ensure_guides_uploaded()
        self._grow_slot_pages(1)
        self._faults.fire("decode")
        num_slots = self.ecfg.num_slots
        dec_slots = list(self._slots.keys())
        if self._residency is not None:
            # Engaged slots decode through _residency_step — their lanes
            # must never enter the classic dispatch (its attend expects
            # the whole causal prefix resident).
            dec_slots = [s for s in dec_slots
                         if s not in self._residency.slots]
            if not dec_slots and not self._prefilling:
                return None
        a = self._mixed_batch_arrays(num_slots + self._mixed_budget)

        t = 0
        for slot in dec_slots:
            a["tokens"][t] = self._last_token[slot]
            a["token_slot"][t] = slot
            a["token_pos"][t] = self._lengths[slot]
            a["sample_src"][slot] = t
            a["feed_tokens"][slot] = self._last_token[slot]
            a["feed_active"][slot] = True
            a["seq_q_start"][slot] = t
            a["seq_q_len"][slot] = 1
            a["seq_pos_start"][slot] = self._lengths[slot]
            t += 1

        completing, chunk_take, t = self._fill_chunk_lanes(a, t)

        want_lp = any(self._slots[s].request.params.logprobs is not None
                      for s in dec_slots)
        want_lp = want_lp or any(
            st.request.params.logprobs is not None
            for _, st, _, _ in completing)
        lengths = np.array(self._lengths)
        tables = self._tables.copy()
        n_chunk = sum(take for _, take in chunk_take)
        self.metrics.mixed_batch_tokens.observe(t)
        if n_chunk:
            self.metrics.mixed_chunk_tokens_total.inc(n_chunk)
        # qmax mirrors the dispatcher: t_flat - b_lanes + 1.
        self._mixed_grid_counters(a["seq_pos_start"], a["seq_q_len"],
                                  self._mixed_budget + 1)
        self._emit("mixed", tables=tables, lengths=lengths, lp=want_lp,
                   **a)
        t0 = time.monotonic()
        args = (self.params, self._cache, self._sampling,
                jnp.asarray(a["tokens"]), jnp.asarray(a["token_slot"]),
                jnp.asarray(a["token_pos"]), jnp.asarray(tables),
                jnp.asarray(a["feed_tokens"]), jnp.asarray(a["feed_active"]),
                jnp.asarray(lengths), jnp.asarray(a["sample_src"]),
                jnp.asarray(a["seq_q_start"]), jnp.asarray(a["seq_q_len"]),
                jnp.asarray(a["seq_pos_start"]), jnp.asarray(a["ov_mask"]),
                jnp.asarray(a["ov_temp"]), jnp.asarray(a["ov_top_p"]),
                jnp.asarray(a["ov_top_k"]), jnp.asarray(a["ov_key"]),
                jnp.asarray(a["ov_bias_ids"]), jnp.asarray(a["ov_bias_vals"]),
                jnp.asarray(a["ov_sup"]), jnp.asarray(a["ov_min_until"]),
                jnp.asarray(a["ov_guide"]), jnp.asarray(a["ov_guide_row"]),
                self._guide_dev)
        lp_devs = None
        if want_lp:
            ids_dev, clps, lvals, lids, self._cache, self._sampling = \
                self._mixed_lp_fn(*args)
            lp_devs = (clps, lvals, lids)
        else:
            ids_dev, self._cache, self._sampling = self._mixed_fn(*args)
        return (dec_slots, completing, chunk_take, want_lp, ids_dev,
                lp_devs, t0)

    @_scoped("mixed")
    def _resolve_mixed(self, rec, exclude_s: float = 0.0) -> None:
        """Host-sync tail of a mixed dispatch: fan the decode tokens out,
        advance every prefilling sequence's position, and promote the
        sequences whose prompt completed (set_slot + registration — the
        same tail as the legacy final chunk, minus its extra sample_one
        dispatch)."""
        (dec_slots, completing, chunk_take, want_lp, ids_dev,
         lp_devs, t0) = rec
        self._faults.fire("resolve")
        t_wait = time.monotonic()
        ids = np.asarray(ids_dev)   # [B] — host sync point
        self.metrics.decode_resolve_wait_seconds_total.inc(
            time.monotonic() - t_wait, mode="sequential")
        if lp_devs is not None:
            clps = np.asarray(lp_devs[0])
            lvals = np.asarray(lp_devs[1])
            lids = np.asarray(lp_devs[2])
        dt = max(time.monotonic() - t0 - exclude_s, 1e-6)
        for slot in dec_slots:
            st = self._slots[slot]
            tok = int(ids[slot])
            n_lp = st.request.params.logprobs
            st.generated.append(tok)
            if want_lp and n_lp is not None:
                st.logprobs.append(self._lp_entry(
                    clps[slot], lvals[slot], lids[slot], n_lp))
            self._lengths[slot] += 1
            self._last_token[slot] = tok
            self.metrics.generation_tokens_total.inc(1)
            self.metrics.time_per_output_token_seconds.observe(dt)
            self.metrics.tpot_seconds.observe(
                dt, tier=self._slo.tier_of(st.request.params.priority))
            if (self._is_stop(st, tok)
                    or len(st.generated) >= st.request.params.max_tokens):
                self._finish(slot, self._finish_reason(st))
            else:
                delta = st.generated[st.num_emitted:]
                lp_delta = (st.logprobs[st.num_emitted:]
                            if n_lp is not None else None)
                st.num_emitted = len(st.generated)
                st.request.outputs.put(RequestOutput(
                    request_id=st.request.request_id, token_ids=delta,
                    num_prompt_tokens=st.num_prompt, logprobs=lp_delta))
        for slot, take in chunk_take:
            st = self._prefilling.get(slot)
            if st is not None:
                st.pos += take
        self._promote_completing(completing, ids, want_lp,
                                 lp_devs and (clps, lvals, lids))

    def _promote_completing(self, completing, ids, want_lp, lp_host) -> None:
        """Promote sequences whose prompt completed inside a mixed (or
        spec-mixed) batch: set_slot + registration — the same tail as the
        legacy final chunk, minus its extra sample_one dispatch."""
        for slot, st, gid, grow0 in completing:
            del self._prefilling[slot]
            p = st.request.params
            first = int(ids[slot])
            first_lp = None
            if want_lp and p.logprobs is not None and lp_host is not None:
                clps, lvals, lids = lp_host
                first_lp = self._lp_entry(clps[slot], lvals[slot],
                                          lids[slot], p.logprobs)
            grow1 = self.guides.next_row(grow0, first) if gid >= 0 else 0
            self._emit("set_slot", slot=slot, temperature=p.temperature,
                       top_p=p.top_p, top_k=p.top_k, seed=st.seed,
                       presence=p.presence_penalty,
                       frequency=p.frequency_penalty,
                       logit_bias=list(p.logit_bias),
                       min_tokens=p.min_tokens,
                       stop_ids=list(p.stop_token_ids),
                       ignore_eos=p.ignore_eos, num_prompt=len(st.ids),
                       guide=gid, guide_row=grow1)
            self._apply_set_slot(slot, p, jax.random.fold_in(st.key, 1),
                                 num_prompt=len(st.ids), guide=gid,
                                 guide_row=grow1)
            self._register_slot(st.request, slot, first, len(st.ids),
                                first_lp=first_lp, seed=st.seed)
            # Zero-cost harvest, as in the legacy chunk path: every full
            # prompt page is now written — register the digest chain so
            # later prompts share on device.
            self._register_prompt_pages(st.ids,
                                        self._slot_pages.get(slot, []),
                                        st.digests)

    # ------------------------------------------------------------------
    # Speculative decoding: draft+verify as a ragged mixed dispatch
    # ------------------------------------------------------------------

    @_scoped("spec")
    def _issue_spec_mixed(self):
        """Build and issue ONE spec-mixed dispatch: every decoding slot
        owns a fixed q_len=draft_len verify block (row 0 its last token —
        the draft's proposals are scattered into rows 1.. ON DEVICE), and
        prefill-chunk tokens ride the region after the blocks, so one
        program per iteration serves decode feeds + prefill chunks + spec
        verify.  ELIGIBLE slots advance 1..draft_len tokens by rejection
        sampling; disabled slots advance exactly one normally-sampled
        token (penalties/logprobs served); greedy slots are byte-exact vs
        the target-only mixed path, sampled slots exact in distribution.
        Returns the pending record for _resolve_spec_mixed."""
        DK = self.ecfg.draft_len
        self._mixed_abort_and_retire(rows=DK)
        if not self._slots and not self._prefilling:
            return None
        self._ensure_guides_uploaded()
        self._grow_slot_pages(DK)
        self._faults.fire("spec")
        num_slots = self.ecfg.num_slots
        spec_t = num_slots * DK
        a = self._mixed_batch_arrays(spec_t + self._mixed_budget)
        spec_enable = np.zeros((num_slots,), bool)

        dec_slots = list(self._slots.keys())
        for slot in dec_slots:
            st = self._slots[slot]
            r0 = slot * DK
            a["tokens"][r0] = self._last_token[slot]
            a["token_slot"][r0: r0 + DK] = slot
            a["token_pos"][r0: r0 + DK] = np.arange(
                self._lengths[slot], self._lengths[slot] + DK)
            a["sample_src"][slot] = r0
            a["feed_tokens"][slot] = self._last_token[slot]
            a["feed_active"][slot] = True
            a["seq_q_start"][slot] = r0
            a["seq_q_len"][slot] = DK
            a["seq_pos_start"][slot] = self._lengths[slot]
            spec_enable[slot] = st.spec_ok

        completing, chunk_take, t = self._fill_chunk_lanes(a, spec_t)

        want_lp = any(self._slots[s].request.params.logprobs is not None
                      for s in dec_slots)
        want_lp = want_lp or any(
            st.request.params.logprobs is not None
            for _, st, _, _ in completing)
        lengths = np.array(self._lengths)
        tables = self._tables.copy()
        n_chunk = sum(take for _, take in chunk_take)
        self.metrics.mixed_batch_tokens.observe(
            len(dec_slots) * DK + n_chunk)
        if n_chunk:
            self.metrics.mixed_chunk_tokens_total.inc(n_chunk)
        self._mixed_grid_counters(
            a["seq_pos_start"], a["seq_q_len"],
            spec_t + self._mixed_budget - num_slots + 1)
        self._emit("spec_mixed", tables=tables, lengths=lengths,
                   lp=want_lp, spec_enable=spec_enable.copy(), **a)
        t0 = time.monotonic()
        args = (self.params, self._draft_params, self._cache,
                self._draft_cache, self._sampling,
                jnp.asarray(a["tokens"]), jnp.asarray(a["token_slot"]),
                jnp.asarray(a["token_pos"]), jnp.asarray(tables),
                jnp.asarray(a["feed_tokens"]), jnp.asarray(a["feed_active"]),
                jnp.asarray(lengths), jnp.asarray(a["sample_src"]),
                jnp.asarray(a["seq_q_start"]), jnp.asarray(a["seq_q_len"]),
                jnp.asarray(a["seq_pos_start"]), jnp.asarray(spec_enable),
                jnp.asarray(a["ov_mask"]), jnp.asarray(a["ov_temp"]),
                jnp.asarray(a["ov_top_p"]), jnp.asarray(a["ov_top_k"]),
                jnp.asarray(a["ov_key"]), jnp.asarray(a["ov_bias_ids"]),
                jnp.asarray(a["ov_bias_vals"]), jnp.asarray(a["ov_sup"]),
                jnp.asarray(a["ov_min_until"]), jnp.asarray(a["ov_guide"]),
                jnp.asarray(a["ov_guide_row"]), self._guide_dev)
        lp_devs = None
        if want_lp:
            (out_dev, counts_dev, comp_dev, clps, lvals, lids, self._cache,
             self._draft_cache, self._sampling) = self._spec_mixed_lp_fn(
                 *args)
            lp_devs = (clps, lvals, lids)
        else:
            (out_dev, counts_dev, comp_dev, self._cache, self._draft_cache,
             self._sampling) = self._spec_mixed_fn(*args)
        return (dec_slots, completing, chunk_take, want_lp, out_dev,
                counts_dev, comp_dev, lp_devs, t0)

    @_scoped("spec")
    def _resolve_spec_mixed(self, rec, exclude_s: float = 0.0) -> None:
        """Host-sync tail of a spec-mixed dispatch: fan each decoding
        slot's accepted block out (1..draft_len tokens), account the
        acceptance metrics, advance the prefilling sequences, and promote
        completed prompts — the same tail shape as _resolve_mixed."""
        (dec_slots, completing, chunk_take, want_lp, out_dev, counts_dev,
         comp_dev, lp_devs, t0) = rec
        self._faults.fire("resolve")
        DK = self.ecfg.draft_len
        t_wait = time.monotonic()
        out = np.asarray(out_dev)        # [B, DK] — host sync point
        counts = np.asarray(counts_dev)  # [B]
        comp = np.asarray(comp_dev)      # [B]
        self.metrics.decode_resolve_wait_seconds_total.inc(
            time.monotonic() - t_wait, mode="sequential")
        lp_host = None
        if lp_devs is not None:
            lp_host = (np.asarray(lp_devs[0]), np.asarray(lp_devs[1]),
                       np.asarray(lp_devs[2]))
        dt = max(time.monotonic() - t0 - exclude_s, 1e-6)
        n_spec = accepted = 0
        for slot in dec_slots:
            st = self._slots[slot]
            c = max(1, min(int(counts[slot]), DK))
            if st.spec_ok:
                n_spec += 1
                accepted += c - 1
                self.metrics.spec_decode_accepted_length.observe(c)
            lp_rows = None
            if want_lp and st.request.params.logprobs is not None:
                # Disabled lp slots advance exactly one token (c == 1);
                # the entry comes from the position-0 verifier logits.
                lp_rows = ([lp_host[0][slot]], [lp_host[1][slot]],
                           [lp_host[2][slot]])
            self._fanout_decode_tokens(
                slot, [int(x) for x in out[slot][:c]], lp_rows, dt)
        if n_spec:
            self.metrics.spec_decode_proposed_tokens_total.inc(
                (DK - 1) * n_spec)
            self.metrics.spec_decode_accepted_tokens_total.inc(accepted)
            self._spec_proposed += (DK - 1) * n_spec
            self._spec_accepted += accepted
            self.metrics.spec_decode_acceptance_rate.set(
                self._spec_accepted / max(self._spec_proposed, 1))
        for slot, take in chunk_take:
            st = self._prefilling.get(slot)
            if st is not None:
                st.pos += take
        self._promote_completing(completing, comp, want_lp, lp_host)

    # ------------------------------------------------------------------
    # Stop handling
    # ------------------------------------------------------------------

    def _is_stop(self, st: _Slot, tok: int) -> bool:
        p = st.request.params
        if p.ignore_eos:
            return tok in p.stop_token_ids
        return tok in self.cfg.eos_token_ids or tok in self.tokenizer.eos_token_ids \
            or tok in p.stop_token_ids

    def _finish_reason(self, st: _Slot) -> str:
        if len(st.generated) >= st.request.params.max_tokens:
            return "length"
        return "stop"

    def _check_finished(self, slot: int) -> bool:
        st = self._slots[slot]
        tok = st.generated[-1]
        if self._is_stop(st, tok) or len(st.generated) >= st.request.params.max_tokens:
            self._finish(slot, self._finish_reason(st))
            return True
        return False

    def _release_slot_pages(self, slot: int) -> None:
        """Paged layout: return the slot's page references and park it at
        the write-drop sentinel (its garbage dispatch rows must never land
        in pages another slot may now own).  Index-retained prefix pages
        live on for future hits."""
        if not self._paged:
            return
        if self._residency is not None:
            # Engaged slots: slot_pages already lists staging + hot tail
            # (the decref below returns them); the host store just drops.
            self._residency.release(slot)
        pages = self._slot_pages.pop(slot, [])
        if pages:
            self._alloc.decref(pages)
        self._lengths[slot] = self._park_sentinel()

    def _finish(self, slot: int, reason: str) -> None:
        st = self._slots.pop(slot)
        self._release_slot_pages(slot)
        self._free.append(slot)
        self._unpin_guide(st.request)
        p = st.request.params
        if (p.presence_penalty or p.frequency_penalty or p.logit_bias
                or p.min_tokens or p.guide is not None):
            # Re-arm shaped()'s lax.cond fast paths: a stale penalty/bias/
            # suppression row on a FREE slot would keep every future
            # dispatch paying the shaping reads.
            self._emit("clear_penalties", slot=slot)
            self._sampling = self._clear_pen_fn(self._sampling,
                                                jnp.asarray(slot, jnp.int32))
        gen = st.generated
        # The stop token itself is not part of the output text.
        if reason == "stop" and gen and self._is_stop(st, gen[-1]):
            final_ids = gen[:-1]
        else:
            final_ids = gen[: st.request.params.max_tokens]
        delta = final_ids[st.num_emitted:]
        lp_delta = None
        if p.logprobs is not None and st.logprobs:
            lp_delta = st.logprobs[st.num_emitted: len(final_ids)]
        st.request.outputs.put(RequestOutput(
            request_id=st.request.request_id,
            token_ids=delta,
            logprobs=lp_delta,
            finished=True, finish_reason=reason,
            num_prompt_tokens=st.num_prompt,
            num_generated_tokens=len(final_ids)))
        now = time.monotonic()
        self.metrics.e2e_request_latency_seconds.observe(now - st.request.arrival_time)
        self.metrics.request_success_total.inc(reason=reason)
        self.metrics.num_requests_running.set(len(self._slots))
        self.trace.evt(st.request.request_id, "finish", "I", reason)
