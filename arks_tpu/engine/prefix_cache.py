"""Prefix KV cache: hash-chained block reuse for shared prompt prefixes.

The reference delegates prefix caching to its runtime containers (vLLM
automatic prefix caching / SGLang radix cache; the reference itself only
surfaces the router's ``--policy cache_aware`` flag —
/root/reference/internal/controller/arksdisaggregatedapplication_controller.go
:1630-1670).  TPU-native rebuild:

- Prompts are split into fixed **blocks** of ``block_tokens`` (= the
  engine's chunked-prefill size, so a reused prefix lands exactly on a
  chunk boundary and the tail continues through the existing chunked-
  prefill program — no new compiled code paths).
- Each block is keyed by a digest of the ENTIRE token prefix up to the
  block's end (hash-chaining by content, like vLLM's block hash), so two
  prompts share cache entries exactly as far as their tokens agree.
- Values are host-resident time-major KV slices ``[L, 1, C, Hkv, D]`` —
  precisely what ``transformer.insert`` consumes.  Host RAM is the right
  home on TPU: HBM is the scarce resource, the PCIe/ICI copy for a hit
  costs far less than recomputing the prefill FLOPs, and eviction never
  fights the decode cache for device memory.
- LRU eviction by byte budget; a block is one entry, shared by every
  prompt whose prefix contains it.

Thread-safety: the engine calls match/get/put from the engine thread only;
a lock still guards the map because the disaggregated prefill path may run
on server threads.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from arks_tpu.engine.paged import chain_digests, iter_chain_digests


class PrefixKVCache:
    def __init__(self, block_tokens: int, capacity_bytes: int) -> None:
        if block_tokens <= 0:
            raise ValueError("block_tokens must be positive")
        self.block = block_tokens
        self.capacity = capacity_bytes
        self._lock = threading.Lock()
        # digest -> (k_block, v_block), LRU order (oldest first).
        self._blocks: "OrderedDict[bytes, tuple[np.ndarray, np.ndarray]]" = OrderedDict()
        self._bytes = 0
        # Stats (read by EngineMetrics).
        self.hit_tokens = 0
        self.query_tokens = 0

    # -- keys ----------------------------------------------------------

    def _keys(self, ids, nblocks: int) -> list[bytes]:
        """Chained digests for blocks 1..nblocks (digest j covers
        ids[: j*block]) — the ONE hash-chaining implementation, shared
        with the paged allocator's prefix index (engine.paged)."""
        return chain_digests(ids, self.block, nblocks)

    # -- read ----------------------------------------------------------

    def match(self, ids) -> int:
        """Longest cached prefix of ``ids`` in tokens (multiple of block;
        0 = miss).  Does not touch LRU order or stats.  Digests LAZILY and
        stops at the first missing block — a first-block miss on a long
        prompt costs ONE SHA1, not len(ids)/block of them."""
        if len(ids) < self.block:
            return 0
        plen = 0
        for key in iter_chain_digests(ids, self.block):
            with self._lock:
                hit = key in self._blocks
            if not hit:
                break
            plen += self.block
        return plen

    def get(self, ids, plen: int) -> tuple[np.ndarray, np.ndarray]:
        """The cached KV for ids[:plen] as one time-major pair
        ``[L, 1, plen, Hkv, D]``.  plen must be a match() result."""
        nblocks = plen // self.block
        keys = self._keys(ids, nblocks)
        with self._lock:
            ks, vs = [], []
            for key in keys:
                k, v = self._blocks[key]
                self._blocks.move_to_end(key)
                ks.append(k)
                vs.append(v)
        return np.concatenate(ks, axis=2), np.concatenate(vs, axis=2)

    # -- write ---------------------------------------------------------

    def missing_blocks(self, ids, length: int) -> list[int]:
        """Indices of full blocks of ids[:length] not yet cached — lets the
        engine skip the device→host KV transfer entirely on a full hit."""
        nblocks = length // self.block
        keys = self._keys(ids, nblocks)
        with self._lock:
            return [j for j, key in enumerate(keys) if key not in self._blocks]

    def put(self, ids, k: np.ndarray, v: np.ndarray, length: int) -> None:
        """Store every full block of ids[:length] from time-major KV
        ``[L, 1, T, Hkv, D]`` (T >= length)."""
        nblocks = length // self.block
        if nblocks == 0:
            return
        keys = self._keys(ids, nblocks)
        with self._lock:
            for j, key in enumerate(keys):
                if key in self._blocks:
                    self._blocks.move_to_end(key)
                    continue
                kb = np.ascontiguousarray(k[:, :, j * self.block:(j + 1) * self.block])
                vb = np.ascontiguousarray(v[:, :, j * self.block:(j + 1) * self.block])
                self._blocks[key] = (kb, vb)
                self._bytes += kb.nbytes + vb.nbytes
            while self._bytes > self.capacity and self._blocks:
                _, (kb, vb) = self._blocks.popitem(last=False)
                self._bytes -= kb.nbytes + vb.nbytes

    def clear(self) -> None:
        """Drop every cached block (fault recovery's blanket fallback: a
        fault storm that survives per-request quarantine may be poisoned
        cached KV itself — the deep clean removes that possibility before
        serving resumes)."""
        with self._lock:
            self._blocks.clear()
            self._bytes = 0

    # -- stats ---------------------------------------------------------

    def record_query(self, num_tokens: int, hit: int) -> None:
        self.query_tokens += num_tokens
        self.hit_tokens += hit

    @property
    def bytes_used(self) -> int:
        return self._bytes

    @property
    def hit_rate(self) -> float:
        return self.hit_tokens / self.query_tokens if self.query_tokens else 0.0


class HostPrefixTier:
    """Tier-1 host-RAM block store for the PAGED engine's prefix cache.

    Tier 0 is the page allocator's on-device index (engine.paged): hits
    there cost nothing — the pages are already in HBM.  This class is the
    spill target behind it: when the device index evicts a page under
    pool pressure, the engine gathers the page's pool-native KV
    (``[L, Hkv, page, D]`` per array, int8 + per-token scales when the
    pool is kv-quantized) and parks it HERE, keyed by the SAME chain
    digest (paged.iter_chain_digests).  A later prompt whose prefix fell
    out of HBM restores the blocks with one H2D scatter instead of
    re-prefilling them.

    Blocks are byte-exact copies of pool pages, so a restore reproduces
    the device state the original prefill wrote — which is what keeps
    token streams byte-identical with the tier enabled or disabled.

    LRU eviction by byte budget (``ARKS_PREFIX_HOST_MB``).  A lock guards
    the map: the engine thread spills/restores, and the disaggregated
    decode path publishes transferred prefixes from server threads.
    """

    def __init__(self, page_tokens: int, capacity_bytes: int) -> None:
        if page_tokens <= 0:
            raise ValueError("page_tokens must be positive")
        self.page = page_tokens
        self.capacity = capacity_bytes
        # Bytes carved out of ``capacity`` by non-prefix tenants (the
        # preempt SwapStore).  The LRU eviction loop honors
        # ``capacity - reserved``: prefix blocks evict around reserved
        # state, reserved state is never LRU-evicted.
        self.reserved = 0
        self._lock = threading.Lock()
        # digest -> block dict {"k","v"[,"k_scale","v_scale"]}, LRU order
        # (oldest first).
        self._blocks: "OrderedDict[bytes, dict]" = OrderedDict()
        self._bytes = 0
        # Membership version for the routing sketch: bumped on every
        # insert/evict/clear (not on LRU touches), so a cached sketch
        # build stays valid exactly as long as membership does.
        self.version = 0
        # Stats (mirrored into EngineMetrics by the engine).
        self.spilled_blocks = 0
        self.restored_blocks = 0

    @staticmethod
    def _block_bytes(block: dict) -> int:
        return sum(a.nbytes for a in block.values() if a is not None)

    def has(self, digest: bytes) -> bool:
        with self._lock:
            return digest in self._blocks

    def put(self, digest: bytes, block: dict) -> bool:
        """Store one pool-native page block (no-op if present; LRU-touches
        it instead).  Returns True when the block was newly stored."""
        block = {k: v for k, v in block.items() if v is not None}
        with self._lock:
            if digest in self._blocks:
                self._blocks.move_to_end(digest)
                return False
            self._blocks[digest] = block
            self._bytes += self._block_bytes(block)
            self.spilled_blocks += 1
            self.version += 1
            self._evict_to_budget()
            return digest in self._blocks

    def _evict_to_budget(self) -> None:
        """LRU-evict prefix blocks past the effective byte budget
        (``capacity - reserved``).  Caller holds the lock."""
        budget = max(self.capacity - self.reserved, 0)
        while self._bytes > budget and self._blocks:
            _, old = self._blocks.popitem(last=False)
            self._bytes -= self._block_bytes(old)
            self.version += 1

    def match_blocks(self, digests: list[bytes], start: int) -> list[dict]:
        """The longest run of consecutively-cached blocks for
        ``digests[start:]``, LRU-touched, under ONE lock hold (a racing
        disagg publish could otherwise evict between a probe and the
        read).  The returned dicts are the stored arrays — callers must
        not mutate them."""
        out: list[dict] = []
        with self._lock:
            for d in digests[start:]:
                blk = self._blocks.get(d)
                if blk is None:
                    break
                self._blocks.move_to_end(d)
                out.append(blk)
        return out

    def clear(self) -> None:
        """Drop every block (fault recovery's blanket deep clean — spilled
        KV may itself be the poison)."""
        with self._lock:
            self._blocks.clear()
            self._bytes = 0
            self.version += 1

    def snapshot(self) -> tuple[list[bytes], int]:
        """Resident digests (LRU order, oldest first) plus the membership
        version — the tier-1 input to the routing sketch."""
        with self._lock:
            return list(self._blocks), self.version

    @property
    def bytes_used(self) -> int:
        return self._bytes

    @property
    def num_blocks(self) -> int:
        with self._lock:
            return len(self._blocks)


class SwapStore:
    """Host-RAM store for PREEMPTED requests' full decode state.

    When an SLO-tier request seizes a running slot (ARKS_PREEMPT), the
    victim's decode state — its pool-native KV page blocks plus the
    sampler-row snapshot (PRNG key, penalty counts, DFA row) — parks
    here, keyed by request id.  Unlike ``HostPrefixTier`` blocks these
    entries are not content-addressed and are NEVER LRU-evicted: a
    swapped-out request must stay resumable until it is resumed or
    aborted.  Instead the store shares the host tier's byte budget by
    accounting its bytes as ``tier.reserved`` — prefix blocks LRU-evict
    around the swap state, and when even the whole budget cannot hold a
    new entry ``put`` refuses and the engine falls back to replay-mode
    preemption (re-queue + deterministic re-execution).

    Entry layout (engine-authored, read back verbatim on resume)::

        {"blocks": [page block dicts], "key": np.uint32[2],
         "counts": np.int32[V], "guide_row": int}

    The host tier's lock guards the budget handshake; the map itself is
    engine-thread only.
    """

    def __init__(self, tier: HostPrefixTier) -> None:
        self._tier = tier
        # rid -> (entry, accounted bytes)
        self._entries: dict[str, tuple[dict, int]] = {}

    @staticmethod
    def _entry_bytes(entry: dict) -> int:
        n = 0
        for blk in entry.get("blocks", ()):
            n += sum(a.nbytes for a in blk.values() if a is not None)
        for key in ("key", "counts"):
            a = entry.get(key)
            if a is not None and hasattr(a, "nbytes"):
                n += a.nbytes
        return n

    def put(self, rid: str, entry: dict) -> bool:
        """Reserve budget and store one victim's decode state.  Returns
        False (storing nothing) when the tier's whole capacity cannot
        cover existing reservations plus this entry."""
        need = self._entry_bytes(entry)
        t = self._tier
        with t._lock:
            if rid in self._entries:
                return True
            if t.reserved + need > t.capacity:
                return False
            t.reserved += need
            t._evict_to_budget()
        self._entries[rid] = (entry, need)
        return True

    def pop(self, rid: str) -> dict | None:
        """Remove and return an entry, releasing its reserved bytes."""
        rec = self._entries.pop(rid, None)
        if rec is None:
            return None
        entry, need = rec
        t = self._tier
        with t._lock:
            t.reserved = max(t.reserved - need, 0)
        return entry

    def discard(self, rid: str) -> bool:
        """Drop an entry if present (abort-while-swapped-out: the host
        bytes must come back).  Returns True when something was freed."""
        return self.pop(rid) is not None

    def clear(self) -> None:
        """Drop every entry (blanket-abort deep clean)."""
        for rid in list(self._entries):
            self.pop(rid)

    @property
    def bytes_used(self) -> int:
        return sum(need for _, need in self._entries.values())

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, rid: str) -> bool:
        return rid in self._entries
