"""Prefix KV cache: hash-chained block reuse for shared prompt prefixes.

The reference delegates prefix caching to its runtime containers (vLLM
automatic prefix caching / SGLang radix cache; the reference itself only
surfaces the router's ``--policy cache_aware`` flag —
/root/reference/internal/controller/arksdisaggregatedapplication_controller.go
:1630-1670).  TPU-native rebuild:

- Prompts are split into fixed **blocks** of ``block_tokens`` (= the
  engine's chunked-prefill size, so a reused prefix lands exactly on a
  chunk boundary and the tail continues through the existing chunked-
  prefill program — no new compiled code paths).
- Each block is keyed by a digest of the ENTIRE token prefix up to the
  block's end (hash-chaining by content, like vLLM's block hash), so two
  prompts share cache entries exactly as far as their tokens agree.
- Values are host-resident time-major KV slices ``[L, 1, C, Hkv, D]`` —
  precisely what ``transformer.insert`` consumes.  Host RAM is the right
  home on TPU: HBM is the scarce resource, the PCIe/ICI copy for a hit
  costs far less than recomputing the prefill FLOPs, and eviction never
  fights the decode cache for device memory.
- LRU eviction by byte budget; a block is one entry, shared by every
  prompt whose prefix contains it.

Thread-safety: the engine calls match/get/put from the engine thread only;
a lock still guards the map because the disaggregated prefill path may run
on server threads.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from arks_tpu.engine.paged import chain_digests, iter_chain_digests


class PrefixKVCache:
    def __init__(self, block_tokens: int, capacity_bytes: int) -> None:
        if block_tokens <= 0:
            raise ValueError("block_tokens must be positive")
        self.block = block_tokens
        self.capacity = capacity_bytes
        self._lock = threading.Lock()
        # digest -> (k_block, v_block), LRU order (oldest first).
        self._blocks: "OrderedDict[bytes, tuple[np.ndarray, np.ndarray]]" = OrderedDict()
        self._bytes = 0
        # Stats (read by EngineMetrics).
        self.hit_tokens = 0
        self.query_tokens = 0

    # -- keys ----------------------------------------------------------

    def _keys(self, ids, nblocks: int) -> list[bytes]:
        """Chained digests for blocks 1..nblocks (digest j covers
        ids[: j*block]) — the ONE hash-chaining implementation, shared
        with the paged allocator's prefix index (engine.paged)."""
        return chain_digests(ids, self.block, nblocks)

    # -- read ----------------------------------------------------------

    def match(self, ids) -> int:
        """Longest cached prefix of ``ids`` in tokens (multiple of block;
        0 = miss).  Does not touch LRU order or stats.  Digests LAZILY and
        stops at the first missing block — a first-block miss on a long
        prompt costs ONE SHA1, not len(ids)/block of them."""
        if len(ids) < self.block:
            return 0
        plen = 0
        for key in iter_chain_digests(ids, self.block):
            with self._lock:
                hit = key in self._blocks
            if not hit:
                break
            plen += self.block
        return plen

    def get(self, ids, plen: int) -> tuple[np.ndarray, np.ndarray]:
        """The cached KV for ids[:plen] as one time-major pair
        ``[L, 1, plen, Hkv, D]``.  plen must be a match() result."""
        nblocks = plen // self.block
        keys = self._keys(ids, nblocks)
        with self._lock:
            ks, vs = [], []
            for key in keys:
                k, v = self._blocks[key]
                self._blocks.move_to_end(key)
                ks.append(k)
                vs.append(v)
        return np.concatenate(ks, axis=2), np.concatenate(vs, axis=2)

    # -- write ---------------------------------------------------------

    def missing_blocks(self, ids, length: int) -> list[int]:
        """Indices of full blocks of ids[:length] not yet cached — lets the
        engine skip the device→host KV transfer entirely on a full hit."""
        nblocks = length // self.block
        keys = self._keys(ids, nblocks)
        with self._lock:
            return [j for j, key in enumerate(keys) if key not in self._blocks]

    def put(self, ids, k: np.ndarray, v: np.ndarray, length: int) -> None:
        """Store every full block of ids[:length] from time-major KV
        ``[L, 1, T, Hkv, D]`` (T >= length)."""
        nblocks = length // self.block
        if nblocks == 0:
            return
        keys = self._keys(ids, nblocks)
        with self._lock:
            for j, key in enumerate(keys):
                if key in self._blocks:
                    self._blocks.move_to_end(key)
                    continue
                kb = np.ascontiguousarray(k[:, :, j * self.block:(j + 1) * self.block])
                vb = np.ascontiguousarray(v[:, :, j * self.block:(j + 1) * self.block])
                self._blocks[key] = (kb, vb)
                self._bytes += kb.nbytes + vb.nbytes
            while self._bytes > self.capacity and self._blocks:
                _, (kb, vb) = self._blocks.popitem(last=False)
                self._bytes -= kb.nbytes + vb.nbytes

    def clear(self) -> None:
        """Drop every cached block (fault recovery's blanket fallback: a
        fault storm that survives per-request quarantine may be poisoned
        cached KV itself — the deep clean removes that possibility before
        serving resumes)."""
        with self._lock:
            self._blocks.clear()
            self._bytes = 0

    # -- stats ---------------------------------------------------------

    def record_query(self, num_tokens: int, hit: int) -> None:
        self.query_tokens += num_tokens
        self.hit_tokens += hit

    @property
    def bytes_used(self) -> int:
        return self._bytes

    @property
    def hit_rate(self) -> float:
        return self.hit_tokens / self.query_tokens if self.query_tokens else 0.0


class HostPrefixTier:
    """Tier-1 host-RAM block store for the PAGED engine's prefix cache.

    Tier 0 is the page allocator's on-device index (engine.paged): hits
    there cost nothing — the pages are already in HBM.  This class is the
    spill target behind it: when the device index evicts a page under
    pool pressure, the engine gathers the page's pool-native KV
    (``[L, Hkv, page, D]`` per array, int8 + per-token scales when the
    pool is kv-quantized) and parks it HERE, keyed by the SAME chain
    digest (paged.iter_chain_digests).  A later prompt whose prefix fell
    out of HBM restores the blocks with one H2D scatter instead of
    re-prefilling them.

    Blocks are byte-exact copies of pool pages, so a restore reproduces
    the device state the original prefill wrote — which is what keeps
    token streams byte-identical with the tier enabled or disabled.

    LRU eviction by byte budget (``ARKS_PREFIX_HOST_MB``).  A lock guards
    the map: the engine thread spills/restores, and the disaggregated
    decode path publishes transferred prefixes from server threads.
    """

    def __init__(self, page_tokens: int, capacity_bytes: int) -> None:
        if page_tokens <= 0:
            raise ValueError("page_tokens must be positive")
        self.page = page_tokens
        self.capacity = capacity_bytes
        # Eviction sink: called as on_evict(digest, block) for every block
        # LRU-evicted past the byte budget — the engine points this at the
        # tier-2 disk spill queue so a block falling out of host RAM gets
        # a chance to survive on disk.  Invoked AFTER the tier lock is
        # released (the callback may take other locks / touch queues).
        self.on_evict = None
        # Bytes carved out of ``capacity`` by non-prefix tenants (the
        # preempt SwapStore).  The LRU eviction loop honors
        # ``capacity - reserved``: prefix blocks evict around reserved
        # state, reserved state is never LRU-evicted.
        self.reserved = 0
        self._lock = threading.Lock()
        # digest -> block dict {"k","v"[,"k_scale","v_scale"]}, LRU order
        # (oldest first).
        self._blocks: "OrderedDict[bytes, dict]" = OrderedDict()
        self._bytes = 0
        # Membership version for the routing sketch: bumped on every
        # insert/evict/clear (not on LRU touches), so a cached sketch
        # build stays valid exactly as long as membership does.
        self.version = 0
        # Stats (mirrored into EngineMetrics by the engine).
        self.spilled_blocks = 0
        self.restored_blocks = 0

    @staticmethod
    def _block_bytes(block: dict) -> int:
        return sum(a.nbytes for a in block.values() if a is not None)

    def has(self, digest: bytes) -> bool:
        with self._lock:
            return digest in self._blocks

    def put(self, digest: bytes, block: dict) -> bool:
        """Store one pool-native page block (no-op if present; LRU-touches
        it instead).  Returns True when the block was newly stored."""
        block = {k: v for k, v in block.items() if v is not None}
        with self._lock:
            if digest in self._blocks:
                self._blocks.move_to_end(digest)
                return False
            self._blocks[digest] = block
            self._bytes += self._block_bytes(block)
            self.spilled_blocks += 1
            self.version += 1
            evicted = self._evict_to_budget()
            stored = digest in self._blocks
        self._notify_evicted(evicted)
        return stored

    def _evict_to_budget(self) -> list[tuple[bytes, dict]]:
        """LRU-evict prefix blocks past the effective byte budget
        (``capacity - reserved``).  Caller holds the lock; the evicted
        (digest, block) pairs are returned so the caller can hand them to
        ``on_evict`` once the lock is dropped."""
        budget = max(self.capacity - self.reserved, 0)
        evicted: list[tuple[bytes, dict]] = []
        while self._bytes > budget and self._blocks:
            d, old = self._blocks.popitem(last=False)
            self._bytes -= self._block_bytes(old)
            self.version += 1
            evicted.append((d, old))
        return evicted

    def _notify_evicted(self, evicted: list[tuple[bytes, dict]]) -> None:
        """Fan evictees out to ``on_evict`` outside the tier lock."""
        cb = self.on_evict
        if cb is None:
            return
        for d, blk in evicted:
            cb(d, blk)

    def match_blocks(self, digests: list[bytes], start: int) -> list[dict]:
        """The longest run of consecutively-cached blocks for
        ``digests[start:]``, LRU-touched, under ONE lock hold (a racing
        disagg publish could otherwise evict between a probe and the
        read).  The returned dicts are the stored arrays — callers must
        not mutate them."""
        out: list[dict] = []
        with self._lock:
            for d in digests[start:]:
                blk = self._blocks.get(d)
                if blk is None:
                    break
                self._blocks.move_to_end(d)
                out.append(blk)
        return out

    def peek(self, digest: bytes) -> dict | None:
        """The stored block WITHOUT an LRU touch — the peer block-serving
        path reads through here, and a remote replica's fetch must not
        distort this replica's own recency ordering."""
        with self._lock:
            return self._blocks.get(digest)

    def clear(self) -> None:
        """Drop every block (fault recovery's blanket deep clean — spilled
        KV may itself be the poison)."""
        with self._lock:
            self._blocks.clear()
            self._bytes = 0
            self.version += 1

    def snapshot(self) -> tuple[list[bytes], int]:
        """Resident digests (LRU order, oldest first) plus the membership
        version — the tier-1 input to the routing sketch."""
        with self._lock:
            return list(self._blocks), self.version

    @property
    def bytes_used(self) -> int:
        return self._bytes

    @property
    def num_blocks(self) -> int:
        with self._lock:
            return len(self._blocks)


class DiskPrefixTier:
    """Tier-2 local-disk block store behind the host tier.

    Same chain-digest keys, same pool-native page blocks (int8/int4 +
    scales) as ``HostPrefixTier`` — serialized one-file-per-block in the
    kv_transfer AKV1 format, so a spill → restore round trip stays
    bit-exact by construction and the same bytes can be served verbatim
    to a fetching peer.  The point of the tier is durability: warm
    prefixes survive an engine restart because the store re-indexes the
    directory on boot.

    Layout safety: chain digests are content-only (token ids), NOT keyed
    by model or pool geometry, so a directory written under one pool
    layout must never be served under another.  Every file's AKV1 meta
    carries the pool layout signature digest (``epoch``), and a
    ``manifest.json`` stamps the directory; a mismatched manifest on boot
    wipes the directory, and a mismatched per-file epoch on read is
    rejected (defense in depth — a crashed writer from a previous layout
    may have left files behind the manifest's back).

    Crash safety: writes go tmp + fsync + rename (a torn write leaves a
    ``.tmp`` orphan, never a half-block under a valid name); corrupt or
    truncated files are swallowed on read, deleted, and counted in
    ``corrupt_blocks`` rather than poisoning a restore.

    Threading: the in-memory index (digest → file size, LRU order) is
    lock-guarded and cheap — ``match_digests``/``has`` are safe from the
    engine thread.  File IO (``get``/``put``) is meant for the spill
    writer / fetch worker / server threads, never the step loop.
    """

    SUFFIX = ".akv"
    MANIFEST = "manifest.json"
    FORMAT = 1

    def __init__(self, page_tokens: int, capacity_bytes: int,
                 directory: str, epoch: str) -> None:
        if page_tokens <= 0:
            raise ValueError("page_tokens must be positive")
        import os
        self.page = page_tokens
        self.capacity = capacity_bytes
        self.epoch = epoch
        self.dir = directory
        self._lock = threading.Lock()
        # digest -> file size in bytes, LRU order (oldest first).
        self._index: "OrderedDict[bytes, int]" = OrderedDict()
        self._bytes = 0
        self.version = 0
        # Stats (mirrored into EngineMetrics by the engine).
        self.spilled_blocks = 0
        self.restored_blocks = 0
        self.evicted_blocks = 0
        self.corrupt_blocks = 0
        os.makedirs(self.dir, exist_ok=True)
        self._boot_scan()

    # -- paths ---------------------------------------------------------

    def _path(self, digest: bytes) -> str:
        import os
        return os.path.join(self.dir, digest.hex() + self.SUFFIX)

    # -- boot ----------------------------------------------------------

    def _boot_scan(self) -> None:
        """Adopt (or wipe) whatever a previous process left behind.  A
        manifest from a different pool layout means every block in the
        directory was written for other bytes-per-page geometry: delete
        them all rather than serving one as a hit."""
        import json
        import os
        mpath = os.path.join(self.dir, self.MANIFEST)
        stale = False
        try:
            with open(mpath, "r", encoding="utf-8") as f:
                m = json.load(f)
            stale = (m.get("epoch") != self.epoch
                     or m.get("format") != self.FORMAT)
        except FileNotFoundError:
            stale = False   # fresh directory: nothing to distrust
        except Exception as e:
            from arks_tpu.engine import faults as faults_mod
            faults_mod.swallowed("disk_tier.manifest", e)
            stale = True
        for name in sorted(os.listdir(self.dir)):
            path = os.path.join(self.dir, name)
            if name.endswith(".tmp"):
                # Torn write from a crashed spill: never adopted.
                self._unlink(path)
                continue
            if not name.endswith(self.SUFFIX):
                continue
            if stale:
                self._unlink(path)
                continue
            try:
                digest = bytes.fromhex(name[:-len(self.SUFFIX)])
                size = os.path.getsize(path)
            except (ValueError, OSError) as e:
                from arks_tpu.engine import faults as faults_mod
                faults_mod.swallowed("disk_tier.scan", e)
                self._unlink(path)
                continue
            self._index[digest] = size
            self._bytes += size
        tmp = mpath + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"epoch": self.epoch, "format": self.FORMAT}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, mpath)
        with self._lock:
            self._evict_to_budget()

    @staticmethod
    def _unlink(path: str) -> None:
        import os
        try:
            os.unlink(path)
        except OSError:
            pass

    # -- membership (engine-thread safe: index only, no file IO) -------

    def has(self, digest: bytes) -> bool:
        with self._lock:
            return digest in self._index

    def match_digests(self, digests: list[bytes], start: int) -> list[bytes]:
        """The longest run of consecutively-indexed digests from
        ``digests[start:]`` — a pure in-memory probe (admission runs on
        the engine thread; the file reads happen later, off-thread).
        LRU-touches the hits so a hot prefix outlives churn."""
        out: list[bytes] = []
        with self._lock:
            for d in digests[start:]:
                if d not in self._index:
                    break
                self._index.move_to_end(d)
                out.append(d)
        return out

    def snapshot(self) -> tuple[list[bytes], int]:
        """Resident digests + membership version (tier-2 sketch input)."""
        with self._lock:
            return list(self._index), self.version

    # -- file IO (worker / server threads) -----------------------------

    def put(self, digest: bytes, block: dict) -> bool:
        """Persist one block (tmp + fsync + rename).  Returns True when
        newly stored.  IO failure is best-effort: swallowed, indexed as
        absent."""
        import os

        from arks_tpu.engine import faults as faults_mod
        from arks_tpu.engine import kv_transfer
        with self._lock:
            if digest in self._index:
                self._index.move_to_end(digest)
                return False
        buf = kv_transfer.pack_block(digest, self.epoch, block)
        path = self._path(digest)
        tmp = path + f".{os.getpid()}.tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(buf)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError as e:
            faults_mod.swallowed("disk_tier.put", e)
            self._unlink(tmp)
            return False
        with self._lock:
            if digest in self._index:   # raced another writer: fine
                return False
            self._index[digest] = len(buf)
            self._bytes += len(buf)
            self.spilled_blocks += 1
            self.version += 1
            evicted = self._evict_to_budget()
        for d in evicted:
            self._unlink(self._path(d))
        return True

    def get(self, digest: bytes) -> dict | None:
        """Read + validate one block.  A corrupt, truncated, or
        cross-epoch file is deleted and counted — the caller sees a miss,
        not an exception (the restore path re-prefills instead)."""
        from arks_tpu.engine import faults as faults_mod
        from arks_tpu.engine import kv_transfer
        path = self._path(digest)
        try:
            with open(path, "rb") as f:
                buf = f.read()
            blk = kv_transfer.unpack_block(buf, digest, self.epoch)
        except FileNotFoundError:
            self._drop(digest)
            return None
        except Exception as e:
            faults_mod.swallowed("disk_tier.get", e)
            self._unlink(path)
            with self._lock:
                self.corrupt_blocks += 1
            self._drop(digest)
            return None
        # Copy out of the frombuffer views so the mmap'd/read buffer is
        # released and callers own mutable, contiguous arrays.
        blk = {k: np.ascontiguousarray(v) for k, v in blk.items()}
        with self._lock:
            if digest in self._index:
                self._index.move_to_end(digest)
            self.restored_blocks += 1
        return blk

    def _drop(self, digest: bytes) -> None:
        with self._lock:
            size = self._index.pop(digest, None)
            if size is not None:
                self._bytes -= size
                self.version += 1

    def _evict_to_budget(self) -> list[bytes]:
        """LRU-evict past the byte budget.  Caller holds the lock; the
        evicted digests are returned for out-of-lock file deletion."""
        evicted: list[bytes] = []
        while self._bytes > self.capacity and self._index:
            d, size = self._index.popitem(last=False)
            self._bytes -= size
            self.version += 1
            self.evicted_blocks += 1
            evicted.append(d)
        return evicted

    def clear(self) -> None:
        """Drop every block, index AND files (blanket-abort deep clean —
        a poisoned disk tier must not resurrect on the next boot)."""
        with self._lock:
            digests = list(self._index)
            self._index.clear()
            self._bytes = 0
            self.version += 1
        for d in digests:
            self._unlink(self._path(d))

    @property
    def bytes_used(self) -> int:
        return self._bytes

    @property
    def num_blocks(self) -> int:
        with self._lock:
            return len(self._index)


class SwapStore:
    """Host-RAM store for PREEMPTED requests' full decode state.

    When an SLO-tier request seizes a running slot (ARKS_PREEMPT), the
    victim's decode state — its pool-native KV page blocks plus the
    sampler-row snapshot (PRNG key, penalty counts, DFA row) — parks
    here, keyed by request id.  Unlike ``HostPrefixTier`` blocks these
    entries are not content-addressed and are NEVER LRU-evicted: a
    swapped-out request must stay resumable until it is resumed or
    aborted.  Instead the store shares the host tier's byte budget by
    accounting its bytes as ``tier.reserved`` — prefix blocks LRU-evict
    around the swap state, and when even the whole budget cannot hold a
    new entry ``put`` refuses and the engine falls back to replay-mode
    preemption (re-queue + deterministic re-execution).

    Entry layout (engine-authored, read back verbatim on resume)::

        {"blocks": [page block dicts], "key": np.uint32[2],
         "counts": np.int32[V], "guide_row": int}

    The host tier's lock guards the budget handshake; the map itself is
    engine-thread only.
    """

    def __init__(self, tier: HostPrefixTier) -> None:
        self._tier = tier
        # rid -> (entry, accounted bytes)
        self._entries: dict[str, tuple[dict, int]] = {}

    @staticmethod
    def _entry_bytes(entry: dict) -> int:
        n = 0
        for blk in entry.get("blocks", ()):
            n += sum(a.nbytes for a in blk.values() if a is not None)
        for key in ("key", "counts"):
            a = entry.get(key)
            if a is not None and hasattr(a, "nbytes"):
                n += a.nbytes
        return n

    def put(self, rid: str, entry: dict) -> bool:
        """Reserve budget and store one victim's decode state.  Returns
        False (storing nothing) when the tier's whole capacity cannot
        cover existing reservations plus this entry."""
        need = self._entry_bytes(entry)
        t = self._tier
        with t._lock:
            if rid in self._entries:
                return True
            if t.reserved + need > t.capacity:
                return False
            t.reserved += need
            evicted = t._evict_to_budget()
        t._notify_evicted(evicted)
        self._entries[rid] = (entry, need)
        return True

    def pop(self, rid: str) -> dict | None:
        """Remove and return an entry, releasing its reserved bytes."""
        rec = self._entries.pop(rid, None)
        if rec is None:
            return None
        entry, need = rec
        t = self._tier
        with t._lock:
            t.reserved = max(t.reserved - need, 0)
        return entry

    def discard(self, rid: str) -> bool:
        """Drop an entry if present (abort-while-swapped-out: the host
        bytes must come back).  Returns True when something was freed."""
        return self.pop(rid) is not None

    def clear(self) -> None:
        """Drop every entry (blanket-abort deep clean)."""
        for rid in list(self._entries):
            self.pop(rid)

    @property
    def bytes_used(self) -> int:
        return sum(need for _, need in self._entries.values())

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, rid: str) -> bool:
        return rid in self._entries
