"""Guided decoding: grammars compiled to token-transition tables on device.

Reference parity: the runtimes the reference launches (vLLM/SGLang via
``internal/controller/arksapplication_controller.go:941-1014``) ship
JSON-mode and regex-constrained decoding ("guided decoding").  Their
recipe — a per-step host-side logits processor walking an automaton —
cannot work here: the engine's fused K-step decode loop never returns
logits to the host mid-dispatch.  The TPU-native shape is an
outlines-style token-level DFA carried as per-slot device state:

  1. The pattern (a byte-level regex; JSON mode is a depth-bounded JSON
     grammar rendered as one) compiles to a character DFA on the host.
  2. Every vocab token's byte string is walked through the char DFA from
     every DFA state at once (vectorized numpy), yielding the token-level
     transition matrix T[state, token] -> next state | dead.
  3. T factors through token EQUIVALENCE CLASSES (tokens with identical
     behavior across all states — the columns of T deduplicated), so the
     device carries only ``class_of_token [V]`` plus a small
     ``trans [states, classes]`` table instead of a [states, V] matrix:
     kilobytes-to-megabytes instead of gigabytes at 150k vocab.
  4. ``sampler.shaped`` masks disallowed tokens to -inf
     (``trans[row][class[v]] < 0``) and ``sampler.sample`` advances the
     per-slot row after each step — both inside the fused loop, both
     lax.cond-gated so unguided batches pay nothing.

All guides live in two fixed-budget arrays (``class_ids [G, V]``,
``trans [R, C]``) allocated at engine init, so compiling a new guide
never retraces the decode programs — the engine just re-uploads table
CONTENTS when the compiler's version bumps.

The registry is a NON-BLOCKING compile pipeline with LRU eviction:

  - Compilation runs OUTSIDE the registry lock, on a small bounded
    worker pool (``ARKS_GUIDE_COMPILE_WORKERS``); the lock is held only
    to check the registry and to pack/publish the finished tables.  A
    cold JSON-mode compile at a 152k vocab (~25 s) therefore never
    stalls the engine thread or other server threads.
  - Concurrent requests for the same (kind, pattern) dedupe onto ONE
    compile through a per-key in-flight ticket (``ensure``/``compile``).
  - When the guide or row budget fills, the least-recently-used guide
    with no active slot (``acquire``/``release`` refcounts, maintained
    by the engine per running/parked slot) is evicted: its id and row
    span return to free lists, ``version`` bumps so device copies
    refresh, and only when EVERY registered guide is pinned does a new
    pattern fail with GuideError (HTTP 400).  Guides never move once
    packed — live slots carry absolute device rows — so eviction frees
    spans instead of compacting over them.
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time

import numpy as np

from arks_tpu.utils import knobs

__all__ = ["GuideError", "GuideCompiler", "compile_regex_dfa",
           "json_mode_regex", "json_schema_regex"]


class GuideError(ValueError):
    """Invalid pattern or exceeded guide-table budget (HTTP 400 at the
    server — never an engine-thread fault)."""


# ---------------------------------------------------------------------------
# Byte-level regex -> character DFA
# ---------------------------------------------------------------------------
# The pattern language is the practical subset guided-decoding grammars
# use: literals, '.', classes with ranges/negation, escapes (\d \w \s \n
# \t \r \xHH and escaped metacharacters), groups, alternation, and the
# * + ? {m} {m,} {m,n} quantifiers.  Semantics are fullmatch, over BYTES:
# non-ASCII literals expand to their UTF-8 byte sequence, and negated
# classes admit continuation bytes (0x80+), so UTF-8 text flows through
# string-shaped grammars without unicode special-casing.

_ALL = (1 << 256) - 1
_DIGIT = sum(1 << b for b in range(0x30, 0x3A))
_WORD = (_DIGIT | sum(1 << b for b in range(0x41, 0x5B))
         | sum(1 << b for b in range(0x61, 0x7B)) | (1 << 0x5F))
_SPACE = sum(1 << b for b in b" \t\n\r\f\v")
_DOT = _ALL & ~(1 << 0x0A)


class _Parser:
    """Recursive-descent parser producing an AST of tuples:
    ('lit', mask) | ('cat', a, b) | ('alt', a, b) | ('star', a) |
    ('plus', a) | ('opt', a) | ('eps',)."""

    def __init__(self, pattern: str) -> None:
        self.p = pattern
        self.i = 0

    def parse(self):
        node = self._alt()
        if self.i != len(self.p):
            raise GuideError(f"unexpected {self.p[self.i]!r} at {self.i}")
        return node

    def _alt(self):
        node = self._concat()
        while self._peek() == "|":
            self.i += 1
            node = ("alt", node, self._concat())
        return node

    def _concat(self):
        node = ("eps",)
        while self._peek() not in ("", "|", ")"):
            node = ("cat", node, self._rep())
        return node

    def _rep(self):
        node = self._atom()
        c = self._peek()
        if c == "*":
            self.i += 1
            node = ("star", node)
        elif c == "+":
            self.i += 1
            node = ("plus", node)
        elif c == "?":
            self.i += 1
            node = ("opt", node)
        elif c == "{":
            node = self._bounded(node)
        return node

    def _bounded(self, node):
        j = self.p.find("}", self.i)
        if j < 0:
            raise GuideError("unterminated {} quantifier")
        spec = self.p[self.i + 1: j]
        self.i = j + 1
        try:
            if "," not in spec:
                lo, hi = int(spec), int(spec)
            else:
                lo_s, hi_s = spec.split(",", 1)
                lo = int(lo_s)
                hi = int(hi_s) if hi_s else None
        except ValueError:
            raise GuideError(f"bad quantifier {{{spec}}}") from None
        if hi is not None and hi < lo:
            raise GuideError(f"bad quantifier {{{spec}}}")
        out = ("eps",)
        for _ in range(lo):
            out = ("cat", out, node)
        if hi is None:
            out = ("cat", out, ("star", node))
        else:
            for _ in range(hi - lo):
                out = ("cat", out, ("opt", node))
        return out

    def _atom(self):
        c = self._peek()
        if c == "(":
            self.i += 1
            if self.p[self.i: self.i + 2] == "?:":
                self.i += 2
            node = self._alt()
            if self._peek() != ")":
                raise GuideError("unbalanced parenthesis")
            self.i += 1
            return node
        if c == "[":
            return ("lit", self._cls())
        if c == ".":
            self.i += 1
            return ("lit", _DOT)
        if c == "\\":
            return ("lit", self._escape())
        if c in ("*", "+", "?", "{", ""):
            raise GuideError(f"dangling quantifier or empty atom at {self.i}")
        self.i += 1
        mask_bytes = c.encode("utf-8")
        node = ("lit", 1 << mask_bytes[0])
        for b in mask_bytes[1:]:  # non-ASCII literal -> UTF-8 byte concat
            node = ("cat", node, ("lit", 1 << b))
        return node

    def _escape(self) -> int:
        self.i += 1  # past backslash
        if self.i >= len(self.p):
            raise GuideError("dangling escape")
        c = self.p[self.i]
        self.i += 1
        table = {"d": _DIGIT, "D": _ALL & ~_DIGIT, "w": _WORD,
                 "W": _ALL & ~_WORD, "s": _SPACE, "S": _ALL & ~_SPACE,
                 "n": 1 << 0x0A, "t": 1 << 0x09, "r": 1 << 0x0D,
                 "f": 1 << 0x0C, "v": 1 << 0x0B, "0": 1 << 0x00}
        if c in table:
            return table[c]
        if c == "x":
            h = self.p[self.i: self.i + 2]
            if len(h) < 2:
                raise GuideError("bad \\x escape")
            self.i += 2
            return 1 << int(h, 16)
        if ord(c) > 127:
            # Non-ASCII is multi-byte in UTF-8; a single-byte mask at
            # ord(c) would match the wrong raw byte.
            raise GuideError(
                f"escaped non-ASCII character {c!r}; use \\xHH bytes")
        return 1 << ord(c)  # escaped metacharacter / punctuation

    def _cls(self) -> int:
        self.i += 1  # past '['
        negate = self._peek() == "^"
        if negate:
            self.i += 1
        mask = 0
        first = True
        while True:
            c = self._peek()
            if c == "":
                raise GuideError("unterminated character class")
            if c == "]" and not first:
                self.i += 1
                break
            first = False
            if c == "\\":
                m = self._escape()
            else:
                self.i += 1
                bs = c.encode("utf-8")
                if len(bs) > 1:
                    raise GuideError(
                        "non-ASCII literals are not supported inside "
                        "character classes (use \\xHH byte ranges)")
                m = 1 << bs[0]
            # Range?  Only when both ends are single bytes.
            if (self._peek() == "-" and self.i + 1 < len(self.p)
                    and self.p[self.i + 1] != "]"):
                self.i += 1
                c2 = self._peek()
                if c2 == "\\":
                    m2 = self._escape()
                else:
                    self.i += 1
                    m2 = 1 << ord(c2)
                lo, hi = m.bit_length() - 1, m2.bit_length() - 1
                if (m.bit_count() != 1 or m2.bit_count() != 1 or hi < lo
                        or hi > 255):
                    raise GuideError("bad character-class range (bounds "
                                     "must be single bytes)")
                m = sum(1 << b for b in range(lo, hi + 1))
            mask |= m
        return (mask ^ _ALL) if negate else mask

    def _peek(self) -> str:
        return self.p[self.i] if self.i < len(self.p) else ""


def _nfa(ast):
    """Thompson construction.  Returns (n_states, eps adjacency list,
    char transitions [(src, mask, dst)], start, accept)."""
    eps: list[list[int]] = []
    chars: list[tuple[int, int, int]] = []

    def new() -> int:
        eps.append([])
        return len(eps) - 1

    def build(node) -> tuple[int, int]:
        kind = node[0]
        if kind == "eps":
            s = new()
            return s, s
        if kind == "lit":
            s, t = new(), new()
            chars.append((s, node[1], t))
            return s, t
        if kind == "cat":
            s1, t1 = build(node[1])
            s2, t2 = build(node[2])
            eps[t1].append(s2)
            return s1, t2
        if kind == "alt":
            s, t = new(), new()
            s1, t1 = build(node[1])
            s2, t2 = build(node[2])
            eps[s] += [s1, s2]
            eps[t1].append(t)
            eps[t2].append(t)
            return s, t
        if kind in ("star", "opt", "plus"):
            s, t = new(), new()
            s1, t1 = build(node[1])
            eps[s].append(s1)
            eps[t1].append(t)
            if kind in ("star", "opt"):
                eps[s].append(t)
            if kind in ("star", "plus"):
                eps[t1].append(s1)
            return s, t
        raise AssertionError(kind)

    start, accept = build(ast)
    return len(eps), eps, chars, start, accept


def compile_regex_dfa(pattern: str) -> tuple[np.ndarray, np.ndarray]:
    """Byte-level pattern -> minimized character DFA.

    Returns (table [S, 256] int32 with -1 = dead, accept [S] bool);
    state 0 is the start state.  Fullmatch semantics."""
    n, eps, chars, start, accept = _nfa(_Parser(pattern).parse())

    # Byte equivalence classes: bytes with identical membership across all
    # literal masks behave identically; subset-construct over classes.
    masks = sorted({m for _, m, _ in chars})
    sig = np.zeros((256, len(masks)), bool)
    for k, m in enumerate(masks):
        arr = np.frombuffer(
            m.to_bytes(32, "little"), np.uint8)
        sig[:, k] = (np.unpackbits(arr, bitorder="little") != 0)
    _, byte_cls = np.unique(sig, axis=0, return_inverse=True)
    ncls = int(byte_cls.max()) + 1
    cls_rep = np.zeros(ncls, np.int64)  # one representative byte per class
    for b in range(255, -1, -1):
        cls_rep[byte_cls[b]] = b

    # Per-NFA-state transitions grouped by byte class (target bitmask).
    delta: list[dict[int, int]] = [dict() for _ in range(n)]
    for s, m, t in chars:
        for c in range(ncls):
            if (m >> int(cls_rep[c])) & 1:
                delta[s][c] = delta[s].get(c, 0) | (1 << t)

    # Epsilon closures as bitmask ints, memoized bottom-up.
    closure = [0] * n
    done = [False] * n
    def close(s: int) -> int:
        if done[s]:
            return closure[s]
        seen = {s}
        stack = [s]
        acc = 1 << s
        while stack:
            u = stack.pop()
            for v in eps[u]:
                if v not in seen:
                    seen.add(v)
                    acc |= 1 << v
                    stack.append(v)
        closure[s] = acc
        done[s] = True
        return acc

    def close_set(mask: int) -> int:
        acc = 0
        while mask:
            low = mask & -mask
            acc |= close(low.bit_length() - 1)
            mask &= mask - 1
        return acc

    start_set = close(start)
    states: dict[int, int] = {start_set: 0}
    order = [start_set]
    rows: list[list[int]] = []
    i = 0
    while i < len(order):
        cur = order[i]
        i += 1
        row = [-1] * ncls
        for c in range(ncls):
            tgt = 0
            m = cur
            while m:
                low = m & -m
                s = low.bit_length() - 1
                tgt |= delta[s].get(c, 0)
                m &= m - 1
            if tgt:
                tgt = close_set(tgt)
                if tgt not in states:
                    states[tgt] = len(order)
                    order.append(tgt)
                row[c] = states[tgt]
        rows.append(row)
    S = len(order)
    cls_table = np.array(rows, np.int32).reshape(S, ncls)
    acc = np.array([(st >> accept) & 1 for st in order], bool)

    # Moore minimization over the class alphabet.
    part = acc.astype(np.int64)
    while True:
        mapped = np.where(cls_table >= 0, part[np.maximum(cls_table, 0)], -1)
        key = np.concatenate([part[:, None], mapped], axis=1)
        _, new_part = np.unique(key, axis=0, return_inverse=True)
        if (new_part == part).all():
            break
        part = new_part
    # Renumber with the start state's block first.
    remap = -np.ones(int(part.max()) + 1, np.int64)
    nxt = 0
    for s in range(S):
        if remap[part[s]] < 0:
            remap[part[s]] = nxt
            nxt += 1
    part = remap[part]
    Sm = nxt
    min_cls = -np.ones((Sm, ncls), np.int32)
    min_acc = np.zeros(Sm, bool)
    for s in range(S):
        ps = part[s]
        min_acc[ps] |= acc[s]
        row = cls_table[s]
        min_cls[ps] = np.where(row >= 0, part[np.maximum(row, 0)], -1)

    table = min_cls[:, byte_cls]  # [Sm, 256]
    return np.ascontiguousarray(table), min_acc


# ---------------------------------------------------------------------------
# JSON mode (depth-bounded JSON grammar as a regex)
# ---------------------------------------------------------------------------

# BOUNDED whitespace between JSON tokens: an unbounded star would let a
# sampling model wander in whitespace forever (whitespace is legal, eos
# is not, and nothing forces progress) — the standard guided-decoding
# recipe (outlines) bounds it for exactly this reason.  Accepting parsers
# are unaffected; generation just cannot stall.
_WS = r"[ \t\n\r]{0,2}"
_STR = r'"([^"\\\x00-\x1f]|\\(["\\/bfnrt]|u[0-9a-fA-F]{4}))*"'
_NUM = r"\-?(0|[1-9][0-9]*)(\.[0-9]+)?([eE][\+\-]?[0-9]+)?"


def json_mode_regex(depth: int | None = None) -> str:
    """A JSON OBJECT with nesting bounded at ``depth`` containers (the one
    non-regular feature of JSON; vLLM's grammar backend tracks it with a
    pushdown stack, here it is unrolled into the DFA).  Default depth via
    ARKS_JSON_DEPTH (3): state count grows ~2x per level."""
    if depth is None:
        depth = knobs.get_int("ARKS_JSON_DEPTH")

    def value(d: int) -> str:
        alts = [_STR, _NUM, "true", "false", "null"]
        if d > 0:
            alts += [obj(d), arr(d)]
        return "(" + "|".join(alts) + ")"

    def obj(d: int) -> str:
        v = value(d - 1)
        member = f"{_STR}{_WS}:{_WS}{v}"
        return (r"\{" + _WS + f"({member}({_WS},{_WS}{member})*)?"
                + _WS + r"\}")

    def arr(d: int) -> str:
        v = value(d - 1)
        return r"\[" + _WS + f"({v}({_WS},{_WS}{v})*)?" + _WS + r"\]"

    if depth < 1:
        raise GuideError("json depth must be >= 1")
    return _WS + obj(depth) + _WS


# ---------------------------------------------------------------------------
# JSON-schema -> regex (the outlines-style subset)
# ---------------------------------------------------------------------------

def _rx_quote(s: str) -> str:
    """Escape a literal for the byte-regex dialect (non-ASCII expands to
    UTF-8 bytes in the parser's literal path, so only ASCII
    metacharacters need escaping)."""
    out = []
    for ch in s:
        if ch in r"\.^$|?*+()[]{}-":
            out.append("\\" + ch)
        else:
            out.append(ch)
    return "".join(out)


def _json_literal(value) -> str:
    return _rx_quote(json.dumps(value, ensure_ascii=False))


def json_schema_regex(schema: dict, depth: int | None = None) -> str:
    """A regex matching JSON documents that satisfy ``schema`` — the
    practical subset structured-output schemas use (object properties in
    declaration order, string/integer/number/boolean/null, enum/const,
    arrays with item schemas and min/maxItems, anyOf/oneOf, local $refs).
    Unsupported constructs raise GuideError rather than silently
    loosening; numeric minimum/maximum are ignored (not regular).
    ``depth`` bounds untyped-value nesting and $ref recursion."""
    if depth is None:
        depth = knobs.get_int("ARKS_JSON_DEPTH")
    defs = {}
    for key in ("$defs", "definitions"):
        defs.update(schema.get(key) or {})

    def resolve(s, d):
        ref = s.get("$ref")
        if ref is None:
            return s
        name = ref.rsplit("/", 1)[-1]
        if name not in defs:
            raise GuideError(f"unresolvable $ref {ref!r}")
        if d <= 0:
            raise GuideError(
                f"$ref {ref!r} recursion exceeds depth {depth} "
                "(raise ARKS_JSON_DEPTH for deeper nesting)")
        return defs[name]

    def value(s, d) -> str:
        if not isinstance(s, dict):
            raise GuideError("schema nodes must be objects")
        if "$ref" in s:
            return value(resolve(s, d), d - 1)
        if "const" in s:
            return _json_literal(s["const"])
        if "enum" in s:
            if not s["enum"]:
                raise GuideError("empty enum")
            return "(" + "|".join(_json_literal(v) for v in s["enum"]) + ")"
        for comb in ("anyOf", "oneOf"):
            if comb in s:
                return ("(" + "|".join(value(sub, d) for sub in s[comb])
                        + ")")
        typ = s.get("type")
        if isinstance(typ, list):
            return "(" + "|".join(value({**s, "type": t}, d) for t in typ) + ")"
        if typ == "string":
            lo = s.get("minLength")
            hi = s.get("maxLength")
            if lo is not None or hi is not None:
                # Bounded strings count CHARS, approximated as bytes with
                # escapes excluded (bounded + escapes is not regular in
                # byte space).  minLength alone keeps the tail UNBOUNDED
                # ({lo,}) — inventing a max would both reject valid
                # documents and unroll ~max DFA states per property.
                bound = "{%d,%s}" % (int(lo or 0),
                                     "" if hi is None else int(hi))
                return '"[^"\\\\\\x00-\\x1f]%s"' % bound
            return _STR
        if typ == "integer":
            return r"\-?(0|[1-9][0-9]*)"
        if typ == "number":
            return _NUM
        if typ == "boolean":
            return "(true|false)"
        if typ == "null":
            return "null"
        if typ == "array":
            item = s.get("items")
            inner = value(item, d - 1) if item else _any_value(d - 1)
            lo = int(s.get("minItems", 0))
            hi = s.get("maxItems")
            if hi is not None and int(hi) == 0:
                return r"\[" + _WS + r"\]"
            rep = (f"({_WS},{_WS}{inner})" + "{%d,%s}"
                   % (max(lo - 1, 0), "" if hi is None else int(hi) - 1))
            seq = f"{inner}{rep}"
            if lo == 0:
                seq = f"({seq})?"
            return r"\[" + _WS + seq + _WS + r"\]"
        if typ == "object" or "properties" in s:
            return obj(s, d)
        if typ is None:
            return _any_value(d)
        raise GuideError(f"unsupported schema type {typ!r}")

    def _any_value(d: int) -> str:
        alts = [_STR, _NUM, "true", "false", "null"]
        if d > 0:
            alts += [obj({"additionalProperties": True}, d),
                     r"\[" + _WS
                     + f"({_any_value(d - 1)}({_WS},{_WS}{_any_value(d - 1)})*)?"
                     + _WS + r"\]"]
        return "(" + "|".join(alts) + ")"

    def obj(s, d) -> str:
        props = s.get("properties") or {}
        if not props:
            # Free-form object (JSON-mode member grammar).
            member = f"{_STR}{_WS}:{_WS}{_any_value(d - 1)}"
            return (r"\{" + _WS + f"({member}({_WS},{_WS}{member})*)?"
                    + _WS + r"\}")
        required = set(s.get("required", list(props)))
        missing = required - set(props)
        if missing:
            raise GuideError(
                f"required properties {sorted(missing)} are not declared "
                "in properties (the guide would silently drop them)")
        parts = []
        seen_required = False
        for name, sub in props.items():
            member = (_json_literal(name) + f"{_WS}:{_WS}"
                      + value(sub, d - 1))
            if name in required:
                prefix = f"{_WS},{_WS}" if seen_required or parts else ""
                parts.append(prefix + member)
                seen_required = True
            else:
                if not seen_required and not parts:
                    raise GuideError(
                        "optional properties before the first required "
                        "one are not supported (declare a required "
                        "property first, or mark all required)")
                parts.append(f"({_WS},{_WS}{member})?")
        return r"\{" + _WS + "".join(parts) + _WS + r"\}"

    return _WS + value(schema, depth) + _WS


# ---------------------------------------------------------------------------
# Token byte table
# ---------------------------------------------------------------------------

# The standard GPT-2 byte<->unicode mapping used by every byte-level BPE
# vocab (GPT-2, Llama-3, Qwen2 tiktoken-style tokenizers).
def _bytes_to_unicode() -> dict[int, str]:
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


def token_byte_table(tokenizer, vocab_size: int
                     ) -> tuple[np.ndarray, np.ndarray]:
    """(bytes [V, L] uint8, lens [V] int32) for every vocab id.  Ids with
    no byte representation (specials, padding rows past the tokenizer
    vocab) get length 0 and are disallowed under every guide."""
    from arks_tpu.engine.tokenizer import ByteTokenizer

    per: list[bytes] = [b""] * vocab_size
    if isinstance(tokenizer, ByteTokenizer):
        off = ByteTokenizer.OFFSET
        for i in range(off, min(vocab_size, off + 256)):
            per[i] = bytes([i - off])
    else:
        hf = getattr(tokenizer, "_tok", tokenizer)
        uni2byte = {u: b for b, u in _bytes_to_unicode().items()}
        special = set(getattr(hf, "all_special_ids", []) or [])
        n = min(vocab_size, int(getattr(hf, "vocab_size", vocab_size))
                + len(getattr(hf, "added_tokens_decoder", {}) or {}))
        toks = hf.convert_ids_to_tokens(list(range(n)))
        for i, t in enumerate(toks):
            if t is None or i in special:
                continue
            if t.startswith("<0x") and t.endswith(">") and len(t) == 6:
                try:
                    per[i] = bytes([int(t[3:5], 16)])  # sentencepiece byte
                    continue
                except ValueError:
                    pass
            if all(ch in uni2byte for ch in t):
                per[i] = bytes(uni2byte[ch] for ch in t)  # byte-level BPE
            else:
                per[i] = t.replace("▁", " ").encode("utf-8")  # spm

    lens = np.array([len(b) for b in per], np.int32)
    L = max(1, int(lens.max()))
    arr = np.zeros((vocab_size, L), np.uint8)
    for i, b in enumerate(per):
        arr[i, : len(b)] = np.frombuffer(b, np.uint8)
    return arr, lens


# ---------------------------------------------------------------------------
# Char DFA -> token-level classes + transition table
# ---------------------------------------------------------------------------

def token_transition_tables(char_table: np.ndarray, accept: np.ndarray,
                            tok_bytes: np.ndarray, tok_lens: np.ndarray,
                            eos_ids: tuple[int, ...]
                            ) -> tuple[np.ndarray, np.ndarray]:
    """(class_id [V] int32, trans [S+1, C] int32) — token-level DFA in
    factored form.  Row S (the last) is the TERMINAL state entered by
    sampling EOS in an accepting state; it allows everything (the host
    finishes the request at the next boundary, and an all-masked row
    would degenerate the sampling distribution for nothing).

    next-state encoding: -1 = token disallowed, else absolute row."""
    S = char_table.shape[0]
    V = tok_bytes.shape[0]
    dead = S + 1  # transient absorbing index during the walk
    ct = np.where(char_table < 0, dead, char_table).astype(np.int32)
    ct = np.vstack([ct, np.full((2, 256), dead, np.int32)])  # term+dead rows

    T = np.empty((S, V), np.int32)
    Lmax = tok_bytes.shape[1]
    chunk = max(1, int(2e8) // max(V, 1))  # ~800MB transient cap
    for s0 in range(0, S, chunk):
        s1 = min(S, s0 + chunk)
        st = np.repeat(np.arange(s0, s1, dtype=np.int32)[:, None], V, axis=1)
        for j in range(Lmax):
            live = (j < tok_lens)[None, :]
            st = np.where(live, ct[st, tok_bytes[:, j][None, :]], st)
        T[s0:s1] = np.where(st >= dead, -1, st)
    T[:, tok_lens == 0] = -1  # specials/padding never advance a guide

    # EOS: allowed exactly in accepting states, entering the terminal row.
    for e in eos_ids:
        if 0 <= e < V:
            T[:, e] = np.where(accept, S, -1)
    term_row = np.full((1, V), S, np.int32)  # terminal: all tokens self-loop
    T = np.vstack([T, term_row])

    # Factor through token classes: dedupe the columns of T.
    _, class_id, inv = np.unique(T.T, axis=0, return_index=True,
                                 return_inverse=True)
    trans = T[:, class_id]  # [S+1, C]
    return inv.astype(np.int32), np.ascontiguousarray(trans.astype(np.int32))


# ---------------------------------------------------------------------------
# Registry: guides packed into fixed-budget arrays
# ---------------------------------------------------------------------------

class Guide:
    __slots__ = ("guide_id", "start_row", "n_states", "n_classes",
                 "key", "refcount", "lru")

    def __init__(self, guide_id: int, start_row: int, n_states: int,
                 n_classes: int, key: tuple[str, str] | None = None) -> None:
        self.guide_id = guide_id
        self.start_row = start_row
        self.n_states = n_states
        self.n_classes = n_classes
        self.key = key
        self.refcount = 0   # active/parked slots using this guide (engine)
        self.lru = 0        # last-touched tick (compiler lock held)


class CompileTicket:
    """Per-key in-flight compile record: concurrent requests for the same
    (kind, pattern) all wait on ONE of these instead of compiling N times.
    ``event`` is set when the compile finished; exactly one of the guide
    being in the registry or ``error`` being set holds afterwards."""

    __slots__ = ("event", "error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.error: str | None = None


class GuideCompiler:
    """Compiles and packs guides; owns the HOST tables.  The engine
    re-uploads device copies when ``version`` bumps (engine thread, between
    dispatches) and multi-host leaders replicate the same arrays over the
    dispatch channel, so followers stay bit-identical.

    Budgets are fixed at init so device shapes never change:
      class_ids [max_guides, V] int32  (class of token v under guide g)
      trans     [max_rows,  max_classes] int32 (ABSOLUTE next row | -1)

    Concurrency contract:
      - ``ensure`` (non-blocking) and ``compile`` (blocking) dedupe onto a
        per-key CompileTicket; the expensive DFA/token-table build runs
        with NO lock held (``ensure`` on a pool worker, ``compile`` on the
        caller's thread), and the lock is re-taken only to publish.
      - ``acquire``/``release`` refcount guides per live slot; eviction
        (triggered by a publish that needs an id or rows) only ever
        removes refcount-0 guides, so a published guide's absolute rows
        stay valid for as long as any slot decodes under it.
      - Row→guide resolution (``next_row``/``allowed``) reads an immutable
        interval-index snapshot — no lock, no O(guides) scan."""

    def __init__(self, tokenizer, vocab_size: int,
                 eos_ids: tuple[int, ...] = (),
                 max_guides: int | None = None,
                 max_rows: int | None = None,
                 max_classes: int | None = None,
                 metrics=None) -> None:
        self.vocab_size = vocab_size
        self.max_guides = max_guides or knobs.get_int("ARKS_GUIDE_MAX")
        self.max_rows = max_rows or knobs.get_int("ARKS_GUIDE_ROWS")
        self.max_classes = max_classes or knobs.get_int(
            "ARKS_GUIDE_CLASSES")
        self._tokenizer = tokenizer
        self._eos_ids = tuple(eos_ids)
        self._tok_table: tuple[np.ndarray, np.ndarray] | None = None
        self._tok_lock = threading.Lock()
        self.class_ids = np.zeros((self.max_guides, vocab_size), np.int32)
        self.trans = np.full((self.max_rows, self.max_classes), -1, np.int32)
        self._registry: dict[tuple[str, str], Guide] = {}
        self._inflight: dict[tuple[str, str], CompileTicket] = {}
        self._free_ids: list[int] = list(range(self.max_guides))
        self._free_spans: list[tuple[int, int]] = [(0, self.max_rows)]
        # Immutable (starts, (start, end, gid)) snapshot for lock-free
        # row→guide bisect on the hot path; rebuilt under the lock on
        # every registry change and swapped atomically.
        self._row_index: tuple[tuple, tuple] = ((), ())
        self._lru_tick = 0
        self._executor = None
        self._metrics = metrics  # namespace of prom metric objects | None
        self.version = 0
        self._lock = threading.Lock()  # registry/publish only, never compile

    # -- public ----------------------------------------------------------

    def validate(self, kind: str, pattern: str = "") -> None:
        """Cheap syntactic check (render + parse, no DFA/token tables):
        raises GuideError for malformed patterns/schemas so callers can
        400 on THEIR thread before the expensive build is ever scheduled."""
        _Parser(self._render(kind, pattern)).parse()

    def ensure(self, kind: str, pattern: str = "") -> "Guide | CompileTicket":
        """Non-blocking: the published Guide on a registry hit (LRU
        touched), else the in-flight CompileTicket — scheduling the build
        on the worker pool if nobody owns it yet.  Never blocks, never
        raises; compile failures surface through ``ticket.error``."""
        key = (kind, pattern)
        g, ticket, owner = self._claim(key)
        if g is not None:
            return g
        if owner:
            self._m_inc("misses")
            self._pool().submit(self._compile_job, key, ticket)
        return ticket

    def compile(self, kind: str, pattern: str = "") -> Guide:
        """Blocking compile: registry hit, or wait on (join) the in-flight
        compile, or run the build on the CALLER's thread.  Idempotent per
        (kind, pattern); raises GuideError on bad patterns or budgets
        exhausted with every guide pinned."""
        key = (kind, pattern)
        first = True
        while True:
            g, ticket, owner = self._claim(key, count_hit=first)
            first = False
            if g is not None:
                return g
            if owner:
                self._m_inc("misses")
                self._compile_job(key, ticket)
            else:
                ticket.event.wait()
            if ticket.error is not None:
                raise GuideError(ticket.error)
            # Published: loop re-claims from the registry.  (A guide
            # evicted in the microseconds before our re-claim just
            # triggers one more compile round.)

    def acquire(self, kind: str, pattern: str = "") -> Guide:
        """Pin a published guide (refcount +1, LRU touch).  The engine
        holds one pin per admitted request from admission through finish;
        pinned guides are never evicted, so their absolute device rows
        stay stable for the slot's lifetime.  Raises GuideError when the
        guide is not (or no longer) registered."""
        with self._lock:
            g = self._registry.get((kind, pattern))
            if g is None:
                raise GuideError(
                    f"guide {kind}:{pattern!r} is not registered")
            g.refcount += 1
            self._touch_locked(g)
            return g

    def release(self, kind: str, pattern: str = "") -> None:
        with self._lock:
            g = self._registry.get((kind, pattern))
            if g is not None and g.refcount > 0:
                g.refcount -= 1

    def lookup(self, kind: str, pattern: str = "") -> Guide | None:
        return self._registry.get((kind, pattern))

    def snapshot(self) -> tuple[np.ndarray, np.ndarray, int]:
        """Consistent (class_ids copy, trans copy, version) for the device
        upload / multi-host table sync."""
        with self._lock:
            return self.class_ids.copy(), self.trans.copy(), self.version

    def next_row(self, row: int, token: int) -> int:
        """Host-side single-token advance (absolute row coords) for the
        first-token paths, where the engine knows the sampled id before
        writing the slot's sampling state."""
        gid = self._guide_of_row(row)
        nxt = int(self.trans[row, int(self.class_ids[gid, token])])
        return row if nxt < 0 else nxt

    def allowed(self, row: int) -> np.ndarray:
        """Host-side [V] bool mask (tests / debugging)."""
        gid = self._guide_of_row(row)
        return self.trans[row, self.class_ids[gid]] >= 0

    def load_state(self, class_ids: np.ndarray, trans: np.ndarray,
                   version: int) -> None:
        """Follower-side table sync from the leader's emit.  Eviction-driven
        repacks need no special handling: the leader always ships the FULL
        fixed-shape arrays, and followers resolve guides by value (guide id
        + absolute row travel in each dispatch payload), never through a
        local registry."""
        with self._lock:
            self.class_ids = np.asarray(class_ids, np.int32)
            self.trans = np.asarray(trans, np.int32)
            self.version = version

    # -- compile pipeline -------------------------------------------------

    def _claim(self, key, count_hit: bool = True):
        """(guide, ticket, owner): registry hit -> (g, None, False); an
        existing in-flight compile -> (None, ticket, False); otherwise this
        caller owns a fresh ticket -> (None, ticket, True)."""
        with self._lock:
            g = self._registry.get(key)
            if g is not None:
                self._touch_locked(g)
                if count_hit:
                    self._m_inc("hits")
                return g, None, False
            ticket = self._inflight.get(key)
            if ticket is not None:
                return None, ticket, False
            ticket = CompileTicket()
            self._inflight[key] = ticket
            return None, ticket, True

    def _compile_job(self, key, ticket: CompileTicket) -> None:
        """Owner-side build + publish.  Runs UNLOCKED except for the final
        publish; never raises (errors land on the ticket for every waiter
        — blocking compile() callers and engine-parked requests alike)."""
        t0 = time.monotonic()
        try:
            rx = self._render(*key)
            cls, trans = self._build(rx)
            with self._lock:
                self._publish_locked(key, cls, trans)
            if self._metrics is not None:
                self._metrics.compile_seconds.observe(time.monotonic() - t0)
        except GuideError as e:
            ticket.error = str(e)
        except Exception as e:  # worker pool must never die silently
            ticket.error = f"{type(e).__name__}: {e}"
        finally:
            with self._lock:
                self._inflight.pop(key, None)
            ticket.event.set()

    def _render(self, kind: str, pattern: str) -> str:
        if kind == "json":
            return json_mode_regex(int(pattern) if pattern else None)
        if kind == "regex":
            return pattern
        if kind == "json_schema":
            try:
                return json_schema_regex(json.loads(pattern))
            except json.JSONDecodeError as e:
                raise GuideError(f"invalid json_schema: {e}") from None
        if kind == "choice":
            # vLLM-style guided_choice: the pattern is a JSON array of
            # literal strings, compiled as an escaped alternation over the
            # same DFA machinery — the decoder can only emit one of the
            # choices verbatim.
            try:
                choices = json.loads(pattern)
            except json.JSONDecodeError as e:
                raise GuideError(f"invalid choice list: {e}") from None
            if (not isinstance(choices, list) or not choices
                    or not all(isinstance(c, str) for c in choices)):
                raise GuideError(
                    "guided_choice requires a non-empty array of strings")
            return "|".join(_rx_quote(c) for c in choices)
        raise GuideError(f"unknown guide kind {kind!r}")

    def _build(self, rx: str) -> tuple[np.ndarray, np.ndarray]:
        """The expensive part (char DFA + vocab walk), lock-free.  An
        instance method so tests can wrap it (compile counting, artificial
        slowdowns) without touching module functions."""
        char_table, accept = compile_regex_dfa(rx)
        with self._tok_lock:
            if self._tok_table is None:
                self._tok_table = token_byte_table(self._tokenizer,
                                                   self.vocab_size)
            tok_table = self._tok_table
        return token_transition_tables(char_table, accept, *tok_table,
                                       self._eos_ids)

    def _pool(self):
        with self._lock:
            if self._executor is None:
                from concurrent.futures import ThreadPoolExecutor
                n = max(1, knobs.get_int("ARKS_GUIDE_COMPILE_WORKERS"))
                self._executor = ThreadPoolExecutor(
                    max_workers=n, thread_name_prefix="guide-compile")
            return self._executor

    # -- packing / eviction (lock held) -----------------------------------

    def _publish_locked(self, key, cls: np.ndarray,
                        trans: np.ndarray) -> Guide:
        n_states, n_classes = trans.shape
        if n_classes > self.max_classes:
            raise GuideError(
                f"guide has {n_classes} token classes > budget "
                f"{self.max_classes}; raise ARKS_GUIDE_CLASSES")
        if n_states > self.max_rows:
            raise GuideError(
                f"guide row budget exhausted ({n_states} states needed, "
                f"{self.max_rows} total rows; raise ARKS_GUIDE_ROWS)")
        while not self._free_ids:
            if not self._evict_one_locked():
                raise GuideError(
                    f"guide budget exhausted ({self.max_guides} guides, "
                    "all with active slots; raise ARKS_GUIDE_MAX)")
        base = self._take_span_locked(n_states)
        while base is None:
            if not self._evict_one_locked():
                raise GuideError(
                    f"guide row budget exhausted ({n_states} states "
                    f"needed, {sum(ln for _, ln in self._free_spans)} rows "
                    "free and every registered guide pinned; raise "
                    "ARKS_GUIDE_ROWS)")
            base = self._take_span_locked(n_states)
        gid = self._free_ids.pop(0)
        g = Guide(gid, base, n_states, n_classes, key=key)
        self.class_ids[gid] = cls
        # Clear the FULL row width first: a previous tenant of this span
        # may have had more classes than the new guide fills.
        self.trans[base: base + n_states] = -1
        self.trans[base: base + n_states, :n_classes] = np.where(
            trans >= 0, trans + base, -1)
        self._registry[key] = g
        self._touch_locked(g)
        self.version += 1
        self._rebuild_row_index_locked()
        self._update_gauges_locked()
        return g

    def _evict_one_locked(self) -> bool:
        """Evict the LRU guide with no active slot; False when every
        registered guide is pinned (or the registry is empty)."""
        victims = [g for g in self._registry.values() if g.refcount <= 0]
        if not victims:
            return False
        v = min(victims, key=lambda g: g.lru)
        del self._registry[v.key]
        bisect.insort(self._free_ids, v.guide_id)
        self._free_span_locked(v.start_row, v.n_states)
        self.trans[v.start_row: v.start_row + v.n_states] = -1
        self.version += 1  # device copies must refresh before id/row reuse
        self._rebuild_row_index_locked()
        self._update_gauges_locked()
        self._m_inc("evictions")
        return True

    def _take_span_locked(self, n: int) -> int | None:
        """First-fit allocation from the free row spans; None when no
        contiguous span covers ``n`` rows."""
        for i, (s, ln) in enumerate(self._free_spans):
            if ln >= n:
                if ln == n:
                    self._free_spans.pop(i)
                else:
                    self._free_spans[i] = (s + n, ln - n)
                return s
        return None

    def _free_span_locked(self, start: int, n: int) -> None:
        spans = self._free_spans
        spans.insert(bisect.bisect_left(spans, (start, 0)), (start, n))
        merged: list[tuple[int, int]] = []
        for s, ln in spans:
            if merged and merged[-1][0] + merged[-1][1] == s:
                merged[-1] = (merged[-1][0], merged[-1][1] + ln)
            else:
                merged.append((s, ln))
        self._free_spans = merged

    def _rebuild_row_index_locked(self) -> None:
        entries = sorted((g.start_row, g.start_row + g.n_states, g.guide_id)
                         for g in self._registry.values())
        self._row_index = (tuple(e[0] for e in entries), tuple(entries))

    def _touch_locked(self, g: Guide) -> None:
        self._lru_tick += 1
        g.lru = self._lru_tick

    def _update_gauges_locked(self) -> None:
        if self._metrics is None:
            return
        self._metrics.guides_in_use.set(len(self._registry))
        self._metrics.rows_in_use.set(
            self.max_rows - sum(ln for _, ln in self._free_spans))

    def _m_inc(self, name: str) -> None:
        if self._metrics is not None:
            getattr(self._metrics, name).inc(1)

    # -- internal --------------------------------------------------------

    def _guide_of_row(self, row: int) -> int:
        # Lock-free: bisect an immutable interval-index snapshot (replaced
        # atomically under the lock on registry changes) instead of the old
        # O(guides) scan under the lock — this sits on the engine thread's
        # first-token path.
        starts, entries = self._row_index
        i = bisect.bisect_right(starts, row) - 1
        if i >= 0:
            s, e, gid = entries[i]
            if s <= row < e:
                return gid
        raise GuideError(f"row {row} belongs to no registered guide")
