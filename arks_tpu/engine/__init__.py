from arks_tpu.engine.types import Request, RequestOutput, SamplingParams
from arks_tpu.engine.engine import EngineConfig, InferenceEngine

__all__ = ["Request", "RequestOutput", "SamplingParams", "EngineConfig", "InferenceEngine"]
