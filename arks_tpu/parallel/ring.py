"""Ring attention: sequence/context-parallel self-attention over a mesh axis.

The reference has NO long-context code of its own — sequence scaling is
whatever vLLM/SGLang do inside their containers, reachable only through the
``runtimeCommonArgs`` passthrough (SURVEY.md §5, /root/reference/api/v1/
arksapplication_types.go:292).  The TPU build makes it first-class: prompts
longer than one chip's prefill budget are sharded across a ``seq`` mesh axis
and attention runs as a ring — each device keeps its Q chunk resident while
KV chunks rotate around the ring over ICI (``ppermute``), accumulating with
an online (flash) softmax.  Peak memory per device is O(T/P) activations +
one in-flight KV chunk, and the KV transfer overlaps with the score/PV
matmuls of the previous chunk under XLA's async collective scheduling.

Chunks are contiguous in ring order: device i holds tokens
[i*Tl, (i+1)*Tl).  Causality falls out of comparing *global* positions, so
fully-masked chunk pairs cost one masked matmul (no separate skip path) —
acceptable because prefill is MXU-bound, not latency-bound.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from arks_tpu.parallel.compat import axis_size

_NEG_INF = -1e30


def ring_self_attention(
    q: jnp.ndarray,  # [B, Tl, H, D] — local sequence chunk
    k: jnp.ndarray,  # [B, Tl, Hkv, D]
    v: jnp.ndarray,  # [B, Tl, Hkv, D]
    *,
    axis_name: str,
    causal: bool = True,
) -> jnp.ndarray:
    """Runs INSIDE shard_map over ``axis_name``. Returns [B, Tl, H, D]."""
    p = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    b, tl, h, d = q.shape
    hkv = k.shape[2]
    g = h // hkv
    qg = q.reshape(b, tl, hkv, g, d)
    scale = 1.0 / (d ** 0.5)
    perm = [(j, (j + 1) % p) for j in range(p)]

    m = jnp.full((b, hkv, g, tl, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, hkv, g, tl, 1), jnp.float32)
    acc = jnp.zeros((b, hkv, g, tl, d), jnp.float32)

    # p is static, so the ring is a Python loop: the last rotation (whose
    # result nobody reads) is simply not issued, and XLA can overlap each
    # ppermute with the previous chunk's matmuls.
    k_cur, v_cur = k, v
    for i in range(p):
        src = (my - i) % p  # which chunk we currently hold
        # [B, Hkv, G, Tq, Ts] f32 on the MXU.
        scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, k_cur,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            pos_q = my * tl + jnp.arange(tl)
            pos_k = src * tl + jnp.arange(tl)
            mask = pos_q[:, None] >= pos_k[None, :]  # [Tq, Ts], global order
            scores = jnp.where(mask[None, None, None], scores, _NEG_INF)
        m_curr = jnp.max(scores, axis=-1, keepdims=True)
        m_next = jnp.maximum(m, m_curr)
        correction = jnp.exp(m - m_next)
        probs = jnp.exp(scores - m_next)
        l = l * correction + jnp.sum(probs, axis=-1, keepdims=True)
        pv = jnp.einsum("bkgqs,bskd->bkgqd", probs.astype(v_cur.dtype), v_cur,
                        preferred_element_type=jnp.float32)
        acc = acc * correction + pv
        m = m_next
        if i < p - 1:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)

    out = acc / (l + 1e-9)  # fully-masked rows can't occur under causal=True
    # [B, Hkv, G, Tl, D] → [B, Tl, H, D]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, tl, h, d).astype(q.dtype)


def ring_prefill_attention(
    q: jnp.ndarray,  # [B, T, H, D], T sharded over ``seq_axis``
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh,
    seq_axis: str = "seq",
    batch_axis: str | None = None,
    causal: bool = True,
    heads_sharded: bool = False,
    model_axis: str = "model",
) -> jnp.ndarray:
    """shard_map wrapper: causal self-attention with T context-parallel.

    With ``heads_sharded`` (q AND kv heads divide the model axis), the head
    dim stays model-sharded inside the ring — TP devices each ring their own
    heads instead of all-gathering q/k/v and redoing every head's FLOPs.
    """
    from arks_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    model = model_axis if heads_sharded else None
    spec = P(batch_axis, seq_axis, model, None)
    fn = shard_map(
        functools.partial(ring_self_attention, axis_name=seq_axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
