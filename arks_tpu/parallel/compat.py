"""jax API compatibility shims shared across modules.

``shard_map`` moved from ``jax.experimental.shard_map`` to the top-level
``jax`` namespace across releases, and its replication-check kwarg was
renamed ``check_rep`` -> ``check_vma``.  Resolve whichever this jax ships
so the sharded ops use one name everywhere.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.5-ish
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map

_HAS_CHECK_VMA = "check_vma" in inspect.signature(_shard_map).parameters


def shard_map(f, **kwargs):
    if not _HAS_CHECK_VMA and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif _HAS_CHECK_VMA and "check_rep" in kwargs:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map(f, **kwargs)


def axis_size(axis_name) -> "int":
    """``jax.lax.axis_size`` where available (newer jax), else the psum-of-1
    identity every release supports inside shard_map/pmap bodies."""
    import jax

    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)
