"""Pipeline parallelism: layer stages over a mesh axis, microbatch pipeline.

The reference has no pipeline-parallel code (its runtimes handle any model
parallelism internally; SURVEY.md §2.4); here PP is a first-class mesh axis
for training and offline forward passes over models deeper than one slice's
memory.

TPU-native formulation (collective-permute pipeline, scaling-book style):
- The stacked layer params [L, ...] shard their leading dim over the
  ``stage`` axis — no re-packing: each device simply holds L/S consecutive
  layers, and the per-stage body is the same ``lax.scan`` the unsharded
  model uses.
- The batch splits into M microbatches.  For M + S - 1 ticks, every stage
  runs its layers on its current microbatch and ``ppermute``s activations to
  the next stage over ICI.  Bubbles are computed-and-discarded (standard:
  utilization M / (M + S - 1)).
- The last stage accumulates outputs; a masked psum over the stage axis
  replicates them at the end.  Gradients flow backward through the
  ppermute/psum transposes automatically, so one ``jax.grad`` differentiates
  the whole pipeline.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from arks_tpu.parallel.compat import shard_map, axis_size
from jax.sharding import NamedSharding, PartitionSpec as P

from arks_tpu.models import transformer as tf
from arks_tpu.parallel.mesh import AXIS_STAGE


def shard_params_pp(params, mesh, stage_axis: str = AXIS_STAGE):
    """Shard the stacked layer dim over the stage axis; everything else
    (embed, final_norm, lm_head) replicated."""
    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    out = dict(params)
    out["layers"] = jax.tree.map(
        lambda x: put(x, P(stage_axis)), params["layers"])
    for k in ("embed", "final_norm", "lm_head"):
        if k in params:
            out[k] = put(params[k], P())
    return out


def pipeline_forward(
    params,
    cfg,
    tokens: jnp.ndarray,  # [B, T] int32
    mesh,
    num_microbatches: int,
    stage_axis: str = AXIS_STAGE,
) -> jnp.ndarray:
    """Hidden states [B, T, E] (pre-final-norm), replicated across stages."""
    num_stages = mesh.shape[stage_axis]
    if cfg.num_layers % num_stages != 0:
        raise ValueError(f"{cfg.num_layers} layers not divisible into "
                         f"{num_stages} stages")
    b, t = tokens.shape
    m = num_microbatches
    if b % m != 0:
        raise ValueError(f"batch {b} not divisible into {m} microbatches")
    mb = b // m
    x_mb = tokens.reshape(m, mb, t)

    def local(layers_local, embed, x_mb):
        s_ax = axis_size(stage_axis)
        s_id = jax.lax.axis_index(stage_axis)
        perm = [(i, (i + 1) % s_ax) for i in range(s_ax)]
        positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (mb, t))

        def run_stage(h):
            def body(h, lp):
                h, _, _ = tf.prefill_layer(h, lp, cfg, positions, None)
                return h, None
            h, _ = jax.lax.scan(body, h, layers_local)
            return h

        e = embed.shape[1]
        # Embed the whole microbatch stream ONCE (only stage 0's copy is
        # read, but hoisting it keeps the vocab-table gather out of the
        # per-tick loop on every stage).
        x_emb = jnp.take(embed, x_mb, axis=0)  # [M, mb, T, E]
        buf = jnp.zeros((mb, t, e), embed.dtype)
        outputs = jnp.zeros((m, mb, t, e), embed.dtype)

        def tick(carry, ti):
            buf, outputs = carry
            # Stage 0 feeds from the embedded microbatch stream; later
            # stages from the ring buffer.  Clamped indices during bubble
            # ticks write garbage that is overwritten before it's read
            # (microbatch i's real result lands at tick i + S - 1).
            x0 = jax.lax.dynamic_index_in_dim(
                x_emb, jnp.clip(ti, 0, m - 1), 0, keepdims=False)
            h_in = jnp.where(s_id == 0, x0, buf)
            h_out = run_stage(h_in)
            out_idx = jnp.clip(ti - (s_ax - 1), 0, m - 1)
            outputs = jax.lax.dynamic_update_slice(
                outputs, h_out[None].astype(outputs.dtype), (out_idx, 0, 0, 0))
            buf = jax.lax.ppermute(h_out, stage_axis, perm)
            return (buf, outputs), None

        (buf, outputs), _ = jax.lax.scan(
            tick, (buf, outputs), jnp.arange(m + s_ax - 1))
        mask = (s_id == s_ax - 1).astype(outputs.dtype)
        return jax.lax.psum(outputs * mask, stage_axis)

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(stage_axis), P(), P()),
        out_specs=P(),
        check_vma=False,
    )
    out = fn(params["layers"], params["embed"], x_mb)  # [M, mb, T, E]
    return out.reshape(b, t, -1)


def pp_loss_fn(params, cfg, tokens, targets, loss_mask, mesh,
               num_microbatches: int):
    from arks_tpu.train.sft import head_loss

    h = pipeline_forward(params, cfg, tokens, mesh, num_microbatches)
    return head_loss(params, cfg, h, targets, loss_mask)


def make_pp_train_step(cfg, optimizer, mesh, num_microbatches: int):
    """Jitted pipeline-parallel train step (same contract as
    arks_tpu.train.sft.make_train_step — shares its loss head and
    optimizer-step body)."""
    from arks_tpu.train.sft import make_step_fn

    step = make_step_fn(
        lambda params, tokens, targets, loss_mask: pp_loss_fn(
            params, cfg, tokens, targets, loss_mask, mesh, num_microbatches),
        optimizer)
    return jax.jit(step, donate_argnums=(0,))


def pp_train_init(cfg, key, optimizer, mesh, dtype=jnp.float32):
    from arks_tpu.train.sft import TrainState

    params = tf.init_params(cfg, key, dtype)
    params = shard_params_pp(params, mesh)
    opt_state = optimizer.init(params)
    return TrainState(params=params, opt_state=opt_state,
                      step=jnp.zeros((), jnp.int32))


# ---------------------------------------------------------------------------
# Serving: stage-sharded KV cache, pipelined decode, one-shot prefill
# ---------------------------------------------------------------------------


def shard_cache_pp(cache, mesh, stage_axis: str = AXIS_STAGE):
    """KV cache sharded over the STAGE axis on its layer dim: each stage
    holds only its own layers' KV — HBM capacity scales with stages, the
    lever serving PP exists for (models whose weights+KV exceed one chip)."""
    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    spec = P(stage_axis)
    return tf.KVCache(
        k=put(cache.k, spec), v=put(cache.v, spec),
        k_scale=put(cache.k_scale, spec) if cache.quantized else None,
        v_scale=put(cache.v_scale, spec) if cache.quantized else None)


def shard_paged_cache_pp(cache, mesh, stage_axis: str = AXIS_STAGE):
    """Paged pool sharded over the STAGE axis on its layer dim — the paged
    counterpart of ``shard_cache_pp``.  Pages (dim 1) stay whole: block
    tables index one global page id space and every stage holds its own
    layers' rows of each page."""
    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    spec = P(stage_axis)
    return tf.PagedKVCache(
        k=put(cache.k, spec), v=put(cache.v, spec),
        k_scale=put(cache.k_scale, spec) if cache.quantized else None,
        v_scale=put(cache.v_scale, spec) if cache.quantized else None)


def pp_decode_step_paged(
    params,
    cfg,
    cache,                 # PagedKVCache, pool sharded over ``stage`` on L
    tables: jnp.ndarray,   # [B, MaxP] int32 block tables
    tokens: jnp.ndarray,   # [B] int32
    lengths: jnp.ndarray,  # [B] int32
    mesh,
    num_microbatches: int,
    stage_axis: str = AXIS_STAGE,
):
    """One decode token for every slot against the PAGED pool, layers
    pipelined over stages — the paged counterpart of ``pp_decode_step``.

    The pool has no batch dim, so unlike the slot path there is no
    per-microbatch cache slice: the whole (stage-local) pool rides the
    tick carry and each microbatch writes through its rows of the block
    tables.  Bubble ticks skip via ``lax.cond`` (a bubble write through a
    clamped microbatch's tables would corrupt a REAL slot's pages); freed
    slots parked at the coverage sentinel are dropped inside the paged op,
    as on the single-stage path (transformer.decode_step).

    NOTE: the tick/bubble/clamp pipelining scaffolding here is the TWIN of
    ``pp_decode_step``'s — the two differ only in per-tick cache access
    (whole pool + table row here vs dynamic batch slice there).  A fix to
    the bubble-skip, out_idx clamp, or psum-collection logic in one almost
    certainly applies to the other.
    """
    num_stages = mesh.shape[stage_axis]
    if cfg.num_layers % num_stages != 0:
        raise ValueError(f"{cfg.num_layers} layers not divisible into "
                         f"{num_stages} stages")
    b = tokens.shape[0]
    m = num_microbatches
    if b % m != 0:
        raise ValueError(f"batch {b} not divisible into {m} microbatches")
    mbs = b // m
    quantized = cache.quantized
    compute_dtype = params["layers"]["attn_norm"].dtype
    page = cache.page
    cover = tables.shape[1] * page
    from arks_tpu.ops.attention import paged_decode_update_and_attend

    def local(layers_local, embed, kc, vc, ksc, vsc, tables, tokens, lengths):
        s_ax = axis_size(stage_axis)
        s_id = jax.lax.axis_index(stage_axis)
        perm = [(i, (i + 1) % s_ax) for i in range(s_ax)]
        toks_mb = tokens.reshape(m, mbs)
        lens_mb = lengths.reshape(m, mbs)
        tbl_mb = tables.reshape(m, mbs, -1)
        e = embed.shape[1]

        def run_stage(h, kc, vc, ksc, vsc, tbl, lens):
            write_idx = lens.astype(jnp.int32)
            # RoPE positions must be real for active slots; the sentinel
            # (>= coverage) only matters to the paged op, which drops it.
            rope_idx = jnp.minimum(write_idx, cover - 1)

            def body(carry, xs):
                h, kc, vc, ksc, vsc = carry
                lp, layer = xs
                x = tf.rms_norm(h, lp["attn_norm"], cfg.rms_norm_eps)
                q, k, v = tf._qkv(x, lp, cfg)
                q = q.reshape(mbs, cfg.num_heads, cfg.head_dim)
                k = k.reshape(mbs, cfg.num_kv_heads, cfg.head_dim)
                v = v.reshape(mbs, cfg.num_kv_heads, cfg.head_dim)
                q = tf.apply_rope(q, rope_idx, cfg.rope_theta)
                k = tf.apply_rope(k, rope_idx, cfg.rope_theta)
                # XLA impl for the same reason as the slot pp path: tiny
                # per-stage microbatches bind the kernels' batch tiling.
                attn, kc, vc, ksc, vsc = paged_decode_update_and_attend(
                    q, k, v, kc, vc, tbl, write_idx, layer, impl="xla",
                    k_scale=ksc, v_scale=vsc)
                attn = attn.reshape(mbs, cfg.q_dim)
                h = h + tf.qeinsum("bq,qe->be", attn, lp["wo"])
                h = h + tf._mlp(h, lp, cfg, None, None)
                return (h, kc, vc, ksc, vsc), None

            n_local = jax.tree.leaves(layers_local)[0].shape[0]
            (h, kc, vc, ksc, vsc), _ = jax.lax.scan(
                body, (h, kc, vc, ksc, vsc),
                (layers_local, jnp.arange(n_local, dtype=jnp.int32)))
            return h, kc, vc, ksc, vsc

        buf = jnp.zeros((mbs, e), compute_dtype)
        h_acc = jnp.zeros((m, mbs, e), compute_dtype)

        def tick(carry, ti):
            kc, vc, ksc, vsc, buf, h_acc = carry
            mi = ti - s_id
            valid = (mi >= 0) & (mi < m)
            mi_c = jnp.clip(mi, 0, m - 1)
            toks = jax.lax.dynamic_index_in_dim(toks_mb, mi_c, 0, keepdims=False)
            lens = jax.lax.dynamic_index_in_dim(lens_mb, mi_c, 0, keepdims=False)
            tbl = jax.lax.dynamic_index_in_dim(tbl_mb, mi_c, 0, keepdims=False)
            h0 = tf.embed_lookup(embed, toks, compute_dtype)
            h_in = jnp.where(s_id == 0, h0, buf)

            def do(h_in, kc, vc, ksc, vsc, tbl, lens):
                return run_stage(h_in, kc, vc, ksc, vsc, tbl, lens)

            def skip(h_in, kc, vc, ksc, vsc, tbl, lens):
                return jnp.zeros_like(h_in), kc, vc, ksc, vsc

            h_out, kc, vc, ksc, vsc = jax.lax.cond(
                valid, do, skip, h_in, kc, vc, ksc, vsc, tbl, lens)
            out_idx = jnp.clip(ti - (s_ax - 1), 0, m - 1)
            h_acc = jax.lax.dynamic_update_slice(
                h_acc, h_out[None].astype(h_acc.dtype), (out_idx, 0, 0))
            buf = jax.lax.ppermute(h_out, stage_axis, perm)
            return (kc, vc, ksc, vsc, buf, h_acc), None

        (kc, vc, ksc, vsc, buf, h_acc), _ = jax.lax.scan(
            tick, (kc, vc, ksc, vsc, buf, h_acc),
            jnp.arange(m + s_ax - 1))
        mask = (s_id == s_ax - 1).astype(h_acc.dtype)
        h_final = jax.lax.psum(h_acc * mask, stage_axis)
        return h_final, kc, vc, ksc, vsc

    cspec = P(stage_axis)
    sspec = cspec if quantized else None
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(stage_axis), P(), cspec, cspec, sspec, sspec,
                  P(), P(), P()),
        out_specs=(P(), cspec, cspec, sspec, sspec),
        check_vma=False,
    )
    h, kc, vc, ksc, vsc = fn(params["layers"], params["embed"],
                             cache.k, cache.v, cache.k_scale, cache.v_scale,
                             tables, tokens, lengths)
    logits = tf._unembed(h.reshape(b, -1), params, cfg, None, None)
    return logits, tf.PagedKVCache(k=kc, v=vc, k_scale=ksc, v_scale=vsc)


def pp_decode_step(
    params,
    cfg,
    cache,
    tokens: jnp.ndarray,   # [B] int32
    lengths: jnp.ndarray,  # [B] int32
    mesh,
    num_microbatches: int,
    stage_axis: str = AXIS_STAGE,
):
    """One decode token for every slot, layers pipelined over stages.

    The batch splits into M microbatches of contiguous slots; for
    M + S - 1 ticks each stage advances one microbatch through its local
    layers (updating its local KV shard) and ``ppermute``s activations on.
    Bubble ticks run a ``lax.cond`` no-op branch: unlike activations
    (overwritten before read), a bubble CACHE write would corrupt a real
    slot's rows, so bubbles must genuinely skip.  The final hidden states
    are psum-collected from the last stage and unembedded OUTSIDE the
    shard_map — once, replicated, instead of S redundant vocab matmuls.

    The attention/update body runs the XLA path (impl="xla"): per-stage
    microbatches are small and kernel batch-tiling constraints would bind;
    PP's win is HBM capacity, not decode-kernel latency.

    NOTE: the tick/bubble/clamp pipelining scaffolding here is the TWIN of
    ``pp_decode_step_paged``'s (see its docstring) — keep fixes in sync.
    """
    num_stages = mesh.shape[stage_axis]
    if cfg.num_layers % num_stages != 0:
        raise ValueError(f"{cfg.num_layers} layers not divisible into "
                         f"{num_stages} stages")
    b = tokens.shape[0]
    m = num_microbatches
    if b % m != 0:
        raise ValueError(f"batch {b} not divisible into {m} microbatches")
    mbs = b // m
    quantized = cache.quantized
    compute_dtype = params["layers"]["attn_norm"].dtype
    from arks_tpu.ops.attention import decode_update_and_attend

    def local(layers_local, embed, kc, vc, ksc, vsc, tokens, lengths):
        s_ax = axis_size(stage_axis)
        s_id = jax.lax.axis_index(stage_axis)
        perm = [(i, (i + 1) % s_ax) for i in range(s_ax)]
        toks_mb = tokens.reshape(m, mbs)
        lens_mb = lengths.reshape(m, mbs)
        e = embed.shape[1]

        def run_stage(h, kc_mb, vc_mb, ks_mb, vs_mb, lens):
            write_idx = lens.astype(jnp.int32)

            def body(carry, xs):
                h, kc, vc, ks, vs = carry
                lp, layer = xs
                x = tf.rms_norm(h, lp["attn_norm"], cfg.rms_norm_eps)
                q, k, v = tf._qkv(x, lp, cfg)
                q = q.reshape(mbs, cfg.num_heads, cfg.head_dim)
                k = k.reshape(mbs, cfg.num_kv_heads, cfg.head_dim)
                v = v.reshape(mbs, cfg.num_kv_heads, cfg.head_dim)
                q = tf.apply_rope(q, write_idx, cfg.rope_theta)
                k = tf.apply_rope(k, write_idx, cfg.rope_theta)
                attn, kc, vc, ks, vs = decode_update_and_attend(
                    q, k, v, kc, vc, write_idx, layer, impl="xla",
                    k_scale=ks, v_scale=vs)
                attn = attn.reshape(mbs, cfg.q_dim)
                h = h + tf.qeinsum("bq,qe->be", attn, lp["wo"])
                h = h + tf._mlp(h, lp, cfg, None, None)
                return (h, kc, vc, ks, vs), None

            n_local = jax.tree.leaves(layers_local)[0].shape[0]
            (h, kc_mb, vc_mb, ks_mb, vs_mb), _ = jax.lax.scan(
                body, (h, kc_mb, vc_mb, ks_mb, vs_mb),
                (layers_local, jnp.arange(n_local, dtype=jnp.int32)))
            return h, kc_mb, vc_mb, ks_mb, vs_mb

        buf = jnp.zeros((mbs, e), compute_dtype)
        h_acc = jnp.zeros((m, mbs, e), compute_dtype)

        def tick(carry, ti):
            kc, vc, ksc, vsc, buf, h_acc = carry
            mi = ti - s_id
            valid = (mi >= 0) & (mi < m)
            mi_c = jnp.clip(mi, 0, m - 1)
            start = mi_c * mbs
            toks = jax.lax.dynamic_index_in_dim(toks_mb, mi_c, 0, keepdims=False)
            lens = jax.lax.dynamic_index_in_dim(lens_mb, mi_c, 0, keepdims=False)
            h0 = tf.embed_lookup(embed, toks, compute_dtype)
            h_in = jnp.where(s_id == 0, h0, buf)

            kc_mb = jax.lax.dynamic_slice_in_dim(kc, start, mbs, axis=1)
            vc_mb = jax.lax.dynamic_slice_in_dim(vc, start, mbs, axis=1)
            ks_mb = (jax.lax.dynamic_slice_in_dim(ksc, start, mbs, axis=1)
                     if quantized else None)
            vs_mb = (jax.lax.dynamic_slice_in_dim(vsc, start, mbs, axis=1)
                     if quantized else None)

            def do(h_in, kc_mb, vc_mb, ks_mb, vs_mb, lens):
                return run_stage(h_in, kc_mb, vc_mb, ks_mb, vs_mb, lens)

            def skip(h_in, kc_mb, vc_mb, ks_mb, vs_mb, lens):
                return jnp.zeros_like(h_in), kc_mb, vc_mb, ks_mb, vs_mb

            h_out, kc_mb, vc_mb, ks_mb, vs_mb = jax.lax.cond(
                valid, do, skip, h_in, kc_mb, vc_mb, ks_mb, vs_mb, lens)

            kc = jax.lax.dynamic_update_slice_in_dim(kc, kc_mb, start, 1)
            vc = jax.lax.dynamic_update_slice_in_dim(vc, vc_mb, start, 1)
            if quantized:
                ksc = jax.lax.dynamic_update_slice_in_dim(ksc, ks_mb, start, 1)
                vsc = jax.lax.dynamic_update_slice_in_dim(vsc, vs_mb, start, 1)
            # Last stage's h_out lands at its microbatch row (bubble-tick
            # garbage at clamped rows is overwritten before the psum reads
            # it — same trick as pipeline_forward).
            out_idx = jnp.clip(ti - (s_ax - 1), 0, m - 1)
            h_acc = jax.lax.dynamic_update_slice(
                h_acc, h_out[None].astype(h_acc.dtype), (out_idx, 0, 0))
            buf = jax.lax.ppermute(h_out, stage_axis, perm)
            return (kc, vc, ksc, vsc, buf, h_acc), None

        (kc, vc, ksc, vsc, buf, h_acc), _ = jax.lax.scan(
            tick, (kc, vc, ksc, vsc, buf, h_acc),
            jnp.arange(m + s_ax - 1))
        mask = (s_id == s_ax - 1).astype(h_acc.dtype)
        h_final = jax.lax.psum(h_acc * mask, stage_axis)
        return h_final, kc, vc, ksc, vsc

    cspec = P(stage_axis)
    sspec = cspec if quantized else None
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(stage_axis), P(), cspec, cspec, sspec, sspec, P(), P()),
        out_specs=(P(), cspec, cspec, sspec, sspec),
        check_vma=False,
    )
    h, kc, vc, ksc, vsc = fn(params["layers"], params["embed"],
                             cache.k, cache.v, cache.k_scale, cache.v_scale,
                             tokens, lengths)
    logits = tf._unembed(h.reshape(b, -1), params, cfg, None, None)
    return logits, tf.KVCache(k=kc, v=vc, k_scale=ksc, v_scale=vsc)


def pp_prefill(
    params,
    cfg,
    tokens: jnp.ndarray,   # [B, T] int32, bucket-padded
    lengths: jnp.ndarray,  # [B] int32
    mesh,
    stage_axis: str = AXIS_STAGE,
):
    """One-shot serving prefill over stages.  Returns (last-token logits
    [B, V] f32 replicated, ks, vs time-major [L, B, T, Hkv, D] sharded over
    ``stage`` on L) — the same contract as transformer.prefill, so the
    engine's insert into a stage-sharded cache stays a local write.

    Single stream (serving prefills one prompt per dispatch), so no
    microbatch overlap: stages run in sequence, each contributing its
    layers; PP prefill trades bubbles for fitting the model at all.
    """
    num_stages = mesh.shape[stage_axis]
    if cfg.num_layers % num_stages != 0:
        raise ValueError(f"{cfg.num_layers} layers not divisible into "
                         f"{num_stages} stages")
    b, t = tokens.shape
    compute_dtype = params["layers"]["attn_norm"].dtype

    def local(layers_local, embed, tokens):
        s_ax = axis_size(stage_axis)
        s_id = jax.lax.axis_index(stage_axis)
        perm = [(i, (i + 1) % s_ax) for i in range(s_ax)]
        positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))

        def run_stage(h):
            def body(h, lp):
                h, k, v = tf.prefill_layer(h, lp, cfg, positions, None)
                return h, (k, v)
            return jax.lax.scan(body, h, layers_local)

        h = tf.embed_lookup(embed, tokens, compute_dtype)
        ks = vs = None
        # S sequential hops: stage s computes on hop s (earlier hops carry
        # zeros through it — cheap relative to fitting the model, and the
        # KV it produces on non-final hops is discarded by the where).
        for hop in range(num_stages):
            h_out, (k_hop, v_hop) = run_stage(h)
            keep = (s_id == hop)
            ks = k_hop if ks is None else jnp.where(keep, k_hop, ks)
            vs = v_hop if vs is None else jnp.where(keep, v_hop, vs)
            h = jax.lax.ppermute(h_out, stage_axis, perm)
        # After S hops the fully-processed h is back at stage 0; every
        # stage's ks/vs hold ITS layers' KV (the shard_map out_spec stacks
        # them into the global [L, ...]).
        mask = (s_id == 0).astype(h.dtype)
        h_final = jax.lax.psum(h * mask, stage_axis)
        return h_final, ks, vs

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(stage_axis), P(), P()),
        out_specs=(P(), P(stage_axis), P(stage_axis)),
        check_vma=False,
    )
    h, ks, vs = fn(params["layers"], params["embed"], tokens)
    h_last = jnp.take_along_axis(
        h, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    logits = tf._unembed(h_last, params, cfg, None, None)
    return logits, ks, vs
