"""Pipeline parallelism: layer stages over a mesh axis, microbatch pipeline.

The reference has no pipeline-parallel code (its runtimes handle any model
parallelism internally; SURVEY.md §2.4); here PP is a first-class mesh axis
for training and offline forward passes over models deeper than one slice's
memory.

TPU-native formulation (collective-permute pipeline, scaling-book style):
- The stacked layer params [L, ...] shard their leading dim over the
  ``stage`` axis — no re-packing: each device simply holds L/S consecutive
  layers, and the per-stage body is the same ``lax.scan`` the unsharded
  model uses.
- The batch splits into M microbatches.  For M + S - 1 ticks, every stage
  runs its layers on its current microbatch and ``ppermute``s activations to
  the next stage over ICI.  Bubbles are computed-and-discarded (standard:
  utilization M / (M + S - 1)).
- The last stage accumulates outputs; a masked psum over the stage axis
  replicates them at the end.  Gradients flow backward through the
  ppermute/psum transposes automatically, so one ``jax.grad`` differentiates
  the whole pipeline.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from arks_tpu.models import transformer as tf
from arks_tpu.parallel.mesh import AXIS_STAGE


def shard_params_pp(params, mesh, stage_axis: str = AXIS_STAGE):
    """Shard the stacked layer dim over the stage axis; everything else
    (embed, final_norm, lm_head) replicated."""
    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    out = dict(params)
    out["layers"] = jax.tree.map(
        lambda x: put(x, P(stage_axis)), params["layers"])
    for k in ("embed", "final_norm", "lm_head"):
        if k in params:
            out[k] = put(params[k], P())
    return out


def pipeline_forward(
    params,
    cfg,
    tokens: jnp.ndarray,  # [B, T] int32
    mesh,
    num_microbatches: int,
    stage_axis: str = AXIS_STAGE,
) -> jnp.ndarray:
    """Hidden states [B, T, E] (pre-final-norm), replicated across stages."""
    num_stages = mesh.shape[stage_axis]
    if cfg.num_layers % num_stages != 0:
        raise ValueError(f"{cfg.num_layers} layers not divisible into "
                         f"{num_stages} stages")
    b, t = tokens.shape
    m = num_microbatches
    if b % m != 0:
        raise ValueError(f"batch {b} not divisible into {m} microbatches")
    mb = b // m
    x_mb = tokens.reshape(m, mb, t)

    def local(layers_local, embed, x_mb):
        s_ax = jax.lax.axis_size(stage_axis)
        s_id = jax.lax.axis_index(stage_axis)
        perm = [(i, (i + 1) % s_ax) for i in range(s_ax)]
        positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (mb, t))

        def run_stage(h):
            def body(h, lp):
                h, _, _ = tf.prefill_layer(h, lp, cfg, positions, None)
                return h, None
            h, _ = jax.lax.scan(body, h, layers_local)
            return h

        e = embed.shape[1]
        # Embed the whole microbatch stream ONCE (only stage 0's copy is
        # read, but hoisting it keeps the vocab-table gather out of the
        # per-tick loop on every stage).
        x_emb = jnp.take(embed, x_mb, axis=0)  # [M, mb, T, E]
        buf = jnp.zeros((mb, t, e), embed.dtype)
        outputs = jnp.zeros((m, mb, t, e), embed.dtype)

        def tick(carry, ti):
            buf, outputs = carry
            # Stage 0 feeds from the embedded microbatch stream; later
            # stages from the ring buffer.  Clamped indices during bubble
            # ticks write garbage that is overwritten before it's read
            # (microbatch i's real result lands at tick i + S - 1).
            x0 = jax.lax.dynamic_index_in_dim(
                x_emb, jnp.clip(ti, 0, m - 1), 0, keepdims=False)
            h_in = jnp.where(s_id == 0, x0, buf)
            h_out = run_stage(h_in)
            out_idx = jnp.clip(ti - (s_ax - 1), 0, m - 1)
            outputs = jax.lax.dynamic_update_slice(
                outputs, h_out[None].astype(outputs.dtype), (out_idx, 0, 0, 0))
            buf = jax.lax.ppermute(h_out, stage_axis, perm)
            return (buf, outputs), None

        (buf, outputs), _ = jax.lax.scan(
            tick, (buf, outputs), jnp.arange(m + s_ax - 1))
        mask = (s_id == s_ax - 1).astype(outputs.dtype)
        return jax.lax.psum(outputs * mask, stage_axis)

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(stage_axis), P(), P()),
        out_specs=P(),
        check_vma=False,
    )
    out = fn(params["layers"], params["embed"], x_mb)  # [M, mb, T, E]
    return out.reshape(b, t, -1)


def pp_loss_fn(params, cfg, tokens, targets, loss_mask, mesh,
               num_microbatches: int):
    from arks_tpu.train.sft import head_loss

    h = pipeline_forward(params, cfg, tokens, mesh, num_microbatches)
    return head_loss(params, cfg, h, targets, loss_mask)


def make_pp_train_step(cfg, optimizer, mesh, num_microbatches: int):
    """Jitted pipeline-parallel train step (same contract as
    arks_tpu.train.sft.make_train_step — shares its loss head and
    optimizer-step body)."""
    from arks_tpu.train.sft import make_step_fn

    step = make_step_fn(
        lambda params, tokens, targets, loss_mask: pp_loss_fn(
            params, cfg, tokens, targets, loss_mask, mesh, num_microbatches),
        optimizer)
    return jax.jit(step, donate_argnums=(0,))


def pp_train_init(cfg, key, optimizer, mesh, dtype=jnp.float32):
    from arks_tpu.train.sft import TrainState

    params = tf.init_params(cfg, key, dtype)
    params = shard_params_pp(params, mesh)
    opt_state = optimizer.init(params)
    return TrainState(params=params, opt_state=opt_state,
                      step=jnp.zeros((), jnp.int32))
