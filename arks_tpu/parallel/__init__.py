from arks_tpu.parallel.mesh import MeshPlan, make_mesh

__all__ = ["MeshPlan", "make_mesh"]
