"""Device-mesh construction for serving and training.

The reference expresses parallelism as container flags
(``--tensor-parallel-size`` / ``--tp``, /root/reference/internal/controller/
arksapplication_controller.go:949-995) executed by NCCL inside runtime
containers.  Here the flag becomes a real mesh dimension: a
``jax.sharding.Mesh`` with axes (data, model), with the model axis laid out
over ICI-adjacent devices so TP collectives never leave the slice.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_MODEL = "model"


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """Resolved parallelism plan for a serving replica group."""

    tensor_parallel: int
    data_parallel: int

    @property
    def num_devices(self) -> int:
        return self.tensor_parallel * self.data_parallel


def resolve_plan(num_devices: int, tensor_parallel: int | None = None,
                 data_parallel: int | None = None) -> MeshPlan:
    if tensor_parallel is None and data_parallel is None:
        tensor_parallel, data_parallel = num_devices, 1
    elif tensor_parallel is None:
        assert num_devices % data_parallel == 0, (num_devices, data_parallel)
        tensor_parallel = num_devices // data_parallel
    elif data_parallel is None:
        assert num_devices % tensor_parallel == 0, (num_devices, tensor_parallel)
        data_parallel = num_devices // tensor_parallel
    plan = MeshPlan(tensor_parallel=tensor_parallel, data_parallel=data_parallel)
    if plan.num_devices != num_devices:
        raise ValueError(f"plan {plan} does not cover {num_devices} devices")
    return plan


def make_mesh(tensor_parallel: int | None = None, data_parallel: int | None = None,
              devices=None) -> Mesh:
    """Mesh with axes (data, model).

    The model (TP) axis is innermost — on TPU, ``jax.devices()`` order follows
    physical topology, so innermost-axis neighbors are ICI-adjacent and TP
    psums ride the fastest links (scaling-book recipe).
    """
    devices = list(devices if devices is not None else jax.devices())
    plan = resolve_plan(len(devices), tensor_parallel, data_parallel)
    grid = np.asarray(devices).reshape(plan.data_parallel, plan.tensor_parallel)
    return Mesh(grid, (AXIS_DATA, AXIS_MODEL))
