"""Device-mesh construction for serving and training.

The reference expresses parallelism as container flags
(``--tensor-parallel-size`` / ``--tp``, /root/reference/internal/controller/
arksapplication_controller.go:949-995) executed by NCCL inside runtime
containers.  Here the flag becomes a real mesh dimension: a
``jax.sharding.Mesh`` with axes (data, model), with the model axis laid out
over ICI-adjacent devices so TP collectives never leave the slice.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_STAGE = "stage"
AXIS_SEQ = "seq"
AXIS_MODEL = "model"
AXIS_SLICE = "slice"  # multi-slice: the DCN-crossing axis (outermost)


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """Resolved parallelism plan for a serving replica group."""

    tensor_parallel: int
    data_parallel: int
    context_parallel: int = 1
    pipeline_parallel: int = 1

    @property
    def num_devices(self) -> int:
        return (self.tensor_parallel * self.data_parallel
                * self.context_parallel * self.pipeline_parallel)


def resolve_plan(num_devices: int, tensor_parallel: int | None = None,
                 data_parallel: int | None = None,
                 context_parallel: int = 1,
                 pipeline_parallel: int = 1) -> MeshPlan:
    fixed = context_parallel * pipeline_parallel
    if num_devices % fixed != 0:
        raise ValueError(
            f"context_parallel*pipeline_parallel={fixed} must divide "
            f"num_devices={num_devices} "
            f"(context_parallel={context_parallel}, pipeline_parallel={pipeline_parallel})")
    rem = num_devices // fixed
    if tensor_parallel is None and data_parallel is None:
        tensor_parallel, data_parallel = rem, 1
    elif tensor_parallel is None:
        if rem % data_parallel != 0:
            raise ValueError(
                f"data_parallel={data_parallel} must divide the remaining "
                f"{rem} devices")
        tensor_parallel = rem // data_parallel
    elif data_parallel is None:
        if rem % tensor_parallel != 0:
            raise ValueError(
                f"tensor_parallel={tensor_parallel} must divide the remaining "
                f"{rem} devices")
        data_parallel = rem // tensor_parallel
    plan = MeshPlan(tensor_parallel=tensor_parallel, data_parallel=data_parallel,
                    context_parallel=context_parallel,
                    pipeline_parallel=pipeline_parallel)
    if plan.num_devices != num_devices:
        raise ValueError(f"plan {plan} does not cover {num_devices} devices")
    return plan


def make_mesh(tensor_parallel: int | None = None, data_parallel: int | None = None,
              context_parallel: int = 1, pipeline_parallel: int = 1,
              devices=None) -> Mesh:
    """Mesh with axes (data, stage, seq, model).

    The model (TP) axis is innermost — on TPU, ``jax.devices()`` order follows
    physical topology, so innermost-axis neighbors are ICI-adjacent and TP
    psums ride the fastest links (scaling-book recipe).  The seq (context-
    parallel) axis sits next: ring-attention ppermutes are
    neighbor-to-neighbor, so they too want ICI adjacency, but TP collectives
    are latency-critical per layer while the ring overlaps with compute.
    The stage (pipeline) axis is outermost of the model axes: its ppermutes
    fire once per microbatch tick, the least latency-sensitive traffic.
    """
    devices = list(devices if devices is not None else jax.devices())
    plan = resolve_plan(len(devices), tensor_parallel, data_parallel,
                        context_parallel, pipeline_parallel)
    # np.array, not np.asarray: the operand is a host list of Device
    # HANDLES (no device data moves), and make_mesh is now reachable
    # from the elastic resize path inside step() — the hot-path lint
    # reads asarray as a D2H fetch.
    grid = np.array(devices).reshape(
        plan.data_parallel, plan.pipeline_parallel, plan.context_parallel,
        plan.tensor_parallel)
    return Mesh(grid, (AXIS_DATA, AXIS_STAGE, AXIS_SEQ, AXIS_MODEL))


def make_multislice_mesh(num_slices: int, tensor_parallel: int | None = None,
                         data_parallel: int | None = None,
                         context_parallel: int = 1,
                         pipeline_parallel: int = 1,
                         devices=None) -> Mesh:
    """Multi-slice mesh with axes (slice, data, stage, seq, model).

    The ``slice`` axis is OUTERMOST: on real multi-slice TPU (v5p pods
    joined over DCN, north-star config #5) ``jax.devices()`` enumerates
    process-major — slice-local devices are contiguous — so only
    slice-axis collectives cross DCN.  Everything else (tp psums, ring
    ppermutes, pipeline sends) stays on ICI inside a slice.  The intended
    use is data parallelism over slices (gradient all-reduce amortizes
    over a whole step — the scaling-book DCN recipe); batch-sharded
    tensors shard over ``("slice", "data")`` (transformer.batch_axis_for).
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_slices < 1 or len(devices) % num_slices != 0:
        raise ValueError(
            f"num_slices={num_slices} must divide {len(devices)} devices")
    per_slice = len(devices) // num_slices
    plan = resolve_plan(per_slice, tensor_parallel, data_parallel,
                        context_parallel, pipeline_parallel)
    grid = np.asarray(devices).reshape(
        num_slices, plan.data_parallel, plan.pipeline_parallel,
        plan.context_parallel, plan.tensor_parallel)
    return Mesh(grid, (AXIS_SLICE, AXIS_DATA, AXIS_STAGE, AXIS_SEQ,
                       AXIS_MODEL))
