"""Router failover: a request moves to the next ready decode backend on
connection error or 503 — iff no response bytes have been streamed yet —
with one bounded backoff round and Retry-After passthrough."""

import json
import socket
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from arks_tpu.router import Discovery, Router


class _FakeBackend:
    """A scriptable decode backend: each element of ``script`` handles one
    request — "ok", "503", or ("503", retry_after).  Past the script's
    end the last entry repeats."""

    def __init__(self, script):
        backend = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                i = min(backend.calls, len(backend.script) - 1)
                backend.calls += 1
                action = backend.script[i]
                retry_after = None
                if isinstance(action, tuple):
                    action, retry_after = action
                if action == "503":
                    data = b'{"error":{"message":"draining","code":503}}'
                    self.send_response(503)
                    if retry_after is not None:
                        self.send_header("Retry-After", str(retry_after))
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                data = json.dumps({
                    "id": "ok", "object": "text_completion",
                    "served_by": backend.name, "choices": []}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self.script = script
        self.calls = 0
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.addr = f"127.0.0.1:{self._httpd.server_port}"
        self.name = self.addr
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self._httpd.shutdown()


def _free_port_addr() -> str:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"127.0.0.1:{port}"


def _mk_router(monkeypatch, decode_addrs, prefill_addr="127.0.0.1:1"):
    monkeypatch.setenv("ARKS_PREFILL_ADDRS", prefill_addr)
    monkeypatch.setenv("ARKS_DECODE_ADDRS", ",".join(decode_addrs))
    monkeypatch.setenv("ARKS_ROUTER_RETRY_BACKOFF_S", "0.01")
    router = Router(Discovery(None), "tiny", host="127.0.0.1", port=0,
                    policy="round_robin")
    router.start(background=True)
    return router


def _post(router, body=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{router.port}/v1/completions",
        data=json.dumps(body or {"model": "tiny", "prompt": "x"}).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=30)


def test_failover_on_503_to_next_backend(monkeypatch):
    bad = _FakeBackend(["503"])
    good = _FakeBackend(["ok"])
    router = _mk_router(monkeypatch, [bad.addr, good.addr])
    try:
        with _post(router) as r:
            out = json.load(r)
        assert out["served_by"] == good.addr
        assert bad.calls == 1 and good.calls == 1
        assert router.retries_total.get(reason="backend_503") >= 1
    finally:
        router.stop()
        bad.stop()
        good.stop()


def test_failover_on_connection_error(monkeypatch):
    dead = _free_port_addr()  # nothing listening: connection refused
    good = _FakeBackend(["ok"])
    router = _mk_router(monkeypatch, [dead, good.addr])
    try:
        with _post(router) as r:
            out = json.load(r)
        assert out["served_by"] == good.addr
        assert router.retries_total.get(reason="connect_error") >= 1
    finally:
        router.stop()
        good.stop()


def test_flapping_backend_recovers_on_backoff_round(monkeypatch):
    """Every backend 503s on the first pass; one comes back on the single
    bounded backoff round — the request still succeeds."""
    flapper = _FakeBackend(["503", "ok"])
    router = _mk_router(monkeypatch, [flapper.addr])
    try:
        with _post(router) as r:
            out = json.load(r)
        assert out["served_by"] == flapper.addr
        assert flapper.calls == 2
    finally:
        router.stop()
        flapper.stop()


def test_all_backends_503_passes_retry_after_through(monkeypatch):
    a = _FakeBackend([("503", 7)])
    b = _FakeBackend([("503", 31)])
    router = _mk_router(monkeypatch, [a.addr, b.addr])
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(router)
        assert ei.value.code == 503
        # Passthrough from a backend (either one's value is legitimate —
        # the router keeps the last seen).
        assert ei.value.headers.get("Retry-After") in ("7", "31")
        # Both backends were tried in both rounds: 2 backends x 2 rounds.
        assert a.calls == 2 and b.calls == 2
    finally:
        router.stop()
        a.stop()
        b.stop()


def test_tenant_shed_429_relays_headers_unchanged(monkeypatch):
    """A backend 429 (tenant_queue_full / shed) is NOT a failover event —
    it is the caller's own backlog.  The router relays the response with
    Retry-After, x-arks-tenant, and x-arks-saturation intact, and
    forwards the gateway-minted tenant header toward the backend."""
    from arks_tpu import tenancy

    seen_headers = {}

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            pass

        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            seen_headers.update(
                {k.lower(): v for k, v in self.headers.items()})
            data = (b'{"error":{"message":"tenant queue full",'
                    b'"code":"tenant_queue_full"}}')
            self.send_response(429)
            self.send_header("Retry-After", "3")
            self.send_header(tenancy.HDR_TENANT, "team-a/alice")
            self.send_header(tenancy.HDR_SATURATION, "0.87")
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    addr = f"127.0.0.1:{httpd.server_port}"
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    router = _mk_router(monkeypatch, [addr])
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{router.port}/v1/completions",
            data=json.dumps({"model": "tiny", "prompt": "x"}).encode(),
            headers={"Content-Type": "application/json",
                     tenancy.HDR_TENANT: "team-a/alice"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 429
        assert ei.value.headers.get("Retry-After") == "3"
        assert ei.value.headers.get(tenancy.HDR_TENANT) == "team-a/alice"
        assert ei.value.headers.get(tenancy.HDR_SATURATION) == "0.87"
        assert json.load(ei.value)["error"]["code"] == "tenant_queue_full"
        # Request-side: the minted identity reached the backend unchanged.
        assert seen_headers.get(tenancy.HDR_TENANT) == "team-a/alice"
    finally:
        router.stop()
        httpd.shutdown()


def test_no_backends_still_503s(monkeypatch):
    monkeypatch.setenv("ARKS_PREFILL_ADDRS", "")
    monkeypatch.setenv("ARKS_DECODE_ADDRS", "")
    router = Router(Discovery(None), "tiny", host="127.0.0.1", port=0)
    router.start(background=True)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(router)
        assert ei.value.code == 503
    finally:
        router.stop()
