"""Paged KV cache at the transformer layer: slot/paged equivalence.

The paged pool + block tables must be a drop-in for the slot-contiguous
cache: same logits from decode_step, same chunk-prefill results, and
prefix pages shared between slots with zero copies."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from arks_tpu.models import get_config
from arks_tpu.models import transformer as tf

PAGE = 16


def _mk(quantized=False):
    cfg = get_config("tiny")
    params = tf.init_params(cfg, jax.random.PRNGKey(0))
    slots, max_len = 4, 64
    max_pages = max_len // PAGE
    slot_cache = tf.init_cache(cfg, slots, max_len, quantized=quantized)
    pool = tf.init_paged_cache(cfg, num_pages=slots * max_pages + 3,
                               page=PAGE, quantized=quantized)
    # Identity-ish tables: slot b owns pages [b*max_pages, ...) shuffled.
    rng = np.random.default_rng(7)
    perm = rng.permutation(slots * max_pages)
    tables = jnp.asarray(perm.reshape(slots, max_pages), jnp.int32)
    return cfg, params, slot_cache, pool, tables, slots, max_len


@pytest.mark.parametrize("quantized", [False, True])
def test_decode_step_paged_matches_slot(quantized):
    cfg, params, slot_cache, pool, tables, slots, max_len = _mk(quantized)
    key = jax.random.PRNGKey(1)
    tokens = jax.random.randint(key, (slots,), 2, 200, jnp.int32)
    lengths = jnp.asarray([3, 17, 29, 5], jnp.int32)

    # Seed both caches with the same prompt KV via insert / insert_pages.
    for slot in range(slots):
        plen = int(lengths[slot])
        pk = jax.random.normal(jax.random.fold_in(key, slot),
                               (cfg.num_layers, 1, plen, cfg.num_kv_heads,
                                cfg.head_dim), jnp.float32)
        pv = pk * 0.5 + 1.0
        slot_cache = tf.insert(slot_cache, pk, pv, jnp.asarray(slot))
        n_pages = -(-plen // PAGE)
        pad = n_pages * PAGE - plen
        pkp = jnp.pad(pk, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
        pvp = jnp.pad(pv, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
        pool = tf.insert_pages(pool, pkp, pvp, tables[slot],
                               jnp.asarray(n_pages))

    logits_s, slot_cache = tf.decode_step(params, cfg, slot_cache, tokens,
                                          lengths)
    logits_p, pool = tf.decode_step(params, cfg, pool, tokens, lengths,
                                    tables=tables)
    np.testing.assert_allclose(np.asarray(logits_p), np.asarray(logits_s),
                               atol=2e-2 if quantized else 2e-4,
                               rtol=2e-2 if quantized else 2e-4)

    # Second step: the paged write of step 1 must land where step 2 reads.
    nxt = jnp.argmax(logits_s, axis=-1).astype(jnp.int32)
    l2 = lengths + 1
    logits_s2, _ = tf.decode_step(params, cfg, slot_cache, nxt, l2)
    logits_p2, _ = tf.decode_step(params, cfg, pool, nxt, l2, tables=tables)
    np.testing.assert_allclose(np.asarray(logits_p2), np.asarray(logits_s2),
                               atol=2e-2 if quantized else 2e-4,
                               rtol=2e-2 if quantized else 2e-4)


@pytest.mark.parametrize("quantized", [False, True])
def test_verify_step_paged_matches_slot(quantized):
    """Speculative verify over the paged cache == slot cache: same block
    logits, and the K written rows land where the next dispatch reads.
    lengths are chosen so one slot's block CROSSES a page boundary
    (29..32 with page 16)."""
    cfg, params, slot_cache, pool, tables, slots, max_len = _mk(quantized)
    key = jax.random.PRNGKey(2)
    K = 4
    blocks = jax.random.randint(key, (slots, K), 2, 200, jnp.int32)
    lengths = jnp.asarray([3, 17, 29, 5], jnp.int32)

    for slot in range(slots):
        plen = int(lengths[slot])
        pk = jax.random.normal(jax.random.fold_in(key, slot),
                               (cfg.num_layers, 1, plen, cfg.num_kv_heads,
                                cfg.head_dim), jnp.float32)
        pv = pk * 0.5 + 1.0
        slot_cache = tf.insert(slot_cache, pk, pv, jnp.asarray(slot))
        n_pages = -(-plen // PAGE)
        pad = n_pages * PAGE - plen
        pkp = jnp.pad(pk, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
        pvp = jnp.pad(pv, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
        pool = tf.insert_pages(pool, pkp, pvp, tables[slot],
                               jnp.asarray(n_pages))

    logits_s, slot_cache = tf.verify_step(params, cfg, slot_cache, blocks,
                                          lengths)
    logits_p, pool = tf.verify_step(params, cfg, pool, blocks, lengths,
                                    tables=tables)
    np.testing.assert_allclose(np.asarray(logits_p), np.asarray(logits_s),
                               atol=2e-2 if quantized else 2e-4,
                               rtol=2e-2 if quantized else 2e-4)

    # Follow-up decode reads the verify-written rows through the tables.
    nxt = jnp.argmax(logits_s[:, -1], axis=-1).astype(jnp.int32)
    l2 = lengths + K
    logits_s2, _ = tf.decode_step(params, cfg, slot_cache, nxt, l2)
    logits_p2, _ = tf.decode_step(params, cfg, pool, nxt, l2, tables=tables)
    np.testing.assert_allclose(np.asarray(logits_p2), np.asarray(logits_s2),
                               atol=2e-2 if quantized else 2e-4,
                               rtol=2e-2 if quantized else 2e-4)


def test_verify_step_paged_sentinel_drops_block_write():
    """Inactive slots (sentinel length) must not touch any page during a
    speculative verify — their whole K-row block is dropped."""
    cfg, params, _, pool, tables, slots, max_len = _mk()
    K = 4
    blocks = jnp.zeros((slots, K), jnp.int32)
    lengths = jnp.asarray([3, max_len, max_len, max_len], jnp.int32)
    before_k = np.asarray(pool.k)
    _, pool2 = tf.verify_step(params, cfg, pool, blocks, lengths,
                              tables=tables)
    after_k = np.asarray(pool2.k)
    # Slot 0 writes positions 3..6 -> table page 0 only.
    touched = {int(tables[0, 0])}
    for pg in range(pool.num_pages):
        if pg not in touched:
            np.testing.assert_array_equal(after_k[:, pg], before_k[:, pg])


def test_decode_step_paged_sentinel_drops_write():
    """An inactive slot (sentinel length) must not touch any page."""
    cfg, params, _, pool, tables, slots, max_len = _mk()
    tokens = jnp.zeros((slots,), jnp.int32)
    lengths = jnp.asarray([3, max_len, max_len, max_len], jnp.int32)
    before_k = np.asarray(pool.k)
    _, pool2 = tf.decode_step(params, cfg, pool, tokens, lengths,
                              tables=tables)
    after_k = np.asarray(pool2.k)
    # Only slot 0's page (position 3 -> table page 0) may change.
    touched = {int(tables[0, 0])}
    for pg in range(pool.num_pages):
        if pg not in touched:
            np.testing.assert_array_equal(after_k[:, pg], before_k[:, pg])


@pytest.mark.parametrize("quantized", [False, True])
def test_chunk_prefill_paged_matches_one_shot(quantized):
    """Chunked paged prefill == one-shot prefill logits (same math,
    blockwise), including a shared-prefix tail continuation."""
    cfg, params, _, pool, tables, slots, _ = _mk(quantized)
    prompt = list(np.random.default_rng(3).integers(2, 200, size=37))
    T = len(prompt)

    # One-shot reference: prefill the full prompt, take last-token logits.
    toks = jnp.asarray([prompt], jnp.int32)
    logits_ref, ks, vs = tf.prefill(params, cfg, toks,
                                    jnp.asarray([T], jnp.int32))

    # Paged chunked: page-sized chunks into slot 0's pages.
    row = tables[0]
    logits = None
    for start in range(0, T, PAGE):
        chunk = prompt[start: start + PAGE]
        valid = len(chunk)
        padded = np.zeros((PAGE,), np.int32)
        padded[:valid] = chunk
        logits, pool = tf.prefill_chunk_paged(
            params, cfg, pool, row, jnp.asarray(padded),
            jnp.asarray(start, jnp.int32), jnp.asarray(valid, jnp.int32))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(logits_ref),
                               atol=2e-2 if quantized else 1e-3,
                               rtol=2e-2 if quantized else 1e-3)

    # Prefix sharing: slot 1 points at slot 0's first 2 pages and chunk-
    # prefills only the tail -> same final logits, no KV copied.
    shared_row = tables[1].at[:2].set(row[:2])
    logits2 = None
    for start in range(2 * PAGE, T, PAGE):
        chunk = prompt[start: start + PAGE]
        valid = len(chunk)
        padded = np.zeros((PAGE,), np.int32)
        padded[:valid] = chunk
        logits2, pool = tf.prefill_chunk_paged(
            params, cfg, pool, shared_row, jnp.asarray(padded),
            jnp.asarray(start, jnp.int32), jnp.asarray(valid, jnp.int32))
    np.testing.assert_allclose(np.asarray(logits2), np.asarray(logits_ref),
                               atol=2e-2 if quantized else 1e-3,
                               rtol=2e-2 if quantized else 1e-3)


def test_insert_pages_then_gather_roundtrip():
    cfg, params, _, pool, tables, slots, _ = _mk()
    plen = 2 * PAGE
    k = jax.random.normal(jax.random.PRNGKey(5),
                          (cfg.num_layers, 1, plen, cfg.num_kv_heads,
                           cfg.head_dim), jnp.float32)
    v = k * 2.0
    pool = tf.insert_pages(pool, k, v, tables[2], jnp.asarray(2))
    gk, gv, _, _ = tf.gather_pages(pool, tables[2], jnp.asarray(0))
    # gather is [Hkv, S, D]; source layer 0 is [1, plen, Hkv, D].  The pool
    # stores the model dtype (bf16 for tiny), so compare post-cast.
    want = np.transpose(
        np.asarray(np.asarray(k)[0, 0].astype(pool.k.dtype)), (1, 0, 2))
    np.testing.assert_allclose(
        np.asarray(gk)[:, :plen].astype(np.float32),
        want.astype(np.float32), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(gv)[:, :plen].astype(np.float32),
        want.astype(np.float32) * 2.0, atol=2e-2)


# ---------------------------------------------------------------------------
# Engine-level: paged layout vs slot layout
# ---------------------------------------------------------------------------


def _run_engine(kv_layout, prompts, max_tokens=6, **cfg_kw):
    from arks_tpu.engine import EngineConfig, InferenceEngine
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.engine.types import Request, SamplingParams

    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=4, max_cache_len=64,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                        prefill_chunk=16, kv_layout=kv_layout, **cfg_kw)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    eng.start()
    outs = []
    try:
        for i, (prompt, seed) in enumerate(prompts):
            r = Request(request_id=f"r{i}", prompt_ids=prompt,
                        params=SamplingParams(max_tokens=max_tokens,
                                              temperature=0.0, seed=seed,
                                              ignore_eos=True))
            eng.add_request(r)
            toks = []
            while True:
                o = r.outputs.get(timeout=120)
                toks.extend(o.token_ids)
                if o.finished:
                    break
            outs.append(toks)
    finally:
        eng.stop()
    return outs, eng


def test_engine_paged_matches_slot_layout():
    """Greedy outputs through the full engine must be identical for both
    KV layouts — one-shot, repeated (prefix-hit), and chunked prompts."""
    tok = list(range(3, 40))
    prompts = [
        (tok[:7], 0),          # one-shot, shorter than a page
        (tok[:20], 0),         # one-shot, > one page
        (tok[:20], 0),         # identical -> paged prefix hit (1 page)
        (tok[:20] + [99, 98], 0),  # shared prefix page, different tail
        (tok[:33], 1),         # > largest bucket -> chunked
        ([5, 6], 2),           # tiny
    ]
    slot_out, _ = _run_engine("slot", prompts)
    paged_out, eng = _run_engine("paged", prompts)
    assert paged_out == slot_out
    assert eng._alloc.hit_tokens > 0  # the repeat actually shared pages
    # All request pages released; only index-retained pages hold refs.
    assert eng._alloc.free_pages == (
        eng._alloc.num_pages - eng._alloc.retained_pages)


def test_engine_paged_slot_reuse_is_clean():
    """Slot churn (finish -> new request in the same slot) must not leak
    pages or corrupt shared ones: outputs stay deterministic across a
    burst larger than the slot count."""
    prompts = [([3 + (i % 5), 7, 9, 11 + i % 3], i) for i in range(12)]
    out1, _ = _run_engine("paged", prompts, max_tokens=4)
    out2, _ = _run_engine("paged", prompts, max_tokens=4)
    assert out1 == out2


def test_page_allocator_refcounts_and_eviction():
    from arks_tpu.engine.paged import OutOfPagesError, PageAllocator, chain_digests

    a = PageAllocator(num_pages=6, page=4)
    p1 = a.alloc(2)
    ids = list(range(8))
    digs = chain_digests(ids, 4, 2)
    a.register(digs, p1)
    # A second request shares via match (its own refs).
    shared = a.match(digs)
    assert shared == p1
    a.decref(shared)       # request done
    a.decref(p1)           # original owner done; index still retains
    assert a.free_pages == 4 and a.retained_pages == 2
    # Pressure evicts LRU retained pages.
    big = a.alloc(6)
    assert len(big) == 6 and a.retained_pages == 0
    a.decref(big)
    # Exhaustion with nothing evictable raises.
    held = a.alloc(6)
    try:
        a.alloc(1)
        raise AssertionError("expected OutOfPagesError")
    except OutOfPagesError:
        pass
    a.decref(held)


def test_page_allocator_reregister_under_new_digest_is_skipped():
    """register() must not hijack a page already indexed under another
    digest: _page_digest is one-to-one, so the overwrite left a stale
    index entry whose eviction deleted the NEW digest's reverse mapping
    (a later eviction then KeyErrors mid-alloc) and leaked a refcount."""
    from arks_tpu.engine.paged import PageAllocator, chain_digests

    a = PageAllocator(num_pages=4, page=4)
    pg = a.alloc(1)
    d1 = chain_digests(list(range(4)), 4, 1)
    d2 = chain_digests(list(range(100, 104)), 4, 1)
    a.register(d1, pg)
    ref_before = a._ref[pg[0]]
    a.register(d2, pg)  # same page, different digest: skipped
    assert d2[0] not in a._index
    assert a._page_digest[pg[0]] == d1[0]
    assert a._ref[pg[0]] == ref_before  # no leaked index reference
    # Both digests evictable paths stay consistent: drain everything.
    a.decref(pg)
    while a.retained_pages:
        a._evict_lru()
    assert a.free_pages == a.num_pages
    assert not a._page_digest and not a._index


def test_page_allocator_interleaved_invariants():
    """Property-style interleaving of match/register/evict/decref: after
    every operation the refcount and free-list invariants must hold —
    every page is free XOR referenced, the index holds exactly one ref
    per entry, and _page_digest mirrors _index exactly."""
    import random

    from arks_tpu.engine.paged import (OutOfPagesError, PageAllocator,
                                       chain_digests)

    rng = random.Random(7)
    a = PageAllocator(num_pages=8, page=4)
    held: list[list[int]] = []     # caller-owned page lists

    def check():
        # _page_digest is the exact inverse of _index.
        assert {pg: d for d, pg in a._index.items()} == a._page_digest
        # Refcount per page == caller holds + index holds; free list is
        # exactly the zero-ref pages, each listed once.
        for pg in range(a.num_pages):
            expect = sum(row.count(pg) for row in held)
            expect += 1 if pg in a._page_digest else 0
            assert a._ref[pg] == expect, (pg, a._ref[pg], expect)
            assert (a._free.count(pg) == 1) == (expect == 0)

    for step in range(400):
        op = rng.choice(["alloc", "match", "register", "decref", "evict"])
        if op == "alloc":
            try:
                held.append(a.alloc(rng.randint(1, 3)))
            except OutOfPagesError:
                pass
        elif op == "match" and a._index:
            digs = list(a._index)[: rng.randint(1, len(a._index))]
            got = a.match(digs)
            if got:
                held.append(got)
        elif op == "register" and held:
            row = rng.choice(held)
            ids = list(range(step * 10, step * 10 + 4 * len(row)))
            a.register(chain_digests(ids, 4, len(row)), row)
        elif op == "decref" and held:
            a.decref(held.pop(rng.randrange(len(held))))
        elif op == "evict" and a._index:
            a._evict_lru()
        check()
    while held:
        a.decref(held.pop())
    while a.retained_pages:
        a._evict_lru()
    check()
    assert a.free_pages == a.num_pages


def test_page_allocator_on_evict_hook_fires_before_free():
    """The spill hook sees every evicted (digest, page) pair, and fires
    while the page is still un-reusable (not yet on the free list) —
    the ordering the async D2H spill's correctness rides on."""
    from arks_tpu.engine.paged import PageAllocator, chain_digests

    seen = []
    a = PageAllocator(num_pages=2, page=4)
    a.on_evict = lambda d, pg: seen.append((d, pg, pg in a._free))
    pages = a.alloc(2)
    digs = chain_digests(list(range(8)), 4, 2)
    a.register(digs, pages)
    a.decref(pages)
    a.alloc(2)  # forces both evictions
    assert [(d, pg) for d, pg, _ in seen] == list(zip(digs, pages))
    assert all(not was_free for _, _, was_free in seen)


def test_engine_paged_multihost_gang_prefix_cache():
    """The paged prefix cache must work under a dispatch leader (the round-2
    single-host restriction is lifted): leader decisions replicate as plain
    page args.  Simulated with a leader engine whose dispatcher is a
    recording stub — the real gang path is covered by test_e2e_local."""
    from arks_tpu.engine import EngineConfig, InferenceEngine
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.engine.types import Request, SamplingParams

    class RecordingDispatcher:
        def __init__(self):
            self.ops = []

        def broadcast(self, op, payload):
            self.ops.append((op, payload))

    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                        prefill_chunk=16, kv_layout="paged")
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    eng.dispatcher = RecordingDispatcher()
    eng.start()
    try:
        for i in range(2):
            r = Request(request_id=f"g{i}", prompt_ids=list(range(3, 21)),
                        params=SamplingParams(max_tokens=3, temperature=0.0,
                                              ignore_eos=True))
            eng.add_request(r)
            while True:
                o = r.outputs.get(timeout=120)
                if o.finished:
                    break
    finally:
        eng.stop()
    assert eng._alloc.hit_tokens > 0  # prefix cache live under a dispatcher
    ops = [op for op, _ in eng.dispatcher.ops]
    # Paged engines default to the mixed scheduler: the model dispatches on
    # the channel are "mixed" ops (each carrying the tables by value).
    assert "mixed" in ops
    mixed_payloads = [p for op, p in eng.dispatcher.ops if op == "mixed"]
    assert all(p.get("tables") is not None for p in mixed_payloads)


def test_chunked_prefill_garbage_writes_cannot_corrupt_shared_pages():
    """While a prompt chunk-prefills, interleaved decode dispatches write K
    garbage rows at len..len+K-1 for its batch row; with len just under a
    page boundary those positions cross into the NEXT page — which must be
    owned by the prefilling slot, never a stale/zero table entry (pool page
    0 usually belongs to another live sequence)."""
    from arks_tpu.engine import EngineConfig, InferenceEngine
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.engine.types import Request, SamplingParams

    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=4, max_cache_len=64,
                        prefill_buckets=(8, 16), steps_per_dispatch=4,
                        prefill_chunk=16, kv_layout="paged")
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    eng.start()
    try:
        # Victim decodes slowly (many tokens) while the attacker prefills.
        victim = Request(request_id="victim", prompt_ids=[3] * 16,
                         params=SamplingParams(max_tokens=40, temperature=0.0,
                                               ignore_eos=True))
        eng.add_request(victim)
        # Attacker: chunked (31 > largest bucket 16), len % 16 == 15 so the
        # garbage-write window 31..34 crosses into page index 2.
        attacker = Request(request_id="attacker", prompt_ids=[5] * 31,
                           params=SamplingParams(max_tokens=4, temperature=0.0,
                                                 ignore_eos=True))
        eng.add_request(attacker)
        outs = {}
        for r in (victim, attacker):
            toks = []
            while True:
                o = r.outputs.get(timeout=120)
                toks.extend(o.token_ids)
                if o.finished:
                    break
            outs[r.request_id] = toks
    finally:
        eng.stop()
    # The victim's output must equal an interference-free run.
    ref_out, _ = _run_engine("paged", [([3] * 16, 0)], max_tokens=40)
    assert outs["victim"] == ref_out[0]


def test_engine_paged_on_tp_mesh():
    """Paged engine over a 2-way tensor-parallel mesh (virtual CPU
    devices): pool sharded on kv heads, tables as dispatch args — outputs
    must match the meshless paged engine (the multi-chip shape the driver
    dry-runs)."""
    from arks_tpu.engine import EngineConfig, InferenceEngine
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.engine.types import Request, SamplingParams

    cfg = get_config("tiny")

    def run(tp):
        ecfg = EngineConfig(model="tiny", num_slots=4, max_cache_len=64,
                            prefill_buckets=(8, 16, 32),
                            steps_per_dispatch=4, prefill_chunk=16,
                            kv_layout="paged")
        mesh = None
        if tp > 1:
            from arks_tpu.parallel.mesh import make_mesh
            mesh = make_mesh(tensor_parallel=tp,
                             devices=jax.devices()[:tp])
        eng = InferenceEngine(cfg, ecfg, ByteTokenizer(), mesh=mesh)
        outs = []
        eng.start()
        try:
            for i, prompt in enumerate(([3] * 20, [3] * 20, [5, 6, 7])):
                r = Request(request_id=f"t{i}", prompt_ids=list(prompt),
                            params=SamplingParams(max_tokens=5,
                                                  temperature=0.0,
                                                  ignore_eos=True))
                eng.add_request(r)
                toks = []
                while True:
                    o = r.outputs.get(timeout=120)
                    toks.extend(o.token_ids)
                    if o.finished:
                        break
                outs.append(toks)
        finally:
            eng.stop()
        return outs, eng

    base, _ = run(1)
    sharded, eng = run(2)
    assert sharded == base
    assert eng.mesh is not None and eng.mesh.shape.get("model") == 2
    assert eng._alloc.hit_tokens > 0  # prefix sharing under the mesh too
