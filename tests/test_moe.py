"""MoE block correctness: routing, decode/prefill agreement, expert
parallelism over the mesh.

Reference parity note: the reference serves MoE models only by naming them
in runtime container commands; the block itself (Mixtral / Qwen2-MoE
semantics) is native here and tested on the CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from arks_tpu.models import get_config
from arks_tpu.models import moe
from arks_tpu.models import transformer as tf
from arks_tpu.parallel.mesh import make_mesh


def test_router_weights_topk_semantics():
    cfg = get_config("tiny-mixtral")  # top-2 of 4, normalized
    logits = jnp.asarray([[2.0, 1.0, 0.5, -1.0]])
    w = np.asarray(moe.router_weights(logits, cfg))
    assert (w[0] > 0).sum() == 2            # exactly k nonzero
    assert w[0, 3] == 0 and w[0, 2] == 0    # lowest logits dropped
    np.testing.assert_allclose(w[0].sum(), 1.0, rtol=1e-6)  # renormalized

    cfg2 = get_config("tiny-moe")  # norm_topk_prob=False
    w2 = np.asarray(moe.router_weights(logits, cfg2))
    assert 0 < w2[0].sum() < 1.0  # global-softmax probs used as-is


@pytest.mark.parametrize("name", ["tiny-moe", "tiny-mixtral"])
def test_moe_decode_matches_prefill(name):
    cfg = get_config(name)
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    ids = [int(x) for x in
           jax.random.randint(jax.random.PRNGKey(1), (8,), 0, cfg.vocab_size)]

    # Oracle: full prefill over each prefix.
    ref = []
    for i in range(1, len(ids) + 1):
        toks = jnp.asarray([ids[:i]], jnp.int32)
        logits, _, _ = tf.prefill(params, cfg, toks, jnp.asarray([i], jnp.int32))
        ref.append(np.asarray(logits[0]))

    n_prefill = 3
    cache = tf.init_cache(cfg, num_slots=2, max_len=32, dtype=jnp.float32)
    toks = jnp.asarray([ids[:n_prefill]], jnp.int32)
    logits, ks, vs = tf.prefill(params, cfg, toks, jnp.asarray([n_prefill], jnp.int32))
    np.testing.assert_allclose(np.asarray(logits[0]), ref[n_prefill - 1],
                               rtol=2e-4, atol=2e-4)
    cache = tf.insert(cache, ks, vs, jnp.asarray(0))
    lengths = jnp.zeros((2,), jnp.int32).at[0].set(n_prefill)
    tokens = jnp.zeros((2,), jnp.int32)
    for i in range(n_prefill, len(ids)):
        tokens = tokens.at[0].set(ids[i])
        logits, cache = tf.decode_step(params, cfg, cache, tokens, lengths)
        np.testing.assert_allclose(np.asarray(logits[0]), ref[i],
                                   rtol=2e-4, atol=2e-4)
        lengths = lengths.at[0].set(i + 1)


@pytest.mark.parametrize("tp,dp", [(4, 1), (2, 2), (8, 1)])
def test_moe_expert_parallel_equivalence(tp, dp):
    """Experts sharded over the model axis must match single-device.
    tp=8 with 8 experts = one expert per device; tp also shards kv heads
    when divisible (tiny-moe has 4)."""
    cfg = get_config("tiny-moe")
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    ids = jax.random.randint(jax.random.PRNGKey(2), (2, 6), 0, cfg.vocab_size)
    lengths = jnp.asarray([6, 6], jnp.int32)

    ref_logits, _, _ = tf.prefill(params, cfg, jnp.asarray(ids), lengths)
    mesh = make_mesh(tensor_parallel=tp, data_parallel=dp,
                     devices=jax.devices()[: tp * dp])
    params_s = tf.shard_params(params, cfg, mesh)
    got_logits, _, _ = tf.prefill(params_s, cfg, jnp.asarray(ids), lengths, mesh)
    np.testing.assert_allclose(np.asarray(got_logits), np.asarray(ref_logits),
                               rtol=5e-4, atol=5e-4)


@pytest.mark.parametrize("name", ["tiny-moe", "tiny-mixtral"])
def test_moe_grouped_matches_dense(name):
    """The dropless grouped (sort + ragged_dot) dispatch is numerically
    equivalent to the dense all-expert dispatch."""
    cfg = get_config(name)
    mp = moe.init_moe_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    lp = jax.tree_util.tree_map(lambda t: t[0], mp)  # layer 0 slice
    x = jax.random.normal(jax.random.PRNGKey(1), (3, 40, cfg.hidden_size),
                          jnp.float32)
    dense = moe.moe_ffn(x, lp, cfg, grouped=False)
    grouped = moe.moe_ffn(x, lp, cfg, grouped=True)
    np.testing.assert_allclose(np.asarray(grouped), np.asarray(dense),
                               rtol=2e-5, atol=2e-5)


def test_moe_grouped_auto_threshold(monkeypatch):
    """Auto mode routes large unsharded [B, T, E] batches through the
    grouped path, decode-shaped [B, E] and small batches through dense —
    verified by counting actual grouped-path invocations."""
    cfg = get_config("tiny-moe")
    mp = moe.init_moe_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    lp = jax.tree_util.tree_map(lambda t: t[0], mp)
    calls = []
    real = moe.moe_ffn_grouped
    monkeypatch.setattr(moe, "moe_ffn_grouped",
                        lambda *a, **kw: calls.append(1) or real(*a, **kw))

    big = jax.random.normal(jax.random.PRNGKey(1), (2, 64, cfg.hidden_size))
    moe.moe_ffn(big, lp, cfg)
    assert len(calls) == 1  # large prefill → grouped
    moe.moe_ffn(big[:, :4], lp, cfg)
    assert len(calls) == 1  # small prefill → dense
    decode = jax.random.normal(jax.random.PRNGKey(2), (128, cfg.hidden_size))
    moe.moe_ffn(decode, lp, cfg)
    assert len(calls) == 1  # decode stays dense no matter the slot count
    moe.moe_ffn(big, lp, cfg, constrain=lambda t, d: t)
    assert len(calls) == 1  # sharded (constrained) → dense


def test_moe_grouped_grad():
    """Training uses the grouped path when unsharded — it must be
    differentiable (ragged_dot grads + scatter-add transpose)."""
    cfg = get_config("tiny-moe")
    mp = moe.init_moe_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    lp = jax.tree_util.tree_map(lambda t: t[0], mp)
    x = jax.random.normal(jax.random.PRNGKey(1), (64, cfg.hidden_size))

    def loss(lp, grouped):
        return jnp.sum(moe.moe_ffn(x, lp, cfg, grouped=grouped) ** 2)

    g_dense = jax.grad(loss)(lp, False)
    g_grouped = jax.grad(loss)(lp, True)
    for k in g_dense:
        np.testing.assert_allclose(np.asarray(g_grouped[k]),
                                   np.asarray(g_dense[k]),
                                   rtol=5e-4, atol=5e-4, err_msg=k)


def test_moe_param_counts():
    assert 40e9 < get_config("mixtral-8x7b").num_params() < 50e9
    assert 50e9 < get_config("qwen2-57b-a14b").num_params() < 62e9


def test_moe_hf_config_roundtrip():
    from arks_tpu.models.config import ModelConfig
    d = {
        "architectures": ["MixtralForCausalLM"], "model_type": "mixtral",
        "vocab_size": 1000, "hidden_size": 64, "intermediate_size": 128,
        "num_hidden_layers": 2, "num_attention_heads": 8,
        "num_key_value_heads": 4, "num_local_experts": 8,
        "num_experts_per_tok": 2, "eos_token_id": 2,
    }
    cfg = ModelConfig.from_hf_config(d)
    assert cfg.num_experts == 8 and cfg.num_experts_per_tok == 2
    assert cfg.norm_topk_prob and cfg.moe_intermediate_size == 128
    d2 = {
        "architectures": ["Qwen2MoeForCausalLM"], "model_type": "qwen2_moe",
        "vocab_size": 1000, "hidden_size": 64, "intermediate_size": 128,
        "num_hidden_layers": 2, "num_attention_heads": 8,
        "num_key_value_heads": 4, "num_experts": 16, "num_experts_per_tok": 4,
        "moe_intermediate_size": 48, "shared_expert_intermediate_size": 96,
        "norm_topk_prob": False,
    }
    cfg2 = ModelConfig.from_hf_config(d2)
    assert cfg2.qkv_bias and cfg2.num_experts == 16
    assert cfg2.shared_expert_intermediate_size == 96 and not cfg2.norm_topk_prob


# ---------------------------------------------------------------------------
# Block-sparse Pallas grouped matmul (ARKS_MOE_KERNEL=pallas)
# ---------------------------------------------------------------------------


def test_grouped_matmul_kernel_matches_ragged_dot():
    """pad_groups + grouped_matmul == ragged_dot on the same sorted rows,
    including the fused int8 dequant."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from arks_tpu.models.quant import quantize_tensor
    from arks_tpu.ops.moe_kernel import grouped_ffn, grouped_matmul, pad_groups

    rng = np.random.default_rng(0)
    t, k, n, nx, bt = 37, 32, 48, 4, 8
    sorted_expert = jnp.asarray(np.sort(rng.integers(0, nx, t)), jnp.int32)
    group_sizes = jnp.bincount(sorted_expert, length=nx)
    xs = jnp.asarray(rng.standard_normal((t, k)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((nx, k, n)), jnp.float32)

    ref = jax.lax.ragged_dot(xs, w, group_sizes)
    xs_p, dest, bexp = pad_groups(xs, sorted_expert, group_sizes, bt)
    got = grouped_matmul(xs_p, w, bexp, block_t=bt, block_n=16,
                         interpret=True)[dest]
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)

    # int8 fused dequant vs materialized dequant + ragged_dot.
    wq = quantize_tensor(w)
    from arks_tpu.models.quant import dequantize
    ref_q = jax.lax.ragged_dot(xs, dequantize(wq, jnp.float32), group_sizes)
    s = wq["s"].astype(jnp.float32)
    s2 = s[:, 0, :] if s.ndim == 3 else s
    got_q = grouped_matmul(xs_p, wq["q"], bexp, s2, block_t=bt, block_n=16,
                           interpret=True)[dest]
    np.testing.assert_allclose(np.asarray(got_q), np.asarray(ref_q),
                               atol=1e-3, rtol=1e-3)

    # int4 groupwise fused dequant vs materialized dequant + ragged_dot.
    from arks_tpu.models.quant import quantize_tensor_int4
    w4 = quantize_tensor_int4(w, group=8)
    ref_4 = jax.lax.ragged_dot(xs, dequantize(w4, jnp.float32), group_sizes)
    got_4 = grouped_matmul(xs_p, w4["q"], bexp,
                           w_group_scale=w4["gs"].astype(jnp.float32),
                           block_t=bt, block_n=16, interpret=True)[dest]
    np.testing.assert_allclose(np.asarray(got_4), np.asarray(ref_4),
                               atol=1e-3, rtol=1e-3)


def test_moe_grouped_pallas_matches_xla_path(monkeypatch):
    """The full grouped MoE FFN through the Pallas kernel == the ragged_dot
    path, float and quantized."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from arks_tpu.models import get_config
    from arks_tpu.models import transformer as tf
    from arks_tpu.models.moe import moe_ffn_grouped
    from arks_tpu.models.quant import quantize_params

    cfg = get_config("tiny-moe")
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    mp = params["layers"]
    mp1 = jax.tree.map(lambda a: a[0], mp)  # layer 0 slice
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 24, cfg.hidden_size),
                          jnp.float32)

    monkeypatch.setenv("ARKS_MOE_KERNEL", "xla")
    ref = moe_ffn_grouped(x, mp1, cfg)
    monkeypatch.setenv("ARKS_MOE_KERNEL", "pallas")
    got = moe_ffn_grouped(x, mp1, cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)

    qp = quantize_params(params)["layers"]
    qp1 = jax.tree.map(lambda a: a[0], qp)
    monkeypatch.setenv("ARKS_MOE_KERNEL", "xla")
    ref_q = moe_ffn_grouped(x, qp1, cfg)
    monkeypatch.setenv("ARKS_MOE_KERNEL", "pallas")
    got_q = moe_ffn_grouped(x, qp1, cfg)
    np.testing.assert_allclose(np.asarray(got_q), np.asarray(ref_q),
                               atol=2e-3, rtol=2e-3)

    # int4 (w4a16) experts: group-scale dequant fused in the kernel.
    q4 = quantize_params(params, bits=4)["layers"]
    q41 = jax.tree.map(lambda a: a[0], q4)
    monkeypatch.setenv("ARKS_MOE_KERNEL", "xla")
    ref_4 = moe_ffn_grouped(x, q41, cfg)
    monkeypatch.setenv("ARKS_MOE_KERNEL", "pallas")
    got_4 = moe_ffn_grouped(x, q41, cfg)
    np.testing.assert_allclose(np.asarray(got_4), np.asarray(ref_4),
                               atol=2e-3, rtol=2e-3)
