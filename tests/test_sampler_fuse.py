"""Depth-0 sampler fusion (ARKS_SAMPLER_FUSE): steady-state pure decode
issues ONE fused attention+sampler device program per step instead of
the classic mixed batch (~20 host-prepped arrays) + separate sampler
dispatch — and the token streams are byte-identical either way.

The fused path reuses the pipelined decode programs in fresh mode with
the threaded state dropped after every resolve, so the host mirrors
stay authoritative; anything non-steady (prefill chunks, admissions,
first-token override columns, aborts) falls back to the classic pair
mid-run, which is exactly what the mixed-traffic workload below
exercises.
"""

import pytest

from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config


def _mk_engine(monkeypatch, *, fuse, depth=0, **kw):
    monkeypatch.setenv("ARKS_MIXED_STEP", "1")
    monkeypatch.setenv("ARKS_SAMPLER_FUSE", "1" if fuse else "0")
    monkeypatch.setenv("ARKS_PIPELINE_DEPTH", str(depth))
    cfg = get_config("tiny")
    defaults = dict(model="tiny", num_slots=2, max_cache_len=64,
                    prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                    prefill_chunk=16, kv_layout="paged", prefix_cache_mb=0)
    defaults.update(kw)
    eng = InferenceEngine(cfg, EngineConfig(**defaults), ByteTokenizer())
    if fuse and not depth and "draft_model" not in kw:
        # The fused path dispatches the pipe programs; wait for the
        # background compile so the run actually exercises fusion
        # instead of racing past it on the classic fallback.
        assert eng._pipe_warm_wait(300) == "ready"
    return cfg, eng


def _drive(eng, n_steps=2000):
    for _ in range(n_steps):
        eng.step(block_s=0.01)
        if (eng.num_running == 0 and eng._queue.empty()
                and not eng._prefilling):
            break


def _collect(req):
    ids, lps, fin = [], [], None
    while True:
        out = req.outputs.get(timeout=120)
        ids.extend(out.token_ids)
        if out.logprobs:
            lps.extend(out.logprobs)
        if out.finished:
            fin = out
            break
    return ids, lps, fin.finish_reason


def _run_workload(eng, cfg, guided=False):
    """Plain greedy (+logprobs) + fixed-seed sampled (+ optionally
    guided) traffic — chunked and one-shot prompts, more requests than
    slots, so the run crosses steady state and fallback repeatedly."""
    reqs = [
        Request("g0", [5, 6, 7], SamplingParams(
            max_tokens=12, temperature=0.0, ignore_eos=True, logprobs=2)),
        Request("s0", [int(x) % cfg.vocab_size for x in range(3, 40)],
                SamplingParams(max_tokens=12, temperature=0.8, top_p=0.9,
                               top_k=40, seed=7, ignore_eos=True)),
        Request("g1", [9] * 20, SamplingParams(
            max_tokens=12, temperature=0.0, ignore_eos=True)),
    ]
    if guided:
        reqs.append(Request("j0", [4, 8, 2], SamplingParams(
            max_tokens=8, temperature=0.0, guide=("json", ""))))
    for r in reqs:
        eng.add_request(r)
    _drive(eng)
    return [_collect(r) for r in reqs]


def test_stream_identity_fused_vs_classic(monkeypatch):
    """Plain + guided + logprob traffic at depth 0: fusion ON emits
    byte-identical streams (ids, logprob floats, finish reasons) to
    fusion OFF, and the fused program actually carried decode steps."""
    outs = {}
    for fuse in (True, False):
        cfg, eng = _mk_engine(monkeypatch, fuse=fuse)
        outs[fuse] = _run_workload(eng, cfg, guided=True)
        n_fused = eng.metrics.sampler_fused_dispatch_total.total()
        if fuse:
            assert n_fused > 0, "fused program never dispatched"
        else:
            assert n_fused == 0
    assert outs[True] == outs[False]


def test_fusion_defers_to_the_pipeline_at_depth(monkeypatch):
    """At ARKS_PIPELINE_DEPTH>0 the pipelined scheduler owns steady
    state — the fused counter stays at zero and the streams still match
    the depth-0 fused run (depth invariance)."""
    cfg, eng = _mk_engine(monkeypatch, fuse=True, depth=2)
    assert eng._pipe_warm_wait(300) == "ready"
    piped = _run_workload(eng, cfg)
    assert eng.metrics.sampler_fused_dispatch_total.total() == 0
    cfg, eng0 = _mk_engine(monkeypatch, fuse=True, depth=0)
    fused = _run_workload(eng0, cfg)
    assert eng0.metrics.sampler_fused_dispatch_total.total() > 0
    assert [(ids, fr) for ids, _, fr in piped] \
        == [(ids, fr) for ids, _, fr in fused]


def test_fusion_disabled_for_spec_engines(monkeypatch):
    """Speculative engines keep the classic spec-mixed dispatch (their
    verify blocks don't ride the fused columns): the fused counter stays
    zero and the run completes."""
    cfg, eng = _mk_engine(monkeypatch, fuse=True, draft_model="tiny",
                          draft_len=3)
    outs = _run_workload(eng, cfg)
    assert eng.metrics.sampler_fused_dispatch_total.total() == 0
    assert all(fr == "length" for _, _, fr in outs)
