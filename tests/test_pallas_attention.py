"""Ragged Pallas decode-attention + KV-update kernels vs the XLA oracle
(interpret mode).

The reference never tests its attention path (it has none — vLLM's kernels
are opaque containers to it); here the kernels are first-class and testable
on CPU via the Pallas interpreter.  Kernels take the FULL stacked cache
[L, B, Hkv, S, D] plus a layer index (see pallas_attention module docs).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from arks_tpu.ops.attention import decode_attention_xla, decode_update_and_attend
from arks_tpu.ops.pallas_attention import kv_cache_update, ragged_decode_attention


def _rand_case(key, b, hkv, g, d, s, num_layers=3):
    ks = jax.random.split(key, 4)
    q = jax.random.normal(ks[0], (b, hkv, g, d), jnp.float32)
    kc = jax.random.normal(ks[1], (num_layers, b, hkv, s, d), jnp.float32)
    vc = jax.random.normal(ks[2], (num_layers, b, hkv, s, d), jnp.float32)
    lengths = jax.random.randint(ks[3], (b,), 1, s + 1)
    return q, kc, vc, lengths.astype(jnp.int32)


@pytest.mark.parametrize("b,hkv,g,d,s,block", [
    (4, 2, 4, 16, 64, 32),    # multi-block, GQA
    (2, 1, 8, 8, 32, 32),     # single block
    (3, 4, 1, 32, 96, 32),    # MQA-per-head (g=1), non-pow2 batch
])
def test_ragged_kernel_matches_xla(b, hkv, g, d, s, block):
    q, kc, vc, lengths = _rand_case(jax.random.PRNGKey(0), b, hkv, g, d, s)
    for layer in (0, 2):
        ref = decode_attention_xla(q, kc[layer], vc[layer], lengths)
        got = ragged_decode_attention(q, kc, vc, lengths, layer,
                                      block_s=block, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_ragged_kernel_edge_lengths():
    """Lengths at block boundaries and full cache."""
    b, hkv, g, d, s = 5, 2, 2, 16, 64
    q, kc, vc, _ = _rand_case(jax.random.PRNGKey(1), b, hkv, g, d, s)
    lengths = jnp.asarray([1, 31, 32, 33, 64], jnp.int32)
    ref = decode_attention_xla(q, kc[1], vc[1], lengths)
    got = ragged_decode_attention(q, kc, vc, lengths, 1, block_s=32,
                                  interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ragged_kernel_empty_slot_is_finite():
    b, hkv, g, d, s = 2, 1, 2, 8, 32
    q, kc, vc, _ = _rand_case(jax.random.PRNGKey(2), b, hkv, g, d, s)
    lengths = jnp.asarray([0, 7], jnp.int32)
    got = np.asarray(ragged_decode_attention(q, kc, vc, lengths, 0, block_s=32,
                                             interpret=True))
    assert np.isfinite(got).all()


def test_kv_cache_update_inplace_rows():
    l, b, hkv, s, d = 3, 4, 2, 64, 16
    key = jax.random.PRNGKey(5)
    kc = jax.random.normal(key, (l, b, hkv, s, d), jnp.float32)
    vc = kc + 1.0
    kn = jnp.full((b, hkv, d), 7.0, jnp.float32)
    vn = jnp.full((b, hkv, d), 9.0, jnp.float32)
    idx = jnp.asarray([0, 15, 16, 63], jnp.int32)
    layer = 1
    kc2, vc2 = kv_cache_update(kc, vc, kn, vn, idx, layer, interpret=True)
    b_idx = jnp.arange(b)[:, None]
    h_idx = jnp.arange(hkv)[None, :]
    ref_k = kc.at[layer, b_idx, h_idx, idx[:, None]].set(kn)
    ref_v = vc.at[layer, b_idx, h_idx, idx[:, None]].set(vn)
    np.testing.assert_array_equal(np.asarray(kc2), np.asarray(ref_k))
    np.testing.assert_array_equal(np.asarray(vc2), np.asarray(ref_v))


def test_kv_cache_update_drops_out_of_range_writes():
    """idx >= S must be dropped (JAX scatter semantics), not clamped into a
    valid interior row."""
    l, b, hkv, s, d = 1, 2, 1, 32, 8
    kc = jnp.zeros((l, b, hkv, s, d), jnp.float32)
    vc = jnp.zeros((l, b, hkv, s, d), jnp.float32)
    kn = jnp.ones((b, hkv, d), jnp.float32)
    vn = jnp.ones((b, hkv, d), jnp.float32)
    idx = jnp.asarray([3, 32], jnp.int32)  # slot 1 overflows
    kc2, _ = kv_cache_update(kc, vc, kn, vn, idx, 0, interpret=True)
    kc2 = np.asarray(kc2)
    assert kc2[0, 0, 0, 3].sum() == d     # slot 0 written
    assert kc2[0, 1].sum() == 0           # slot 1 untouched


@pytest.mark.parametrize("tp,dp", [(2, 2), (1, 4), (4, 1)])
def test_decode_update_and_attend_sharded_pallas(tp, dp):
    """The shard_map Pallas path (the production multi-chip decode) must
    match the unsharded XLA oracle — including dp-only meshes, which also
    take the kernels (the op is embarrassingly parallel over batch)."""
    from arks_tpu.parallel.mesh import make_mesh
    b, hkv, g, d, s = 8, 4, 2, 16, 64
    key = jax.random.PRNGKey(8)
    ks = jax.random.split(key, 5)
    q = jax.random.normal(ks[0], (b, hkv * g, d), jnp.float32)
    kc = jax.random.normal(ks[1], (2, b, hkv, s, d), jnp.float32)
    vc = jax.random.normal(ks[2], (2, b, hkv, s, d), jnp.float32)
    kn = jax.random.normal(ks[3], (b, hkv, d), jnp.float32)
    vn = jax.random.normal(ks[4], (b, hkv, d), jnp.float32)
    widx = jnp.asarray([0, 5, 17, 31, 32, 40, 55, 63], jnp.int32)
    ref_o, ref_k, ref_v = decode_update_and_attend(
        q, kn, vn, kc, vc, widx, 1, impl="xla")
    mesh = make_mesh(tensor_parallel=tp, data_parallel=dp,
                     devices=jax.devices()[: tp * dp])
    kv_sharded = tp > 1 and hkv % tp == 0
    got_o, got_k, got_v = decode_update_and_attend(
        q, kn, vn, kc, vc, widx, 1, mesh=mesh,
        batch_axis="data" if dp > 1 else None,
        kv_sharded=kv_sharded, impl="pallas")
    np.testing.assert_allclose(np.asarray(got_o), np.asarray(ref_o), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(got_k), np.asarray(ref_k), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(ref_v), rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("layer", [0, 1])
def test_decode_update_and_attend_pallas_matches_xla(layer):
    b, hkv, g, d, s = 4, 2, 3, 16, 64
    key = jax.random.PRNGKey(6)
    ks = jax.random.split(key, 5)
    q = jax.random.normal(ks[0], (b, hkv * g, d), jnp.float32)
    kc = jax.random.normal(ks[1], (2, b, hkv, s, d), jnp.float32)
    vc = jax.random.normal(ks[2], (2, b, hkv, s, d), jnp.float32)
    kn = jax.random.normal(ks[3], (b, hkv, d), jnp.float32)
    vn = jax.random.normal(ks[4], (b, hkv, d), jnp.float32)
    widx = jnp.asarray([0, 5, 31, 63], jnp.int32)
    ref_o, ref_k, ref_v = decode_update_and_attend(
        q, kn, vn, kc, vc, widx, layer, impl="xla")
    got_o, got_k, got_v = decode_update_and_attend(
        q, kn, vn, kc, vc, widx, layer, impl="pallas")
    np.testing.assert_allclose(np.asarray(got_o), np.asarray(ref_o), rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(got_k), np.asarray(ref_k))
    np.testing.assert_array_equal(np.asarray(got_v), np.asarray(ref_v))
