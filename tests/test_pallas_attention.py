"""Ragged Pallas decode-attention + KV-update kernels vs the XLA oracle
(interpret mode).

The reference never tests its attention path (it has none — vLLM's kernels
are opaque containers to it); here the kernels are first-class and testable
on CPU via the Pallas interpreter.  Kernels take the FULL stacked cache
[L, B, Hkv, S, D] plus a layer index (see pallas_attention module docs).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from arks_tpu.ops.attention import decode_attention_xla, decode_update_and_attend
from arks_tpu.ops.pallas_attention import kv_cache_update, ragged_decode_attention


def _rand_case(key, b, hkv, g, d, s, num_layers=3):
    ks = jax.random.split(key, 4)
    q = jax.random.normal(ks[0], (b, hkv, g, d), jnp.float32)
    kc = jax.random.normal(ks[1], (num_layers, b, hkv, s, d), jnp.float32)
    vc = jax.random.normal(ks[2], (num_layers, b, hkv, s, d), jnp.float32)
    lengths = jax.random.randint(ks[3], (b,), 1, s + 1)
    return q, kc, vc, lengths.astype(jnp.int32)


@pytest.mark.parametrize("b,hkv,g,d,s,block", [
    (4, 2, 4, 16, 64, 32),    # multi-block, GQA
    (2, 1, 8, 8, 32, 32),     # single block
    (3, 4, 1, 32, 96, 32),    # MQA-per-head (g=1), non-pow2 batch
])
def test_ragged_kernel_matches_xla(b, hkv, g, d, s, block):
    q, kc, vc, lengths = _rand_case(jax.random.PRNGKey(0), b, hkv, g, d, s)
    for layer in (0, 2):
        ref = decode_attention_xla(q, kc[layer], vc[layer], lengths)
        got = ragged_decode_attention(q, kc, vc, lengths, layer,
                                      block_s=block, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_ragged_kernel_edge_lengths():
    """Lengths at block boundaries and full cache."""
    b, hkv, g, d, s = 5, 2, 2, 16, 64
    q, kc, vc, _ = _rand_case(jax.random.PRNGKey(1), b, hkv, g, d, s)
    lengths = jnp.asarray([1, 31, 32, 33, 64], jnp.int32)
    ref = decode_attention_xla(q, kc[1], vc[1], lengths)
    got = ragged_decode_attention(q, kc, vc, lengths, 1, block_s=32,
                                  interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ragged_kernel_empty_slot_is_finite():
    b, hkv, g, d, s = 2, 1, 2, 8, 32
    q, kc, vc, _ = _rand_case(jax.random.PRNGKey(2), b, hkv, g, d, s)
    lengths = jnp.asarray([0, 7], jnp.int32)
    got = np.asarray(ragged_decode_attention(q, kc, vc, lengths, 0, block_s=32,
                                             interpret=True))
    assert np.isfinite(got).all()


def test_kv_cache_update_inplace_rows():
    l, b, hkv, s, d = 3, 4, 2, 64, 16
    key = jax.random.PRNGKey(5)
    kc = jax.random.normal(key, (l, b, hkv, s, d), jnp.float32)
    vc = kc + 1.0
    kn = jnp.full((b, hkv, d), 7.0, jnp.float32)
    vn = jnp.full((b, hkv, d), 9.0, jnp.float32)
    idx = jnp.asarray([0, 15, 16, 63], jnp.int32)
    layer = 1
    kc2, vc2 = kv_cache_update(kc, vc, kn, vn, idx, layer, interpret=True)
    b_idx = jnp.arange(b)[:, None]
    h_idx = jnp.arange(hkv)[None, :]
    ref_k = kc.at[layer, b_idx, h_idx, idx[:, None]].set(kn)
    ref_v = vc.at[layer, b_idx, h_idx, idx[:, None]].set(vn)
    np.testing.assert_array_equal(np.asarray(kc2), np.asarray(ref_k))
    np.testing.assert_array_equal(np.asarray(vc2), np.asarray(ref_v))


def test_kv_cache_update_drops_out_of_range_writes():
    """idx >= S must be dropped (JAX scatter semantics), not clamped into a
    valid interior row."""
    l, b, hkv, s, d = 1, 2, 1, 32, 8
    kc = jnp.zeros((l, b, hkv, s, d), jnp.float32)
    vc = jnp.zeros((l, b, hkv, s, d), jnp.float32)
    kn = jnp.ones((b, hkv, d), jnp.float32)
    vn = jnp.ones((b, hkv, d), jnp.float32)
    idx = jnp.asarray([3, 32], jnp.int32)  # slot 1 overflows
    kc2, _ = kv_cache_update(kc, vc, kn, vn, idx, 0, interpret=True)
    kc2 = np.asarray(kc2)
    assert kc2[0, 0, 0, 3].sum() == d     # slot 0 written
    assert kc2[0, 1].sum() == 0           # slot 1 untouched


@pytest.mark.parametrize("tp,dp", [(2, 2), (1, 4), (4, 1)])
def test_decode_update_and_attend_sharded_pallas(tp, dp):
    """The shard_map Pallas path (the production multi-chip decode) must
    match the unsharded XLA oracle — including dp-only meshes, which also
    take the kernels (the op is embarrassingly parallel over batch)."""
    from arks_tpu.parallel.mesh import make_mesh
    b, hkv, g, d, s = 8, 4, 2, 16, 64
    key = jax.random.PRNGKey(8)
    ks = jax.random.split(key, 5)
    q = jax.random.normal(ks[0], (b, hkv * g, d), jnp.float32)
    kc = jax.random.normal(ks[1], (2, b, hkv, s, d), jnp.float32)
    vc = jax.random.normal(ks[2], (2, b, hkv, s, d), jnp.float32)
    kn = jax.random.normal(ks[3], (b, hkv, d), jnp.float32)
    vn = jax.random.normal(ks[4], (b, hkv, d), jnp.float32)
    widx = jnp.asarray([0, 5, 17, 31, 32, 40, 55, 63], jnp.int32)
    ref_o, ref_k, ref_v, _, _ = decode_update_and_attend(
        q, kn, vn, kc, vc, widx, 1, impl="xla")
    mesh = make_mesh(tensor_parallel=tp, data_parallel=dp,
                     devices=jax.devices()[: tp * dp])
    kv_sharded = tp > 1 and hkv % tp == 0
    got_o, got_k, got_v, _, _ = decode_update_and_attend(
        q, kn, vn, kc, vc, widx, 1, mesh=mesh,
        batch_axis="data" if dp > 1 else None,
        kv_sharded=kv_sharded, impl="pallas")
    np.testing.assert_allclose(np.asarray(got_o), np.asarray(ref_o), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(got_k), np.asarray(ref_k), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(ref_v), rtol=1e-6, atol=1e-6)


def test_quantize_kv_roundtrip():
    from arks_tpu.ops.pallas_attention import quantize_kv
    x = jax.random.normal(jax.random.PRNGKey(9), (3, 2, 16), jnp.float32) * 5
    q, s = quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == (3, 2)
    deq = q.astype(jnp.float32) * s[..., None]
    np.testing.assert_allclose(np.asarray(deq), np.asarray(x), atol=np.abs(x).max() / 100)


def test_kv_cache_update_quant_inplace():
    from arks_tpu.ops.pallas_attention import kv_cache_update_quant, quantize_kv
    l, b, hkv, s, d = 2, 4, 2, 128, 16
    kc = jnp.zeros((l, b, hkv, s, d), jnp.int8)
    vc = jnp.zeros((l, b, hkv, s, d), jnp.int8)
    kss = jnp.zeros((l, b, hkv, s), jnp.float32)
    vss = jnp.zeros((l, b, hkv, s), jnp.float32)
    key = jax.random.PRNGKey(10)
    kn = jax.random.normal(key, (b, hkv, d), jnp.float32) * 3
    vn = kn + 1.0
    idx = jnp.asarray([0, 17, 100, 127], jnp.int32)
    kc2, vc2, kss2, vss2 = kv_cache_update_quant(
        kc, vc, kss, vss, kn, vn, idx, 1, interpret=True)
    kq_ref, ks_ref = quantize_kv(kn)
    for slot in range(b):
        np.testing.assert_array_equal(
            np.asarray(kc2[1, slot, :, idx[slot]]), np.asarray(kq_ref[slot]))
        np.testing.assert_allclose(
            np.asarray(kss2[1, slot, :, idx[slot]]), np.asarray(ks_ref[slot]),
            rtol=1e-6)
    assert np.asarray(kc2[0]).sum() == 0  # other layer untouched
    # Dequantized row approximates the original.
    deq = np.asarray(kc2[1, 0, :, idx[0]]).astype(np.float32) \
        * np.asarray(kss2[1, 0, :, idx[0]])[:, None]
    np.testing.assert_allclose(deq, np.asarray(kn[0]), atol=0.05)


@pytest.mark.parametrize("mesh_kind", ["none", "tp"])
def test_decode_update_and_attend_int8_close_to_fp(mesh_kind):
    """int8 KV path (pallas kernels, incl. sharded) tracks the full-width
    XLA oracle within quantization tolerance."""
    b, hkv, g, d, s = 4, 2, 3, 16, 128
    key = jax.random.PRNGKey(11)
    ks_ = jax.random.split(key, 7)
    q = jax.random.normal(ks_[0], (b, hkv * g, d), jnp.float32)
    kf = jax.random.normal(ks_[1], (2, b, hkv, s, d), jnp.float32)
    vf = jax.random.normal(ks_[2], (2, b, hkv, s, d), jnp.float32)
    kn = jax.random.normal(ks_[3], (b, hkv, d), jnp.float32)
    vn = jax.random.normal(ks_[4], (b, hkv, d), jnp.float32)
    widx = jnp.asarray([0, 5, 64, 127], jnp.int32)
    ref_o, *_ = decode_update_and_attend(q, kn, vn, kf, vf, widx, 1, impl="xla")

    from arks_tpu.ops.pallas_attention import quantize_kv
    kq, kss = quantize_kv(kf)
    vq, vss = quantize_kv(vf)
    kwargs = {}
    if mesh_kind == "tp":
        from arks_tpu.parallel.mesh import make_mesh
        kwargs = dict(mesh=make_mesh(tensor_parallel=2,
                                     devices=jax.devices()[:2]),
                      kv_sharded=True)
    got_o, kc2, vc2, kss2, vss2 = decode_update_and_attend(
        q, kn, vn, kq, vq, widx, 1, impl="pallas",
        k_scale=kss, v_scale=vss, **kwargs)
    assert kc2.dtype == jnp.int8
    np.testing.assert_allclose(np.asarray(got_o), np.asarray(ref_o),
                               rtol=0.05, atol=0.05)


def test_model_decode_int8_cache_tracks_fp():
    """Whole-model decode with an int8 cache stays close to the fp cache."""
    from arks_tpu.models import get_config
    from arks_tpu.models import transformer as tf
    cfg = get_config("tiny-gqa")
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    ids = jax.random.randint(jax.random.PRNGKey(1), (1, 6), 0, cfg.vocab_size)

    def run(quantized):
        cache = tf.init_cache(cfg, num_slots=2, max_len=128,
                              dtype=jnp.float32, quantized=quantized)
        _, ks, vs = tf.prefill(params, cfg, ids, jnp.asarray([6], jnp.int32))
        cache = tf.insert(cache, ks, vs, jnp.asarray(0))
        lengths = jnp.zeros((2,), jnp.int32).at[0].set(6)
        logits, cache = tf.decode_step(
            params, cfg, cache, jnp.zeros((2,), jnp.int32), lengths)
        return np.asarray(logits[0])

    ref, got = run(False), run(True)
    # Logits in f32; int8 KV error shows up at ~1e-2 scale.
    np.testing.assert_allclose(got, ref, rtol=0.1, atol=0.1)


@pytest.mark.parametrize("layer", [0, 1])
def test_decode_update_and_attend_pallas_matches_xla(layer):
    b, hkv, g, d, s = 4, 2, 3, 16, 64
    key = jax.random.PRNGKey(6)
    ks = jax.random.split(key, 5)
    q = jax.random.normal(ks[0], (b, hkv * g, d), jnp.float32)
    kc = jax.random.normal(ks[1], (2, b, hkv, s, d), jnp.float32)
    vc = jax.random.normal(ks[2], (2, b, hkv, s, d), jnp.float32)
    kn = jax.random.normal(ks[3], (b, hkv, d), jnp.float32)
    vn = jax.random.normal(ks[4], (b, hkv, d), jnp.float32)
    widx = jnp.asarray([0, 5, 31, 63], jnp.int32)
    ref_o, ref_k, ref_v, _, _ = decode_update_and_attend(
        q, kn, vn, kc, vc, widx, layer, impl="xla")
    got_o, got_k, got_v, _, _ = decode_update_and_attend(
        q, kn, vn, kc, vc, widx, layer, impl="pallas")
    np.testing.assert_allclose(np.asarray(got_o), np.asarray(ref_o), rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(got_k), np.asarray(ref_k))
    np.testing.assert_array_equal(np.asarray(got_v), np.asarray(ref_v))
