"""Weight loading: HF safetensors -> params (dense + MoE) and Orbax
sharded checkpoint roundtrips.

Covers the model-cache path the reference only half-owns (it downloads raw
HF snapshots — scripts/download.py — and leaves parsing to the runtimes);
here conversion and sharded loading are native.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from safetensors.numpy import save_file

from arks_tpu.models import get_config
from arks_tpu.models import transformer as tf
from arks_tpu.models import weights as w
from arks_tpu.parallel.mesh import make_mesh


def _rng_tensors(cfg):
    """Synthesize an HF-style checkpoint for a tiny config."""
    rng = np.random.RandomState(0)
    e, f, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    t = {
        "model.embed_tokens.weight": rng.randn(v, e).astype(np.float32),
        "model.norm.weight": np.ones((e,), np.float32),
    }
    if not cfg.tie_word_embeddings:
        t["lm_head.weight"] = rng.randn(v, e).astype(np.float32)
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}"
        t[f"{p}.input_layernorm.weight"] = np.ones((e,), np.float32)
        t[f"{p}.post_attention_layernorm.weight"] = np.ones((e,), np.float32)
        t[f"{p}.self_attn.q_proj.weight"] = rng.randn(cfg.q_dim, e).astype(np.float32)
        t[f"{p}.self_attn.k_proj.weight"] = rng.randn(cfg.kv_dim, e).astype(np.float32)
        t[f"{p}.self_attn.v_proj.weight"] = rng.randn(cfg.kv_dim, e).astype(np.float32)
        t[f"{p}.self_attn.o_proj.weight"] = rng.randn(e, cfg.q_dim).astype(np.float32)
        if cfg.qkv_bias:
            t[f"{p}.self_attn.q_proj.bias"] = rng.randn(cfg.q_dim).astype(np.float32)
            t[f"{p}.self_attn.k_proj.bias"] = rng.randn(cfg.kv_dim).astype(np.float32)
            t[f"{p}.self_attn.v_proj.bias"] = rng.randn(cfg.kv_dim).astype(np.float32)
        if cfg.num_experts:
            fm = cfg.moe_intermediate_size
            if cfg.shared_expert_intermediate_size:  # qwen2-moe naming
                t[f"{p}.mlp.gate.weight"] = rng.randn(cfg.num_experts, e).astype(np.float32)
                for x in range(cfg.num_experts):
                    t[f"{p}.mlp.experts.{x}.gate_proj.weight"] = rng.randn(fm, e).astype(np.float32)
                    t[f"{p}.mlp.experts.{x}.up_proj.weight"] = rng.randn(fm, e).astype(np.float32)
                    t[f"{p}.mlp.experts.{x}.down_proj.weight"] = rng.randn(e, fm).astype(np.float32)
                fs = cfg.shared_expert_intermediate_size
                t[f"{p}.mlp.shared_expert.gate_proj.weight"] = rng.randn(fs, e).astype(np.float32)
                t[f"{p}.mlp.shared_expert.up_proj.weight"] = rng.randn(fs, e).astype(np.float32)
                t[f"{p}.mlp.shared_expert.down_proj.weight"] = rng.randn(e, fs).astype(np.float32)
                t[f"{p}.mlp.shared_expert_gate.weight"] = rng.randn(1, e).astype(np.float32)
            else:  # mixtral naming
                t[f"{p}.block_sparse_moe.gate.weight"] = rng.randn(cfg.num_experts, e).astype(np.float32)
                for x in range(cfg.num_experts):
                    t[f"{p}.block_sparse_moe.experts.{x}.w1.weight"] = rng.randn(fm, e).astype(np.float32)
                    t[f"{p}.block_sparse_moe.experts.{x}.w3.weight"] = rng.randn(fm, e).astype(np.float32)
                    t[f"{p}.block_sparse_moe.experts.{x}.w2.weight"] = rng.randn(e, fm).astype(np.float32)
        else:
            t[f"{p}.mlp.gate_proj.weight"] = rng.randn(f, e).astype(np.float32)
            t[f"{p}.mlp.up_proj.weight"] = rng.randn(f, e).astype(np.float32)
            t[f"{p}.mlp.down_proj.weight"] = rng.randn(e, f).astype(np.float32)
    return t


@pytest.mark.parametrize("name", ["tiny", "tiny-moe", "tiny-mixtral"])
def test_params_from_hf_shapes_and_forward(tmp_path, name):
    cfg = get_config(name)
    save_file(_rng_tensors(cfg), str(tmp_path / "model.safetensors"))
    params = w.params_from_hf(cfg, str(tmp_path), jnp.float32)

    # Pytree structure must match init_params exactly.
    ref = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    assert jax.tree.structure(params) == jax.tree.structure(ref)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(ref)):
        assert a.shape == b.shape, (a.shape, b.shape)

    # And the model must run with the loaded weights.
    logits, _, _ = tf.prefill(params, cfg, jnp.zeros((1, 4), jnp.int32),
                              jnp.asarray([4], jnp.int32))
    assert np.isfinite(np.asarray(logits)).all()


def test_orbax_roundtrip_sharded(tmp_path):
    cfg = get_config("tiny-gqa")
    params = tf.init_params(cfg, jax.random.PRNGKey(3), jnp.float32)
    w.save_orbax(params, str(tmp_path))
    mesh = make_mesh(tensor_parallel=4, data_parallel=2)
    restored = w.load_orbax(cfg, str(tmp_path), mesh, jnp.float32)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Restored leaves carry the mesh sharding (each host reads its shards).
    wq = restored["layers"]["wq"]
    assert wq.sharding.mesh.shape["model"] == 4


def test_load_params_fallback_chain(tmp_path):
    cfg = get_config("tiny")
    # Nothing on disk -> random init, no crash.
    p = w.load_params(cfg, str(tmp_path / "missing"))
    assert p["embed"].shape[0] == cfg.vocab_size
    assert not w.has_real_weights(str(tmp_path / "missing"))


def test_load_params_int8_from_safetensors(tmp_path):
    """--weight-dtype int8 quantizes during load (leaf-by-leaf, so a 7B
    checkpoint never materializes full-width on a 16GB chip) and matches
    the full-width model within quantization error."""
    from arks_tpu.models import quant
    cfg = get_config("tiny")
    save_file(_rng_tensors(cfg), str(tmp_path / "model.safetensors"))
    full = w.load_params(cfg, str(tmp_path), dtype=jnp.float32)
    q = w.load_params(cfg, str(tmp_path), dtype=jnp.float32,
                      weight_dtype="int8")
    assert quant.is_quantized(q["layers"]["wq"])
    assert quant.is_quantized(q["embed"])
    toks = jnp.zeros((1, 4), jnp.int32).at[0, 1].set(7)
    lens = jnp.asarray([4], jnp.int32)
    ref, _, _ = tf.prefill(full, cfg, toks, lens)
    got, _, _ = tf.prefill(q, cfg, toks, lens)
    assert np.argmax(np.asarray(got)) == np.argmax(np.asarray(ref))


def test_load_orbax_int8_single_chip(tmp_path):
    """Orbax + int8 with no mesh restores via host memory, then quantizes
    leaf-by-leaf onto the device."""
    from arks_tpu.models import quant
    cfg = get_config("tiny")
    params = tf.init_params(cfg, jax.random.PRNGKey(3), jnp.float32)
    w.save_orbax(params, str(tmp_path))
    q = w.load_params(cfg, str(tmp_path), dtype=jnp.float32,
                      weight_dtype="int8")
    assert quant.is_quantized(q["layers"]["wq"])
    deq = quant.dequantize(q["layers"]["wq"], jnp.float32)
    err = np.abs(np.asarray(deq) - np.asarray(params["layers"]["wq"])).max()
    assert err < np.abs(np.asarray(params["layers"]["wq"])).max() / 100


def test_weights_kind_single_directory_read(tmp_path, monkeypatch):
    """Classification costs exactly ONE opendir.  has_real_weights and
    load_params used to stat the Orbax subdir AND list the directory —
    on a network filesystem that doubled the metadata reads on every
    model switch."""
    (tmp_path / w.ORBAX_SUBDIR).mkdir()
    (tmp_path / "model.safetensors").write_bytes(b"")
    calls = []
    real = w.os.scandir
    monkeypatch.setattr(w.os, "scandir",
                        lambda p: (calls.append(p), real(p))[1])

    assert w.weights_kind(str(tmp_path)) == "orbax"
    assert len(calls) == 1
    calls.clear()
    assert w.has_real_weights(str(tmp_path)) is True
    assert len(calls) == 1
    calls.clear()
    assert w.weights_kind(str(tmp_path / "missing")) is None
    assert len(calls) == 1


def test_weights_kind_prefers_orbax_over_safetensors(tmp_path):
    assert w.weights_kind(None) is None
    assert w.weights_kind(str(tmp_path)) is None  # empty dir
    (tmp_path / "model.safetensors").write_bytes(b"")
    assert w.weights_kind(str(tmp_path)) == "safetensors"
    (tmp_path / w.ORBAX_SUBDIR).mkdir()
    assert w.weights_kind(str(tmp_path)) == "orbax"


def test_load_params_classifies_once(tmp_path, monkeypatch):
    """load_params branches on one weights_kind call instead of probing
    the directory per format."""
    cfg = get_config("tiny")
    save_file(_rng_tensors(cfg), str(tmp_path / "model.safetensors"))
    n = {"calls": 0}
    real = w.weights_kind

    def counting(p):
        n["calls"] += 1
        return real(p)

    monkeypatch.setattr(w, "weights_kind", counting)
    p = w.load_params(cfg, str(tmp_path), dtype=jnp.float32)
    assert n["calls"] == 1
    assert p["embed"].shape[0] == cfg.vocab_size


@pytest.mark.parametrize("kind", ["safetensors", "orbax"])
def test_load_params_streaming_matches_blocking(tmp_path, kind):
    """The async per-leaf streaming loader (live model switches) must
    produce the exact tree the blocking loader does."""
    cfg = get_config("tiny")
    if kind == "safetensors":
        save_file(_rng_tensors(cfg), str(tmp_path / "model.safetensors"))
    else:
        params = tf.init_params(cfg, jax.random.PRNGKey(3), jnp.float32)
        w.save_orbax(params, str(tmp_path))
    ref = w.load_params(cfg, str(tmp_path), dtype=jnp.float32)
    got = w.load_params_streaming(cfg, str(tmp_path), dtype=jnp.float32)
    assert jax.tree.structure(got) == jax.tree.structure(ref)
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(got)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
