"""Hierarchical prefix KV cache: device page index (tier 0) + host-RAM
spill tier (tier 1).

Acceptance surface for the tiered cache:

- token streams are BYTE-IDENTICAL with the host tier enabled vs
  disabled (greedy + seeded, paged/mixed, pipeline depths 0 and 2);
- a prompt whose prefix was evicted from the device index is served from
  the host tier with ZERO re-prefill of the hit blocks (chunk-token
  dispatch accounting), and the restore never blocks the issue path
  (tests/test_hotpath_guard.py covers the AST side);
- kv-quantized pools spill/restore raw int8 blocks + scales;
- aborts, engine drain, and the disaggregated publish path behave.
"""

import numpy as np
import pytest

from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
from arks_tpu.engine.prefix_cache import HostPrefixTier
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config

CHUNK = 16  # page size for every engine below


def _mk_engine(monkeypatch, host_mb, depth=0, mixed="auto", **kw):
    monkeypatch.setenv("ARKS_PIPELINE_DEPTH", str(depth))
    monkeypatch.setenv("ARKS_MIXED_STEP", mixed)
    monkeypatch.setenv("ARKS_PREFIX_HOST_MB", str(host_mb))
    cfg = get_config("tiny")
    # prefix_cache_mb=0: zero retention surplus, so finished prompts'
    # index-retained pages are evicted (and spilled) by the next
    # admissions — the shape that exercises the tiers hardest.
    defaults = dict(model="tiny", num_slots=2, max_cache_len=64,
                    prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                    prefill_chunk=CHUNK, kv_layout="paged",
                    prefix_cache_mb=0)
    defaults.update(kw)
    eng = InferenceEngine(cfg, EngineConfig(**defaults), ByteTokenizer())
    if depth:
        assert eng._pipe_warm_wait(300) == "ready"
    return cfg, eng


def _drive(eng, n_steps=4000):
    for _ in range(n_steps):
        eng.step(block_s=0.01)
        if eng.idle:
            break


def _run_one(eng, req):
    eng.add_request(req)
    _drive(eng)
    toks, fin = [], None
    while True:
        out = req.outputs.get(timeout=120)
        toks.extend(out.token_ids)
        if out.finished:
            fin = out
            break
    return toks, fin


def _workload(cfg):
    """Sequential multi-turn-ish workload: a warm prompt, churn that
    evicts it, then the warm prompt again (the tier-1 hit in enabled
    runs).  Greedy and seeded-sampled, one-shot and chunked lengths."""
    warm = [int(x) % cfg.vocab_size for x in range(3, 36)]   # 2 pages + tail
    churn = [[(7 + i) % cfg.vocab_size] * 33 for i in range(5)]
    reqs = [("warm1", warm, 0.0, None),
            *[(f"churn{i}", c, 0.0, None) for i, c in enumerate(churn)],
            ("warm2", warm, 0.0, None),
            ("warm3", warm, 0.9, 21)]
    return [Request(rid, ids, SamplingParams(
        max_tokens=6, temperature=temp, top_p=0.9, top_k=40, seed=seed,
        ignore_eos=True)) for rid, ids, temp, seed in reqs]


@pytest.mark.parametrize("depth", [0, 2])
@pytest.mark.parametrize("mixed", ["0", "auto"],
                         ids=["paged-legacy", "paged-mixed"])
def test_streams_byte_identical_with_host_tier_on_and_off(
        monkeypatch, depth, mixed):
    """The host tier is a pure schedule optimization: every stream's
    tokens and finish reasons must be byte-identical with it enabled or
    disabled, on both paged scheduler flavors and at pipeline depths 0
    and 2 — restored pages carry the exact bytes a re-prefill would have
    written."""
    outs = {}
    for host_mb in (0, 64):
        cfg, eng = _mk_engine(monkeypatch, host_mb, depth=depth, mixed=mixed)
        assert (eng._host is not None) == bool(host_mb)
        outs[host_mb] = [_run_one(eng, r) for r in _workload(cfg)]
        if host_mb:
            # The enabled run actually exercised the tier (otherwise the
            # parity assertion is vacuous).
            assert eng.metrics.prefix_restore_blocks_total.total() > 0, \
                "workload never restored from the host tier"
    assert [(t, f.finish_reason) for t, f in outs[64]] == \
           [(t, f.finish_reason) for t, f in outs[0]]


def test_evicted_prefix_restores_with_zero_reprefill(monkeypatch):
    """After churn evicts a prompt's pages from the device index, its
    repeat must be served from the host tier: only the un-hit tail goes
    through chunked prefill (chunk-token accounting — the dispatch-count
    assertion), the restore counters advance, and the restore latency
    histogram observes."""
    cfg, eng = _mk_engine(monkeypatch, 64)
    warm = [int(x) % cfg.vocab_size for x in range(3, 36)]   # 33 tokens
    t1, _ = _run_one(eng, Request("w1", warm, SamplingParams(
        max_tokens=4, temperature=0.0, ignore_eos=True)))
    for i in range(5):
        _run_one(eng, Request(f"c{i}", [(9 + i) % cfg.vocab_size] * 33,
                              SamplingParams(max_tokens=3, temperature=0.0,
                                             ignore_eos=True)))
    # The warm prompt's 2 full pages fell out of the device index and
    # were spilled to the host tier.
    from arks_tpu.engine.paged import chain_digests
    digs = chain_digests(warm, CHUNK, 2)
    assert all(eng._host.has(d) for d in digs), "spill never landed"
    assert eng.metrics.prefix_spill_blocks_total.total() >= 2

    chunk0 = eng.metrics.mixed_chunk_tokens_total.total()
    host_hit0 = eng.metrics.prefix_cache_hit_tokens_total.get(tier="host")
    t2, _ = _run_one(eng, Request("w2", warm, SamplingParams(
        max_tokens=4, temperature=0.0, ignore_eos=True)))
    assert t2 == t1
    # 2 pages (32 tokens) restored; ONLY the 1-token tail was prefilled.
    assert eng.metrics.prefix_cache_hit_tokens_total.get(
        tier="host") - host_hit0 == 32
    assert eng.metrics.mixed_chunk_tokens_total.total() - chunk0 == \
        len(warm) - 32
    assert eng.metrics.prefix_restore_blocks_total.total() == 2
    assert eng.metrics.prefix_restore_seconds._data, \
        "restore latency never observed"
    # The restore repopulated tier 0: pages retained under the digests.
    probe = eng._alloc.match(digs)
    assert len(probe) == 2
    eng._alloc.decref(probe)


def test_quantized_pool_spills_int8_blocks(monkeypatch):
    """kv-int8 pools spill RAW int8 pages + per-token scales (half the
    host bytes, zero re-quantization drift) and restores stay
    byte-identical."""
    outs = {}
    for host_mb in (0, 64):
        cfg, eng = _mk_engine(monkeypatch, host_mb, kv_cache_dtype="int8")
        outs[host_mb] = [_run_one(eng, r) for r in _workload(cfg)]
        if host_mb:
            assert eng.metrics.prefix_restore_blocks_total.total() > 0
            blk = next(iter(eng._host._blocks.values()))
            assert blk["k"].dtype == np.int8
            assert blk["k_scale"].dtype == np.float32
    assert [(t, f.finish_reason) for t, f in outs[64]] == \
           [(t, f.finish_reason) for t, f in outs[0]]


def test_int4_pool_spill_restore_bit_exact(monkeypatch):
    """kv-int4 pools spill RAW packed pages (token pairs per byte + f32
    per-token scales — a quarter of the bf16 host bytes) and a restore
    lands the EXACT bytes back in the pool: stream parity with the tier
    on/off, plus a direct byte comparison of the restored device page
    against the spilled host block."""
    outs = {}
    for host_mb in (0, 64):
        cfg, eng = _mk_engine(monkeypatch, host_mb, kv_cache_dtype="int4")
        assert eng._cache.kv_bits == 4
        outs[host_mb] = [_run_one(eng, r) for r in _workload(cfg)]
        if host_mb:
            assert eng.metrics.prefix_restore_blocks_total.total() > 0
            blk = next(iter(eng._host._blocks.values()))
            assert blk["k"].dtype == np.int8
            # Packed: half the token rows of the scale stripe.
            assert blk["k"].shape[-2] * 2 == blk["k_scale"].shape[-1]
    assert [(t, f.finish_reason) for t, f in outs[64]] == \
           [(t, f.finish_reason) for t, f in outs[0]]

    # Direct bit-exactness: spill a warm prompt's pages, restore them,
    # and compare the device page bytes against the host block.
    cfg, eng = _mk_engine(monkeypatch, 64, kv_cache_dtype="int4")
    warm = [int(x) % cfg.vocab_size for x in range(3, 36)]
    _run_one(eng, Request("w1", warm, SamplingParams(
        max_tokens=3, temperature=0.0, ignore_eos=True)))
    for i in range(5):
        _run_one(eng, Request(f"c{i}", [(9 + i) % cfg.vocab_size] * 33,
                              SamplingParams(max_tokens=3, temperature=0.0,
                                             ignore_eos=True)))
    from arks_tpu.engine.paged import chain_digests
    digs = chain_digests(warm, CHUNK, 2)
    assert all(eng._host.has(d) for d in digs), "spill never landed"
    host_blks = [{k: np.array(v) for k, v in eng._host._blocks[d].items()}
                 for d in digs]
    _run_one(eng, Request("w2", warm, SamplingParams(
        max_tokens=3, temperature=0.0, ignore_eos=True)))
    pages = eng._alloc.match(digs)
    assert len(pages) == 2
    for pg, blk in zip(pages, host_blks):
        np.testing.assert_array_equal(
            np.asarray(eng._cache.k[:, pg]), blk["k"])
        np.testing.assert_array_equal(
            np.asarray(eng._cache.v[:, pg]), blk["v"])
        np.testing.assert_array_equal(
            np.asarray(eng._cache.k_scale[:, pg]), blk["k_scale"])
    eng._alloc.decref(pages)


def test_abort_while_parked_on_restore(monkeypatch):
    """An abort raised while the request is parked in awaiting_restore
    finishes it as "abort" and releases every page it held (refcount
    accounting: all non-retained pages return to the free list)."""
    cfg, eng = _mk_engine(monkeypatch, 64)
    warm = [int(x) % cfg.vocab_size for x in range(3, 36)]
    _run_one(eng, Request("w1", warm, SamplingParams(
        max_tokens=3, temperature=0.0, ignore_eos=True)))
    for i in range(5):
        _run_one(eng, Request(f"c{i}", [(9 + i) % cfg.vocab_size] * 33,
                              SamplingParams(max_tokens=3, temperature=0.0,
                                             ignore_eos=True)))
    req = Request("victim", warm, SamplingParams(
        max_tokens=4, temperature=0.0, ignore_eos=True))
    eng.add_request(req)
    # Step until the request parks, then abort before it can unpark.
    for _ in range(200):
        eng.step(block_s=0.01)
        if eng._awaiting_restore:
            break
    assert eng._awaiting_restore, "request never parked on the restore"
    eng.abort("victim")
    _drive(eng)
    out = req.outputs.get(timeout=60)
    assert out.finished and out.finish_reason == "abort"
    assert not eng._awaiting_restore
    assert eng._alloc.free_pages == (
        eng._alloc.num_pages - eng._alloc.retained_pages)


def test_engine_drain_aborts_parked_restores(monkeypatch):
    """Engine stop with a request parked on a restore must fail it as
    "abort" (no scheduler remains to unpark it) — the SIGTERM-drain
    contract extended to the new park state."""
    cfg, eng = _mk_engine(monkeypatch, 64)
    warm = [int(x) % cfg.vocab_size for x in range(3, 36)]
    _run_one(eng, Request("w1", warm, SamplingParams(
        max_tokens=3, temperature=0.0, ignore_eos=True)))
    for i in range(5):
        _run_one(eng, Request(f"c{i}", [(9 + i) % cfg.vocab_size] * 33,
                              SamplingParams(max_tokens=3, temperature=0.0,
                                             ignore_eos=True)))
    req = Request("parked", warm, SamplingParams(
        max_tokens=4, temperature=0.0, ignore_eos=True))
    eng.add_request(req)
    for _ in range(200):
        eng.step(block_s=0.01)
        if eng._awaiting_restore:
            break
    assert eng._awaiting_restore
    assert not eng.idle  # a parked restore is in-flight work
    eng._abort_awaiting_restores()
    out = req.outputs.get(timeout=60)
    assert out.finished and out.finish_reason == "abort"


def test_disagg_prefill_publishes_into_host_tier(monkeypatch):
    """A disaggregated admission (prefilled KV + prompt ids) registers
    the inserted pages in the device index AND publishes them into the
    host tier, so a decode-side device reset keeps the warm prefix."""
    from arks_tpu.engine.types import PrefilledState

    cfg, eng = _mk_engine(monkeypatch, 64, num_slots=2)
    # 32 tokens: the one-shot disagg limit, and exactly 2 full pages.
    ids = [int(x) % cfg.vocab_size for x in range(5, 37)]
    pf = eng.prefill_detached(ids, SamplingParams(
        max_tokens=4, temperature=0.0, ignore_eos=True))
    assert pf.prompt_ids == ids  # the wire meta carries the prompt
    req = Request("dg", [], SamplingParams(
        max_tokens=4, temperature=0.0, ignore_eos=True), prefilled=pf)
    _run_one(eng, req)
    _drive(eng)  # let the spill resolve
    eng._resolve_spills(force=True)
    from arks_tpu.engine.paged import chain_digests
    digs = chain_digests(ids, CHUNK, 2)
    assert all(eng._host.has(d) for d in digs), \
        "disagg prefill was not published into the host tier"
    # Survives the device rebuild (the "decode-side restart" property).
    eng._reset_device_state()
    assert all(eng._host.has(d) for d in digs)


def test_resolved_config_reports_host_budget(monkeypatch):
    _, on = _mk_engine(monkeypatch, 32)
    assert on.resolved_config["prefix_host_mb"] == "32"
    _, off = _mk_engine(monkeypatch, 0)
    assert off.resolved_config["prefix_host_mb"] == "0"
    # Slot-layout engines never build the tier regardless of the budget.
    cfg = get_config("tiny")
    slot = InferenceEngine(cfg, EngineConfig(
        model="tiny", num_slots=2, max_cache_len=64,
        prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
        prefill_chunk=16, kv_layout="slot"), ByteTokenizer())
    assert slot.resolved_config["prefix_host_mb"] == "0"
    assert slot._host is None


# ---------------------------------------------------------------------------
# HostPrefixTier unit semantics
# ---------------------------------------------------------------------------


def _blk(seed, nbytes=256):
    rng = np.random.default_rng(seed)
    return {"k": rng.standard_normal(nbytes // 8).astype(np.float32),
            "v": rng.standard_normal(nbytes // 8).astype(np.float32)}


def test_host_tier_lru_eviction_by_bytes():
    blk = _blk(0)
    per = sum(a.nbytes for a in blk.values())
    tier = HostPrefixTier(16, capacity_bytes=2 * per)
    assert tier.put(b"a", _blk(1))
    assert tier.put(b"b", _blk(2))
    assert tier.match_blocks([b"a"], 0)          # touch a -> b is LRU
    assert tier.put(b"c", _blk(3))
    assert tier.has(b"a") and tier.has(b"c") and not tier.has(b"b")
    assert tier.bytes_used <= 2 * per
    # Duplicate put is a no-op touch, not a second copy.
    before = tier.bytes_used
    assert not tier.put(b"a", _blk(1))
    assert tier.bytes_used == before


def test_host_tier_match_blocks_is_consecutive():
    tier = HostPrefixTier(16, capacity_bytes=1 << 20)
    for d in (b"d0", b"d1", b"d3"):
        tier.put(d, _blk(hash(d) % 100))
    # The chain stops at the first missing digest (d2), even though d3
    # is present — a restore must never leave holes in the prefix.
    got = tier.match_blocks([b"d0", b"d1", b"d2", b"d3"], 0)
    assert len(got) == 2
    assert tier.match_blocks([b"d0", b"d1", b"d2", b"d3"], 3) == \
        [tier._blocks[b"d3"]]
    assert tier.match_blocks([b"x"], 0) == []


def test_host_tier_clear():
    tier = HostPrefixTier(16, capacity_bytes=1 << 20)
    tier.put(b"a", _blk(1))
    tier.clear()
    assert tier.bytes_used == 0 and not tier.has(b"a")
