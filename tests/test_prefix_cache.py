"""Prefix KV cache: block store semantics + engine-level reuse.

VERDICT acceptance for the prefix-caching item: reuse exercised end to end
with the cache-hit-rate metric asserted."""

import numpy as np
import pytest

from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
from arks_tpu.engine.prefix_cache import PrefixKVCache
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config


def _kv(t, seed=0):
    rng = np.random.default_rng(seed)
    shape = (2, 1, t, 2, 4)  # [L, 1, T, Hkv, D]
    return (rng.standard_normal(shape).astype(np.float32),
            rng.standard_normal(shape).astype(np.float32))


# ---------------------------------------------------------------------------
# Block store
# ---------------------------------------------------------------------------


def test_match_walks_hash_chain():
    pc = PrefixKVCache(block_tokens=4, capacity_bytes=1 << 20)
    ids = list(range(12))
    k, v = _kv(12)
    pc.put(ids, k, v, 12)
    assert pc.match(ids) == 12
    # Shared prefix matches exactly as far as tokens agree (block-aligned).
    assert pc.match(ids[:8] + [99, 98, 97, 96]) == 8
    assert pc.match([99] + ids[1:]) == 0
    # Sub-block queries can't match.
    assert pc.match(ids[:3]) == 0


def test_get_roundtrips_blocks():
    pc = PrefixKVCache(block_tokens=4, capacity_bytes=1 << 20)
    ids = list(range(8))
    k, v = _kv(8)
    pc.put(ids, k, v, 8)
    gk, gv = pc.get(ids, 8)
    np.testing.assert_array_equal(gk, k)
    np.testing.assert_array_equal(gv, v)
    gk4, _ = pc.get(ids, 4)
    np.testing.assert_array_equal(gk4, k[:, :, :4])


def test_shared_prefix_stored_once():
    pc = PrefixKVCache(block_tokens=4, capacity_bytes=1 << 20)
    a = list(range(8))
    b = list(range(4)) + [50, 51, 52, 53]
    k, v = _kv(8)
    pc.put(a, k, v, 8)
    used = pc.bytes_used
    pc.put(b, k, v, 8)  # first block identical -> only one new block stored
    per_block = used // 2
    assert pc.bytes_used == used + per_block


def test_lru_eviction_by_bytes():
    k, v = _kv(4)
    per_block = k.nbytes + v.nbytes
    pc = PrefixKVCache(block_tokens=4, capacity_bytes=2 * per_block)
    pc.put(list(range(4)), k, v, 4)
    pc.put(list(range(100, 104)), k, v, 4)
    assert pc.match(list(range(4))) == 4
    # Touch the first entry so the second is LRU.
    pc.get(list(range(4)), 4)
    pc.put(list(range(200, 204)), k, v, 4)
    assert pc.bytes_used <= 2 * per_block
    assert pc.match(list(range(4))) == 4
    assert pc.match(list(range(100, 104))) == 0  # evicted


# ---------------------------------------------------------------------------
# Engine-level reuse
# ---------------------------------------------------------------------------


def _drive(engine, n_steps=300):
    for _ in range(n_steps):
        engine.step(block_s=0.01)
        if (engine.num_running == 0 and engine._queue.empty()
                and not engine._prefilling):
            break


def _collect(req, timeout=60):
    ids, finished = [], None
    while True:
        out = req.outputs.get(timeout=timeout)
        ids.extend(out.token_ids)
        if out.finished:
            finished = out
            break
    return ids, finished


@pytest.fixture(scope="module")
def peng():
    cfg = get_config("tiny")
    # chunk = 16 (divides 64); blocks of 16 tokens.
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                        prefill_chunk=16, prefix_cache_mb=64)
    return InferenceEngine(cfg, ecfg, ByteTokenizer())


def test_engine_prefix_reuse_same_output(peng):
    cfg = get_config("tiny")
    prompt = [int(x) % cfg.vocab_size for x in range(7, 39)]  # 32 tokens
    r1 = Request("p1", prompt, SamplingParams(max_tokens=6, temperature=0.0,
                                              ignore_eos=True))
    peng.add_request(r1)
    _drive(peng)
    ids1, fin1 = _collect(r1)
    assert peng._prefix.bytes_used > 0  # harvested 2 blocks of 16

    # Identical prompt again: served from the cache (hit tokens recorded),
    # same greedy continuation.
    r2 = Request("p2", prompt, SamplingParams(max_tokens=6, temperature=0.0,
                                              ignore_eos=True))
    peng.add_request(r2)
    _drive(peng)
    ids2, fin2 = _collect(r2)
    assert ids2 == ids1
    assert fin2.num_prompt_tokens == 32
    # Whole-prompt hit is capped one block short: >=1 tail token computes
    # the first-token logits.
    assert peng._prefix.hit_tokens == 16
    assert peng._prefix.hit_rate > 0

    # Metric family exposed under the normalized names.
    text = peng.metrics.registry.render()
    assert "prefix_cache_hit_tokens_total" in text
    assert "prefix_cache_hit_rate" in text


def test_engine_prefix_reuse_divergent_tail(peng):
    cfg = get_config("tiny")
    shared = [int(x) % cfg.vocab_size for x in range(7, 39)]  # 32 cached above
    tail = [3, 4, 5, 6, 7, 8, 9, 10]

    # Oracle: fresh engine with the cache disabled.
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                        prefill_chunk=16, prefix_cache_mb=0)
    cold = InferenceEngine(cfg, ecfg, ByteTokenizer())
    rc = Request("c", shared + tail, SamplingParams(max_tokens=5, temperature=0.0,
                                                    ignore_eos=True))
    cold.add_request(rc)
    _drive(cold)
    ids_cold, _ = _collect(rc)

    before = peng._prefix.hit_tokens
    rw = Request("w", shared + tail, SamplingParams(max_tokens=5, temperature=0.0,
                                                    ignore_eos=True))
    peng.add_request(rw)
    _drive(peng)
    ids_warm, fin = _collect(rw)
    assert fin.num_prompt_tokens == 40
    assert peng._prefix.hit_tokens - before == 32  # both shared blocks reused
    assert ids_warm == ids_cold


def test_chunked_prompt_harvested_for_reuse():
    """Long (chunk-prefilled) prompts must also populate the cache — their
    KV is read back out of the slotted cache (transformer.extract)."""
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(8,), steps_per_dispatch=4,
                        prefill_chunk=16, prefix_cache_mb=64)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    prompt = [int(x) % cfg.vocab_size for x in range(3, 51)]  # 48 tokens, chunked
    r1 = Request("h1", prompt, SamplingParams(max_tokens=3, temperature=0.0,
                                              ignore_eos=True))
    eng.add_request(r1)
    _drive(eng)
    ids1, _ = _collect(r1)
    assert eng._prefix.match(prompt) == 48

    r2 = Request("h2", prompt, SamplingParams(max_tokens=3, temperature=0.0,
                                              ignore_eos=True))
    eng.add_request(r2)
    _drive(eng)
    ids2, _ = _collect(r2)
    assert ids2 == ids1
    assert eng._prefix.hit_tokens == 32  # 48 capped one block short
