"""Static guard over exception handling in the engine package.

The fault-isolation contract (engine.faults) lives or dies on faults
being VISIBLE: an ``except Exception`` that silently swallows inside
arks_tpu/engine/ can strand a request (client blocks forever), hide a
poisoned-device state, or defeat the quarantine accounting — and it would
pass every behavior test, because swallowing only changes what happens on
the paths tests rarely exercise.  This test walks every module under
arks_tpu/engine/ via AST and requires each broad handler
(``except Exception`` / bare ``except``) to either

- re-raise (a ``raise`` statement anywhere in the handler body), or
- route through the fault-context API: a call to one of FAULT_API
  (faults.swallowed / StepFault construction / the recovery entry
  points), or os._exit (the escalation ladder's last rung).

Narrow handlers (specific exception classes) are exempt — they encode a
deliberate, reviewable decision already.
"""

import ast
import pathlib

import arks_tpu.engine as engine_pkg

ENGINE_DIR = pathlib.Path(engine_pkg.__file__).parent

# Calls that count as routing through the fault-context API.
FAULT_API = {
    "swallowed",            # faults.swallowed — sanctioned intentional swallow
    "StepFault",            # re-raise as an attributed fault
    "classify",             # building a StepFault's kind
    "_recover_from_fault",  # the recovery entry point itself
    "_exit",                # os._exit — the watchdog/gang escalation rung
}

# Reviewed exceptions, keyed (filename, enclosing function).  Every entry
# must stay justifiable as fault-ROUTING by other means:
#   - guides.py/_compile_job: lands the error on the compile ticket —
#     every waiter (blocking compile() callers and engine-parked
#     requests) receives it as a per-request failure.
#   - engine.py/_recover_from_fault: the retry loop OF the fault API —
#     the caught exception feeds the next recovery round or the blanket
#     fallback; nothing is dropped.
#   - model_pool.py/_load: lands the error on the LoadTicket (the guide
#     _compile_job pattern) — every waiter (blocking load() callers and
#     model-parked requests polled by _issue_model_load) receives it as
#     a per-request failure.
ALLOWED = {
    ("guides.py", "_compile_job"),
    ("engine.py", "_recover_from_fault"),
    ("model_pool.py", "_load"),
}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    names = []
    if isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    elif isinstance(t, ast.Name):
        names = [t.id]
    return any(n in ("Exception", "BaseException") for n in names)


def _routes_fault(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if name in FAULT_API:
                return True
    return False


def _enclosing_function(tree: ast.Module, lineno: int) -> str:
    best = "<module>"
    best_line = 0
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.lineno <= lineno and node.lineno > best_line:
                end = getattr(node, "end_lineno", None)
                if end is None or lineno <= end:
                    best = node.name
                    best_line = node.lineno
    return best


def test_no_silent_swallows_in_engine_package():
    violations = []
    for path in sorted(ENGINE_DIR.glob("*.py")):
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if _routes_fault(node):
                continue
            fn = _enclosing_function(tree, node.lineno)
            if (path.name, fn) in ALLOWED:
                continue
            violations.append(f"{path.name}:{node.lineno} in {fn}()")
    assert not violations, (
        "broad exception handler neither re-raises nor routes through the "
        "fault-context API (faults.swallowed / StepFault / recovery); "
        "handle it or justify an ALLOWED entry: " + ", ".join(violations))


def test_allowed_entries_still_exist():
    """A stale ALLOWED entry means the justified handler moved — the
    allowlist must shrink with it, not silently cover new code."""
    for fname, fn in ALLOWED:
        tree = ast.parse((ENGINE_DIR / fname).read_text())
        names = {n.name for n in ast.walk(tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        assert fn in names, f"stale ALLOWED entry: {fname}/{fn}"


def test_fault_api_names_exist():
    from arks_tpu.engine import faults
    from arks_tpu.engine.engine import InferenceEngine
    assert callable(faults.swallowed)
    assert callable(faults.classify)
    assert callable(getattr(InferenceEngine, "_recover_from_fault"))


def test_preempt_paths_carry_the_fault_phase():
    """Preemptive-swap review row: every preemption entry point that
    touches the device (spill issue, harvest, resume) must be reachable
    by the 'preempt' chaos phase AND raise its failures as attributed
    StepFaults — a preemption fault that escaped as a bare exception
    would blanket-abort every innocent stream instead of quarantining
    the one victim."""
    import inspect

    from arks_tpu.engine.engine import InferenceEngine

    for name in ("_issue_preempt_swap", "_preempt_replay",
                 "_resolve_preempt_swaps", "_resume_swapped"):
        src = inspect.getsource(getattr(InferenceEngine, name))
        tree = ast.parse("class _C:\n" + src if src.startswith("    ")
                         else src)
        fires = [n for n in ast.walk(tree) if isinstance(n, ast.Call)
                 and isinstance(n.func, ast.Attribute)
                 and n.func.attr == "fire"
                 and n.args and isinstance(n.args[0], ast.Constant)
                 and n.args[0].value == "preempt"]
        assert fires, f"{name} lost its faults.fire('preempt') hook"
        faults = [n for n in ast.walk(tree) if isinstance(n, ast.Call)
                  and ((isinstance(n.func, ast.Name)
                        and n.func.id == "StepFault")
                       or (isinstance(n.func, ast.Attribute)
                           and n.func.attr == "StepFault"))
                  and n.args and isinstance(n.args[0], ast.Constant)
                  and n.args[0].value == "preempt"]
        assert faults, f"{name} no longer raises StepFault('preempt', ...)"
