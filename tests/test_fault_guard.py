"""Static guard over exception handling — thin wrapper over arkslint.

The broad-handler discipline this file used to implement by hand (every
``except Exception`` in arks_tpu/engine/ must re-raise or route through
the fault API) now lives in ``arks_tpu/analysis/rules/exceptions.py``,
extended REPO-WIDE: engine modules keep the strict contract, everything
else may alternatively log with ``exc_info``/``log.exception``.  The old
``ALLOWED`` set became reviewed entries in
``tools/arkslint-baseline.json``, whose staleness check replaces
``test_allowed_entries_still_exist``.

The runtime checks at the bottom (fault-API symbols exist, preemption
paths carry the chaos phase) stay here — they inspect live objects the
pure-AST analyzer deliberately never imports.
"""

import ast
import functools

from arks_tpu.analysis import SourceTree, repo_root, run_rules
from arks_tpu.analysis.baseline import Baseline


@functools.lru_cache(maxsize=1)
def _apply():
    root = repo_root()
    findings = run_rules(SourceTree.load(root), ["exceptions"])
    baseline = Baseline.load(root / "tools" / "arkslint-baseline.json")
    baseline.entries = [e for e in baseline.entries
                        if e["rule"] == "exceptions"]
    return baseline.apply(findings)


def test_no_silent_swallows_in_engine_package():
    active, _suppressed, _stale = _apply()
    bad = [f.render() for f in active
           if f.severity == "error"
           and f.path.startswith("arks_tpu/engine/")]
    assert not bad, bad


def test_no_silent_swallows_repo_wide():
    """The same discipline outside the engine: a broad handler must
    re-raise, call swallowed(), or log with the traceback attached."""
    active, _suppressed, _stale = _apply()
    bad = [f.render() for f in active if f.severity == "error"]
    assert not bad, bad


def test_allowed_entries_still_exist():
    """A stale suppression means the justified handler moved — the
    baseline must shrink with it, not silently cover new code."""
    _active, _suppressed, stale = _apply()
    assert not stale, f"stale arkslint suppressions: {stale}"


def test_fault_api_names_exist():
    from arks_tpu.engine import faults
    from arks_tpu.engine.engine import InferenceEngine
    assert callable(faults.swallowed)
    assert callable(faults.classify)
    assert callable(getattr(InferenceEngine, "_recover_from_fault"))


def test_preempt_paths_carry_the_fault_phase():
    """Preemptive-swap review row: every preemption entry point that
    touches the device (spill issue, harvest, resume) must be reachable
    by the 'preempt' chaos phase AND raise its failures as attributed
    StepFaults — a preemption fault that escaped as a bare exception
    would blanket-abort every innocent stream instead of quarantining
    the one victim."""
    import inspect

    from arks_tpu.engine.engine import InferenceEngine

    for name in ("_issue_preempt_swap", "_preempt_replay",
                 "_resolve_preempt_swaps", "_resume_swapped"):
        src = inspect.getsource(getattr(InferenceEngine, name))
        tree = ast.parse("class _C:\n" + src if src.startswith("    ")
                         else src)
        fires = [n for n in ast.walk(tree) if isinstance(n, ast.Call)
                 and isinstance(n.func, ast.Attribute)
                 and n.func.attr == "fire"
                 and n.args and isinstance(n.args[0], ast.Constant)
                 and n.args[0].value == "preempt"]
        assert fires, f"{name} lost its faults.fire('preempt') hook"
        faults = [n for n in ast.walk(tree) if isinstance(n, ast.Call)
                  and ((isinstance(n.func, ast.Name)
                        and n.func.id == "StepFault")
                       or (isinstance(n.func, ast.Attribute)
                           and n.func.attr == "StepFault"))
                  and n.args and isinstance(n.args[0], ast.Constant)
                  and n.args[0].value == "preempt"]
        assert faults, f"{name} no longer raises StepFault('preempt', ...)"
