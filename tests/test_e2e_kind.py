"""Executable real-cluster e2e (tools/e2e_kind.sh) — the counterpart of
the reference's Kind suite (/root/reference/test/e2e/e2e_test.go:45-270).

The full run needs kind + docker + kubectl on the host; environments
without them (this repo's CPU CI included) still get a syntax gate so the
script cannot rot silently."""

import os
import shutil
import subprocess

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(ROOT, "tools", "e2e_kind.sh")


def test_e2e_kind_script_parses():
    subprocess.run(["bash", "-n", SCRIPT], check=True)
    assert os.access(SCRIPT, os.X_OK), "script must be executable"


def test_e2e_kind_script_gates_on_missing_tools():
    """Without kind/docker the script exits 3 ("SKIP") before touching
    anything — the CI-safe behavior."""
    if shutil.which("kind") and shutil.which("docker"):
        pytest.skip("cluster tooling present; the full run covers this")
    r = subprocess.run(["bash", SCRIPT], capture_output=True, text=True,
                       timeout=60)
    assert r.returncode == 3
    assert "SKIP" in r.stderr


@pytest.mark.skipif(
    not (shutil.which("kind") and shutil.which("docker")
         and shutil.which("kubectl")),
    reason="kind/docker/kubectl not installed")
def test_e2e_kind_full():
    """The real thing: green on a fresh Kind cluster (~10 min: image
    build + quickstart serve + failover)."""
    r = subprocess.run(["bash", SCRIPT], timeout=2400)
    assert r.returncode == 0
