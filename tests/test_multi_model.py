"""Multi-model serving: pooled token streams must be byte-identical to
single-model engines, switches must be fault-isolated, and a second
model must not grow the program-shape budget.

Three contracts:

1. **Byte identity.**  One engine serving two models through the pool
   (park -> drain -> streaming switch -> unpark) emits, per request,
   exactly the stream a dedicated single-model engine of that config
   would emit — greedy and seeded, at pipeline depths 0 and 2.  The
   second config is structurally DIFFERENT (fewer layers) so a routing
   bug cannot hide behind identical weights.

2. **Fault isolation.**  A fault injected in the new "model_switch"
   phase quarantines at most the requests parked for the target model;
   recovery replays them and the retried switch converges to the same
   byte-identical streams.  With a zero retry budget, the parked
   requests fail ALONE — streams already served on the active model are
   untouched.

3. **Compile budget.**  A same-shape second model re-uses every program
   SHAPE: its per-model context compiles the same (name, variant-count)
   set the first model did, no more.  New executables are expected (jit
   caches are per-context); new shapes are not.

Engines are driven synchronously through the same
step/_recover_from_fault contract the engine thread runs, like
test_chaos.py.
"""

import dataclasses
import threading

import pytest

from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
from arks_tpu.engine.model_pool import ModelPool
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config

# The flagship paged/mixed layout; multi-model rides the same scheduler.
DEFAULTS = dict(num_slots=2, max_cache_len=64, prefill_buckets=(8, 16, 32),
                steps_per_dispatch=4, prefill_chunk=16, kv_layout="paged")


def _second_cfg(same_shape=False):
    cfg = get_config("tiny")
    if same_shape:
        return dataclasses.replace(cfg, name="tiny-b")
    return dataclasses.replace(cfg, name="tiny2", num_layers=1)


def _env(monkeypatch, depth, inject=None, retries=None):
    monkeypatch.setenv("ARKS_PIPELINE_DEPTH", str(depth))
    monkeypatch.setenv("ARKS_MIXED_STEP", "auto")
    if inject is None:
        monkeypatch.delenv("ARKS_FAULT_INJECT", raising=False)
    else:
        monkeypatch.setenv("ARKS_FAULT_INJECT", inject)
    if retries is None:
        monkeypatch.delenv("ARKS_FAULT_RETRIES", raising=False)
    else:
        monkeypatch.setenv("ARKS_FAULT_RETRIES", str(retries))


def _mk_pool_engine(monkeypatch, depth, cfg_b, inject=None, retries=None):
    _env(monkeypatch, depth, inject, retries)
    cfg = get_config("tiny")
    eng = InferenceEngine(cfg, EngineConfig(model="tiny", **DEFAULTS),
                          ByteTokenizer(), pool=ModelPool())
    eng.register_model(cfg_b)
    if depth:
        assert eng._pipe_warm_wait(300) == "ready"
    return cfg, eng


def _mk_single_engine(monkeypatch, depth, cfg):
    _env(monkeypatch, depth)
    eng = InferenceEngine(cfg, EngineConfig(model=cfg.name, **DEFAULTS),
                          ByteTokenizer())
    if depth:
        assert eng._pipe_warm_wait(300) == "ready"
    return eng


def _drive(eng, n_steps=4000):
    """The engine thread's own step/recover contract, synchronously.
    ``idle`` covers the model-parked state, so this only exits once
    every parked request has been switched to and served."""
    for _ in range(n_steps):
        try:
            eng.step(block_s=0.01)
        except Exception as e:  # noqa: BLE001 — routed exactly like _run_loop
            eng._recover_from_fault(e)
        if eng.idle and eng.state == "serving" and not eng._model_loads:
            break


def _quiesce(eng, depth):
    # The active context's pipe warmup compiles on a daemon thread; join
    # it before the test returns so nothing races interpreter teardown.
    if depth:
        assert eng._pipe_warm_wait(600) == "ready"


def _collect(req, timeout=120):
    ids, fin = [], None
    while True:
        out = req.outputs.get(timeout=timeout)
        ids.extend(out.token_ids)
        if out.finished:
            fin = out
            break
    return ids, fin


# (model-slot, prompt, greedy?) — interleaved across the two models,
# greedy + seeded per model.  Seeds are explicit: the engine's fallback
# seed counter is engine-global and would differ between a pooled run
# and two single-model runs.
WORKLOAD = [
    ("a", [5, 6, 7], True),
    ("b", [9] * 5, True),
    ("a", [11] * 4, False),
    ("b", [3, 1, 4], False),
]


def _requests(cfg_b, only=None):
    reqs = []
    for i, (slot, prompt, greedy) in enumerate(WORKLOAD):
        if only is not None and slot != only:
            continue
        sp = SamplingParams(max_tokens=12, temperature=0.0 if greedy else 0.9,
                            top_p=0.9, top_k=40, seed=31 + i, ignore_eos=True)
        model = cfg_b.name if slot == "b" else None
        reqs.append(Request(f"m{i}", list(prompt), sp, model=model))
    return reqs


def _single_model_baseline(monkeypatch, depth, cfg_b):
    """Per-request streams from two dedicated engines, one per config."""
    base = {}
    for slot, cfg in (("a", get_config("tiny")), ("b", cfg_b)):
        eng = _mk_single_engine(monkeypatch, depth, cfg)
        reqs = _requests(cfg_b, only=slot)
        for r in reqs:
            r.model = None  # single-model engine: no routing field
            eng.add_request(r)
        _drive(eng)
        _quiesce(eng, depth)
        for r in reqs:
            base[r.request_id] = _collect(r)
    return base


def _pooled_run(monkeypatch, depth, cfg_b, inject=None, retries=None):
    cfg, eng = _mk_pool_engine(monkeypatch, depth, cfg_b,
                               inject=inject, retries=retries)
    reqs = _requests(cfg_b)
    for r in reqs:
        eng.add_request(r)
    _drive(eng)
    _quiesce(eng, depth)
    return {r.request_id: _collect(r) for r in reqs}, eng


@pytest.mark.parametrize("depth", [0, 2])
def test_pooled_streams_byte_identical_to_single_model_engines(
        monkeypatch, depth):
    base = _single_model_baseline(monkeypatch, depth, _second_cfg())
    got, eng = _pooled_run(monkeypatch, depth, _second_cfg())
    assert {rid: f.finish_reason for rid, (_, f) in got.items()} == \
        {rid: "length" for rid in base}
    assert got == base, "pooled streams diverged from single-model engines"
    # The switch actually happened and was measured.
    assert eng.metrics.model_switch_seconds._data
    assert eng.last_switch_stats is not None
    assert sum(eng.metrics.engine_faults_total._values.values()) == 0


@pytest.mark.chaos
@pytest.mark.parametrize("depth", [0, 2])
def test_model_switch_fault_recovers_byte_identical(monkeypatch, depth):
    """A fault in the model_switch phase must replay the parked requests
    through a retried switch and still converge to the exact streams of
    a fault-free pooled run."""
    base, _ = _pooled_run(monkeypatch, depth, _second_cfg())
    got, eng = _pooled_run(monkeypatch, depth, _second_cfg(),
                           inject="model_switch:1:runtime")
    assert {rid: f.finish_reason for rid, (_, f) in got.items()} == \
        {rid: "length" for rid in base}
    assert got == base, "streams diverged after a model_switch fault"
    faults = dict(eng.metrics.engine_faults_total._values)
    assert sum(faults.values()) == 1
    assert any("model_switch" in str(k) for k in faults)
    # Both parked-for-tiny2 requests replayed (plain requeue: nothing
    # was emitted for them yet), nobody quarantined.
    assert sum(eng.metrics.requests_recovered_total._values.values()) == 2
    assert sum(eng.metrics.requests_quarantined_total._values.values()) == 0
    assert eng.state == "serving"


@pytest.mark.chaos
def test_model_switch_fault_quarantines_parked_culprits_only(monkeypatch):
    """With a zero retry budget the switch's culprits — exactly the
    requests parked for the target model — fail alone; the active
    model's streams are untouched (they had already drained: switches
    run at fully drained boundaries)."""
    base, _ = _pooled_run(monkeypatch, 0, _second_cfg())
    got, eng = _pooled_run(monkeypatch, 0, _second_cfg(),
                           inject="model_switch:1:runtime", retries=0)
    for rid, (ids, fin) in got.items():
        if rid in ("m1", "m3"):  # the two tiny2-routed requests
            assert fin.finish_reason == "error"
            assert "model_switch" in fin.error
        else:
            assert (ids, fin) == base[rid], \
                "a fault in another model's switch touched an active stream"
    assert sum(eng.metrics.requests_quarantined_total._values.values()) == 2
    assert eng.state == "serving"


def test_unknown_model_fails_fast(monkeypatch):
    _, eng = _mk_pool_engine(monkeypatch, 0, _second_cfg())
    sp = SamplingParams(max_tokens=4, temperature=0.0)
    req = Request("nope", [1, 2, 3], sp, model="no-such-model")
    eng.add_request(req)
    _drive(eng, n_steps=50)
    _, fin = _collect(req)
    assert fin.finish_reason == "error" and fin.error == "model_not_found"
    assert not eng._awaiting_model


def test_abort_while_parked_for_model(monkeypatch):
    """An abort must reach a request parked on a model load, and the
    waiting gauge must come back down."""
    _, eng = _mk_pool_engine(monkeypatch, 0, _second_cfg())
    entry = eng.pool.entry("tiny2")
    orig, gate = entry.loader, threading.Event()
    entry.loader = lambda: (gate.wait(30), orig())[1]
    sp = SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True)
    req = Request("parked", [7, 8, 9], sp, model="tiny2")
    eng.add_request(req)
    for _ in range(200):
        eng.step(block_s=0.01)
        if eng._awaiting_model:
            break
    assert eng._awaiting_model, "request never parked for its model"
    eng.abort("parked")
    for _ in range(200):
        eng.step(block_s=0.01)
        if not eng._awaiting_model:
            break
    gate.set()
    _, fin = _collect(req)
    assert fin.finish_reason == "abort"
    assert not eng._awaiting_model
    assert sum(eng.metrics.num_requests_waiting._values.values()) == 0
    _drive(eng, n_steps=100)  # let the (now unblocked) load settle


def test_second_model_adds_no_new_program_shapes(monkeypatch):
    """A same-shape second model must ride the first model's program
    shapes: after serving identical workloads on both, the per-context
    compiled-variant census (program name -> shape count) matches
    exactly.  New executables are fine — new shapes are a compile-budget
    regression."""
    cfg_b = _second_cfg(same_shape=True)
    _, eng = _mk_pool_engine(monkeypatch, 0, cfg_b)

    def serve(model):
        reqs = []
        for i, (_, prompt, greedy) in enumerate(WORKLOAD):
            sp = SamplingParams(max_tokens=12,
                                temperature=0.0 if greedy else 0.9,
                                top_p=0.9, top_k=40, seed=31 + i,
                                ignore_eos=True)
            reqs.append(Request(f"{model or 'a'}-{i}", list(prompt), sp,
                                model=model))
        for r in reqs:
            eng.add_request(r)
        _drive(eng)
        for r in reqs:
            _collect(r)

    serve(None)
    variants_a = eng.compiled_program_variants()
    assert eng.cfg.name == "tiny"
    serve(cfg_b.name)
    assert eng.cfg.name == cfg_b.name
    variants_b = eng.compiled_program_variants()
    assert variants_b == variants_a, (
        "the second model compiled different program shapes: "
        f"{variants_a} vs {variants_b}")
