"""Elastic parallelism: live resize over HTTP, streaming
scale-from-zero, router planned membership, and the signals-mode
autoscaler — the control loop that turns overload evidence (per-tier
SLO burn, admission saturation) into topology changes.

Byte-identity note: greedy (argmax) streams are byte-identical across a
TP shape change; seeded SAMPLED streams are distribution-exact but not
byte-exact (the psum reduction order shifts with the mesh), so every
cross-shape assertion here rides greedy streams.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from arks_tpu import prefix_sketch as ps
from arks_tpu.engine import (EngineConfig, InferenceEngine, Request,
                             SamplingParams)
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config
from arks_tpu.router import Discovery, Router
from arks_tpu.server import OpenAIServer


def _mk_engine(monkeypatch, **kw):
    cfg = get_config("tiny")
    defaults = dict(model="tiny", num_slots=2, max_cache_len=64,
                    prefill_buckets=(8, 16, 32), steps_per_dispatch=4)
    defaults.update(kw)
    return cfg, InferenceEngine(cfg, EngineConfig(**defaults),
                                ByteTokenizer())


def _greedy(cfg, rid, prompt, max_tokens=10):
    return Request(rid, [int(x) % cfg.vocab_size for x in prompt],
                   SamplingParams(max_tokens=max_tokens, temperature=0.0,
                                  ignore_eos=True))


def _collect(req, timeout=120):
    ids, fin = [], None
    while True:
        out = req.outputs.get(timeout=timeout)
        ids.extend(out.token_ids)
        if out.finished:
            fin = out
            break
    return ids, fin


# ---------------------------------------------------------------------------
# Engine: resize request surface + scale-to-zero / re-arm
# ---------------------------------------------------------------------------

def test_resize_reject_matrix(monkeypatch):
    """Cheap-shape validation raises immediately; capability rejections
    land as outcome="rejected" on the engine thread where the check can
    read coherent scheduler state."""
    cfg, eng = _mk_engine(monkeypatch)
    with pytest.raises(ValueError):
        eng.request_resize(tensor_parallel=0)
    hold = eng.request_resize(tensor_parallel=1024)  # > visible devices
    with pytest.raises(RuntimeError):
        eng.request_resize(tensor_parallel=2)        # one in flight already
    eng.step(block_s=0.01)
    assert hold.wait(10) and hold.outcome == "rejected"
    assert "devices" in hold.error
    assert eng.metrics.engine_resizes_total.get(
        mode="resize", outcome="rejected") == 1
    assert eng._mesh_shape_str() == "tp1xdp1"


def test_resize_to_current_shape_is_trivially_ok(monkeypatch):
    cfg, eng = _mk_engine(monkeypatch)
    hold = eng.request_resize(tensor_parallel=1, data_parallel=1)
    eng.step(block_s=0.01)
    assert hold.wait(10) and hold.outcome == "ok"
    assert eng.elastic_status()["resize_inflight"] is False


def test_scale_to_zero_and_rearm_on_demand(monkeypatch):
    """An idle engine disarms after ARKS_ELASTIC_IDLE_ZERO_S (weights +
    device KV dropped), then a queue arrival re-arms it and the demand
    stream completes byte-identical to a never-disarmed run."""
    monkeypatch.setenv("ARKS_ELASTIC_IDLE_ZERO_S", "0.05")
    cfg, base_eng = _mk_engine(monkeypatch)
    r0 = _greedy(cfg, "b0", [5, 6, 7])
    base_eng.add_request(r0)
    for _ in range(200):
        base_eng.step(block_s=0.01)
        if base_eng.num_running == 0 and base_eng._queue.empty():
            break
    base = _collect(r0)

    cfg, eng = _mk_engine(monkeypatch)
    deadline = time.monotonic() + 30
    while eng.armed and time.monotonic() < deadline:
        eng.step(block_s=0.01)
        time.sleep(0.01)
    assert not eng.armed, "idle engine never scaled to zero"
    assert eng.params is None and eng._cache is None
    st = eng.elastic_status()
    assert st["armed"] is False
    assert eng.metrics.engine_resizes_total.get(
        mode="scale_to_zero", outcome="ok") == 1

    # Demand re-arms: the warm-up request compiles the programs, then
    # the client stream rides them.
    r1 = _greedy(cfg, "d0", [5, 6, 7])
    eng.add_request(r1)
    for _ in range(400):
        eng.step(block_s=0.01)
        if (eng.armed and eng.num_running == 0 and eng._queue.empty()
                and not eng._prefilling):
            break
    assert eng.armed, "demand did not re-arm the engine"
    got = _collect(r1)
    assert (got[0], got[1].finish_reason) == (base[0], "length"), \
        "post-re-arm stream diverged"
    assert eng.last_rearm_stats is not None
    assert eng.last_rearm_stats["seconds"] > 0
    assert eng.metrics.engine_resizes_total.get(
        mode="rearm", outcome="ok") == 1


def test_disarmed_resize_rearms_at_requested_shape(monkeypatch):
    """request_resize against a scaled-to-zero engine re-arms it AT the
    requested shape — the streaming scale-up path the autoscaler's
    actuator drives (no demand needed)."""
    monkeypatch.setenv("ARKS_ELASTIC_IDLE_ZERO_S", "0.05")
    cfg, eng = _mk_engine(monkeypatch)
    deadline = time.monotonic() + 30
    while eng.armed and time.monotonic() < deadline:
        eng.step(block_s=0.01)
        time.sleep(0.01)
    assert not eng.armed
    hold = eng.request_resize(tensor_parallel=2)
    for _ in range(200):
        eng.step(block_s=0.01)
        if hold.outcome is not None:
            break
    assert hold.outcome == "ok", hold.error
    assert eng.armed and eng._mesh_shape_str() == "tp2xdp1"
    assert eng.last_rearm_stats["shape"] == "tp2xdp1"


# ---------------------------------------------------------------------------
# Server: /v1/elastic endpoints + disarmed readiness
# ---------------------------------------------------------------------------

def _get_json(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=30) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _post_json(port, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_elastic_http_surface(monkeypatch):
    """The operator surface end to end: status, a live resize over
    POST /v1/elastic/resize (2xx with the new shape), the reject matrix
    as HTTP codes, and the elastic/slo_burn blocks on /readiness."""
    cfg, eng = _mk_engine(monkeypatch)
    eng.start()
    srv = OpenAIServer(eng, served_model_name="t", host="127.0.0.1", port=0)
    srv.start(background=True)
    try:
        code, st = _get_json(srv.port, "/v1/elastic/status")
        assert code == 200 and st["armed"] and st["shape"] == "tp1xdp1"

        code, rdy = _get_json(srv.port, "/readiness")
        assert code == 200
        assert rdy["elastic"]["armed"] is True
        assert "slo_burn" in rdy and "admission" in rdy

        code, out = _post_json(srv.port, "/v1/elastic/resize",
                               {"tensor_parallel": 2})
        assert code == 200 and out["status"] == "ok"
        assert out["elastic"]["shape"] == "tp2xdp1"
        assert out["seconds"] > 0

        code, out = _post_json(srv.port, "/v1/elastic/resize",
                               {"tensor_parallel": 1024})
        assert code == 422 and out["status"] == "rejected"
        code, out = _post_json(srv.port, "/v1/elastic/resize",
                               {"tensor_parallel": 0})
        assert code == 400
        code, out = _post_json(srv.port, "/v1/elastic/resize",
                               {"tensor_parallel": "nope"})
        assert code == 400
    finally:
        srv.stop()
        eng.stop()


@pytest.mark.slow
def test_disarmed_readiness_and_http_rearm(monkeypatch):
    """A scaled-to-zero replica 503s /readiness with a "disarmed" reason
    (the router's planned-join gate and the autoscaler's disarmed count
    both read it) while /v1/elastic/status stays reachable; a resize
    POST re-arms it and readiness returns 200."""
    monkeypatch.setenv("ARKS_ELASTIC_IDLE_ZERO_S", "0.05")
    cfg, eng = _mk_engine(monkeypatch)
    eng.start()
    srv = OpenAIServer(eng, served_model_name="t", host="127.0.0.1", port=0)
    srv.start(background=True)
    try:
        deadline = time.monotonic() + 30
        while eng.armed and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not eng.armed
        code, out = _get_json(srv.port, "/readiness")
        assert code == 503 and "disarmed" in out["error"]["message"]
        code, st = _get_json(srv.port, "/v1/elastic/status")
        assert code == 200 and st["armed"] is False

        code, out = _post_json(srv.port, "/v1/elastic/resize",
                               {"tensor_parallel": 1})
        assert code == 200 and out["status"] == "ok", out
        code, rdy = _get_json(srv.port, "/readiness")
        assert code == 200 and rdy["elastic"]["armed"] is True
    finally:
        srv.stop()
        eng.stop()


# ---------------------------------------------------------------------------
# Router: planned membership (join/leave without a dropped byte)
# ---------------------------------------------------------------------------

class _Backend:
    """A decode backend stub: scripted /readiness (ready flag), a
    mutable sketch payload, and a counting completion handler."""

    def __init__(self, ready=True, sketch=None, name=None):
        backend = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _send(self, code, data):
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/readiness":
                    if backend.ready:
                        self._send(200, json.dumps(
                            {"status": "ready",
                             "admission": {"saturation": backend.saturation},
                             "slo_burn": backend.burn,
                             "elastic": {"armed": backend.armed}}).encode())
                    else:
                        self._send(503, json.dumps(
                            {"error": {"message": backend.reason}}).encode())
                elif self.path == "/v1/cache/sketch" and backend.sketch:
                    self._send(200, json.dumps(backend.sketch).encode())
                else:
                    self._send(404, b"{}")

            def do_POST(self):
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                backend.calls += 1
                if backend.fail:
                    self._send(503, b'{"error":{"code":503}}')
                    return
                self._send(200, json.dumps(
                    {"id": "ok", "served_by": backend.name,
                     "choices": []}).encode())

        self.ready = ready
        self.sketch = sketch
        self.calls = 0
        self.fail = False
        self.armed = True
        self.saturation = 0.0
        self.burn = {}
        self.reason = "engine scaled to zero (disarmed)"
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.addr = f"127.0.0.1:{self._httpd.server_port}"
        self.name = name or self.addr
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def _sketch_payload(epoch="boot1.0"):
    ex = ps.SketchExporter(4)
    p = ex.build([], ("k", 1), [], 1)
    p["epoch"] = epoch
    return p


def _mk_router(monkeypatch, decode="", **kw):
    monkeypatch.setenv("ARKS_PREFILL_ADDRS", "")
    monkeypatch.setenv("ARKS_DECODE_ADDRS", decode)
    monkeypatch.setenv("ARKS_ROUTER_RETRY_BACKOFF_S", "0.01")
    monkeypatch.setenv("ARKS_ROUTER_SKETCH_POLL_S", "60")
    return Router(Discovery(None), "tiny", host="127.0.0.1", port=0,
                  policy="cache_aware", **kw)


def test_discovery_overlay_add_remove(monkeypatch):
    monkeypatch.setenv("ARKS_PREFILL_ADDRS", "")
    monkeypatch.setenv("ARKS_DECODE_ADDRS", "10.0.0.1:1")
    d = Discovery(None)
    assert d.backends()[1] == ["10.0.0.1:1"]
    d.add("decode", "10.0.0.2:1")
    assert d.backends()[1] == ["10.0.0.1:1", "10.0.0.2:1"]
    d.add("decode", "10.0.0.2:1")  # idempotent
    assert d.backends()[1] == ["10.0.0.1:1", "10.0.0.2:1"]
    # remove masks even env/file-listed backends, and survives re-reads.
    d.remove("decode", "10.0.0.1:1")
    assert d.backends()[1] == ["10.0.0.2:1"]
    assert d.backends()[1] == ["10.0.0.2:1"]
    d.add("decode", "10.0.0.1:1")  # unmask by re-adding
    assert "10.0.0.1:1" in d.backends()[1]
    with pytest.raises(ValueError):
        d.add("frontend", "10.0.0.3:1")


def test_plan_join_admits_mid_workload_with_zero_5xx(monkeypatch):
    """A new backend joins THROUGH plan_join while a client workload
    runs: every request in flight across the handoff gets a 2xx (the
    joiner is admitted only after its readiness gate + sketch prime),
    and post-join traffic reaches the joiner."""
    a = _Backend(sketch=_sketch_payload("a.0"))
    b = _Backend(sketch=_sketch_payload("b.0"))
    r = _mk_router(monkeypatch, decode=a.addr, unified=True)
    r.start(background=True)
    failures, done = [], threading.Event()

    def workload():
        n = 0
        while not done.is_set():
            # Varied prompts: rendezvous hashing spreads distinct prefix
            # keys across the rotation, so the joiner takes a share.
            n += 1
            body = json.dumps({"model": "tiny",
                               "prompt": [1, 2, 3, n % 97]}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{r.port}/v1/completions", data=body,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    if resp.status != 200:
                        failures.append(resp.status)
            except Exception as e:  # noqa: BLE001 — any 5xx/raise counts
                failures.append(repr(e))
            time.sleep(0.002)

    t = threading.Thread(target=workload, daemon=True)
    t.start()
    try:
        time.sleep(0.1)
        out = r.plan_join(b.addr)
        assert out["addr"] == b.addr and out["seconds"] >= 0
        assert out["sketch_primed"], "join must prime the sketch"
        time.sleep(0.3)
    finally:
        done.set()
        t.join(timeout=10)
        r.stop()
        a.stop()
        b.stop()
    assert not failures, f"client-visible failures across the join: {failures}"
    assert b.addr in r.discovery.backends()[1]
    assert b.calls > 0, "the joined backend never took traffic"
    assert r.metrics.planned_membership_total.get(
        op="join", outcome="ok") == 1
    assert r.metrics.join_seconds.get(backend=b.addr) >= 0


def test_plan_join_primes_sketch_then_resize_epoch_drops_once(monkeypatch):
    """The join's sketch prime is DROP-FREE (first observation, no
    pre-resize epoch to discard); the backend's post-resize epoch bump
    then drops the stale membership EXACTLY once on the next poll."""
    b = _Backend(sketch=_sketch_payload("boot1.0"))
    r = _mk_router(monkeypatch, decode="")
    try:
        r.plan_join(b.addr)
        assert r.sketches.get(b.addr) is not None
        assert r.metrics.sketch_epoch_drops_total.get(backend=b.addr) == 0, \
            "the prime must not count an epoch drop"
        # The backend live-resizes: its sketch epoch bumps (the tier-0
        # index restarted empty at the new shape).
        b.sketch = _sketch_payload("boot1.1-resize")
        r.sketches.poll_once()
        assert r.metrics.sketch_epoch_drops_total.get(backend=b.addr) == 1
        r.sketches.poll_once()
        assert r.metrics.sketch_epoch_drops_total.get(backend=b.addr) == 1, \
            "a stable epoch must not keep dropping"
    finally:
        b.stop()


def test_plan_join_times_out_on_unready_backend(monkeypatch):
    """An unready (still re-arming) backend never joins: plan_join
    bounds the readiness poll and leaves the membership untouched."""
    b = _Backend(ready=False)
    r = _mk_router(monkeypatch, decode="")
    try:
        with pytest.raises(TimeoutError):
            r.plan_join(b.addr, timeout_s=0.3)
        assert b.addr not in r.discovery.backends()[1]
        assert r.metrics.planned_membership_total.get(
            op="join", outcome="timeout") == 1
    finally:
        b.stop()


def test_plan_leave_removes_backend_and_sketch(monkeypatch):
    b = _Backend(sketch=_sketch_payload())
    r = _mk_router(monkeypatch, decode=b.addr)
    try:
        r.sketches.poll_once()
        assert r.sketches.get(b.addr) is not None
        r.plan_leave(b.addr)
        assert b.addr not in r.discovery.backends()[1]
        assert r.sketches.get(b.addr) is None
        assert r.metrics.planned_membership_total.get(
            op="leave", outcome="ok") == 1
    finally:
        b.stop()


def test_joined_backend_failover_restabilizes(monkeypatch):
    """The joined backend starts 503ing: requests fail over to the
    incumbent exactly like pre-join failover — the planned membership
    changes the rotation, never the retry semantics."""
    a = _Backend()
    b = _Backend()
    r = _mk_router(monkeypatch, decode=a.addr, unified=True)
    r.start(background=True)
    try:
        r.plan_join(b.addr)
        b.fail = True
        body = json.dumps({"model": "tiny", "prompt": [1, 2, 3]}).encode()
        for _ in range(6):
            req = urllib.request.Request(
                f"http://127.0.0.1:{r.port}/v1/completions", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.status == 200
    finally:
        r.stop()
        a.stop()
        b.stop()


# ---------------------------------------------------------------------------
# Autoscaler: signals mode (SLO burn / saturation -> replicas)
# ---------------------------------------------------------------------------

def _mk_autoscaler(sig, actuator=None):
    from arks_tpu.control import resources as res
    from arks_tpu.control.autoscaler import AutoscalerController
    from arks_tpu.control.store import Store

    store = Store()
    app = store.create(res.Application(name="app", spec={
        "replicas": 1, "servedModelName": "m",
        "autoscale": {"minReplicas": 0, "maxReplicas": 3,
                      "scaleDownStabilizationSeconds": 0},
    }))
    ctl = AutoscalerController(store, rate_source=lambda ns, m: 0.0,
                               signals_source=lambda ns, m: sig["v"],
                               actuator=actuator)
    return store, app, ctl


def _reconcile(store, ctl):
    from arks_tpu.control import resources as res
    app = store.get(res.Application, "app")
    ctl.reconcile(app)
    return store.get(res.Application, "app")


def test_signals_scale_up_on_burn_with_cooldown(monkeypatch):
    """An SLO burn over the high-water mark adds ONE replica; the next
    burning tick inside the cooldown holds (reason="cooldown")."""
    monkeypatch.setenv("ARKS_ELASTIC_COOLDOWN_S", "60")
    sig = {"v": {"burn": 2.0, "saturation": 0.1, "ready": 1}}
    store, app, ctl = _mk_autoscaler(sig)
    app = _reconcile(store, ctl)
    assert app.spec["replicas"] == 2
    st = app.status["autoscale"]
    assert st["mode"] == "signals" and st["reason"] == "signal_high"
    assert st["burnRate"] == 2.0 and st["ready"] == 1
    app = _reconcile(store, ctl)
    assert app.spec["replicas"] == 2, "cooldown must damp the second step"
    assert app.status["autoscale"]["reason"] == "cooldown"


def test_signals_saturation_alone_scales_up(monkeypatch):
    sig = {"v": {"burn": 0.0, "saturation": 0.95}}
    store, app, ctl = _mk_autoscaler(sig)
    app = _reconcile(store, ctl)
    assert app.spec["replicas"] == 2
    assert app.status["autoscale"]["reason"] == "signal_high"


def test_signals_hysteresis_band_holds_shape(monkeypatch):
    """Between the water marks (burn under HI but over LO) the shape
    holds — the band is what keeps an oscillating signal from flapping
    the fleet."""
    sig = {"v": {"burn": 0.5, "saturation": 0.5}}
    store, app, ctl = _mk_autoscaler(sig)
    app = _reconcile(store, ctl)
    assert app.spec["replicas"] == 1
    assert app.status["autoscale"]["reason"] == "steady"


def test_signals_scale_down_requires_all_signals_low(monkeypatch):
    monkeypatch.setenv("ARKS_ELASTIC_COOLDOWN_S", "0")
    sig = {"v": {"burn": 0.0, "saturation": 0.8}}  # sat still mid-band
    store, app, ctl = _mk_autoscaler(sig)
    app = _reconcile(store, ctl)
    assert app.spec["replicas"] == 1, "one low signal is not enough"
    sig["v"] = {"burn": 0.0, "saturation": 0.0}
    app = _reconcile(store, ctl)
    assert app.spec["replicas"] == 0, \
        "all-low signals with min=0 scale to zero"
    assert app.status["autoscale"]["reason"] == "signal_low"


def test_signals_scale_up_from_zero_skips_cooldown(monkeypatch):
    """The cooldown exemption: a burn against ZERO replicas scales up
    immediately even right after a scaling action — rescuing a
    scaled-to-zero fleet is the loop's whole point."""
    monkeypatch.setenv("ARKS_ELASTIC_COOLDOWN_S", "3600")
    sig = {"v": {"burn": 0.0, "saturation": 0.0}}
    store, app, ctl = _mk_autoscaler(sig)
    app = _reconcile(store, ctl)
    assert app.spec["replicas"] == 0
    sig["v"] = {"burn": 5.0, "saturation": 0.0, "disarmed": 1}
    app = _reconcile(store, ctl)
    assert app.spec["replicas"] == 1, \
        "scale-up from zero must not sit out the cooldown"
    assert app.status["autoscale"]["disarmed"] == 1


def test_signals_missing_evidence_holds_shape(monkeypatch):
    sig = {"v": None}
    store, app, ctl = _mk_autoscaler(sig)
    app = _reconcile(store, ctl)
    assert app.spec["replicas"] == 1
    assert "autoscale" not in app.status, \
        "no evidence: no action, no status churn"


def test_signals_actuator_fires_on_scale_and_failure_is_contained(
        monkeypatch):
    calls = []

    def actuator(app, desired, sig):
        calls.append((desired, sig["burn"]))
        raise RuntimeError("boom")  # must be contained

    sig = {"v": {"burn": 2.0, "saturation": 0.0}}
    store, app, ctl = _mk_autoscaler(sig, actuator=actuator)
    app = _reconcile(store, ctl)
    assert app.spec["replicas"] == 2, "actuator failure must not derail"
    assert calls == [(2, 2.0)]


def test_scrape_and_fleet_signals(monkeypatch):
    """scrape_signals parses the readiness payload (saturation, worst
    per-tier burn, armed); a 503 disarmed replica yields a row with
    disarmed=True; fleet_signals merges worst-case across the fleet."""
    from arks_tpu.control.autoscaler import fleet_signals, scrape_signals
    up = _Backend()
    up.saturation = 0.4
    up.burn = {"gold": 1.5, "best_effort": 0.2}
    down = _Backend(ready=False)
    try:
        s = scrape_signals(up.addr)
        assert s == {"ready": True, "saturation": 0.4, "burn": 1.5,
                     "disarmed": False, "reason": ""}
        s = scrape_signals(down.addr)
        assert s["ready"] is False and s["disarmed"] is True
        assert scrape_signals("127.0.0.1:1") is None  # unreachable
        fleet = fleet_signals([up.addr, down.addr, "127.0.0.1:1"])
        assert fleet["burn"] == 1.5 and fleet["saturation"] == 0.4
        assert fleet["ready"] == 1 and fleet["disarmed"] == 1
        assert fleet_signals(["127.0.0.1:1"]) is None
    finally:
        up.stop()
        down.stop()
