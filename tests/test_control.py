"""Control-plane tests: store semantics + the controller phase machines,
driven end-to-end with the fake gang driver (the envtest analogue, but with
behavior assertions the reference's scaffolded tests lack — SURVEY.md §4)."""

import os
import time

import pytest


def wait_for(predicate, timeout=15.0, interval=0.05):
    """Poll until predicate() is truthy (needed where progress rides the
    GangSet controller's periodic resync rather than a store event)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = predicate()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")

from arks_tpu.control import resources as res
from arks_tpu.control.manager import build_manager
from arks_tpu.control.store import Conflict, NotFound, Store
from arks_tpu.control.workloads import FakeGangDriver


# ---------------------------------------------------------------------------
# Store semantics
# ---------------------------------------------------------------------------

def test_store_crud_and_conflict():
    s = Store()
    m = res.Model(name="m1", spec={"model": "x"})
    s.create(m)
    got = s.get(res.Model, "m1")
    assert got.spec["model"] == "x"

    stale = s.get(res.Model, "m1")
    got.spec["model"] = "y"
    s.update(got)
    stale.spec["model"] = "z"
    with pytest.raises(Conflict):
        s.update(stale)


def test_store_finalizers_and_cascade():
    s = Store()
    app = res.Application(name="a1")
    s.create(app)
    s.add_finalizer(app, "test/finalizer")
    child = res.GangSet(name="g1", owner_refs=[("Application", "a1")])
    s.create(child)

    s.delete(res.Application, "a1")
    # Finalizer holds the object.
    held = s.get(res.Application, "a1")
    assert held.deletion_requested
    s.strip_finalizer(held, "test/finalizer")
    with pytest.raises(NotFound):
        s.get(res.Application, "a1")
    # Cascade removed the owned GangSet.
    with pytest.raises(NotFound):
        s.get(res.GangSet, "g1")


def test_store_watch_replays_and_streams():
    s = Store()
    s.create(res.Model(name="pre"))
    q = s.watch(res.Model)
    ev, obj = q.get(timeout=1)
    assert ev == "ADDED" and obj.name == "pre"
    s.create(res.Model(name="post"))
    ev, obj = q.get(timeout=1)
    assert ev == "ADDED" and obj.name == "post"


# ---------------------------------------------------------------------------
# Controller stack (fake driver)
# ---------------------------------------------------------------------------

@pytest.fixture()
def stack(tmp_path):
    driver = FakeGangDriver()
    mgr = build_manager(models_root=str(tmp_path / "models"), driver=driver)
    mgr.start()
    yield mgr, mgr.store, driver
    mgr.stop()


def test_model_existing_storage_ready(stack):
    mgr, store, _ = stack
    store.create(res.Model(name="m-exist", spec={"model": "org/m"}))
    assert mgr.wait_idle()
    m = store.get(res.Model, "m-exist")
    assert m.status["phase"] == res.MODEL_PHASE_READY
    assert m.condition(res.COND_STORAGE_CREATED)
    assert m.condition(res.COND_MODEL_LOADED)
    assert os.path.isdir(m.status["path"])
    # generateModelPath layout parity: <root>/models/<ns>/<name>
    assert m.status["path"].endswith("models/default/m-exist")


def test_model_local_source_download(stack, tmp_path):
    mgr, store, _ = stack
    src = tmp_path / "src-model"
    src.mkdir()
    (src / "weights.bin").write_bytes(b"w" * 32)
    store.create(res.Model(name="m-dl", spec={
        "model": "org/m", "source": {"local": {"path": str(src)}}}))
    assert mgr.wait_idle()
    m = store.get(res.Model, "m-dl")
    assert m.status["phase"] == res.MODEL_PHASE_READY
    assert os.path.exists(os.path.join(m.status["path"], "weights.bin"))


def test_model_bad_source_fails_with_message(stack):
    mgr, store, _ = stack
    store.create(res.Model(name="m-bad", spec={
        "model": "org/m", "source": {"local": {"path": "/does/not/exist"}}}))
    assert mgr.wait_idle()
    m = store.get(res.Model, "m-bad")
    assert m.status["phase"] == res.MODEL_PHASE_FAILED
    conds = {c["type"]: c for c in m.status["conditions"]}
    assert conds[res.COND_MODEL_LOADED]["status"] == "False"
    assert "/does/not/exist" in conds[res.COND_MODEL_LOADED]["message"]


def test_application_full_lifecycle(stack):
    mgr, store, driver = stack
    # App first: must wait in Loading until the model is Ready.
    store.create(res.Application(name="app1", spec={
        "replicas": 2, "runtime": "jax", "model": {"name": "m-app"},
        "servedModelName": "my-model", "tensorParallel": 1,
        "modelConfig": "tiny"}))
    assert mgr.wait_idle()
    app = store.get(res.Application, "app1")
    assert app.status["phase"] == res.PHASE_LOADING
    assert not app.condition(res.COND_LOADED)

    store.create(res.Model(name="m-app", spec={"model": "org/m"}))
    assert mgr.wait_idle()
    app = store.get(res.Application, "app1")
    assert app.status["phase"] == res.PHASE_RUNNING
    assert app.condition(res.COND_READY)
    assert app.status["readyReplicas"] == 2

    # Workload + Service exist with the reference naming/labels.
    gs = store.get(res.GangSet, "app1")
    assert gs.spec["replicas"] == 2
    assert "arks_tpu.server" in " ".join(gs.spec["leader"]["command"])
    svc = store.get(res.Service, "arks-application-app1")
    assert len(svc.status["addresses"]) == 2

    # Endpoint discovers the ready app.
    store.create(res.Endpoint(name="my-model", spec={"defaultWeight": 3}))
    assert mgr.wait_idle()
    ep = store.get(res.Endpoint, "my-model")
    routes = ep.status["routes"]
    assert len(routes) == 1
    assert routes[0]["weight"] == 3
    assert routes[0]["backend"]["service"] == "arks-application-app1"
    assert len(routes[0]["backend"]["addresses"]) == 2
    assert ep.status["match"] == {"namespace": "default", "model": "my-model"}

    # Group failure flips readiness; the route SURVIVES on the remaining
    # group (serving() semantics) but its address list shrinks — and the
    # app's phase reflects the degradation.
    driver.fail_group(gs.key, 0)
    wait_for(lambda: store.get(res.Application, "app1").status["readyReplicas"] == 1)
    app = store.get(res.Application, "app1")
    assert app.status["phase"] == res.PHASE_CREATING
    wait_for(lambda: len(store.get(res.Endpoint, "my-model")
                         .status["routes"][0]["backend"]["addresses"]) == 1)

    # ALL groups failing does drop the route.
    driver.fail_group(gs.key, 1)
    wait_for(lambda: store.get(res.Endpoint, "my-model").status["routes"] == [])

    driver.recover_group(gs.key, 0)
    driver.recover_group(gs.key, 1)
    wait_for(lambda: store.get(res.Application, "app1").status["phase"] == res.PHASE_RUNNING)

    # Deletion tears down the gang and cascades the service.
    store.delete(res.Application, "app1")
    wait_for(lambda: store.try_get(res.Application, "app1") is None)
    assert store.try_get(res.GangSet, "app1") is None
    assert store.try_get(res.Service, "arks-application-app1") is None
    assert ("default", "app1") in driver.torn_down


def test_application_invalid_runtime_fails(stack):
    mgr, store, _ = stack
    store.create(res.Application(name="bad-rt", spec={
        "runtime": "tensorrt", "model": {"name": "whatever"}}))
    assert mgr.wait_idle()
    app = store.get(res.Application, "bad-rt")
    assert app.status["phase"] == res.PHASE_FAILED
    conds = {c["type"]: c for c in app.status["conditions"]}
    assert conds[res.COND_PRECHECK]["status"] == "False"


def test_endpoint_static_routes_priority(stack):
    mgr, store, _ = stack
    store.create(res.Endpoint(name="static-ep", spec={
        "defaultWeight": 1,
        "routeConfigs": [{"backend": {"addresses": ["10.0.0.9:8080"]},
                          "weight": 7}]}))
    assert mgr.wait_idle()
    ep = store.get(res.Endpoint, "static-ep")
    assert ep.status["routes"][0]["static"] is True
    assert ep.status["routes"][0]["weight"] == 7


def test_rolling_spec_update_regenerates_workload(stack):
    mgr, store, _ = stack
    store.create(res.Model(name="m-roll", spec={"model": "org/m"}))
    store.create(res.Application(name="app-roll", spec={
        "replicas": 1, "runtime": "jax", "model": {"name": "m-roll"},
        "modelConfig": "tiny"}))
    assert mgr.wait_idle()
    app = store.get(res.Application, "app-roll")
    app.spec["replicas"] = 3
    store.update(app)
    assert mgr.wait_idle(timeout=10)
    gs = store.get(res.GangSet, "app-roll")
    assert gs.spec["replicas"] == 3
    assert store.get(res.Application, "app-roll").status["readyReplicas"] == 3


def test_rolling_update_sequential_and_route_survives(stack):
    """VERDICT acceptance: changing runtimeCommonArgs on a replicas=2 app
    restarts both groups sequentially (maxUnavailable=1, gated on the
    previous group's readiness) and the endpoint's backend list never goes
    empty during the rollout."""
    mgr, store, driver = stack
    store.create(res.Model(name="m-ru", spec={"model": "org/m"}))
    store.create(res.Application(name="app-ru", spec={
        "replicas": 2, "runtime": "jax", "model": {"name": "m-ru"},
        "servedModelName": "ru-model", "modelConfig": "tiny"}))
    store.create(res.Endpoint(name="ru-model", spec={}))
    assert mgr.wait_idle()
    wait_for(lambda: store.get(res.Application, "app-ru").status["readyReplicas"] == 2)
    gs_key = store.get(res.GangSet, "app-ru").key
    assert driver.restarts == []

    # Watch the endpoint's backends continuously during the rollout.
    import threading
    empties, stop = [], threading.Event()

    def watch():
        while not stop.is_set():
            ep = store.try_get(res.Endpoint, "ru-model")
            if ep is not None and ep.status.get("routes") is not None:
                if not ep.status["routes"]:
                    empties.append(True)
            time.sleep(0.01)

    t = threading.Thread(target=watch, daemon=True)
    t.start()
    try:
        app = store.get(res.Application, "app-ru")
        app.spec["runtimeCommonArgs"] = ["--max-model-len", "2048"]
        store.update(app)
        # Both groups roll, one at a time (driver records order).
        wait_for(lambda: len(driver.restarts) >= 2, timeout=30)
    finally:
        stop.set()
        t.join(timeout=5)

    assert driver.restarts[:2] == [(gs_key, 0), (gs_key, 1)]
    assert not empties, "endpoint backend list went empty during rollout"
    # New command propagated to the workload spec.
    gs = store.get(res.GangSet, "app-ru")
    assert "--max-model-len" in " ".join(gs.spec["leader"]["command"])
    wait_for(lambda: store.get(res.Application, "app-ru").status["readyReplicas"] == 2)


def test_pick_rolling_restart_semantics():
    from arks_tpu.control.workloads import pick_rolling_restart
    # No outdated groups -> nothing to do.
    assert pick_rolling_restart({0: "a", 1: "a"}, "a", {0: True, 1: True}) is None
    # All ready -> lowest outdated index first.
    assert pick_rolling_restart({0: "old", 1: "old"}, "new",
                                {0: True, 1: True}) == 0
    # Previous restart not ready yet -> hold (maxUnavailable=1).
    assert pick_rolling_restart({0: "new", 1: "old"}, "new",
                                {0: False, 1: True}) is None
    # Previous restart ready -> next one rolls.
    assert pick_rolling_restart({0: "new", 1: "old"}, "new",
                                {0: True, 1: True}) == 1
    # The candidate itself being unready does not block its own restart.
    assert pick_rolling_restart({0: "old", 1: "new"}, "new",
                                {0: False, 1: True}) == 0
    # A hung (alive-but-unready) outdated group rolls even when others are
    # unready too — restarting it can't reduce availability, and holding it
    # would wedge a corrective rollout forever.
    assert pick_rolling_restart({0: "old", 1: "old"}, "new",
                                {0: False, 1: False}) == 0
    assert pick_rolling_restart({0: "old", 1: "old"}, "new",
                                {0: True, 1: False}) == 1


# ---------------------------------------------------------------------------
# Autoscaler (native HPA analogue over gateway request rates)
# ---------------------------------------------------------------------------


def test_request_rate_tracker(monkeypatch):
    from arks_tpu.gateway import server as gws

    t = [960.0]  # exactly a minute boundary (minute 16)
    monkeypatch.setattr(gws.time, "time", lambda: t[0])
    tr = gws.RequestRateTracker()
    for _ in range(30):
        tr.record("ns", "m")
    # Same window: the 30 fresh requests count in full.
    assert tr.rpm("ns", "m") == 30
    # One window later at its midpoint: prev 30 weighted by the un-elapsed
    # half + 12 current.
    t[0] = 1050.0  # minute 17 + 30s
    for _ in range(12):
        tr.record("ns", "m")
    assert abs(tr.rpm("ns", "m") - (30 * 0.5 + 12)) < 1e-6
    # Two windows later: the old minutes have aged out entirely.
    t[0] = 1140.0  # minute 19
    assert tr.rpm("ns", "m") == 0
    assert tr.rpm("other", "m") == 0


def test_autoscaler_scales_up_then_down(tmp_path):
    import time as _time

    rpm = {"v": 500.0}
    driver = FakeGangDriver()
    mgr = build_manager(models_root=str(tmp_path / "models"), driver=driver,
                        rate_source=lambda ns, model: rpm["v"],
                        autoscale_interval_s=0.1)
    mgr.start()
    try:
        store = mgr.store
        store.create(res.Model(name="m1", spec={"model": "org/m"}))
        store.create(res.Application(name="auto", spec={
            "replicas": 1, "runtime": "jax", "model": {"name": "m1"},
            "servedModelName": "auto-m", "modelConfig": "tiny",
            "autoscale": {"minReplicas": 1, "maxReplicas": 3,
                          "targetRPMPerReplica": 100,
                          "scaleDownStabilizationSeconds": 1},
        }))
        deadline = _time.monotonic() + 20
        # 500 rpm / 100 target -> 5, clamped to max 3; scale-up immediate.
        while _time.monotonic() < deadline:
            app = store.get(res.Application, "auto")
            if app.spec.get("replicas") == 3:
                break
            _time.sleep(0.05)
        assert store.get(res.Application, "auto").spec["replicas"] == 3
        # Gang followed.
        gs = store.get(res.GangSet, "auto")
        assert gs.spec["replicas"] == 3

        # Demand drops; scale-down waits the stabilization window then lands
        # on the clamped minimum.
        rpm["v"] = 0.0
        t0 = _time.monotonic()
        while _time.monotonic() < deadline:
            app = store.get(res.Application, "auto")
            if app.spec.get("replicas") == 1:
                break
            _time.sleep(0.05)
        app = store.get(res.Application, "auto")
        assert app.spec["replicas"] == 1
        assert _time.monotonic() - t0 >= 0.9  # damped, not instant
        assert app.status["autoscale"]["desiredReplicas"] == 1
    finally:
        mgr.stop()


def test_autoscaler_splits_demand_across_peer_apps(tmp_path):
    """Multiple Applications behind one served name split the endpoint's
    demand — each must scale to its SHARE, not the full total."""
    import time as _time

    driver = FakeGangDriver()
    mgr = build_manager(models_root=str(tmp_path / "models"), driver=driver,
                        rate_source=lambda ns, model: 400.0,
                        autoscale_interval_s=0.1)
    mgr.start()
    try:
        store = mgr.store
        store.create(res.Model(name="m1", spec={"model": "org/m"}))
        for name in ("peer-a", "peer-b"):
            store.create(res.Application(name=name, spec={
                "replicas": 1, "runtime": "jax", "model": {"name": "m1"},
                "servedModelName": "shared-m", "modelConfig": "tiny",
                "autoscale": {"minReplicas": 1, "maxReplicas": 8,
                              "targetRPMPerReplica": 100,
                              # Short window: before both peers are
                              # serving(), shares are transiently too big
                              # and the test must not wait the 60s default
                              # to correct down.
                              "scaleDownStabilizationSeconds": 1},
            }))
        deadline = _time.monotonic() + 20
        while _time.monotonic() < deadline:
            reps = [store.get(res.Application, n).spec.get("replicas")
                    for n in ("peer-a", "peer-b")]
            if reps == [2, 2]:
                break
            _time.sleep(0.05)
        # 400 rpm / 2 peers = 200 each -> 2 replicas each (not 4).
        assert [store.get(res.Application, n).spec["replicas"]
                for n in ("peer-a", "peer-b")] == [2, 2]
    finally:
        mgr.stop()


def test_multislice_accelerator_maps_to_gang_and_flags(stack):
    """North-star config #5: a multi-slice accelerator spec
    ("tpu-v5p-16x2" = 2 slices x 2 hosts) sizes the gang to ALL hosts
    across slices, and the serve command carries --num-slices so the
    engine builds the DCN-crossing 'slice' mesh axis."""
    mgr, store, driver = stack
    store.create(res.Model(name="m-ms", spec={"model": "org/ms"}))
    store.create(res.Application(name="ms-app", spec={
        "replicas": 1, "runtime": "jax", "model": {"name": "m-ms"},
        "servedModelName": "ms-served", "tensorParallel": 4,
        "modelConfig": "tiny", "accelerator": "tpu-v5p-16x2"}))
    assert mgr.wait_idle()
    gs = store.get(res.GangSet, "ms-app")
    assert gs.spec["size"] == 4              # 2 hosts/slice x 2 slices
    cmd = " ".join(gs.spec["leader"]["command"])
    assert "--num-slices 2" in cmd
    assert gs.spec["accelerator"] == "tpu-v5p-16x2"

    # Single-slice shapes keep deriving size from the shape too.
    store.create(res.Application(name="ss-app", spec={
        "replicas": 1, "runtime": "jax", "model": {"name": "m-ms"},
        "servedModelName": "ss-served", "tensorParallel": 4,
        "modelConfig": "tiny", "accelerator": "tpu-v5e-16"}))
    assert mgr.wait_idle()
    gs2 = store.get(res.GangSet, "ss-app")
    assert gs2.spec["size"] == 4             # 4 hosts, one slice
    assert "--num-slices" not in " ".join(gs2.spec["leader"]["command"])

    # An explicit spec.size always wins over the shape derivation.
    store.create(res.Application(name="ovr-app", spec={
        "replicas": 1, "runtime": "jax", "model": {"name": "m-ms"},
        "servedModelName": "ovr-served", "size": 2,
        "modelConfig": "tiny", "accelerator": "tpu-v5e-16"}))
    assert mgr.wait_idle()
    assert store.get(res.GangSet, "ovr-app").spec["size"] == 2
